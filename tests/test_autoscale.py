"""Closed-loop autoscaling (ome_tpu/autoscale/, docs/autoscaling.md).

Units cover the pure layers with no subprocesses: trace generation
and transforms, reqlog schema v2 arrival reconstruction, the
exposition parser + windowed histogram quantiles, the tick-based
hysteresis policy, the controller's decision path with injected
scrapes and fake pools (including run-to-run determinism), and the
router's guarded /backends registration surface.

The live layers get two tests: a tier-1 closed-loop smoke (router +
real CPU engine pool, bursty synthetic trace, scale up then drain
down, zero lost requests, greedy streams prefix-consistent) and the
EnginePool kill-during-scale-down resume path. The full bursty soak
with the engine-seconds-vs-static-provisioning acceptance check is
`slow`.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from ome_tpu.autoscale import controller as ctl_mod
from ome_tpu.autoscale import replay as replay_mod
from ome_tpu.autoscale import scrape as scrape_mod
from ome_tpu.autoscale import trace as trace_mod
from ome_tpu.autoscale.policy import PolicyConfig, PoolPolicy
from ome_tpu.autoscale.pool import EnginePool
from ome_tpu.chaos import ManagedProc, free_port, journal_live_entries
from ome_tpu.telemetry import Registry
from ome_tpu.telemetry import reqlog as reqlog_mod


# -- traces -----------------------------------------------------------


class TestTrace:
    def test_synthetic_deterministic(self):
        a = trace_mod.synthetic_trace(7, n=20)
        b = trace_mod.synthetic_trace(7, n=20)
        assert a == b
        assert a != trace_mod.synthetic_trace(8, n=20)
        assert all(x.arrival <= y.arrival for x, y in zip(a, a[1:]))
        assert a[0].arrival == 0.0

    def test_burst_window_is_denser(self):
        tr = trace_mod.synthetic_trace(3, n=60, base_rate=2.0,
                                       burst_factor=8.0)
        gaps = [y.arrival - x.arrival for x, y in zip(tr, tr[1:])]
        third = len(gaps) // 3
        edge = gaps[:third] + gaps[-third:]
        mid = gaps[third:-third]
        assert (sum(mid) / len(mid)) < (sum(edge) / len(edge))

    def test_compress(self):
        tr = trace_mod.synthetic_trace(1, n=8)
        fast = trace_mod.compress(tr, 4.0)
        for orig, comp in zip(tr, fast):
            assert comp.arrival == pytest.approx(orig.arrival / 4.0,
                                                 abs=1e-5)
            assert comp.prompt_tokens == orig.prompt_tokens
        with pytest.raises(ValueError):
            trace_mod.compress(tr, 0)

    def test_amplify_bursts(self):
        tr = trace_mod.synthetic_trace(2, n=20, burst_factor=6.0)
        assert trace_mod.amplify_bursts(tr, 1) == sorted(
            tr, key=lambda r: r.arrival)
        amp = trace_mod.amplify_bursts(tr, 3, seed=5)
        assert len(amp) > len(tr)
        assert amp == trace_mod.amplify_bursts(tr, 3, seed=5)
        assert all(x.arrival <= y.arrival
                   for x, y in zip(amp, amp[1:]))
        with pytest.raises(ValueError):
            trace_mod.amplify_bursts(tr, 0)

    def test_save_load_roundtrip(self, tmp_path):
        tr = trace_mod.synthetic_trace(4, n=10)
        tr[0].prompt = "explicit text"
        p = tmp_path / "trace.jsonl"
        trace_mod.save_trace(tr, p)
        assert trace_mod.load_trace(p) == tr

    def test_prompt_text(self):
        r = trace_mod.TraceRequest(arrival=0, prompt_tokens=8,
                                   max_tokens=4)
        assert r.prompt_text(0) == r.prompt_text(0)
        assert len(r.prompt_text(0)) == 8
        # deterministic in (seed, length) only: repeated lengths
        # repeat prompts, so greedy oracles are comparable
        r2 = trace_mod.TraceRequest(arrival=9, prompt_tokens=8,
                                    max_tokens=2)
        assert r2.prompt_text(0) == r.prompt_text(0)
        assert r.prompt_text(1) != r.prompt_text(0)
        r.prompt = "mine"
        assert r.prompt_text(0) == "mine"


# -- reqlog schema v2 -------------------------------------------------


class TestReqlogV2:
    def _v2(self, admit_ts, admit_mono, **kw):
        rec = {"component": "engine", "model": "m", "ts": admit_ts + 5,
               "admit_ts": admit_ts, "admit_mono": admit_mono,
               "prompt_tokens": 4, "output_tokens": 3,
               "e2e_s": 5.0, "finish_reason": "length"}
        rec.update(kw)
        return rec

    def test_admit_times_v2_and_v1(self):
        wall, mono = reqlog_mod.admit_times(
            self._v2(1000.0, 50.0))
        assert (wall, mono) == (1000.0, 50.0)
        # v1 record: derive the admit instant as ts - e2e_s
        wall, mono = reqlog_mod.admit_times(
            {"ts": 1007.5, "e2e_s": 2.5})
        assert wall == pytest.approx(1005.0)
        assert mono is None
        assert reqlog_mod.admit_times({"model": "m"}) == (None, None)

    def test_load_reqlog_orders_by_admit_not_finish(self, tmp_path):
        # request A admitted first but finished LAST: a finish-time
        # ordering would invert the gap the replay must reproduce
        recs = [self._v2(100.0, 10.0, ts=120.0, trace_id="a"),
                self._v2(103.0, 13.0, ts=104.0, trace_id="b")]
        p = tmp_path / "req.jsonl"
        p.write_text("\n".join(json.dumps(r) for r in reversed(recs))
                     + "\n" + '{"component": "router", "ts": 1}\n'
                     + '{"torn')
        tr = trace_mod.load_reqlog(p)
        assert [r.trace_id for r in tr] == ["a", "b"]
        assert tr[0].arrival == 0.0
        assert tr[1].arrival == pytest.approx(3.0)
        assert tr[0].max_tokens == 3

    def test_requestlog_write_roundtrips_to_trace(self, tmp_path):
        """v2 round trip through the real sink: records written by
        RequestLog come back as a replayable trace with the original
        gap."""
        p = tmp_path / "req.jsonl"
        sink = reqlog_mod.RequestLog(path=str(p))
        sink.write(self._v2(1000.0, 50.0, trace_id="a"))
        sink.write(self._v2(1001.25, 51.25, trace_id="b"))
        sink.close()
        tr = trace_mod.load_reqlog(p)
        assert [r.trace_id for r in tr] == ["a", "b"]
        assert tr[1].arrival == pytest.approx(1.25)


# -- exposition parsing + windowed quantiles --------------------------


class TestScrape:
    def test_parse_real_render(self):
        r = Registry()
        h = r.histogram("ome_engine_ttft_seconds", "t",
                        buckets=(0.1, 0.5, 1.0))
        for v in (0.05, 0.3, 0.7):
            h.observe(v)
        r.gauge("ome_engine_queue_depth", "d").set(3)
        samples = scrape_mod.parse_exposition(r.render())
        assert samples["ome_engine_queue_depth"] == 3.0
        buckets = scrape_mod.bucket_counts(
            samples, "ome_engine_ttft_seconds")
        assert buckets[-1][0] == float("inf")
        assert buckets[-1][1] == 3.0
        name, labels = scrape_mod.split_key(
            'x_bucket{le="0.5",pool="engine"}')
        assert name == "x_bucket"
        assert labels == {"le": "0.5", "pool": "engine"}

    def test_quantile_from_buckets(self):
        # 10 obs: 5 in (0, 0.1], 4 in (0.1, 0.5], 1 beyond 1.0
        buckets = [(0.1, 5.0), (0.5, 9.0), (1.0, 9.0),
                   (float("inf"), 10.0)]
        q50 = scrape_mod.quantile_from_buckets(buckets, 0.5)
        assert 0.0 < q50 <= 0.1
        # the +Inf bucket clamps to the last finite bound
        assert scrape_mod.quantile_from_buckets(buckets, 0.99) == 1.0
        assert scrape_mod.quantile_from_buckets([], 0.5) is None
        assert scrape_mod.quantile_from_buckets(
            [(0.1, 0.0), (float("inf"), 0.0)], 0.5) is None

    def _samples(self, counts):
        bounds = (0.1, 0.5, 1.0)
        out = {}
        cum = 0.0
        for b, c in zip(bounds, counts):
            cum += c
            out[f'ome_engine_ttft_seconds_bucket{{le="{b}"}}'] = cum
        out['ome_engine_ttft_seconds_bucket{le="+Inf"}'] = cum
        return out

    def test_histogram_window(self):
        w = scrape_mod.HistogramWindow("ome_engine_ttft_seconds")
        w.update("u1", self._samples([100, 0, 0]))
        # one scrape = no delta yet
        assert w.quantile(0.99) is None
        # 10 new observations all in (0.5, 1.0]: the window sees the
        # recent latency regression the cumulative p99 would bury
        s2 = self._samples([100, 0, 10])
        w.update("u1", s2)
        assert w.window_count() == 10.0
        assert 0.5 < w.quantile(0.99) <= 1.0
        # counter reset (engine restart) discards and re-bases
        w.update("u1", self._samples([1, 0, 0]))
        assert w.quantile(0.99) is None
        w.update("u1", self._samples([1, 2, 0]))
        assert w.window_count() == 2.0
        w.forget("u1")
        assert w.quantile(0.99) is None

    def test_window_merges_sources(self):
        w = scrape_mod.HistogramWindow("ome_engine_ttft_seconds")
        w.update("u1", self._samples([0, 0, 0]))
        w.update("u2", self._samples([0, 0, 0]))
        w.update("u1", self._samples([5, 0, 0]))
        w.update("u2", self._samples([0, 0, 5]))
        assert w.window_count() == 10.0
        assert w.quantile(0.99) > 0.5


# -- hysteresis policy ------------------------------------------------


class TestPolicy:
    CFG = dict(min_size=1, max_size=3, up_stable_ticks=2,
               down_stable_ticks=3, cooldown_ticks=2,
               down_threshold=0.3)

    def test_validate(self):
        with pytest.raises(ValueError):
            PolicyConfig(min_size=2, max_size=1).validate()
        with pytest.raises(ValueError):
            PolicyConfig(down_threshold=1.5).validate()
        with pytest.raises(ValueError):
            PolicyConfig(up_stable_ticks=0).validate()

    def _run(self, pressures):
        pol = PoolPolicy(PolicyConfig(**self.CFG))
        size, sizes = 1, []
        for p in pressures:
            size = pol.decide(size, p)
            sizes.append(size)
        return sizes

    def test_decision_sequence(self):
        # hand-simulated: up after 2 stable ticks, cooldown holds the
        # next 2, second up at tick 5; down is slower (3 ticks) and
        # interleaves with cooldown; min_size clamps the tail
        sizes = self._run([2.0] * 6 + [0.1] * 10)
        assert sizes == [1, 2, 2, 2, 3, 3,
                         3, 3, 2, 2, 2, 1, 1, 1, 1, 1]

    def test_spike_does_not_scale(self):
        # a single-tick spike never clears up_stable_ticks
        assert self._run([2.0, 0.6, 2.0, 0.6, 2.0, 0.6]) == [1] * 6

    def test_mid_band_resets_both_counters(self):
        pol = PoolPolicy(PolicyConfig(**self.CFG))
        pol.decide(1, 2.0)        # above x1
        pol.decide(1, 0.6)        # mid-band: resets
        assert pol.decide(1, 2.0) == 1   # above x1 again, not x2
        assert pol.decide(1, 2.0) == 2

    def test_clamps(self):
        pol = PoolPolicy(PolicyConfig(**self.CFG))
        # never exceeds max_size even under sustained pressure
        size = 3
        for _ in range(10):
            size = pol.decide(size, 5.0)
        assert size == 3
        # and a too-small starting size clamps up to min_size
        assert PoolPolicy(PolicyConfig(**self.CFG)).decide(0, 0.5) == 1


# -- controller with fakes --------------------------------------------


class _FakePool:
    """Stands in for EnginePool: pure counters, no subprocesses."""

    def __init__(self, size=1):
        self._size = size
        self.spawned = 0
        self.drained = 0

    def size(self):
        return self._size

    def member_urls(self):
        return [f"http://fake:{i}" for i in range(self._size)]

    def draining_count(self):
        return 0

    def engine_seconds(self):
        return float(self._size)

    def spawn(self):
        self._size += 1
        self.spawned += 1

    def drain_one(self):
        if self._size == 0:
            return None
        self._size -= 1
        self.drained += 1
        return "victim"


def _scripted_fetch(depth_by_tick):
    """fetch_fn whose queue_depth follows a per-TICK script: every
    member scraped in the same tick sees the same depth. The first
    fake URL (":0", always present) advances the clock —
    deterministic because member_urls() order is fixed."""
    state = {"tick": -1}

    def fetch(url):
        if url.endswith(":0"):
            state["tick"] += 1
        i = min(max(state["tick"], 0), len(depth_by_tick) - 1)
        depth = depth_by_tick[i]
        if depth is None:
            raise OSError("scrape down")
        return {"ome_engine_queue_depth": float(depth),
                "ome_engine_kv_block_utilization_ratio": 0.1}

    return fetch


class TestController:
    SLO = ctl_mod.SLOConfig(ttft_p99_s=1.0, queue_wait_p99_s=1.0,
                            kv_util_high=0.9, queue_depth_high=2.0)

    def _controller(self, script, pool=None):
        pool = pool or _FakePool()
        pol = PoolPolicy(PolicyConfig(
            min_size=1, max_size=3, up_stable_ticks=2,
            down_stable_ticks=3, cooldown_ticks=2,
            down_threshold=0.3))
        c = ctl_mod.ScaleController(
            {"engine": pool}, {"engine": pol}, self.SLO,
            fetch_fn=_scripted_fetch(script))
        return c, pool

    def test_scales_up_then_down(self):
        # depth 8 => pressure 4.0; depth 0 => pressure 0
        c, pool = self._controller([8, 8, 8, 0, 0, 0, 0, 0, 0, 0])
        for _ in range(10):
            c.tick()
        assert pool.spawned >= 1
        assert pool.drained >= 1
        ups = [d for d in c.decisions if d.target > d.size]
        downs = [d for d in c.decisions if d.target < d.size]
        assert ups and downs
        assert ups[0].pressure == pytest.approx(4.0)
        assert ups[0].signals["queue_depth"] == 8.0
        reg = c.registry
        assert reg.get("ome_autoscale_scale_ups_total",
                       pool="engine") >= 1
        assert reg.get("ome_autoscale_ticks_total") == 10
        assert reg.get("ome_autoscale_pool_size", pool="engine") \
            == pool.size()

    def test_identical_decisions_run_to_run(self):
        """The satellite determinism property: a given (trace ->
        metrics) series maps to exactly one decision sequence."""
        script = [1, 6, 7, 9, 9, 2, 1, 0, 0, 0, 0, 0, 0, 0]

        def run():
            c, _ = self._controller(list(script))
            for _ in range(len(script)):
                c.tick()
            return [d.to_dict() for d in c.decisions]

        first, second = run(), run()
        assert first == second
        assert any(d["target"] != d["size"] for d in first)

    def test_scrape_failure_counted_not_fatal(self):
        c, pool = self._controller([None, None])
        c.tick()
        c.tick()
        assert pool.spawned == 0
        assert c.registry.get(
            "ome_autoscale_scrape_errors_total") == 2
        # no signals at all -> pressure 0, which is still a decision
        assert c.decisions[-1].pressure == 0.0

    def test_failed_spawn_does_not_kill_tick(self):
        class Exploding(_FakePool):
            def spawn(self):
                raise RuntimeError("no capacity")

        c, pool = self._controller([9] * 5, pool=Exploding())
        for _ in range(5):
            c.tick()
        assert pool.size() == 1  # wanted to scale, could not
        assert c.tick_count == 5


# -- router /backends registration surface ----------------------------


class TestRouterBackends:
    def _server(self, debug):
        from ome_tpu.router.server import (Backend, Router,
                                           RouterServer)
        router = Router([Backend("http://127.0.0.1:9")],
                        policy="round_robin")
        srv = RouterServer(router, host="127.0.0.1", port=0,
                           debug_endpoints=debug).start()
        return router, srv, f"http://127.0.0.1:{srv.port}"

    def _call(self, base, method, path, payload=None):
        data = (json.dumps(payload).encode()
                if payload is not None else None)
        req = urllib.request.Request(
            base + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status, json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            body = e.read()
            e.close()
            return e.code, (json.loads(body) if body else {})

    def test_guarded_without_flag(self):
        router, srv, base = self._server(debug=False)
        try:
            for method, payload in (("GET", None),
                                    ("POST", {"url": "http://x:1"}),
                                    ("DELETE", {"url": "http://x:1"})):
                status, _ = self._call(base, method, "/backends",
                                       payload)
                assert status == 403, method
            assert len(router.backends) == 1
        finally:
            srv.stop()

    def test_add_remove_and_stale_gauges(self):
        router, srv, base = self._server(debug=True)
        try:
            status, body = self._call(
                base, "POST", "/backends",
                {"url": "http://127.0.0.1:10", "pool": "decode"})
            assert status == 200
            status, body = self._call(base, "GET", "/backends")
            assert status == 200
            urls = {b["url"]: b for b in body["backends"]}
            assert "http://127.0.0.1:10" in urls
            assert urls["http://127.0.0.1:10"]["pool"] == "decode"
            assert {"healthy", "draining", "inflight",
                    "cb_state"} <= set(urls["http://127.0.0.1:10"])

            # idempotent re-add; re-add also cancels a drain
            router.backends[-1].draining = True
            status, _ = self._call(
                base, "POST", "/backends",
                {"url": "http://127.0.0.1:10", "pool": "decode"})
            assert status == 200
            assert len(router.backends) == 2
            assert not router.backends[-1].draining

            # the inflight gauge exists for the live backend...
            router.update_gauges()
            assert router.registry.get(
                "ome_router_backend_inflight",
                backend="http://127.0.0.1:10", pool="decode") == 0

            # ...and is zeroed (not leaked) once the backend leaves
            router.backends[-1].inflight = 7
            router.update_gauges()
            status, _ = self._call(base, "DELETE", "/backends",
                                   {"url": "http://127.0.0.1:10"})
            assert status == 200
            router.update_gauges()
            assert router.registry.get(
                "ome_router_backend_inflight",
                backend="http://127.0.0.1:10", pool="decode") == 0

            status, _ = self._call(base, "DELETE", "/backends",
                                   {"url": "http://127.0.0.1:10"})
            assert status == 404
            status, _ = self._call(base, "POST", "/backends", {})
            assert status == 400
        finally:
            srv.stop()


# -- live closed loop -------------------------------------------------


def _engine_args_factory(model_dir, drain_grace=6.0):
    def engine_args(port, name, journal_dir):
        return ["--model-dir", str(model_dir), "--random-weights",
                "--dtype", "float32", "--host", "127.0.0.1",
                "--port", str(port), "--max-slots", "2",
                "--kv-block", "16", "--kv-blocks", "40",
                "--prefix-cache-mb", "8",
                "--drain-grace", str(drain_grace),
                "--journal", str(journal_dir),
                "--journal-fsync", "always"]
    return engine_args


def _spawn_router(pool, base, debug=True):
    rport = free_port()
    rargs = ["--bind", "127.0.0.1", "--port", str(rport),
             "--policy", "round_robin", "--health-interval", "0.5"]
    if debug:
        rargs.append("--debug-endpoints")
    for url in pool.member_urls():
        rargs += ["--backend", url]
    router = ManagedProc("router", "router", rargs, rport,
                         base / "router.log")
    router.start()
    router.wait_ready()
    return router


def _journal_leftover(pool):
    return sum(len(journal_live_entries(p)) for p in pool.journals())


def _assert_greedy_prefix_consistent(results):
    """Greedy streams for the same prompt must agree byte-for-byte,
    whatever engine (or scale event) served them — the chaos
    invariant, applied across a scaling run. Same (prompt,
    max_tokens) pairs compare exactly; different output budgets
    compare on the common prefix — exactly, since the streaming path
    holds incomplete UTF-8 tails until the codepoint completes and
    drops a tail cut off at EOS instead of flushing U+FFFD."""
    by_prompt = {}
    for r in results:
        if r.temperature == 0.0 and r.ok:
            by_prompt.setdefault(r.prompt, []).append(
                (r.max_tokens, r.text))
    compared = 0
    for pairs in by_prompt.values():
        pairs.sort()
        for (mt_a, a), (mt_b, b) in zip(pairs, pairs[1:]):
            if mt_a == mt_b:
                assert a == b, (a, b)
            else:
                assert b.startswith(a), (a, b)
            compared += 1
    assert compared > 0  # the trace really did repeat prompts


def _run_closed_loop(tmp_path, trace, min_engines, max_engines,
                     on_tick=None, settle=30.0):
    """Compose the pieces of controller.run_closed_loop directly so
    the test keeps the per-request results and live objects."""
    model_dir = tmp_path / "model"
    model_dir.mkdir()
    pool = EnginePool("engine", None,
                      _engine_args_factory(model_dir), tmp_path,
                      drain_exit_timeout=60.0)
    router = None
    ctl = None
    try:
        for _ in range(min_engines):
            pool.spawn()
        router = _spawn_router(pool, tmp_path)
        pool.router_url = router.url
        slo = ctl_mod.SLOConfig(ttft_p99_s=0.4,
                                queue_wait_p99_s=0.2,
                                queue_depth_high=1.5)
        pol = PoolPolicy(PolicyConfig(
            min_size=min_engines, max_size=max_engines,
            up_stable_ticks=2, down_stable_ticks=4,
            cooldown_ticks=3, down_threshold=0.3))
        ctl = ctl_mod.ScaleController(
            {"engine": pool}, {"engine": pol}, slo,
            router_url=router.url, interval=0.5).start()
        if on_tick is not None:
            watcher = threading.Thread(
                target=on_tick, args=(pool,), daemon=True)
            watcher.start()
        results = replay_mod.replay(router.url, trace, timeout=180)
        # settle until the controller has shed the burst capacity and
        # every drain has fully completed (bounded, not a fixed sleep)
        deadline = time.monotonic() + settle
        while time.monotonic() < deadline:
            if (any(d.target < d.size for d in ctl.decisions)
                    and pool.draining_count() == 0
                    and pool.size() == min_engines):
                break
            time.sleep(0.5)
        ctl.stop()
        pool.join_drains(timeout=90.0)
        # the finally below tears the topology down, so capture the
        # steady-state size the controller converged to first
        return results, ctl, pool, pool.size()
    finally:
        if ctl is not None:
            ctl.stop()
        pool.stop_all()
        if router is not None:
            router.stop()


class TestClosedLoopSmoke:
    def test_scale_up_burst_then_drain_down(self, tmp_path):
        """The tier-1 acceptance smoke: a bursty trace pushes the
        pool from 1 to 2 engines, the post-burst quiet drains it back
        to 1, no admitted request is lost, and greedy streams stay
        byte-consistent across the scale events."""
        # max_tokens is the lever that makes the burst SUSTAIN: long
        # decodes hold the 2 slots, so queue wait stays high across
        # several 0.5s ticks (a single-tick spike must not scale)
        trace = trace_mod.synthetic_trace(
            7, n=16, base_rate=2.0, burst_factor=8.0,
            max_tokens=(24, 48))
        results, ctl, pool, final_size = _run_closed_loop(
            tmp_path, trace, min_engines=1, max_engines=2)

        errs = [r for r in results if not r.ok]
        assert errs == [], [(r.trace_id, r.status, r.error)
                            for r in errs]
        assert any(d.target > d.size for d in ctl.decisions), \
            [d.to_dict() for d in ctl.decisions]
        assert any(d.target < d.size for d in ctl.decisions), \
            [d.to_dict() for d in ctl.decisions]
        assert pool.drains and all(d.ok for d in pool.drains)
        assert _journal_leftover(pool) == 0
        assert final_size == 1
        _assert_greedy_prefix_consistent(results)
        # every stream really decoded tokens
        assert all(r.output_tokens > 0 for r in results)


class TestDrainResume:
    def test_kill_during_scale_down_resumes_journal(self, tmp_path):
        """SIGKILL the victim mid-drain with admitted work
        outstanding: the pool must respawn it on the same journal,
        let restart-resume finish the request, and still end with a
        clean (zero-leftover) drain — the scale-down guarantee under
        the worst-case chaos event."""
        model_dir = tmp_path / "model"
        model_dir.mkdir()
        pool = EnginePool(
            "engine", None,
            _engine_args_factory(model_dir, drain_grace=30.0),
            tmp_path, drain_exit_timeout=60.0, resume_timeout=90.0)
        try:
            pool.spawn()
            url = pool.member_urls()[0]
            body = json.dumps({"prompt": "abcd", "max_tokens": 400,
                               "temperature": 0.0,
                               "stream": True}).encode()

            def long_request():
                req = urllib.request.Request(
                    url + "/v1/completions", data=body,
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=120) as r:
                        for _ in r:
                            pass
                except (urllib.error.URLError, OSError):
                    pass  # the kill tears this stream; that's the point

            t = threading.Thread(target=long_request, daemon=True)
            t.start()
            with pool._lock:
                member = pool._members[0]
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if journal_live_entries(member.journal):
                    break
                time.sleep(0.1)
            assert journal_live_entries(member.journal), \
                "request never admitted"

            assert pool.drain_one() is not None
            member.proc.kill()  # mid-drain, with journaled work live
            pool.join_drains(timeout=180.0)

            assert len(pool.drains) == 1
            rec = pool.drains[0]
            assert rec.resumed, vars(rec)
            assert rec.ok, vars(rec)
            assert _journal_leftover(pool) == 0
            assert pool.size() == 0
        finally:
            pool.stop_all()


@pytest.mark.slow
class TestAutoscaleSoak:
    def test_bursty_soak_with_kill_mid_drain(self, tmp_path):
        """The acceptance run: a bigger bursty trace scales 1->N and
        back with a chaos SIGKILL landing on the first draining
        engine; zero admitted requests lost, greedy streams stay
        consistent, and the elastic pool spends fewer engine-seconds
        than static max provisioning."""
        trace = trace_mod.amplify_bursts(
            trace_mod.synthetic_trace(
                11, n=40, base_rate=2.0, burst_factor=8.0,
                max_tokens=(30, 60)),
            3, seed=11)
        killed = threading.Event()

        def chaos_kill(pool):
            # SIGKILL the first member that starts draining
            while not killed.is_set():
                with pool._lock:
                    victims = [m for m in pool._members if m.draining]
                if victims:
                    victims[0].proc.kill()
                    killed.set()
                    return
                time.sleep(0.2)

        t0 = time.monotonic()
        results, ctl, pool, _final = _run_closed_loop(
            tmp_path, trace, min_engines=1, max_engines=3,
            on_tick=chaos_kill, settle=60.0)
        wall = time.monotonic() - t0

        errs = [r for r in results if not r.ok]
        assert errs == [], [(r.trace_id, r.status, r.error)
                            for r in errs]
        assert _journal_leftover(pool) == 0
        assert any(d.target > d.size for d in ctl.decisions)
        assert any(d.target < d.size for d in ctl.decisions)
        assert pool.drains and all(d.ok for d in pool.drains), \
            [vars(d) for d in pool.drains]
        # the chaos kill really landed on a draining engine; the
        # drain still completes cleanly (when the victim had
        # journaled work outstanding, via the respawn/resume path —
        # TestDrainResume pins that arm deterministically)
        assert killed.is_set()
        _assert_greedy_prefix_consistent(results)

        # elasticity must beat static max provisioning over the run
        static_max = 3 * wall
        assert pool.engine_seconds() < static_max, \
            (pool.engine_seconds(), static_max)

        # the replayed burst held a (generous, CPU-engine) TTFT SLO
        rep = replay_mod.report(results, slo_ttft_s=5.0)
        assert rep["slo_ttft_attainment"] >= 0.9, rep
