"""GCP credential depth (r4 verdict #6): service-account key files,
workload-identity federation, and expiry-driven refresh — all against
local mock token servers, in the tests/test_cloudkms.py style. The
reference's analog is its multi-cloud principal factory
(/root/reference/pkg/auth/factory.go:21, pkg/principals)."""

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from ome_tpu.storage.signing import (FederatedSigner,
                                     ServiceAccountSigner,
                                     gcp_signer_from_credentials,
                                     signer_from_env)

cryptography = pytest.importorskip("cryptography")


def _rsa_pem():
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pem = key.private_bytes(serialization.Encoding.PEM,
                            serialization.PrivateFormat.PKCS8,
                            serialization.NoEncryption())
    return key, pem.decode()


@pytest.fixture()
def token_server():
    """Mock OAuth/STS endpoint recording every request body."""
    calls = []
    state = {"expires_in": 3600}

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            body = self.rfile.read(
                int(self.headers.get("Content-Length", 0)))
            if self.headers.get("Content-Type", "").startswith(
                    "application/json"):
                parsed = json.loads(body)
            else:
                parsed = dict(urllib.parse.parse_qsl(body.decode()))
            calls.append((self.path, parsed,
                          dict(self.headers.items())))
            if self.path == "/impersonate":
                out = {"accessToken": "impersonated-token",
                       "expireTime": "2099-01-01T00:00:00Z"}
            else:
                out = {"access_token": f"tok-{len(calls)}",
                       "expires_in": state["expires_in"],
                       "token_type": "Bearer"}
            data = json.dumps(out).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}", calls, state
    srv.shutdown()


def test_service_account_jwt_grant(token_server, tmp_path):
    url, calls, _ = token_server
    key, pem = _rsa_pem()
    info = {"type": "service_account",
            "client_email": "sa@proj.iam.gserviceaccount.com",
            "private_key": pem, "token_uri": f"{url}/token"}
    keyfile = tmp_path / "sa.json"
    keyfile.write_text(json.dumps(info))
    signer = gcp_signer_from_credentials(str(keyfile))
    assert isinstance(signer, ServiceAccountSigner)
    headers = signer.sign("GET", "https://storage.googleapis.com/b/o")
    assert headers["Authorization"] == "Bearer tok-1"
    # the JWT assertion must verify against the SA's public key
    path, parsed, _ = calls[0]
    assert path == "/token"
    assert parsed["grant_type"] == \
        "urn:ietf:params:oauth:grant-type:jwt-bearer"
    h, c, sig = parsed["assertion"].split(".")
    import base64

    def unb64(s):
        return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))

    from cryptography.hazmat.primitives.asymmetric import padding
    from cryptography.hazmat.primitives.hashes import SHA256
    key.public_key().verify(unb64(sig), f"{h}.{c}".encode(),
                            padding.PKCS1v15(), SHA256())
    claims = json.loads(unb64(c))
    assert claims["iss"] == info["client_email"]
    assert claims["aud"] == info["token_uri"]
    # cached: second sign does not re-hit the server
    signer.sign("GET", "https://storage.googleapis.com/b/o2")
    assert len(calls) == 1


def test_token_refresh_near_expiry(token_server, tmp_path):
    """Multi-hour downloads: a token expiring within 60 s is replaced
    on the next request instead of failing mid-file."""
    url, calls, state = token_server
    state["expires_in"] = 30  # expires inside the refresh window
    _, pem = _rsa_pem()
    keyfile = tmp_path / "sa.json"
    keyfile.write_text(json.dumps({
        "type": "service_account", "client_email": "sa@p.iam",
        "private_key": pem, "token_uri": f"{url}/token"}))
    signer = gcp_signer_from_credentials(str(keyfile))
    assert signer.sign("GET", "u")["Authorization"] == "Bearer tok-1"
    assert signer.sign("GET", "u")["Authorization"] == "Bearer tok-2"
    assert len(calls) == 2


def test_workload_identity_federation_file_source(token_server,
                                                  tmp_path):
    url, calls, _ = token_server
    subject = tmp_path / "oidc.jwt"
    subject.write_text("subject-token-abc")
    cred = tmp_path / "wif.json"
    cred.write_text(json.dumps({
        "type": "external_account",
        "audience": "//iam.googleapis.com/projects/1/locations/global/"
                    "workloadIdentityPools/p/providers/x",
        "subject_token_type": "urn:ietf:params:oauth:token-type:jwt",
        "token_url": f"{url}/sts",
        "credential_source": {"file": str(subject)}}))
    signer = gcp_signer_from_credentials(str(cred))
    assert isinstance(signer, FederatedSigner)
    headers = signer.sign("GET", "https://storage.googleapis.com/b/o")
    assert headers["Authorization"] == "Bearer tok-1"
    path, parsed, _ = calls[0]
    assert path == "/sts"
    assert parsed["subject_token"] == "subject-token-abc"
    assert parsed["grant_type"] == \
        "urn:ietf:params:oauth:grant-type:token-exchange"


def test_federation_with_impersonation(token_server, tmp_path):
    url, calls, _ = token_server
    subject = tmp_path / "oidc.json"
    subject.write_text(json.dumps({"access_token": "inner-tok"}))
    cred = tmp_path / "wif.json"
    cred.write_text(json.dumps({
        "type": "external_account",
        "audience": "//iam.googleapis.com/pool",
        "token_url": f"{url}/sts",
        "service_account_impersonation_url": f"{url}/impersonate",
        "credential_source": {
            "file": str(subject),
            "format": {"type": "json",
                       "subject_token_field_name": "access_token"}}}))
    signer = gcp_signer_from_credentials(str(cred))
    headers = signer.sign("GET", "u")
    assert headers["Authorization"] == "Bearer impersonated-token"
    assert [c[0] for c in calls] == ["/sts", "/impersonate"]
    assert calls[0][1]["subject_token"] == "inner-tok"
    # impersonation call authenticates with the STS token
    assert calls[1][2].get("Authorization") == "Bearer tok-1"


def test_signer_from_env_dispatch(token_server, tmp_path, monkeypatch):
    url, _, _ = token_server
    _, pem = _rsa_pem()
    keyfile = tmp_path / "sa.json"
    keyfile.write_text(json.dumps({
        "type": "service_account", "client_email": "sa@p.iam",
        "private_key": pem, "token_uri": f"{url}/token"}))
    monkeypatch.setenv("GOOGLE_APPLICATION_CREDENTIALS", str(keyfile))
    signer = signer_from_env("gcs")
    assert isinstance(signer, ServiceAccountSigner)
    monkeypatch.setenv("GOOGLE_APPLICATION_CREDENTIALS",
                       str(tmp_path / "missing.json"))
    monkeypatch.delenv("GOOGLE_OAUTH_ACCESS_TOKEN", raising=False)
    monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
    monkeypatch.delenv("OME_GCS_METADATA_AUTH", raising=False)
    assert signer_from_env("gcs") is None  # anonymous fallback


def test_gopher_private_gcs_all_three_modes(token_server, tmp_path,
                                            monkeypatch):
    """The verdict's done-when: a private-bucket download works in SA
    / federation / metadata auth modes — mocked GCS checks the bearer
    token before serving bytes."""
    url, _, _ = token_server
    from ome_tpu.storage.signing import GCSTokenSigner

    blob = b"model-bytes-" * 64
    seen_auth = []

    class GCS(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            auth = self.headers.get("Authorization", "")
            seen_auth.append(auth)
            if not auth.startswith("Bearer "):
                self.send_response(401)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

    gcs = HTTPServer(("127.0.0.1", 0), GCS)
    threading.Thread(target=gcs.serve_forever, daemon=True).start()
    gcs_url = f"http://127.0.0.1:{gcs.server_port}/bucket/obj"
    try:
        _, pem = _rsa_pem()
        sa = tmp_path / "sa.json"
        sa.write_text(json.dumps({
            "type": "service_account", "client_email": "sa@p.iam",
            "private_key": pem, "token_uri": f"{url}/token"}))
        subject = tmp_path / "sub.jwt"
        subject.write_text("sub")
        wif = tmp_path / "wif.json"
        wif.write_text(json.dumps({
            "type": "external_account", "audience": "//iam/pool",
            "token_url": f"{url}/sts",
            "credential_source": {"file": str(subject)}}))
        import urllib.request
        for signer in (gcp_signer_from_credentials(str(sa)),
                       gcp_signer_from_credentials(str(wif)),
                       GCSTokenSigner(token="metadata-style-token")):
            headers = signer.sign("GET", gcs_url)
            req = urllib.request.Request(gcs_url, headers=headers)
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert resp.read() == blob
        assert len(seen_auth) == 3
        assert all(a.startswith("Bearer ") for a in seen_auth)
    finally:
        gcs.shutdown()
