"""Model correctness tests: causality, decode/prefill consistency,
MoE routing, parameter accounting."""

import jax
import jax.numpy as jnp
import pytest

from ome_tpu.models import config as cfgs
from ome_tpu.models import llama


@pytest.fixture(scope="module")
def tiny():
    return cfgs.tiny_test().replace(dtype=jnp.float32)


@pytest.fixture(scope="module")
def tiny_params(tiny):
    return llama.init_params(jax.random.PRNGKey(0), tiny)


class TestForward:
    def test_shapes(self, tiny, tiny_params):
        tokens = jnp.ones((2, 16), jnp.int32)
        logits, cache = llama.forward(tiny_params, tiny, tokens)
        assert logits.shape == (2, 16, tiny.vocab_size)
        assert logits.dtype == jnp.float32
        assert cache is None

    def test_causality(self, tiny, tiny_params):
        """Changing a future token must not affect earlier logits."""
        rng = jax.random.PRNGKey(1)
        tokens = jax.random.randint(rng, (1, 12), 0, tiny.vocab_size)
        logits_a, _ = llama.forward(tiny_params, tiny, tokens)
        tampered = tokens.at[0, 8].set((tokens[0, 8] + 7) % tiny.vocab_size)
        logits_b, _ = llama.forward(tiny_params, tiny, tampered)
        assert jnp.allclose(logits_a[0, :8], logits_b[0, :8], atol=1e-5)
        assert not jnp.allclose(logits_a[0, 8:], logits_b[0, 8:], atol=1e-3)

    def test_decode_matches_prefill(self, tiny, tiny_params):
        """Cached chunked decode must reproduce uncached prefill logits."""
        rng = jax.random.PRNGKey(2)
        T = 10
        tokens = jax.random.randint(rng, (2, T), 0, tiny.vocab_size)
        full_logits, _ = llama.forward(tiny_params, tiny, tokens)

        cache = llama.KVCache.create(tiny, batch=2, max_seq=32,
                                     dtype=jnp.float32)
        pre_logits, cache = llama.forward(tiny_params, tiny, tokens[:, :6],
                                          cache=cache)
        assert jnp.allclose(pre_logits, full_logits[:, :6], atol=1e-4)
        # decode one token at a time
        for t in range(6, T):
            step_logits, cache = llama.forward(tiny_params, tiny,
                                               tokens[:, t:t + 1], cache=cache)
            assert jnp.allclose(step_logits[:, 0], full_logits[:, t],
                                atol=1e-4), f"mismatch at {t}"
        assert int(cache.index) == T

    def test_jit_decode_compiles_once(self, tiny, tiny_params):
        decode = jax.jit(lambda p, tok, c: llama.forward(p, tiny, tok, cache=c))
        cache = llama.KVCache.create(tiny, batch=1, max_seq=32)
        tok = jnp.zeros((1, 1), jnp.int32)
        logits, cache = decode(tiny_params, tok, cache)
        logits, cache = decode(tiny_params, tok + 1, cache)
        assert int(cache.index) == 2

    def test_tied_embeddings(self):
        cfg = cfgs.tiny_test().replace(tie_word_embeddings=True,
                                       dtype=jnp.float32)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        assert "lm_head" not in params
        logits, _ = llama.forward(params, cfg, jnp.ones((1, 4), jnp.int32))
        assert logits.shape == (1, 4, cfg.vocab_size)

    def test_sliding_window(self, tiny, tiny_params):
        cfg = tiny.replace(sliding_window=4)
        tokens = jnp.ones((1, 12), jnp.int32)
        logits, _ = llama.forward(tiny_params, cfg, tokens)
        assert logits.shape == (1, 12, cfg.vocab_size)


class TestRoPE:
    def test_llama3_scaling_matches_reference_formula(self):
        """Check all three bands against transformers'
        _compute_llama3_parameters (modeling_rope_utils.py) in numpy."""
        import numpy as np
        cfg = cfgs.tiny_test().replace(
            head_dim=128, rope_theta=500000.0,
            rope_scaling={"rope_type": "llama3", "factor": 8.0,
                          "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                          "original_max_position_embeddings": 8192})
        got = np.asarray(llama._rope_frequencies(cfg))

        inv = 1.0 / cfg.rope_theta ** (np.arange(64) / 64)
        lo_wave = 8192 / 1.0
        hi_wave = 8192 / 4.0
        want = []
        for f in inv:
            wl = 2 * np.pi / f
            if wl < hi_wave:
                want.append(f)
            elif wl > lo_wave:
                want.append(f / 8.0)
            else:
                smooth = (8192 / wl - 1.0) / (4.0 - 1.0)
                want.append((1 - smooth) * f / 8.0 + smooth * f)
        np.testing.assert_allclose(got, np.array(want, np.float32), rtol=1e-6)


class TestMoE:
    def test_shared_experts_contribute(self):
        cfg = cfgs.tiny_test(moe=True).replace(dtype=jnp.float32,
                                               num_shared_experts=2)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        assert "ws_gate" in params["layers"]
        tokens = jnp.ones((1, 4), jnp.int32)
        logits, _ = llama.forward(params, cfg, tokens)
        # zeroing the shared expert weights must change the output
        params2 = dict(params)
        params2["layers"] = dict(params["layers"])
        params2["layers"]["ws_down"] = jnp.zeros_like(
            params["layers"]["ws_down"])
        logits2, _ = llama.forward(params2, cfg, tokens)
        assert not jnp.allclose(logits, logits2, atol=1e-5)

    def test_moe_forward_and_grad(self):
        cfg = cfgs.tiny_test(moe=True).replace(dtype=jnp.float32)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        assert "router" in params["layers"]
        tokens = jnp.ones((2, 8), jnp.int32)
        logits, _ = llama.forward(params, cfg, tokens)
        assert logits.shape == (2, 8, cfg.vocab_size)
        g = jax.grad(llama.loss_fn)(params, cfg, tokens, tokens)
        assert jnp.isfinite(g["layers"]["router"]).all()


class TestAccounting:
    def test_llama3_8b_param_count(self):
        cfg = cfgs.llama3_8b()
        # analytic count (no materialization): embed + head + layers
        L, D, H, K, Dh, F, V = (cfg.num_layers, cfg.hidden_size, cfg.num_heads,
                                cfg.num_kv_heads, cfg.head_dim,
                                cfg.intermediate_size, cfg.vocab_size)
        n = V * D * 2 + D  # embed + lm_head + final norm
        n += L * (2 * D + D * H * Dh + 2 * D * K * Dh + H * Dh * D + 3 * D * F)
        assert n == pytest.approx(8.03e9, rel=0.01)

    def test_loss_decreases_with_sgd(self):
        cfg = cfgs.tiny_test().replace(dtype=jnp.float32)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(3), (4, 16), 0,
                                    cfg.vocab_size)
        targets = jnp.roll(tokens, -1, axis=1)

        @jax.jit
        def step(p):
            l, g = jax.value_and_grad(llama.loss_fn)(p, cfg, tokens, targets)
            return l, jax.tree.map(lambda w, gw: w - 0.05 * gw, p, g)

        l0, params = step(params)
        for _ in range(5):
            l1, params = step(params)
        assert l1 < l0
