"""engine.serve CLI: the runtime-catalog entrypoint boots a server
from a model directory — random weights or a real safetensors
checkpoint — and answers the OpenAI surface."""

import json
import threading
import urllib.request

import numpy as np
import pytest

from ome_tpu.engine.serve import build_parser, load_engine


def _mk_model_dir(tmp_path, with_weights: bool):
    import jax

    from ome_tpu.models import checkpoint as ck
    from ome_tpu.models import llama
    from ome_tpu.models.config import ModelConfig

    d = tmp_path / "model"
    d.mkdir()
    hf_cfg = {
        "architectures": ["LlamaForCausalLM"], "vocab_size": 64,
        "hidden_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2,
        "head_dim": 8, "intermediate_size": 64,
        "max_position_embeddings": 64, "rope_theta": 10000.0,
        "rms_norm_eps": 1e-5, "tie_word_embeddings": False,
    }
    (d / "config.json").write_text(json.dumps(hf_cfg))
    if with_weights:
        cfg = ModelConfig.from_hf_config(hf_cfg)
        L, D, H, K, Dh, F = (cfg.num_layers, cfg.hidden_size,
                             cfg.num_heads, cfg.num_kv_heads,
                             cfg.head_dim, cfg.intermediate_size)
        rng = np.random.RandomState(0)

        def w(*shape):
            return rng.randn(*shape).astype(np.float32) * 0.02

        tensors = {"model.embed_tokens.weight": w(cfg.vocab_size, D),
                   "model.norm.weight": np.ones(D, np.float32),
                   "lm_head.weight": w(cfg.vocab_size, D)}
        for i in range(L):
            p = f"model.layers.{i}."
            tensors.update({
                p + "input_layernorm.weight": np.ones(D, np.float32),
                p + "post_attention_layernorm.weight":
                    np.ones(D, np.float32),
                p + "self_attn.q_proj.weight": w(H * Dh, D),
                p + "self_attn.k_proj.weight": w(K * Dh, D),
                p + "self_attn.v_proj.weight": w(K * Dh, D),
                p + "self_attn.o_proj.weight": w(D, H * Dh),
                p + "mlp.gate_proj.weight": w(F, D),
                p + "mlp.up_proj.weight": w(F, D),
                p + "mlp.down_proj.weight": w(D, F),
            })
        ck.save_safetensors(str(d / "model.safetensors"), tensors)
    return str(d)


def test_load_engine_random_weights(tmp_path):
    d = _mk_model_dir(tmp_path, with_weights=False)
    args = build_parser().parse_args(
        ["--model-dir", d, "--random-weights", "--max-slots", "2",
         "--max-seq", "32"])
    engine = load_engine(args)
    assert engine.max_slots == 2
    tok, kv, true_len, bucket = engine.prefill([1, 2, 3])
    assert 0 <= tok < 64


def test_load_engine_from_safetensors_and_serve(tmp_path):
    d = _mk_model_dir(tmp_path, with_weights=True)
    args = build_parser().parse_args(
        ["--model-dir", d, "--max-slots", "2", "--max-seq", "32",
         "--dtype", "float32"])
    engine = load_engine(args)

    from ome_tpu.engine import ByteTokenizer, EngineServer, Scheduler
    sched = Scheduler(engine)
    server = EngineServer(sched, tokenizer=ByteTokenizer(),
                          model_name="m", port=0)
    server.start()
    try:
        body = json.dumps({"model": "m", "prompt": "ab",
                           "max_tokens": 3}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/completions", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            out = json.loads(resp.read())
        assert out["usage"]["completion_tokens"] == 3
    finally:
        server.stop()
        sched.stop()


def test_kv_block_with_tp_rejected_loudly(tmp_path):
    """Paged KV is single-host tp=1: a tp>1 launch with --kv-block must
    refuse at startup rather than silently serve the dense cache the
    operator sized a paged pool for."""
    d = _mk_model_dir(tmp_path, with_weights=False)
    args = build_parser().parse_args(
        ["--model-dir", d, "--random-weights", "--tp", "2",
         "--kv-block", "16"])
    with pytest.raises(SystemExit, match="paged KV"):
        load_engine(args)


class TestPlanPreconditions:
    """serve-time validation of composition flags against the
    assembled engine stack (docs/step-plan.md): a requested feature
    the stack cannot dispatch fails loudly with the failed plan
    precondition named; supported combinations — including the
    formerly-refused multi-host ones — pass."""

    class _Bare:
        pass

    class _Capable:
        supports_multi_step = True

        def verify(self, *a, **kw):
            pass

        def decode_multi(self, *a, **kw):
            pass

        def commit_spec(self, *a, **kw):
            pass

    @staticmethod
    def _args(*extra):
        from ome_tpu.engine.serve import build_parser
        return build_parser().parse_args(["--model-dir", "x", *extra])

    def test_spec_without_verify_names_precondition(self):
        from ome_tpu.engine.serve import check_plan_preconditions
        err = check_plan_preconditions(
            self._Bare(), self._args("--spec-tokens", "2"))
        assert err is not None
        assert "--spec-tokens" in err and "engine.verify" in err
        assert "_Bare" in err  # names the refusing engine type

    def test_multistep_without_decode_multi_names_precondition(self):
        from ome_tpu.engine.serve import check_plan_preconditions
        err = check_plan_preconditions(
            self._Bare(), self._args("--steps-per-dispatch", "4"))
        assert err is not None
        assert "--steps-per-dispatch" in err
        assert "engine.decode_multi" in err

    def test_capable_stack_passes(self):
        from ome_tpu.engine.serve import check_plan_preconditions
        args = self._args("--spec-tokens", "2",
                          "--steps-per-dispatch", "4",
                          "--pipeline-depth", "1")
        assert check_plan_preconditions(self._Capable(), args) is None

    def test_replicated_stack_passes(self):
        """The combo that used to exit 2: spec + multi-step over the
        multi-host ReplicatedEngine now satisfies every plan
        precondition (decode_multi / verify / commit_spec are in the
        replicated op vocabulary)."""
        from ome_tpu.engine.multihost import ReplicatedEngine
        from ome_tpu.engine.serve import check_plan_preconditions

        class _Pub:
            def send(self, m):
                pass

        eng = ReplicatedEngine(self._Capable(), _Pub())
        args = self._args("--spec-tokens", "2",
                          "--steps-per-dispatch", "4")
        assert check_plan_preconditions(eng, args) is None

    def test_flags_off_never_refuse(self):
        from ome_tpu.engine.serve import check_plan_preconditions
        assert check_plan_preconditions(
            self._Bare(), self._args()) is None


def test_paged_unsupported_arch_falls_back_to_dense(tmp_path, caplog):
    """An auto-selected runtime may pass --kv-block for a model the
    paged coverage guard refuses (here: sliding-window attention).
    load_engine degrades to the dense cache with a prominent warning
    instead of crash-looping the pod."""
    import logging

    d = _mk_model_dir(tmp_path, with_weights=False)
    cfg = json.loads(open(d + "/config.json").read())
    cfg["sliding_window"] = 16
    open(d + "/config.json", "w").write(json.dumps(cfg))
    args = build_parser().parse_args(
        ["--model-dir", d, "--random-weights", "--max-slots", "2",
         "--max-seq", "32", "--kv-block", "16"])
    with caplog.at_level(logging.WARNING, logger="ome.engine.serve"):
        engine = load_engine(args)
    assert engine.kv_block == 0  # dense
    assert any("FALLING BACK" in r.message for r in caplog.records)
    # still serves: the degraded engine is a working dense engine
    tok, kv, true_len, bucket = engine.prefill([1, 2, 3])
    assert 0 <= tok < 64
