"""Round-5 native-engine architectures (r4 verdict #5): phi3, Phi-3.5
-MoE (phimoe), command-r (cohere), gpt-oss.

Same standard as tests/test_mla.py: build tiny random HF models with
`transformers`, save_pretrained, load through the pure-numpy reader +
converter, and compare full-precision logits and argmax. Then one
engine-level decode continuation per family, so the serving stack (not
just forward()) covers the new architectures.

cite: the reference only PARSES these configs
(/root/reference/pkg/hfutil/modelconfig/{phi3,phimoe,commandr,
gpt_oss}.go) and serves them via external SGLang/vLLM images; here the
in-repo TPU engine executes them.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ome_tpu.engine.core import InferenceEngine
from ome_tpu.models import checkpoint as ck
from ome_tpu.models import llama

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _save_hf(tmp_path, hf_cfg):
    torch.manual_seed(0)
    model = transformers.AutoModelForCausalLM.from_config(hf_cfg).eval()
    d = str(tmp_path / "model")
    model.save_pretrained(d, safe_serialization=True)
    return model, d


def _compare_logits(model, model_dir, atol=3e-4):
    params, cfg = ck.load_params(model_dir, dtype=jnp.float32)
    tokens = np.array([[1, 5, 9, 2, 7, 3, 8, 4]], np.int32)
    logits, _ = llama.forward(params, cfg, jnp.asarray(tokens))
    with torch.no_grad():
        ref = model(torch.tensor(tokens, dtype=torch.long)).logits
    np.testing.assert_allclose(np.asarray(logits, np.float32),
                               ref.numpy(), atol=atol, rtol=1e-3)
    np.testing.assert_array_equal(
        np.argmax(np.asarray(logits), -1), ref.argmax(-1).numpy())
    return params, cfg


def test_phi3_logits_match_transformers(tmp_path):
    """Fused qkv_proj / gate_up_proj split + sliding window."""
    hf = transformers.Phi3Config(
        vocab_size=120, hidden_size=64, intermediate_size=96,
        num_hidden_layers=3, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0, sliding_window=None,
        pad_token_id=0, bos_token_id=1, eos_token_id=2,
        tie_word_embeddings=False)
    model, d = _save_hf(tmp_path, hf)
    params, cfg = _compare_logits(model, d)
    assert "wq" in params["layers"]
    assert cfg.norm_type == "rmsnorm"


def test_phi3_sliding_window(tmp_path):
    hf = transformers.Phi3Config(
        vocab_size=120, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=64,
        sliding_window=4, pad_token_id=0, bos_token_id=1,
        eos_token_id=2, tie_word_embeddings=False)
    model, d = _save_hf(tmp_path, hf)
    _compare_logits(model, d)


def test_phimoe_logits_match_transformers(tmp_path):
    """LayerNorm(+bias) blocks, attention+lm_head biases, sparsemixer
    top-2 routing."""
    hf = transformers.PhimoeConfig(
        vocab_size=120, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, num_local_experts=4,
        num_experts_per_tok=2, max_position_embeddings=64,
        attention_bias=True, lm_head_bias=True,
        router_jitter_noise=0.01, tie_word_embeddings=False,
        pad_token_id=0, bos_token_id=1, eos_token_id=2,
        sliding_window=None)
    model, d = _save_hf(tmp_path, hf)
    params, cfg = _compare_logits(model, d)
    assert cfg.router_scoring == "sparsemixer"
    assert "attn_norm_bias" in params["layers"]
    assert "bo" in params["layers"]
    assert "lm_head_bias" in params


def test_cohere_logits_match_transformers(tmp_path):
    """command-r: parallel attn+MLP block off one shared LayerNorm
    (weight-only, mean-centered), interleaved rope, logit_scale."""
    hf = transformers.CohereConfig(
        vocab_size=120, hidden_size=64, intermediate_size=96,
        num_hidden_layers=3, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        logit_scale=0.25, use_qk_norm=False)
    model, d = _save_hf(tmp_path, hf)
    params, cfg = _compare_logits(model, d)
    assert cfg.parallel_block and cfg.logit_scale == 0.25
    assert "mlp_norm" not in params["layers"]
    assert "lm_head" not in params  # cohere ties embeddings


def test_cohere_qk_norm(tmp_path):
    """command-r-plus per-(head, dim) q/k LayerNorms."""
    hf = transformers.CohereConfig(
        vocab_size=120, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        logit_scale=0.8, use_qk_norm=True)
    model, d = _save_hf(tmp_path, hf)
    params, cfg = _compare_logits(model, d)
    assert cfg.qk_norm
    assert params["layers"]["q_norm"].shape[-2:] == (4, 16)


def test_cohere2_logits_match_transformers(tmp_path):
    """command-r7b / command-a: cohere parallel block + period-4
    sliding pattern with NoPE global layers."""
    hf = transformers.Cohere2Config(
        vocab_size=120, hidden_size=64, intermediate_size=96,
        num_hidden_layers=4, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        logit_scale=0.5, sliding_window=4, sliding_window_pattern=4)
    model, d = _save_hf(tmp_path, hf)
    params, cfg = _compare_logits(model, d)
    assert cfg.alt_sliding_window and cfg.sliding_pattern == 4
    assert cfg.rope_skip_global and cfg.parallel_block


def test_gpt_oss_logits_match_transformers(tmp_path):
    """gpt-oss: attention sinks, alternating sliding layers, biased
    top-k router + clamped-GLU experts with biases."""
    hf = transformers.GptOssConfig(
        vocab_size=120, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, num_local_experts=4,
        num_experts_per_tok=2, sliding_window=4,
        max_position_embeddings=64, rope_scaling=None,
        tie_word_embeddings=False)
    model, d = _save_hf(tmp_path, hf)
    params, cfg = _compare_logits(model, d)
    assert cfg.attn_sinks and cfg.alt_sliding_window
    assert "sinks" in params["layers"]
    assert "we_gate_b" in params["layers"]
    assert "router_b" in params["layers"]


def test_gpt_oss_yarn_rope_scaling(tmp_path):
    """Real gpt-oss ships yarn rope_scaling; inv_freq remapping +
    attention factor (folded into query_scale as att^2) must match
    transformers. atol reflects this CPU's reduced-precision matmul
    noise floor (~2e-3 at this depth); argmax is exact."""
    hf = transformers.GptOssConfig(
        vocab_size=120, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, num_local_experts=4,
        num_experts_per_tok=2, sliding_window=4,
        max_position_embeddings=256,
        rope_scaling={"rope_type": "yarn", "factor": 8.0,
                      "beta_fast": 32.0, "beta_slow": 1.0,
                      "original_max_position_embeddings": 32},
        tie_word_embeddings=False)
    model, d = _save_hf(tmp_path, hf)
    params, cfg = _compare_logits(model, d, atol=1e-2)
    assert cfg.query_scale is not None  # att^2 folded in


def test_phi3_longrope_scaling(tmp_path):
    hf = transformers.Phi3Config(
        vocab_size=120, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=256,
        original_max_position_embeddings=32,
        rope_scaling={"type": "longrope",
                      "short_factor": [1.0] * 8,
                      "long_factor": [2.0, 2.0, 2.5, 3.0, 3.5, 4.0,
                                      5.0, 6.0]},
        sliding_window=None, pad_token_id=0, bos_token_id=1,
        eos_token_id=2, tie_word_embeddings=False)
    model, d = _save_hf(tmp_path, hf)
    _compare_logits(model, d, atol=1e-2)


def test_unknown_rope_scaling_rejected(tmp_path):
    """'dynamic' etc. would silently serve wrong logits past the
    original window — loading must refuse."""
    import json as _json
    import os
    hf = transformers.Phi3Config(
        vocab_size=120, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=64,
        sliding_window=None, pad_token_id=0, bos_token_id=1,
        eos_token_id=2, tie_word_embeddings=False)
    _, d = _save_hf(tmp_path, hf)
    cfgp = os.path.join(d, "config.json")
    raw = _json.load(open(cfgp))
    raw["rope_scaling"] = {"type": "dynamic", "factor": 2.0}
    _json.dump(raw, open(cfgp, "w"))
    params, cfg = ck.load_params(d, dtype=jnp.float32)
    with pytest.raises(ValueError, match="rope_scaling"):
        llama.forward(params, cfg,
                      jnp.asarray([[1, 2, 3]], jnp.int32))


@pytest.mark.parametrize("family", ["phi3", "cohere", "cohere2"])
def test_engine_decode_continuation(tmp_path, family):
    """The serving engine decodes greedily to the same tokens the
    materialized forward would produce for the new families."""
    if family == "phi3":
        hf = transformers.Phi3Config(
            vocab_size=120, hidden_size=64, intermediate_size=96,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=128,
            sliding_window=None, pad_token_id=0, bos_token_id=1,
            eos_token_id=2, tie_word_embeddings=False)
    elif family == "cohere2":
        hf = transformers.Cohere2Config(
            vocab_size=120, hidden_size=64, intermediate_size=96,
            num_hidden_layers=4, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=128,
            logit_scale=0.5, sliding_window=4,
            sliding_window_pattern=4)
    else:
        hf = transformers.CohereConfig(
            vocab_size=120, hidden_size=64, intermediate_size=96,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=128,
            logit_scale=0.5, use_qk_norm=False)
    _, d = _save_hf(tmp_path, hf)
    params, cfg = ck.load_params(d, dtype=jnp.float32)
    cfg = cfg.replace(max_seq_len=64)
    engine = InferenceEngine(params, cfg, max_slots=2,
                             prefill_buckets=[16])
    prompt = [1, 5, 9, 2]
    tok, kv, true_len, bucket = engine.prefill(prompt)
    state = engine.new_state()
    state = engine.insert(state, kv, 0, true_len, tok, bucket)
    toks = [tok]
    zeros = np.zeros(2, np.float32)
    for _ in range(8):
        state, t = engine.decode(state, zeros,
                                 np.zeros(2, np.int32),
                                 np.ones(2, np.float32))
        toks.append(int(np.asarray(t)[0]))
    # reference: greedy argmax over the full materialized forward
    ids = list(prompt)
    ref = []
    for _ in range(9):
        logits, _ = llama.forward(params, cfg,
                                  jnp.asarray([ids], jnp.int32))
        nxt = int(np.argmax(np.asarray(logits)[0, -1]))
        ref.append(nxt)
        ids.append(nxt)
    assert toks == ref
