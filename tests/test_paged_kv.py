"""Paged (block) KV cache: ops/paged.py + engine integration.

The round-4 verdict's #2 structural item: the dense decode cache
allocates worst-case [L, B, Smax, K, D] HBM per slot; the paged pool
allocates by tokens in flight. These tests pin:

  * numerics: the XLA paged path is exactly the dense computation on
    gathered blocks; the Pallas kernel (interpret mode) agrees within
    the platform's reduced-precision matmul noise;
  * the engine serves TOKEN-IDENTICAL outputs dense vs paged across
    mixed lengths, slot reuse, and block-boundary growth;
  * 2x the slot count fits the SAME cache HBM budget with mixed-length
    sequences (the capacity win);
  * pool exhaustion fails fast with a sizing hint;
  * structured outputs ride the paged masked-decode program.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ome_tpu.engine.core import InferenceEngine
from ome_tpu.engine.scheduler import Request, Scheduler
from ome_tpu.engine.tokenizer import ByteTokenizer
from ome_tpu.models import llama
from ome_tpu.models.config import tiny_test
from ome_tpu.ops.attention import attention
from ome_tpu.ops.paged import paged_attention_xla, paged_flash_decode

CFG = tiny_test().replace(dtype=jnp.float32, max_seq_len=128)


def _pool(rng, B, H, K, D, bs, M, N):
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((N, bs, K, D)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((N, bs, K, D)), jnp.float32)
    ids = rng.permutation(N)[:B * M].reshape(B, M)
    return q, kp, vp, jnp.asarray(ids, jnp.int32)


class TestPagedAttentionNumerics:
    def test_xla_matches_dense_gather(self):
        rng = np.random.default_rng(0)
        B, H, K, D, bs, M, N = 4, 16, 8, 128, 128, 4, 32
        q, kp, vp, table = _pool(rng, B, H, K, D, bs, M, N)
        kv_len = jnp.asarray([5, 128, 200, 512], jnp.int32)
        out = paged_attention_xla(q, kp, vp, table, kv_len)
        kg = jnp.take(kp, table, axis=0).reshape(B, M * bs, K, D)
        vg = jnp.take(vp, table, axis=0).reshape(B, M * bs, K, D)
        ref = attention(q, kg, vg, positions=(kv_len - 1)[:, None],
                        kv_len=kv_len, backend="xla")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6)

    def test_pallas_kernel_matches_xla(self):
        rng = np.random.default_rng(1)
        B, H, K, D, bs, M, N = 4, 16, 8, 128, 128, 4, 32
        q, kp, vp, table = _pool(rng, B, H, K, D, bs, M, N)
        kv_len = jnp.asarray([1, 100, 256, 512], jnp.int32)
        out = paged_flash_decode(q, kp, vp, table, kv_len,
                                 interpret=True)
        ref = paged_attention_xla(q, kp, vp, table, kv_len)
        # platform note: this CPU build's default f32 matmul is
        # reduced-precision, so block partitioning differences show up
        # at ~1e-2 — the same kernels on TPU agree with XLA at bf16
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-2)

    def test_kernel_uncovered_shapes_return_none(self):
        rng = np.random.default_rng(2)
        q, kp, vp, table = _pool(rng, 2, 4, 2, 64, 16, 2, 8)
        assert paged_flash_decode(
            q, kp, vp, table, jnp.asarray([3, 9], jnp.int32),
            interpret=True) is None


def _run(engine, prompts, max_new=24, temperature=0.0, maskers=None):
    tok = ByteTokenizer()
    sched = Scheduler(engine)
    reqs = []
    for i, p in enumerate(prompts):
        kw = {}
        if maskers:
            kw["masker"] = maskers[i]
        reqs.append(sched.submit(Request(
            prompt_ids=tok.encode(p), max_new_tokens=max_new,
            temperature=temperature, stop_ids=[tok.eos_id], **kw)))
    while not all(r.done.is_set() for r in reqs):
        sched.step()
    return [r.output_ids for r in reqs]


PROMPTS = ["hello world", "a", "the quick brown fox jumps over",
           "xyzzy plugh abc", "short", "another prompt here",
           "yet more text", "z"]


def test_paged_tokens_identical_to_dense():
    """Greedy tokens byte-exact vs the dense path, incl. slot reuse
    (8 requests through 4 slots) and growth across block boundaries
    (24 new tokens cross the 16-token block repeatedly)."""
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    dense = InferenceEngine(params, CFG, max_slots=4,
                            prefill_buckets=[16, 32])
    paged = InferenceEngine(params, CFG, max_slots=4,
                            prefill_buckets=[16, 32], kv_block=16)
    out_d = _run(dense, PROMPTS)
    out_p = _run(paged, PROMPTS)
    assert out_d == out_p
    # every block returned to the pool after the last request
    assert paged.kv_pool_stats["kv_blocks_free"] == \
        paged.kv_blocks - 1


def test_double_slots_same_hbm_budget():
    """The capacity win: dense 4 slots x 128 rows = 512 cache rows;
    the paged pool with the SAME 512-row budget serves 8 slots of
    mixed-length sequences concurrently."""
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    rows_budget = 4 * CFG.max_seq_len  # dense HBM budget, in rows
    paged = InferenceEngine(params, CFG, max_slots=8,
                            prefill_buckets=[16, 32], kv_block=16,
                            kv_blocks=rows_budget // 16 + 1)
    k_bytes = paged.new_state().k.nbytes
    dense_bytes = InferenceEngine(
        params, CFG, max_slots=4,
        prefill_buckets=[16, 32]).new_state().k.nbytes
    assert k_bytes <= dense_bytes + paged.kv_block * 16 * 1024
    out = _run(paged, PROMPTS, max_new=20)  # 8 concurrent slots
    assert all(len(o) == 20 for o in out)


def test_pool_pressure_preempts_and_recovers():
    """An undersized pool (tokens in flight < sum of worst cases) is a
    NORMAL condition: requests are requeued / preempted with their
    progress carried as prompt, and all finish — no node outage."""
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    # each request worst-case: ~16 prompt + 25 new + 1 = 42 rows = 3
    # blocks; pool of 4 usable blocks fits ONE such stream at a time
    paged = InferenceEngine(params, CFG, max_slots=4,
                            prefill_buckets=[16], kv_block=16,
                            kv_blocks=5)
    tok = ByteTokenizer()
    sched = Scheduler(paged)
    reqs = [sched.submit(Request(prompt_ids=tok.encode(p)[:16],
                                 max_new_tokens=25, temperature=0.0,
                                 stop_ids=[tok.eos_id]))
            for p in PROMPTS[:4]]
    for _ in range(2000):
        if all(r.done.is_set() for r in reqs):
            break
        sched.step()
    assert all(r.done.is_set() for r in reqs)
    # a resumed stream may legitimately emit EOS before the budget
    # (resume prompts recompute the HONEST continuation — the fold of
    # generated tokens into the prompt is deduplicated across repeated
    # preemptions); every other request must use its full budget
    for r in reqs:
        if r.finish_reason == "stop":
            assert r.output_ids[-1] == tok.eos_id
            assert len(r.output_ids) <= 25
        else:
            assert r.finish_reason == "length"
            assert len(r.output_ids) == 25, len(r.output_ids)
    # pool fully reclaimed
    assert paged.kv_pool_stats["kv_blocks_free"] == paged.kv_blocks - 1


def test_impossible_request_rejected_upfront():
    """A request whose worst case exceeds the whole pool would
    livelock (always its own cheapest victim): reject at admission."""
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    paged = InferenceEngine(params, CFG, max_slots=2,
                            prefill_buckets=[16], kv_block=16,
                            kv_blocks=3)  # 2 usable blocks = 32 rows
    tok = ByteTokenizer()
    sched = Scheduler(paged)
    req = sched.submit(Request(prompt_ids=tok.encode("hi"),
                               max_new_tokens=100, temperature=0.0,
                               stop_ids=[tok.eos_id]))
    for _ in range(50):
        if req.done.is_set():
            break
        sched.step()
    assert req.done.is_set()
    assert req.finish_reason == "error"


def test_paged_structured_outputs():
    """The masked decode program has a paged variant: a schema-
    constrained request over the paged engine emits conforming JSON."""
    from ome_tpu.engine.schema import SchemaAutomaton
    from ome_tpu.engine.structured import TokenMasker
    cfg = tiny_test().replace(dtype=jnp.float32, max_seq_len=160)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    paged = InferenceEngine(params, cfg, max_slots=2,
                            prefill_buckets=[16], kv_block=16)
    tok = ByteTokenizer()
    schema = {"type": "object",
              "properties": {"n": {"type": "integer"}},
              "required": ["n"], "additionalProperties": False}
    out = _run(paged, ["emit json"], max_new=40, temperature=0.9,
               maskers=[TokenMasker(tok,
                                    automaton=SchemaAutomaton(schema))])
    obj = json.loads(tok.decode(out[0]))
    assert isinstance(obj["n"], int)


def test_paged_rejects_unsupported_models():
    cfg = tiny_test().replace(dtype=jnp.float32, sliding_window=8)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="paged KV"):
        InferenceEngine(params, cfg, max_slots=2, kv_block=16)


def test_grow_blocks_exhaustion_preempts_explicitly(monkeypatch):
    """When the pool is empty and no victim is evictable, _grow_blocks
    must preempt the growing slot EXPLICITLY (requeue via
    take_preempted) — never let its next write land in the trash
    block, which would silently desync host/device lengths."""
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    paged = InferenceEngine(params, CFG, max_slots=2,
                            prefill_buckets=[16], kv_block=16,
                            kv_blocks=3)  # blocks 1,2 usable; 0=trash
    # hand-build the corner: slot 0 owns the whole pool and its next
    # write needs a third block
    paged._owned[0] = [1, 2]
    paged._free_blocks.clear()
    paged._table[0, 0] = 1
    paged._table[0, 1] = 2
    paged._host_len[0] = 32
    # force "nothing evictable" (the defensive branch is unreachable
    # through _preempt_victim today — pin the contract directly)
    monkeypatch.setattr(paged, "_preempt_victim", lambda: False)
    paged._grow_blocks()
    assert paged.take_preempted() == [0]
    assert paged._owned[0] == []        # blocks returned to the pool
    assert len(paged._free_blocks) == 2
    assert paged._host_len[0] == 0      # no phantom write advanced it
