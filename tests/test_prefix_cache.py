"""Prefix caching: suffix prefill atop cached KV must produce the
same tokens as a cold full prefill, hits/misses/LRU behave, and the
engine stays correct through insert+decode."""

import jax
import jax.numpy as jnp
import numpy as np

from ome_tpu.engine.core import InferenceEngine, PrefixCache
from ome_tpu.models import llama
from ome_tpu.models.config import tiny_test


def _greedy(engine, prompt, steps=6, slot=0):
    state = engine.new_state()
    tok, kv, true_len, bucket = engine.prefill(prompt)
    state = engine.insert(state, kv, slot, true_len, tok, bucket)
    out = [tok]
    B = engine.max_slots
    for _ in range(steps):
        state, toks = engine.decode(state, np.zeros(B, np.float32),
                                    np.zeros(B, np.int32),
                                    np.ones(B, np.float32))
        out.append(int(np.asarray(toks)[slot]))
    return out


def _cfg():
    return tiny_test().replace(dtype=jnp.float32, max_seq_len=256)


def test_suffix_prefill_matches_cold_prefill():
    cfg = _cfg()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    base = list(range(2, 40))  # 38-token shared prefix
    prompt = base + [77, 78, 79]

    cold = InferenceEngine(params, cfg, max_slots=2, max_seq=128,
                           prefill_buckets=[16, 32, 64, 128])
    want = _greedy(cold, prompt)

    warm = InferenceEngine(params, cfg, max_slots=2, max_seq=128,
                           prefill_buckets=[16, 32, 64, 128],
                           prefix_cache_size=4)
    _greedy(warm, base)                     # seeds the cache
    assert warm.prefix_cache.misses == 1
    got = _greedy(warm, prompt)             # suffix path
    assert warm.prefix_cache.hits == 1
    assert got == want


def test_exact_repeat_reuses_all_but_last_token():
    cfg = _cfg()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(params, cfg, max_slots=2, max_seq=128,
                          prefill_buckets=[16, 32, 64],
                          prefix_cache_size=4)
    prompt = list(range(1, 30))
    a = _greedy(eng, prompt)
    b = _greedy(eng, prompt)  # strict-prefix rule: matches 28 of 29
    assert eng.prefix_cache.hits >= 1
    assert a == b


def test_cache_disabled_by_default():
    cfg = _cfg()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(params, cfg, max_slots=2, max_seq=64,
                          prefill_buckets=[16, 32])
    _greedy(eng, list(range(1, 20)))
    assert eng.prefix_cache.hits == 0
    assert eng.prefix_cache.misses == 0


class TestPrefixCacheUnit:
    def test_lru_eviction(self):
        pc = PrefixCache(capacity=2, min_prefix=2)
        pc.put([1, 2, 3], "k1", "v1", 3, 16)
        pc.put([4, 5, 6], "k2", "v2", 3, 16)
        pc.put([7, 8, 9], "k3", "v3", 3, 16)  # evicts [1,2,3]
        assert pc.match([1, 2, 3, 4]) is None
        assert pc.match([4, 5, 6, 7])[0] == "k2"

    def test_longest_prefix_wins(self):
        pc = PrefixCache(capacity=4, min_prefix=2)
        pc.put([1, 2], "short", "v", 2, 16)
        pc.put([1, 2, 3, 4], "long", "v", 4, 16)
        assert pc.match([1, 2, 3, 4, 5])[0] == "long"

    def test_strict_prefix_semantics(self):
        pc = PrefixCache(capacity=4, min_prefix=2)
        pc.put([1, 2, 3], "k", "v", 3, 16)
        # equal prompt: reuses all but the last token
        assert pc.match([1, 2, 3])[2] == 2
        assert pc.match([1, 9, 3, 4]) is None   # diverges
        hit = pc.match([1, 2, 3, 4])
        assert hit is not None and hit[2] == 3

    def test_min_prefix_floor(self):
        pc = PrefixCache(capacity=4, min_prefix=16)
        pc.put([1, 2, 3], "k", "v", 3, 16)      # too short to keep
        assert pc.match([1, 2, 3, 4]) is None
