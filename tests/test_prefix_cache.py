"""Radix prefix caching: suffix prefill atop cached KV must produce
the same tokens as a cold full prefill, partial (block-level) prefix
sharing works across sibling prompts, and the HBM byte budget bounds
the cache under churn."""

import jax
import jax.numpy as jnp
import numpy as np

from ome_tpu.engine.core import InferenceEngine, PrefixCache
from ome_tpu.models import llama
from ome_tpu.models.config import tiny_test

MB64 = 64 << 20


def _greedy(engine, prompt, steps=6, slot=0):
    state = engine.new_state()
    tok, kv, true_len, bucket = engine.prefill(prompt)
    state = engine.insert(state, kv, slot, true_len, tok, bucket)
    out = [tok]
    B = engine.max_slots
    for _ in range(steps):
        state, toks = engine.decode(state, np.zeros(B, np.float32),
                                    np.zeros(B, np.int32),
                                    np.ones(B, np.float32))
        out.append(int(np.asarray(toks)[slot]))
    return out


def _cfg():
    return tiny_test().replace(dtype=jnp.float32, max_seq_len=256)


def test_suffix_prefill_matches_cold_prefill():
    cfg = _cfg()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    base = list(range(2, 40))  # 38 tokens -> one cached 32-block
    prompt = base + [77, 78, 79]

    cold = InferenceEngine(params, cfg, max_slots=2, max_seq=128,
                           prefill_buckets=[16, 32, 64, 128])
    want = _greedy(cold, prompt)

    warm = InferenceEngine(params, cfg, max_slots=2, max_seq=128,
                           prefill_buckets=[16, 32, 64, 128],
                           prefix_cache_bytes=MB64)
    _greedy(warm, base)                     # seeds the cache
    assert warm.prefix_cache.misses == 1
    got = _greedy(warm, prompt)             # suffix path
    assert warm.prefix_cache.hits == 1
    assert got == want


def test_sibling_prompts_share_partial_prefix():
    """A prompt that diverges from a cached one after the first block
    still reuses the shared block — the radix sharing a whole-entry
    LRU cannot give."""
    cfg = _cfg()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    shared = list(range(2, 34))             # exactly one 32-block
    a = shared + list(range(50, 80))        # diverges after block 1
    b = shared + list(range(90, 120))       # different continuation

    cold = InferenceEngine(params, cfg, max_slots=2, max_seq=128,
                           prefill_buckets=[16, 32, 64, 128])
    want_b = _greedy(cold, b)

    warm = InferenceEngine(params, cfg, max_slots=2, max_seq=128,
                           prefill_buckets=[16, 32, 64, 128],
                           prefix_cache_bytes=MB64)
    _greedy(warm, a)
    got_b = _greedy(warm, b)                # hits the shared block
    assert warm.prefix_cache.hits == 1
    assert got_b == want_b


def test_exact_repeat_reuses_cached_blocks():
    cfg = _cfg()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(params, cfg, max_slots=2, max_seq=128,
                          prefill_buckets=[16, 32, 64],
                          prefix_cache_bytes=MB64)
    prompt = list(range(1, 40))
    a = _greedy(eng, prompt)
    b = _greedy(eng, prompt)  # strict-prefix rule: last token re-runs
    assert eng.prefix_cache.hits >= 1
    assert a == b


def test_cache_disabled_by_default():
    cfg = _cfg()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(params, cfg, max_slots=2, max_seq=64,
                          prefill_buckets=[16, 32])
    _greedy(eng, list(range(1, 20)))
    assert eng.prefix_cache.hits == 0
    assert eng.prefix_cache.misses == 0


class TestPrefixCacheUnit:
    """Trie mechanics with small device arrays ([L=1,1,S,1,2])."""

    @staticmethod
    def _kv(n):
        k = jnp.arange(n * 2, dtype=jnp.float32).reshape(1, 1, n, 1, 2)
        return k, -k

    def test_block_dedup_and_bytes(self):
        pc = PrefixCache(capacity_bytes=1 << 30, block=4, min_prefix=4)
        k, v = self._kv(8)
        pc.put(list(range(8)), k, v, 8, 8)
        first = pc.bytes
        assert first == 2 * (1 * 1 * 8 * 1 * 2 * 4)  # both planes
        # same prefix again: no new bytes (blocks deduped)
        k2, v2 = self._kv(12)
        pc.put(list(range(8)) + [99], k2, v2, 9, 16)
        assert pc.bytes == first

    def test_partial_match_in_blocks(self):
        pc = PrefixCache(capacity_bytes=1 << 30, block=4, min_prefix=4)
        k, v = self._kv(8)
        pc.put([1, 2, 3, 4, 5, 6, 7, 8], k, v, 8, 8)
        # diverges in the second block: first block still matches
        hit = pc.match([1, 2, 3, 4, 9, 9, 9, 9, 9])
        assert hit is not None and hit[2] == 4
        np.testing.assert_array_equal(np.asarray(hit[0]),
                                      np.asarray(k[:, :, :4]))
        # full match across both blocks (strict: needs len > 8)
        hit = pc.match([1, 2, 3, 4, 5, 6, 7, 8, 9])
        assert hit[2] == 8
        np.testing.assert_array_equal(np.asarray(hit[0]), np.asarray(k))

    def test_strict_prefix_semantics(self):
        pc = PrefixCache(capacity_bytes=1 << 30, block=4, min_prefix=4)
        k, v = self._kv(8)
        pc.put([1, 2, 3, 4, 5, 6, 7, 8], k, v, 8, 8)
        # equal prompt: the last token must re-run -> only block 1
        assert pc.match([1, 2, 3, 4, 5, 6, 7, 8])[2] == 4
        assert pc.match([9, 2, 3, 4, 5]) is None   # diverges at start

    def test_min_prefix_floor(self):
        pc = PrefixCache(capacity_bytes=1 << 30, block=4, min_prefix=8)
        k, v = self._kv(4)
        pc.put([1, 2, 3, 4], k, v, 4, 4)
        assert pc.match([1, 2, 3, 4, 5]) is None   # 4 < min_prefix
        assert pc.misses == 1

    def test_byte_budget_bounds_cache_under_churn(self):
        block_bytes = 2 * (1 * 1 * 4 * 1 * 2 * 4)
        pc = PrefixCache(capacity_bytes=3 * block_bytes, block=4,
                         min_prefix=4)
        for start in range(0, 40, 4):
            k, v = self._kv(4)
            pc.put(list(range(start, start + 4)), k, v, 4, 4)
            assert pc.bytes <= 3 * block_bytes
        # the most recent blocks survived, the oldest were evicted
        assert pc.match(list(range(36, 41))) is not None
        assert pc.match(list(range(0, 5))) is None

    def test_lru_eviction_prefers_stale_leaves(self):
        block_bytes = 2 * (1 * 1 * 4 * 1 * 2 * 4)
        pc = PrefixCache(capacity_bytes=2 * block_bytes, block=4,
                         min_prefix=4)
        k1, v1 = self._kv(4)
        pc.put([1, 2, 3, 4], k1, v1, 4, 4)
        k2, v2 = self._kv(4)
        pc.put([5, 6, 7, 8], k2, v2, 4, 4)
        pc.match([1, 2, 3, 4, 9])           # refresh the first entry
        k3, v3 = self._kv(4)
        pc.put([9, 10, 11, 12], k3, v3, 4, 4)  # evicts [5,6,7,8]
        assert pc.match([1, 2, 3, 4, 0]) is not None
        assert pc.match([5, 6, 7, 8, 0]) is None


BLOCK_BYTES = 2 * (1 * 1 * 4 * 1 * 2 * 4)  # one _kv(4) block, k + v


class TestHostTier:
    """Host-DRAM spill tier (--prefix-cache-host-mb): eviction spills
    instead of dropping; a host hit enqueues an ASYNC swap-in and the
    current request recomputes; the next same-prefix request hits on
    device. docs/kv-hierarchy.md Tier 1."""

    _kv = staticmethod(TestPrefixCacheUnit._kv)

    def _pc(self, dev_blocks=2, host_blocks=8):
        return PrefixCache(capacity_bytes=dev_blocks * BLOCK_BYTES,
                           block=4, min_prefix=4,
                           host_capacity_bytes=host_blocks
                           * BLOCK_BYTES)

    def test_evict_spills_then_next_request_hits_after_swapin(self):
        pc = self._pc(dev_blocks=2)
        ka, va = self._kv(4)
        pc.put([1, 2, 3, 4], ka, va, 4, 4)
        pc.put([5, 6, 7, 8], *self._kv(4), 4, 4)
        pc.put([9, 10, 11, 12], *self._kv(4), 4, 4)  # spills [1..4]
        assert pc.evictions == 1
        assert pc.host_bytes == BLOCK_BYTES
        # the admitting request gets NO device hit — it recomputes —
        # but the host hit queues the block for swap-in
        assert pc.match([1, 2, 3, 4, 0]) is None
        assert (pc.host_hits, pc.host_recomputes) == (1, 1)
        pc.drain_swapins()
        assert pc.host_swapins == 1
        # swapped in; the NEXT same-prefix request serves from device,
        # with the ORIGINAL bytes (spill->swap-in round trips exactly)
        hit = pc.match([1, 2, 3, 4, 0])
        assert hit is not None and hit[2] == 4
        np.testing.assert_array_equal(np.asarray(hit[0]),
                                      np.asarray(ka))
        ok, dev_blocks, host_blocks = pc.tier_conservation()
        assert ok

    def test_divergent_suffix_still_shares_swapped_block(self):
        """A prompt diverging AFTER the swapped-in block reuses it —
        the radix property survives the spill/swap-in round trip."""
        pc = self._pc(dev_blocks=2)
        pc.put([1, 2, 3, 4, 5, 6, 7, 8], *self._kv(8), 8, 8)
        pc.put([20, 21, 22, 23], *self._kv(4), 4, 4)  # spills a leaf
        assert pc.host_bytes > 0
        pc.match([1, 2, 3, 4, 5, 6, 7, 8, 0])
        pc.drain_swapins()
        # divergent continuation: shares only the leading blocks
        hit = pc.match([1, 2, 3, 4, 99, 98, 97, 96, 0])
        assert hit is not None and hit[2] == 4
        assert pc.tier_conservation()[0]

    def test_host_budget_bounds_tier_lru(self):
        pc = self._pc(dev_blocks=1, host_blocks=2)
        for start in range(0, 24, 4):
            pc.put(list(range(start, start + 4)), *self._kv(4), 4, 4)
            assert pc.host_bytes <= 2 * BLOCK_BYTES
        assert pc.tier_conservation()[0]
        # most recent spills survived; the oldest were dropped (their
        # paths produce no host hit, hence no swap-in queue growth)
        before = pc.host_hits
        assert pc.match([0, 1, 2, 3, 9]) is None
        assert pc.host_hits == before

    def test_reput_drops_stale_host_copy(self):
        """When the device copy becomes authoritative again (a fresh
        put of the same path), the host copy is dropped — a block must
        never be resident in both tiers."""
        pc = self._pc(dev_blocks=2)
        pc.put([1, 2, 3, 4], *self._kv(4), 4, 4)
        pc.put([5, 6, 7, 8], *self._kv(4), 4, 4)
        pc.put([9, 10, 11, 12], *self._kv(4), 4, 4)  # spills [1..4]
        assert pc.host_bytes == BLOCK_BYTES
        pc.put([1, 2, 3, 4], *self._kv(4), 4, 4)     # re-authoritative
        ok, _, host_blocks = pc.tier_conservation()
        assert ok
        assert ([1, 2, 3, 4] not in
                [list(p) for p in pc._host])  # stale copy gone

    def test_swapin_requires_device_resident_parent_chain(self):
        """A hosted block whose parent chain was evicted stays hosted
        (it would be unreachable by match()); a later deeper hit
        re-queues it."""
        pc = self._pc(dev_blocks=8)
        k, v = self._kv(8)
        orphan = (1, 2, 3, 4, 5, 6, 7, 8)
        ks, vs = np.asarray(k[:, :, 4:8]), np.asarray(v[:, :, 4:8])
        with pc._tier_lock:
            pc._host[orphan] = (ks, vs, ks.nbytes + vs.nbytes)
            pc.host_bytes += ks.nbytes + vs.nbytes
        pc._swapin_one(orphan)
        assert pc.host_swapins == 0 and orphan in pc._host
        # parent appears on device -> the same swap-in now lands
        pc.put([1, 2, 3, 4], *self._kv(4), 4, 4)
        pc._swapin_one(orphan)
        assert pc.host_swapins == 1 and orphan not in pc._host
        assert pc.match([1, 2, 3, 4, 5, 6, 7, 8, 0])[2] == 8
        assert pc.tier_conservation()[0]

    def test_tier_disabled_without_budget(self):
        pc = PrefixCache(capacity_bytes=2 * BLOCK_BYTES, block=4,
                         min_prefix=4)
        for start in range(0, 16, 4):
            pc.put(list(range(start, start + 4)), *self._kv(4), 4, 4)
        assert pc.host_bytes == 0 and pc.host_hits == 0
        assert pc.tier_conservation()[0]


def test_engine_host_tier_spill_swapin_divergent_suffix():
    """Engine-level Tier 1 flow (prefix_host_bytes): evict -> spill,
    host hit -> recompute with the SAME tokens as a cold engine, drain
    -> device hit, and a divergent suffix decodes correctly off the
    swapped-in prefix. kv_conservation() folds the two-tier check."""
    cfg = _cfg()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    base1 = list(range(2, 40))    # one cached 32-block each
    base2 = list(range(100, 138))
    p1 = base1 + [77, 78, 79]
    p2 = base1 + [90, 91, 92]     # divergent suffix, same block

    cold = InferenceEngine(params, cfg, max_slots=2, max_seq=128,
                           prefill_buckets=[16, 32, 64, 128])
    want1, want2 = _greedy(cold, p1), _greedy(cold, p2)

    # device capacity: exactly ONE 32-block (measured, not assumed)
    probe = InferenceEngine(params, cfg, max_slots=2, max_seq=128,
                            prefill_buckets=[16, 32, 64, 128],
                            prefix_cache_bytes=MB64)
    _greedy(probe, base1)
    one_block = probe.prefix_cache.bytes
    assert one_block > 0

    eng = InferenceEngine(params, cfg, max_slots=2, max_seq=128,
                          prefill_buckets=[16, 32, 64, 128],
                          prefix_cache_bytes=one_block,
                          prefix_host_bytes=MB64)
    pc = eng.prefix_cache
    _greedy(eng, base1)           # seeds [base1 block]
    _greedy(eng, base2)           # evicts it -> host tier
    assert pc.host_bytes == one_block
    # host-resident prefix: this request recomputes (cold-identical
    # tokens) and queues the swap-in
    got1 = _greedy(eng, p1)
    assert got1 == want1
    assert pc.host_hits >= 1 and pc.host_recomputes >= 1
    pc.drain_swapins()
    assert pc.host_swapins >= 1
    # next same-prefix request, divergent suffix: device hit
    hits_before = pc.hits
    got2 = _greedy(eng, p2)
    assert got2 == want2
    assert pc.hits == hits_before + 1
    assert eng.kv_conservation()[0]
