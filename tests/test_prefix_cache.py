"""Radix prefix caching: suffix prefill atop cached KV must produce
the same tokens as a cold full prefill, partial (block-level) prefix
sharing works across sibling prompts, and the HBM byte budget bounds
the cache under churn."""

import jax
import jax.numpy as jnp
import numpy as np

from ome_tpu.engine.core import InferenceEngine, PrefixCache
from ome_tpu.models import llama
from ome_tpu.models.config import tiny_test

MB64 = 64 << 20


def _greedy(engine, prompt, steps=6, slot=0):
    state = engine.new_state()
    tok, kv, true_len, bucket = engine.prefill(prompt)
    state = engine.insert(state, kv, slot, true_len, tok, bucket)
    out = [tok]
    B = engine.max_slots
    for _ in range(steps):
        state, toks = engine.decode(state, np.zeros(B, np.float32),
                                    np.zeros(B, np.int32),
                                    np.ones(B, np.float32))
        out.append(int(np.asarray(toks)[slot]))
    return out


def _cfg():
    return tiny_test().replace(dtype=jnp.float32, max_seq_len=256)


def test_suffix_prefill_matches_cold_prefill():
    cfg = _cfg()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    base = list(range(2, 40))  # 38 tokens -> one cached 32-block
    prompt = base + [77, 78, 79]

    cold = InferenceEngine(params, cfg, max_slots=2, max_seq=128,
                           prefill_buckets=[16, 32, 64, 128])
    want = _greedy(cold, prompt)

    warm = InferenceEngine(params, cfg, max_slots=2, max_seq=128,
                           prefill_buckets=[16, 32, 64, 128],
                           prefix_cache_bytes=MB64)
    _greedy(warm, base)                     # seeds the cache
    assert warm.prefix_cache.misses == 1
    got = _greedy(warm, prompt)             # suffix path
    assert warm.prefix_cache.hits == 1
    assert got == want


def test_sibling_prompts_share_partial_prefix():
    """A prompt that diverges from a cached one after the first block
    still reuses the shared block — the radix sharing a whole-entry
    LRU cannot give."""
    cfg = _cfg()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    shared = list(range(2, 34))             # exactly one 32-block
    a = shared + list(range(50, 80))        # diverges after block 1
    b = shared + list(range(90, 120))       # different continuation

    cold = InferenceEngine(params, cfg, max_slots=2, max_seq=128,
                           prefill_buckets=[16, 32, 64, 128])
    want_b = _greedy(cold, b)

    warm = InferenceEngine(params, cfg, max_slots=2, max_seq=128,
                           prefill_buckets=[16, 32, 64, 128],
                           prefix_cache_bytes=MB64)
    _greedy(warm, a)
    got_b = _greedy(warm, b)                # hits the shared block
    assert warm.prefix_cache.hits == 1
    assert got_b == want_b


def test_exact_repeat_reuses_cached_blocks():
    cfg = _cfg()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(params, cfg, max_slots=2, max_seq=128,
                          prefill_buckets=[16, 32, 64],
                          prefix_cache_bytes=MB64)
    prompt = list(range(1, 40))
    a = _greedy(eng, prompt)
    b = _greedy(eng, prompt)  # strict-prefix rule: last token re-runs
    assert eng.prefix_cache.hits >= 1
    assert a == b


def test_cache_disabled_by_default():
    cfg = _cfg()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(params, cfg, max_slots=2, max_seq=64,
                          prefill_buckets=[16, 32])
    _greedy(eng, list(range(1, 20)))
    assert eng.prefix_cache.hits == 0
    assert eng.prefix_cache.misses == 0


class TestPrefixCacheUnit:
    """Trie mechanics with small device arrays ([L=1,1,S,1,2])."""

    @staticmethod
    def _kv(n):
        k = jnp.arange(n * 2, dtype=jnp.float32).reshape(1, 1, n, 1, 2)
        return k, -k

    def test_block_dedup_and_bytes(self):
        pc = PrefixCache(capacity_bytes=1 << 30, block=4, min_prefix=4)
        k, v = self._kv(8)
        pc.put(list(range(8)), k, v, 8, 8)
        first = pc.bytes
        assert first == 2 * (1 * 1 * 8 * 1 * 2 * 4)  # both planes
        # same prefix again: no new bytes (blocks deduped)
        k2, v2 = self._kv(12)
        pc.put(list(range(8)) + [99], k2, v2, 9, 16)
        assert pc.bytes == first

    def test_partial_match_in_blocks(self):
        pc = PrefixCache(capacity_bytes=1 << 30, block=4, min_prefix=4)
        k, v = self._kv(8)
        pc.put([1, 2, 3, 4, 5, 6, 7, 8], k, v, 8, 8)
        # diverges in the second block: first block still matches
        hit = pc.match([1, 2, 3, 4, 9, 9, 9, 9, 9])
        assert hit is not None and hit[2] == 4
        np.testing.assert_array_equal(np.asarray(hit[0]),
                                      np.asarray(k[:, :, :4]))
        # full match across both blocks (strict: needs len > 8)
        hit = pc.match([1, 2, 3, 4, 5, 6, 7, 8, 9])
        assert hit[2] == 8
        np.testing.assert_array_equal(np.asarray(hit[0]), np.asarray(k))

    def test_strict_prefix_semantics(self):
        pc = PrefixCache(capacity_bytes=1 << 30, block=4, min_prefix=4)
        k, v = self._kv(8)
        pc.put([1, 2, 3, 4, 5, 6, 7, 8], k, v, 8, 8)
        # equal prompt: the last token must re-run -> only block 1
        assert pc.match([1, 2, 3, 4, 5, 6, 7, 8])[2] == 4
        assert pc.match([9, 2, 3, 4, 5]) is None   # diverges at start

    def test_min_prefix_floor(self):
        pc = PrefixCache(capacity_bytes=1 << 30, block=4, min_prefix=8)
        k, v = self._kv(4)
        pc.put([1, 2, 3, 4], k, v, 4, 4)
        assert pc.match([1, 2, 3, 4, 5]) is None   # 4 < min_prefix
        assert pc.misses == 1

    def test_byte_budget_bounds_cache_under_churn(self):
        block_bytes = 2 * (1 * 1 * 4 * 1 * 2 * 4)
        pc = PrefixCache(capacity_bytes=3 * block_bytes, block=4,
                         min_prefix=4)
        for start in range(0, 40, 4):
            k, v = self._kv(4)
            pc.put(list(range(start, start + 4)), k, v, 4, 4)
            assert pc.bytes <= 3 * block_bytes
        # the most recent blocks survived, the oldest were evicted
        assert pc.match(list(range(36, 41))) is not None
        assert pc.match(list(range(0, 5))) is None

    def test_lru_eviction_prefers_stale_leaves(self):
        block_bytes = 2 * (1 * 1 * 4 * 1 * 2 * 4)
        pc = PrefixCache(capacity_bytes=2 * block_bytes, block=4,
                         min_prefix=4)
        k1, v1 = self._kv(4)
        pc.put([1, 2, 3, 4], k1, v1, 4, 4)
        k2, v2 = self._kv(4)
        pc.put([5, 6, 7, 8], k2, v2, 4, 4)
        pc.match([1, 2, 3, 4, 9])           # refresh the first entry
        k3, v3 = self._kv(4)
        pc.put([9, 10, 11, 12], k3, v3, 4, 4)  # evicts [5,6,7,8]
        assert pc.match([1, 2, 3, 4, 0]) is not None
        assert pc.match([5, 6, 7, 8, 0]) is None
