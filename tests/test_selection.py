"""Runtime + accelerator selection tests (mirrors the reference's
runtimeselector/selector_test.go and acceleratorclassselector
policy_helpers_test.go table-driven style)."""

import pytest

from ome_tpu.apis import v1
from ome_tpu.core.client import InMemoryClient
from ome_tpu.core.meta import ObjectMeta
from ome_tpu.selection.accelerator_selector import (
    AcceleratorSelectionError,
    AcceleratorSelector,
    chips_needed,
    required_hbm_gb,
    smallest_fitting_topology,
)
from ome_tpu.selection.runtime_selector import (
    NoRuntimeFoundError,
    RuntimeDisabledError,
    RuntimeIncompatibleError,
    RuntimeNotFoundError,
    RuntimeSelector,
)


def make_runtime(name, cluster=True, formats=None, size_range=None,
                 disabled=None, accel_req=None):
    cls = v1.ClusterServingRuntime if cluster else v1.ServingRuntime
    spec = v1.ServingRuntimeSpec(
        supported_model_formats=formats or [],
        model_size_range=size_range,
        disabled=disabled,
        accelerator_requirements=accel_req)
    return cls(metadata=ObjectMeta(name=name, namespace="" if cluster else "default"),
               spec=spec)


def safetensors_fmt(**kw):
    return v1.SupportedModelFormat(
        model_format={"name": "safetensors"}, auto_select=True, **kw)


def llama_model(size="8B", arch="LlamaForCausalLM", quant=None):
    return v1.BaseModelSpec(
        model_format=v1.ModelFormat(name="safetensors"),
        model_framework=v1.ModelFrameworkSpec(name="transformers"),
        model_architecture=arch,
        model_parameter_size=size,
        quantization=quant)


@pytest.fixture
def client():
    return InMemoryClient()


class TestRuntimeSelector:
    def test_select_by_format(self, client):
        client.create(make_runtime("vllm-tpu", formats=[safetensors_fmt()]))
        client.create(make_runtime("onnx-rt", formats=[
            v1.SupportedModelFormat(model_format={"name": "onnx"},
                                    auto_select=True)]))
        sel = RuntimeSelector(client)
        m = sel.select(llama_model(), "default")
        assert m.name == "vllm-tpu"

    def test_no_runtime_found_reports_reasons(self, client):
        client.create(make_runtime("onnx-rt", formats=[
            v1.SupportedModelFormat(model_format={"name": "onnx"},
                                    auto_select=True)]))
        sel = RuntimeSelector(client)
        with pytest.raises(NoRuntimeFoundError) as exc:
            sel.select(llama_model(), "default")
        assert "onnx-rt" in str(exc.value)

    def test_size_range_filters(self, client):
        client.create(make_runtime(
            "small-rt", formats=[safetensors_fmt()],
            size_range=v1.ModelSizeRangeSpec(min="0.1B", max="20B")))
        client.create(make_runtime(
            "big-rt", formats=[safetensors_fmt()],
            size_range=v1.ModelSizeRangeSpec(min="30B", max="700B")))
        sel = RuntimeSelector(client)
        assert sel.select(llama_model("8B"), "default").name == "small-rt"
        assert sel.select(llama_model("70B"), "default").name == "big-rt"

    def test_architecture_specific_beats_generic(self, client):
        client.create(make_runtime("generic", formats=[safetensors_fmt()]))
        client.create(make_runtime("llama-tuned", formats=[
            safetensors_fmt(model_architecture="LlamaForCausalLM")]))
        sel = RuntimeSelector(client)
        assert sel.select(llama_model(), "default").name == "llama-tuned"

    def test_priority_breaks_ties(self, client):
        client.create(make_runtime("low", formats=[safetensors_fmt(priority=1)]))
        client.create(make_runtime("high", formats=[safetensors_fmt(priority=2)]))
        sel = RuntimeSelector(client)
        assert sel.select(llama_model(), "default").name == "high"

    def test_namespace_scoped_beats_cluster_scoped(self, client):
        client.create(make_runtime("rt-cluster", cluster=True,
                                   formats=[safetensors_fmt()]))
        client.create(make_runtime("rt-ns", cluster=False,
                                   formats=[safetensors_fmt()]))
        sel = RuntimeSelector(client)
        assert sel.select(llama_model(), "default").name == "rt-ns"

    def test_name_determinism(self, client):
        client.create(make_runtime("b-rt", formats=[safetensors_fmt()]))
        client.create(make_runtime("a-rt", formats=[safetensors_fmt()]))
        sel = RuntimeSelector(client)
        assert sel.select(llama_model(), "default").name == "a-rt"

    def test_auto_select_false_excluded(self, client):
        client.create(make_runtime("manual-only", formats=[
            v1.SupportedModelFormat(model_format={"name": "safetensors"},
                                    auto_select=False)]))
        sel = RuntimeSelector(client)
        with pytest.raises(NoRuntimeFoundError):
            sel.select(llama_model(), "default")

    def test_disabled_runtime_excluded(self, client):
        client.create(make_runtime("off", formats=[safetensors_fmt()],
                                   disabled=True))
        sel = RuntimeSelector(client)
        with pytest.raises(NoRuntimeFoundError):
            sel.select(llama_model(), "default")

    def test_validate_explicit(self, client):
        client.create(make_runtime("off", formats=[safetensors_fmt()],
                                   disabled=True))
        client.create(make_runtime("onnx-rt", formats=[
            v1.SupportedModelFormat(model_format={"name": "onnx"})]))
        sel = RuntimeSelector(client)
        with pytest.raises(RuntimeNotFoundError):
            sel.validate("missing", llama_model(), "default")
        with pytest.raises(RuntimeDisabledError):
            sel.validate("off", llama_model(), "default")
        with pytest.raises(RuntimeIncompatibleError):
            sel.validate("onnx-rt", llama_model(), "default")

    def test_quantization_match(self, client):
        client.create(make_runtime("fp8-rt", formats=[
            safetensors_fmt(quantization="fp8")]))
        sel = RuntimeSelector(client)
        m = sel.select(llama_model(quant=v1.ModelQuantization.FP8), "default")
        assert m.name == "fp8-rt"
        with pytest.raises(NoRuntimeFoundError):
            sel.select(llama_model(), "default")  # unquantized model

    def test_accelerator_requirements_respected(self, client):
        client.create(make_runtime(
            "v5p-only", formats=[safetensors_fmt()],
            accel_req=v1.AcceleratorRequirements(accelerator_classes=["tpu-v5p"])))
        sel = RuntimeSelector(client)
        v5e = make_accelerator("tpu-v5e")
        with pytest.raises(NoRuntimeFoundError):
            sel.select(llama_model(), "default", accelerator=v5e)


def make_accelerator(name, model="v5e", hbm=16.0, tflops=197.0, bw=819.0,
                     cost=1.2, topologies=("1x1", "2x2", "2x4", "4x4", "4x8"),
                     node_count=0, features=()):
    topos = [v1.parse_topology(t) for t in topologies]
    return v1.AcceleratorClass(
        metadata=ObjectMeta(name=name),
        spec=v1.AcceleratorClassSpec(
            vendor="google", family="tpu", model=model,
            capabilities=v1.AcceleratorCapabilities(
                memory_gb=hbm, bf16_tflops=tflops,
                memory_bandwidth_gbps=bw, topologies=topos,
                features=list(features)),
            cost=v1.AcceleratorCost(per_chip_hour_usd=cost),
            resources={v1.TPU_RESOURCE: "1"}),
        status=v1.AcceleratorClassStatus(node_count=node_count))


class TestSizing:
    def test_required_hbm(self):
        assert required_hbm_gb(llama_model("70B")) == pytest.approx(189, rel=0.01)
        assert required_hbm_gb(llama_model("70B", quant=v1.ModelQuantization.INT4)) \
            == pytest.approx(47.25, rel=0.01)

    def test_chips_needed_and_topology(self):
        ac = make_accelerator("tpu-v5e")
        assert chips_needed(llama_model("8B"), ac) == 2
        assert chips_needed(llama_model("70B"), ac) == 12
        topo = smallest_fitting_topology(ac, 12)
        assert topo.name == "4x4" and topo.hosts == 4


class TestAcceleratorSelector:
    def _isvc(self, policy=None, ac_class=None, topology=None):
        return v1.InferenceService(
            metadata=ObjectMeta(name="i", namespace="default"),
            spec=v1.InferenceServiceSpec(
                accelerator_selector=v1.AcceleratorSelector(
                    accelerator_class=ac_class, policy=policy,
                    topology=topology)))

    def test_explicit_name(self, client):
        client.create(make_accelerator("tpu-v5e"))
        sel = AcceleratorSelector(client)
        c = sel.resolve(self._isvc(ac_class="tpu-v5e"), model=llama_model("8B"))
        assert c.name == "tpu-v5e" and c.topology.name == "2x2"

    def test_best_fit_prefers_least_waste(self, client):
        client.create(make_accelerator("tpu-v5e", hbm=16.0))
        client.create(make_accelerator("tpu-v5p", model="v5p", hbm=95.0,
                                       tflops=459.0, cost=4.2,
                                       topologies=("2x2x1", "2x2x2", "2x2x4")))
        sel = AcceleratorSelector(client)
        c = sel.resolve(self._isvc(v1.AcceleratorSelectorPolicy.BEST_FIT),
                        model=llama_model("8B"))
        # 8B bf16 ~21.6GB: v5e 2x2 (64GB) wastes less than v5p 2x2x1 (380GB)
        assert c.name == "tpu-v5e" and c.topology.name == "2x2"

    def test_cheapest(self, client):
        client.create(make_accelerator("tpu-v5e", cost=1.2))
        client.create(make_accelerator("tpu-v6e", model="v6e", hbm=32,
                                       tflops=918, cost=2.97))
        sel = AcceleratorSelector(client)
        c = sel.resolve(self._isvc(v1.AcceleratorSelectorPolicy.CHEAPEST),
                        model=llama_model("8B"))
        # v5e rounds up to a 2x2 slice: 4 x $1.2 = $4.8; v6e fits on one
        # chip: 1 x $2.97 — slice-shape rounding makes v6e cheaper
        assert c.name == "tpu-v6e" and c.chips == 1

    def test_most_capable(self, client):
        client.create(make_accelerator("tpu-v5e"))
        client.create(make_accelerator("tpu-v6e", model="v6e", hbm=32,
                                       tflops=918, bw=1638, cost=2.97))
        sel = AcceleratorSelector(client)
        c = sel.resolve(self._isvc(v1.AcceleratorSelectorPolicy.MOST_CAPABLE),
                        model=llama_model("8B"))
        assert c.name == "tpu-v6e"

    def test_first_available_needs_nodes(self, client):
        client.create(make_accelerator("tpu-v5e", node_count=0))
        client.create(make_accelerator("tpu-v6e", model="v6e", node_count=3))
        sel = AcceleratorSelector(client)
        c = sel.resolve(self._isvc(v1.AcceleratorSelectorPolicy.FIRST_AVAILABLE),
                        model=llama_model("8B"))
        assert c.name == "tpu-v6e"

    def test_topology_pin(self, client):
        client.create(make_accelerator("tpu-v5e"))
        sel = AcceleratorSelector(client)
        c = sel.resolve(self._isvc(v1.AcceleratorSelectorPolicy.BEST_FIT,
                                   topology="4x4"),
                        model=llama_model("8B"))
        assert c.topology.name == "4x4" and c.chips == 16

    def test_runtime_requirements_filter(self, client):
        client.create(make_accelerator("tpu-v5e"))
        client.create(make_accelerator("tpu-v5p", model="v5p", hbm=95,
                                       topologies=("2x2x1", "2x2x2")))
        rt_spec = v1.ServingRuntimeSpec(
            accelerator_requirements=v1.AcceleratorRequirements(
                min_memory_gb=90))
        sel = AcceleratorSelector(client)
        c = sel.resolve(self._isvc(v1.AcceleratorSelectorPolicy.BEST_FIT),
                        runtime_spec=rt_spec, model=llama_model("8B"))
        assert c.name == "tpu-v5p"

    def test_model_must_fit_largest_slice(self, client):
        client.create(make_accelerator("tiny", hbm=16.0, topologies=("1x1",)))
        sel = AcceleratorSelector(client)
        with pytest.raises(AcceleratorSelectionError):
            sel.resolve(self._isvc(v1.AcceleratorSelectorPolicy.BEST_FIT),
                        model=llama_model("70B"))

    def test_missing_explicit_class_errors(self, client):
        sel = AcceleratorSelector(client)
        with pytest.raises(AcceleratorSelectionError):
            sel.resolve(self._isvc(ac_class="nope"), model=llama_model("8B"))
