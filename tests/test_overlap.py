"""Prefill/decode overlap (Scheduler overlap=True): the admission
thread prefills while the scheduler thread keeps stepping decode —
insert is the only synchronization point. JetStream separates prefill
and generate machines for the same reason (round-2 review weak #3)."""

import threading
import time

import jax
import numpy as np
import pytest

from ome_tpu.engine import InferenceEngine, Request, Scheduler
from ome_tpu.models import config as cfgs
from ome_tpu.models import llama


class SlowFakeEngine:
    """Engine double with a deliberately slow prefill and fast decode,
    recording the wall-clock of every decode call. No device work, so
    the test isolates SCHEDULER behavior from 1-core CPU contention."""

    max_slots = 8
    max_seq = 1024

    def __init__(self, prefill_s=0.25, decode_s=0.002):
        self.prefill_s = prefill_s
        self.decode_s = decode_s
        self.decode_times = []

    def new_state(self):
        return "state"

    def prefill(self, ids, t, k, p):
        time.sleep(self.prefill_s)
        return 1, "kv", len(ids), 64

    def insert(self, state, kv, slot, true_len, token, bucket):
        return state

    def decode(self, state, t, k, p):
        self.decode_times.append(time.monotonic())
        time.sleep(self.decode_s)
        return state, np.full(self.max_slots, 3, np.int32)


def _drive(overlap: bool) -> float:
    """Max gap between decode steps while 8 slow prefills arrive
    mid-stream."""
    eng = SlowFakeEngine()
    sched = Scheduler(eng, overlap=overlap)
    sched.start()
    try:
        # one long-running stream keeps decode active
        sched.submit(Request(prompt_ids=[1, 2], max_new_tokens=10_000))
        deadline = time.monotonic() + 10
        while len(eng.decode_times) < 20:
            assert time.monotonic() < deadline, "decode never started"
            time.sleep(0.005)
        # burst: 8 long prompts arrive during active decode
        for i in range(7):
            sched.submit(Request(prompt_ids=[1] * 64,
                                 max_new_tokens=10_000))
        start = len(eng.decode_times)
        while len(eng.decode_times) < start + 400:
            assert time.monotonic() < deadline + 20
            time.sleep(0.005)
    finally:
        sched.stop()
    times = eng.decode_times[start:start + 400]
    gaps = np.diff(np.asarray(times))
    return float(np.percentile(gaps, 99))


def test_burst_prefills_do_not_stall_decode_cadence():
    """With overlap, p99 decode-step gap during a burst of slow
    prefills stays near the decode cost; without it, gaps include
    whole prefills (the stall the overlap removes)."""
    p99_overlap = _drive(overlap=True)
    eng_prefill_s = SlowFakeEngine().prefill_s
    # well under one prefill: decode cadence never absorbed a prefill
    assert p99_overlap < eng_prefill_s / 2, p99_overlap
    p99_sync = _drive(overlap=False)
    assert p99_sync > eng_prefill_s  # the synchronous path does stall


def test_overlap_matches_synchronous_tokens():
    """Same requests through overlap and synchronous scheduling must
    produce identical greedy token streams (insert-order independent
    because each slot's stream only depends on its own prefill)."""
    cfg = cfgs.tiny_test().replace(max_seq_len=64)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prompts = [[1, 7, 42], [9, 9, 9, 9], [3, 14, 15, 92, 6]]

    def run(overlap):
        engine = InferenceEngine(params, cfg, max_slots=4,
                                 prefill_buckets=[16])
        sched = Scheduler(engine, overlap=overlap)
        sched.start()
        try:
            reqs = [sched.submit(Request(prompt_ids=p, max_new_tokens=6))
                    for p in prompts]
            outs = [r.wait_output(120) for r in reqs]
        finally:
            sched.stop()
        return outs

    assert run(True) == run(False)


def test_overlap_failure_fails_requests_and_health():
    """A prefill error on the admission thread must fail the request,
    flip health, and fail in-flight work (same contract as sync).
    max_restarts=0 pins the fail-fast behavior; the recovery paths
    live in test_faults.py."""
    eng = SlowFakeEngine(prefill_s=0.01)

    def boom(ids, t, k, p):
        raise RuntimeError("device fell over")

    eng.prefill = boom
    sched = Scheduler(eng, overlap=True, max_restarts=0)
    sched.start()
    try:
        req = sched.submit(Request(prompt_ids=[1, 2], max_new_tokens=4))
        assert req.done.wait(30)
        assert req.finish_reason == "error"
        # the health flip is owned by the scheduler thread; the request
        # fails on the admission thread first, so poll briefly
        deadline = time.monotonic() + 10
        while sched.healthy:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        with pytest.raises(RuntimeError):
            sched.submit(Request(prompt_ids=[1], max_new_tokens=1))
    finally:
        sched.stop()
