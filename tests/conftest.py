"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding tests
(tp/pp/dp/sp/ep over jax.sharding.Mesh) run without TPU hardware — the
same trick the driver uses for dryrun_multichip validation.

Must run before any jax import, hence the env mutation at module scope of
the earliest-loaded conftest.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
