"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding tests
(tp/pp/dp/sp/ep over jax.sharding.Mesh) run without TPU hardware — the
same setup the driver uses for dryrun_multichip validation.

The image's sitecustomize pre-imports jax pinned to the axon TPU
backend, so env vars alone don't switch platforms; reuse the
config-level forcing from __graft_entry__.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")  # for subprocess children

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _force_cpu_devices  # noqa: E402

_force_cpu_devices(8)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running (chaos soak / multi-node) tests, excluded "
        "from the tier-1 `-m 'not slow'` run")
