"""Span timeline + flight recorder + Perfetto export (ISSUE 7).

Covers the introspection layer end to end: span parenting across
router -> engine -> PD prefill within one trace, chunked decode spans
tiling a request's stream without overlap, the flight ring's bounds
and eviction, the crash auto-dump on engine-fault recovery, the
guarded /debug/events + /debug/state surfaces, and the Chrome Trace
Event exporter (telemetry/export.py) producing monotonic-consistent
JSON that Perfetto can load.
"""

import json
import pathlib
import time
import urllib.error
import urllib.request

import pytest

from ome_tpu.engine.scheduler import Request, Scheduler
from ome_tpu.engine.server import EngineServer
from ome_tpu.engine.tokenizer import ByteTokenizer
from ome_tpu.telemetry import export
from ome_tpu.telemetry.flight import FlightRecorder
from ome_tpu.telemetry.tracing import Span, SpanLog, new_trace

from test_faults import FakeEngine, _get, _post


def _wait_spans(path, want, timeout=15.0):
    """Spans from a JSONL log once at least `want(spans)` holds —
    writers flush after the response bytes, so reads can race."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        spans = export.load_spans([path])
        if want(spans):
            return spans
        time.sleep(0.05)
    raise AssertionError(
        f"span log {path} never satisfied the predicate; "
        f"have {[s['name'] for s in export.load_spans([path])]}")


# -- span record unit behavior ---------------------------------------


class TestSpan:
    def test_begin_under_context_keeps_trace_new_span(self):
        ctx = new_trace()
        span = Span.begin("x", ctx=ctx)
        assert span.trace_id == ctx.trace_id
        assert span.parent_id == ctx.span_id
        assert span.span_id != ctx.span_id

    def test_record_schema_and_monotonic_duration(self):
        span = Span.begin("phase")
        span.set(k="v")
        span.end()
        rec = span.record()
        assert rec["kind"] == "span"
        assert rec["name"] == "phase"
        assert rec["dur_s"] >= 0
        assert rec["t_start"] > 0
        assert rec["attrs"] == {"k": "v"}
        for key in ("trace_id", "span_id"):
            assert rec[key]

    def test_attrs_bounded_and_truncated(self):
        span = Span.begin("x")
        for i in range(32):
            span.set(**{f"a{i:02d}": "y" * 1000})
        span.end()
        attrs = span.record()["attrs"]
        assert len(attrs) == 16
        assert all(len(v) <= 256 for v in attrs.values())

    def test_spanlog_writes_component_and_autoends(self, tmp_path):
        p = tmp_path / "s.jsonl"
        log = SpanLog(str(p), component="t")
        log.write(Span.begin("open"))  # never .end()ed: log ends it
        log.close()
        (rec,) = export.load_spans([p])
        assert rec["component"] == "t"
        assert rec["dur_s"] >= 0
        assert isinstance(rec["pid"], int)


# -- flight recorder -------------------------------------------------


class TestFlightRecorder:
    def test_ring_bounds_and_eviction(self):
        fl = FlightRecorder(capacity=4, component="t")
        for i in range(10):
            fl.record("ev", i=i)
        events = fl.snapshot()
        assert len(events) == 4
        assert [e["i"] for e in events] == [6, 7, 8, 9]  # newest kept
        assert [e["seq"] for e in events] == [7, 8, 9, 10]
        assert fl.recorded == 10
        assert fl.dropped == 6
        assert [e["i"] for e in fl.snapshot(2)] == [8, 9]
        st = fl.state()
        assert st["capacity"] == 4 and st["buffered"] == 4
        assert st["recorded"] == 10 and st["dropped"] == 6

    def test_capacity_must_be_positive(self):
        with pytest.raises(ValueError):
            FlightRecorder(capacity=0)

    def test_dump_writes_loadable_doc(self, tmp_path):
        fl = FlightRecorder(capacity=8, component="t")
        fl.record("admit", request="r1")
        path = tmp_path / "dump.json"
        fl.dump(str(path), reason="test")
        doc = json.loads(path.read_text())
        assert doc["reason"] == "test"
        assert doc["component"] == "t"
        assert isinstance(doc["pid"], int)
        assert [e["event"] for e in doc["events"]] == ["admit"]
        # the exporter accepts the same file
        assert export.load_flight_dumps([path]) == [doc]


# -- chunked decode spans tile the stream ----------------------------


def test_decode_chunks_tile_without_overlap(tmp_path):
    log_path = tmp_path / "engine.jsonl"
    sched = Scheduler(FakeEngine(max_slots=1), span_log=str(log_path),
                      span_chunk_steps=3)
    sched.start()
    req = sched.submit(Request(prompt_ids=[1, 2, 3],
                               max_new_tokens=10))
    assert req.done.wait(timeout=30)
    sched.stop()

    spans = export.load_spans([log_path])
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    (root,) = by_name["engine.request"]
    (q,) = by_name["engine.queue"]
    (pre,) = by_name["engine.prefill"]
    chunks = sorted(by_name["engine.decode"],
                    key=lambda s: s["attrs"]["chunk"])
    # every phase span hangs off the request span
    for s in (q, pre, *chunks):
        assert s["trace_id"] == root["trace_id"]
        assert s["parent_id"] == root["span_id"]
    # 10 tokens = 1 prefill + 9 decode steps -> chunks of 3/3/3
    assert [c["attrs"]["chunk"] for c in chunks] == [0, 1, 2]
    assert sum(c["attrs"]["steps"] for c in chunks) == 9
    assert sum(c["attrs"]["tokens"] for c in chunks) == 9
    # consecutive chunks tile: next start == previous end, so the
    # chunk spans cover the decode stream with no gaps or overlap
    for prev, nxt in zip(chunks, chunks[1:]):
        assert nxt["t_start"] == pytest.approx(
            prev["t_start"] + prev["dur_s"], abs=1e-4)
    # and the whole tiling nests inside the request span's window
    assert chunks[0]["t_start"] >= root["t_start"] - 1e-4
    end = chunks[-1]["t_start"] + chunks[-1]["dur_s"]
    assert end <= root["t_start"] + root["dur_s"] + 1e-4


# -- crash dump on engine-fault recovery -----------------------------


class _FaultyEngine(FakeEngine):
    """Raises on the second decode step, then behaves."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.decode_calls = 0

    def decode(self, state, t, k, p):
        self.decode_calls += 1
        if self.decode_calls == 2:
            raise RuntimeError("injected decode fault")
        return super().decode(state, t, k, p)


def test_engine_fault_recovery_autodumps_flight_ring(tmp_path):
    sched = Scheduler(_FaultyEngine(max_slots=1),
                      flight_dump_dir=str(tmp_path),
                      restart_backoff=0.01)
    sched.start()
    req = sched.submit(Request(prompt_ids=[1, 2], max_new_tokens=6))
    assert req.done.wait(timeout=30)
    assert req.finish_reason == "engine_fault"
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        dumps = sorted(tmp_path.glob("flight-*.json"))
        if dumps:
            break
        time.sleep(0.05)
    sched.stop()
    assert dumps, "no flight auto-dump after engine-fault recovery"
    doc = json.loads(dumps[0].read_text())
    assert doc["reason"] == "engine_fault"
    events = [e["event"] for e in doc["events"]]
    assert "admit" in events
    assert "crash_recovery" in events
    assert sched.registry.get("ome_engine_flight_dumps_total") >= 1
    assert sched.registry.get("ome_engine_flight_events_total") >= \
        len(doc["events"])


# -- guarded debug endpoints -----------------------------------------


class TestDebugEndpoints:
    def test_403_when_disabled(self):
        srv = EngineServer(Scheduler(FakeEngine(max_slots=1)),
                           tokenizer=ByteTokenizer(), model_name="t",
                           port=0)  # debug_endpoints defaults off
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            for path in ("/debug/events", "/debug/state"):
                status, body = _get(base + path)
                assert status == 403
                assert "--debug-endpoints" in body["error"]
        finally:
            srv.stop()

    def test_events_and_state_schema_when_enabled(self):
        sched = Scheduler(FakeEngine(max_slots=2))
        srv = EngineServer(sched, tokenizer=ByteTokenizer(),
                           model_name="t", port=0,
                           debug_endpoints=True)
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            status, _, out = _post(base + "/v1/completions",
                                   {"prompt": "hi", "max_tokens": 3})
            assert status == 200

            status, doc = _get(base + "/debug/events")
            assert status == 200
            assert doc["component"] == "engine"
            assert doc["recorded"] >= len(doc["events"]) > 0
            names = [e["event"] for e in doc["events"]]
            assert "admit" in names and "slot_assign" in names
            for e in doc["events"]:
                assert e["seq"] > 0 and e["t_wall"] > 0

            status, one = _get(base + "/debug/events?n=1")
            assert status == 200 and len(one["events"]) == 1
            assert one["events"][0]["seq"] == doc["events"][-1]["seq"]
            status, _ = _get(base + "/debug/events?n=bogus")
            assert status == 400

            status, state = _get(base + "/debug/state")
            assert status == 200
            assert state["status"] == "ok"
            assert state["max_slots"] == 2
            assert state["queue_depth"] == 0
            assert state["flight"]["recorded"] == doc["recorded"]
            assert isinstance(state["slots"], list)
        finally:
            srv.stop()


# -- exporter --------------------------------------------------------


def _span_rec(name, trace, span, parent, t0, dur, component="c",
              pid=1, **attrs):
    rec = {"kind": "span", "name": name, "trace_id": trace,
           "span_id": span, "parent_id": parent,
           "t_start": t0, "dur_s": dur, "component": component,
           "pid": pid}
    if attrs:
        rec["attrs"] = attrs
    return rec


class TestExporter:
    def test_load_spans_skips_torn_and_foreign_lines(self, tmp_path):
        p = tmp_path / "s.jsonl"
        p.write_text(
            json.dumps(_span_rec("a", "t1", "s1", None, 10.0, 0.5))
            + "\n"
            + '{"kind": "other", "x": 1}\n'
            + json.dumps({"kind": "span", "name": "no-times"}) + "\n"
            + '{"kind": "span", "na')  # torn tail
        spans = export.load_spans([p])
        assert [s["name"] for s in spans] == ["a"]
        assert export.load_spans([tmp_path / "absent.jsonl"]) == []

    def test_build_trace_is_valid_and_monotonic(self):
        spans = [
            _span_rec("router.request", "t1", "r", None, 100.0, 2.0,
                      component="router", pid=10),
            _span_rec("engine.request", "t1", "e", "r", 100.5, 1.0,
                      component="engine", pid=20),
            _span_rec("engine.request", "t2", "e2", None, 101.0, 0.5,
                      component="engine", pid=20),
        ]
        flight = {"component": "engine", "pid": 20, "events": [
            {"event": "admit", "t_wall": 100.6, "seq": 1}]}
        doc = export.build_trace(spans, [flight])
        events = doc["traceEvents"]
        xs = [e for e in events if e["ph"] == "X"]
        metas = [e for e in events if e["ph"] == "M"]
        marks = [e for e in events if e["ph"] == "i"]
        # every event well-formed; complete events rebased to t=0 in
        # ascending order with positive duration
        for e in events:
            assert {"name", "ph", "pid"} <= set(e)
        ts = [e["ts"] for e in xs]
        assert ts == sorted(ts) and ts[0] == 0.0
        assert all(e["dur"] >= 1.0 for e in xs)
        assert doc["otherData"]["epoch_us"] == 100.0 * 1e6
        assert doc["otherData"]["span_count"] == 3
        # one process track per (component, pid); one thread per trace
        proc_names = {m["args"]["name"] for m in metas
                      if m["name"] == "process_name"}
        assert proc_names == {"router (pid 10)", "engine (pid 20)"}
        engine_pid = next(e["pid"] for e in xs
                          if e["name"] == "engine.request")
        engine_tids = {e["tid"] for e in xs if e["pid"] == engine_pid}
        assert len(engine_tids) == 2  # t1 and t2 rows
        # span links survive into args; flight marks are instants
        x = next(e for e in xs if e["args"]["span_id"] == "e")
        assert x["args"]["parent_id"] == "r"
        assert [m["name"] for m in marks] == ["flight:admit"]
        assert marks[0]["ts"] == pytest.approx(0.6 * 1e6)

    def test_trace_filter_and_ids(self):
        spans = [_span_rec("a", "t1", "s1", None, 1.0, 0.1),
                 _span_rec("b", "t2", "s2", None, 2.0, 0.1)]
        assert export.trace_ids(spans) == ["t1", "t2"]
        doc = export.build_trace(spans, trace_id="t2")
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert [e["name"] for e in xs] == ["b"]

    def test_cli_writes_merged_and_split_traces(self, tmp_path):
        log = tmp_path / "s.jsonl"
        log.write_text(
            json.dumps(_span_rec("a", "t1", "s1", None, 1.0, 0.1))
            + "\n"
            + json.dumps(_span_rec("b", "t2", "s2", None, 2.0, 0.1))
            + "\n")
        out = tmp_path / "trace.json"
        per = tmp_path / "per"
        rc = export.main([str(log), "-o", str(out),
                          "--split-by-trace", str(per)])
        assert rc == 0
        doc = json.loads(out.read_text())
        assert doc["otherData"]["span_count"] == 2
        assert sorted(p.name for p in per.glob("trace-*.json")) == \
            ["trace-t1.json", "trace-t2.json"]
        # no spans at all -> rc 1 (a trace of nothing is a user error)
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        assert export.main([str(empty), "-o",
                            str(tmp_path / "e.json")]) == 1

    def test_script_shim_resolves(self):
        repo = pathlib.Path(__file__).resolve().parents[1]
        assert (repo / "scripts" / "trace_export.py").exists()


# -- the acceptance path: router -> engine -> PD in one trace --------


@pytest.fixture(scope="module")
def world():
    import jax
    import jax.numpy as jnp
    from ome_tpu.models import config as cfgs
    from ome_tpu.models import llama
    cfg = cfgs.tiny_test().replace(max_seq_len=128, dtype=jnp.float32)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def test_router_engine_pd_spans_share_one_trace(world, tmp_path):
    """Fault-free two-request run through router + PD pair: the span
    logs merge into one trace per request with the nesting the ISSUE
    promises — router.request > router.attempt > engine.request >
    {queue, prefill > pd.fetch (peer-attributed), decode chunks}."""
    from ome_tpu.engine import InferenceEngine
    from ome_tpu.engine.pd import (RemotePrefillEngine,
                                   make_pd_prefill_handler)
    from ome_tpu.engine.serve import _PrefillNodeScheduler
    from ome_tpu.router.server import Backend, Router, RouterServer
    cfg, params = world

    def engine():
        return InferenceEngine(params, cfg, max_slots=2,
                               prefill_buckets=[16, 32])

    pre_engine = engine()
    pre_srv = EngineServer(_PrefillNodeScheduler(pre_engine),
                           model_name="m",
                           pd_prefill=make_pd_prefill_handler(
                               pre_engine))
    pre_srv.start()
    pre_url = f"http://127.0.0.1:{pre_srv.port}"

    engine_spans = tmp_path / "engine.spans.jsonl"
    router_spans = tmp_path / "router.spans.jsonl"
    slog = SpanLog(str(engine_spans), component="engine")
    sched = Scheduler(RemotePrefillEngine(engine(), pre_url,
                                          span_log=slog),
                      overlap=True, span_log=slog, span_chunk_steps=4)
    esrv = EngineServer(sched, model_name="m", port=0)
    esrv.start()
    router = Router([Backend(f"http://127.0.0.1:{esrv.port}")])
    rsrv = RouterServer(router, host="127.0.0.1", port=0,
                        span_log=str(router_spans)).start()
    try:
        base = f"http://127.0.0.1:{rsrv.port}"
        for prompt in ("hi there", "second request"):
            status, _, out = _post(base + "/v1/completions",
                                   {"model": "m", "prompt": prompt,
                                    "max_tokens": 6,
                                    "temperature": 0}, timeout=120)
            assert status == 200
            assert out["usage"]["completion_tokens"] == 6
        r_spans = _wait_spans(
            router_spans,
            lambda s: sum(x["name"] == "router.request"
                          for x in s) >= 2)
        e_spans = _wait_spans(
            engine_spans,
            lambda s: sum(x["name"] == "engine.request"
                          for x in s) >= 2)
    finally:
        rsrv.stop()
        esrv.stop()
        pre_srv.stop()

    spans = r_spans + e_spans
    traces = export.trace_ids([s for s in spans
                               if s["name"] == "router.request"])
    assert len(traces) == 2  # one trace per request
    for tid in traces:
        mine = [s for s in spans if s["trace_id"] == tid]
        by = {}
        for s in mine:
            by.setdefault(s["name"], []).append(s)
        (rroot,) = by["router.request"]
        (attempt,) = by["router.attempt"]
        (ereq,) = by["engine.request"]
        (queue,) = by["engine.queue"]
        (prefill,) = by["engine.prefill"]
        fetches = by["pd.fetch"]
        chunks = by["engine.decode"]
        # the parent chain the timeline hangs on
        assert attempt["parent_id"] == rroot["span_id"]
        assert ereq["parent_id"] == attempt["span_id"]
        for s in (queue, prefill, *chunks):
            assert s["parent_id"] == ereq["span_id"]
        for f in fetches:
            assert f["parent_id"] == prefill["span_id"]
            assert f["attrs"]["peer"] == pre_url  # peer-attributed
            assert f["attrs"]["status"] == "ok"
        assert rroot["attrs"]["status"] == "ok"
        assert attempt["attrs"]["status"] == "ok"
        assert ereq["attrs"]["finish_reason"] == "length"
        assert sum(c["attrs"]["tokens"] for c in chunks) == 5
        # wall-clock nesting: the router span encloses the engine
        # span, which encloses every phase span (same host, so the
        # cross-process comparison is meaningful here)
        def window(s):
            return s["t_start"], s["t_start"] + s["dur_s"]
        r0, r1 = window(rroot)
        e0, e1 = window(ereq)
        assert r0 - 1e-3 <= e0 and e1 <= r1 + 1e-3
        for s in (queue, prefill, *fetches, *chunks):
            s0, s1 = window(s)
            assert e0 - 1e-3 <= s0 and s1 <= e1 + 1e-3

        # and the exporter turns it into a loadable per-request doc
        doc = export.build_trace(spans, trace_id=tid)
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in xs} >= {
            "router.request", "router.attempt", "engine.request",
            "engine.queue", "engine.prefill", "pd.fetch",
            "engine.decode"}
        assert min(e["ts"] for e in xs) == 0.0
        assert all(e["dur"] >= 1.0 for e in xs)
        # router and engine land on separate process tracks
        assert len({e["pid"] for e in doc["traceEvents"]
                    if e["ph"] == "M"
                    and e["name"] == "process_name"}) == 2
