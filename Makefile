# Developer entry points. The same commands the CI tiers run — no
# extra tooling, everything here works with the stdlib + the baked-in
# JAX toolchain.

PYTHON ?= python

.PHONY: lint test

# omelint: the repo's static-analysis gate (docs/static-analysis.md).
# Runs every registered analyzer over ome_tpu/ and fails on any
# finding that is neither inline-suppressed (with a reason) nor
# grandfathered in lint-baseline.json.
lint:
	$(PYTHON) scripts/omelint.py --all

# tier-1: the fast correctness suite (see ROADMAP.md for the exact
# CI invocation with log capture)
test:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider
