# Developer entry points. The same commands the CI tiers run — no
# extra tooling, everything here works with the stdlib + the baked-in
# JAX toolchain.

PYTHON ?= python

.PHONY: lint test replay autoscale-soak noisy-neighbor router-soak \
	benchgate simulate chaos-sim slo-report model-fleet-soak

# omelint: the repo's static-analysis gate (docs/static-analysis.md).
# Runs every registered analyzer over ome_tpu/ and fails on any
# finding that is neither inline-suppressed (with a reason) nor
# grandfathered in lint-baseline.json.
lint:
	$(PYTHON) scripts/omelint.py --all

# tier-1: the fast correctness suite (see ROADMAP.md for the exact
# CI invocation with log capture)
test:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

# bench regression gate (docs/perf-attribution.md): run bench.py
# fresh and diff it against the newest checked-in BENCH_r*.json with
# noise-aware per-metric bands; non-zero exit on regression. Known,
# accepted regressions go in bench-waivers.json with a reason.
benchgate:
	$(PYTHON) scripts/perfgate.py --run

# fleet simulator smoke (docs/simulation.md): the autoscale scenario
# (diurnal + flash-crowd trace through the real controller on virtual
# time) run twice with the same seed; fails unless the two reports —
# decision log included — are byte-identical
simulate:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/simulate.py \
		--scenario autoscale --seed 7 --check-determinism --full

# fleet-scale chaos in the simulator (docs/simulation.md): a seeded
# fault schedule — kill/restart, slow/stuck replicas, partitions,
# transport faults — against 100 engines with the fleet-wide
# durability invariants checked (no admitted request lost, every
# journal reconciled), run twice for byte-identity. Exit 2 =
# invariant violation; add --shrink --bundle-dir to minimize it.
chaos-sim:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/simulate.py \
		--scenario chaos --seed 7 --engines 100 --requests 2000 \
		--kills 12 --check-determinism

# fleet SLO report (docs/slo.md): the steady scenario through the
# virtual-time SLO engine, printing the per-class attainment /
# error-budget / alert-state table to stderr (canonical JSON report
# on stdout, pipe it somewhere if you want it)
slo-report:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/simulate.py \
		--scenario steady --seed 7 --slo-table >/dev/null

# trace replay against a self-spawned router + CPU engine: the quick
# "does the load generator work here" check (docs/autoscaling.md);
# point scripts/replay.py at --url/--trace for real endpoints/logs
replay:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/replay.py --topology 1 \
		--seed 7 --requests 10 --compress 2

# multi-tenant isolation under overload (docs/multi-tenancy.md): a
# seeded batch-class flood at 5x slot capacity with steady
# interactive traffic and a mid-episode SIGKILL, checked against the
# noisy-neighbor invariants (no admitted class starves, weighted
# shares hold, interactive is never shed)
noisy-neighbor:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/chaos_soak.py --seed 7 \
		--episodes 1 --noisy-neighbor --prefill 0 --decode 0 \
		--unified 1 --spread 4

# ingress HA under router loss (docs/router-ha.md): three gossiping
# async routers front two engines, one takes a keyed forward fault
# and is SIGKILLed mid-replay; the driver fails over client-side and
# the runner checks the HA invariants (no request lost or duplicated
# fleet-wide, survivors converge on the victim's breaker
# observations within one anti-entropy round)
router-soak:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/chaos_soak.py --seed 3 \
		--episodes 1 --router-loss --routers 3 --prefill 0 \
		--decode 0 --unified 2 --requests 10 --spread 4

# hardened weight plane under mid-download SIGKILLs
# (docs/model-fleet.md): seeded episodes that kill the model agent
# after a seed-derived number of objects are manifest-recorded, then
# check the failure contract — serving path never partial, manifest
# never ahead of the disk, re-run resumes from every verified object
# and publishes a byte-identical tree
model-fleet-soak:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/modelfleet_soak.py --seed 7 \
		--episodes 5

# the closed-loop demo: bursty replayed trace + SLO-aware scaling of
# a live engine pool, reporting engine-seconds vs static max
# provisioning and the full decision log
autoscale-soak:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/autoscale.py --seed 7 \
		--requests 30 --burst-factor 6 --min-engines 1 \
		--max-engines 3 --slo-ttft-p99 0.5 --slo-queue-wait-p99 \
		0.25 --queue-depth-high 2 --settle-seconds 10 --json
