#!/usr/bin/env python
"""Driver benchmark: sustained decode throughput of the flagship model.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} plus
context keys (int8/int4 throughput, a measured per-step decode time
breakdown, prefill MFU, and measured-vs-spec rooflines).

The reference (bcfre/ome) publishes no hardware numbers (BASELINE.md) —
its headline metric is BenchmarkJob *output tokens/sec* against a served
InferenceService (SURVEY.md §6). This bench measures the same quantity
at the layer we own end-to-end on one chip: batched autoregressive
decode tokens/sec of the flagship Llama-class model with a KV cache.

Round-4 structure (measured ablations, scripts/perf_lab.py):
  * decode runs UNROLLED over layers with per-layer cache planes and
    lax.scan over MULTISTEP tokens per dispatch — vs round 3's
    scan-over-layers/one-step-per-dispatch shape this avoids the
    full-cache stacked-ys rewrite (~1.2 ms/step) and amortizes the
    ~1.6 ms axon host-dispatch latency (bf16 3,003 -> ~4,200 tok/s).
  * the per-step breakdown is MEASURED, not modeled: host dispatch
    (empty jit), weights+sampling floor (attention ablated), and the
    attention/KV remainder — persisted in the parsed JSON so the gap
    between quantized modes is attributable (round-3 verdict #1).
  * round-5 (verdict #1): the floor is `floor_k` — decode_k itself
    with ONLY the KV-cache read ablated (same unrolled layers, same
    8-step scan, same per-layer cache planes and writes, same chained
    dispatch loop) — so weights + attn_kv + dispatch ≈ step by
    construction and the achievable anchor (weights bytes / floor
    time) sits ABOVE the decode-effective bandwidth, where a credible
    ceiling must be. Round 4's floor used a different dispatch shape
    (stacked-layer scan, 1 step/dispatch) whose ~8 ms of host arg
    marshaling landed in weights_ms, pushing the "floor" above the
    full step and clamping attn_kv to 0.
  * vs_baseline stays spec-anchored for round-over-round
    comparability, vs_achievable reports against the measured ceiling.
  * prefill reports tokens/sec AND MFU against the chip's bf16 peak
    (verdict #3).
"""

from __future__ import annotations

import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def sync(x):
    """Force completion. On the axon-tunneled TPU backend
    jax.block_until_ready returns before execution finishes; only a
    device->host fetch truly synchronizes, so time through a fetch."""
    jax.block_until_ready(x)
    return np.asarray(jax.device_get(x))

# Device spec tables are canonical in ome_tpu/perf/ledger.py now —
# the engine's online roofline and this offline bench must never
# disagree about what the hardware can do.
from ome_tpu.perf.ledger import DEVICE_HBM_GBPS as HBM_GBPS
from ome_tpu.perf.ledger import DEVICE_PEAK_TFLOPS as PEAK_TFLOPS

import os

BATCH = 32
PREFILL = 128
DECODE_STEPS = 128
# 8 amortizes the ~1.6 ms tunnel dispatch to 0.2 ms/step; 16 halves
# that again at the cost of a bigger unrolled program (env knob for
# perf experiments)
MULTISTEP = int(os.environ.get("OME_BENCH_MULTISTEP", "8"))
CACHE_LEN = PREFILL + DECODE_STEPS
TRIALS = 3


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _lookup(table) -> float:
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", d.platform).lower()
    for key, val in table.items():
        if key in kind:
            return val
    return table["cpu" if d.platform == "cpu" else "v5e"]


def dispatch_ms() -> float:
    """Per-call host-dispatch (enqueue) cost: N CHAINED empty calls,
    ONE sync. On the axon tunnel the enqueue costs ~1.6 ms and is
    serialized with execution, while the final result FETCH can add up
    to ~200 ms of polling latency depending on session health — so
    every timing in this bench divides one fetch across many chained
    dispatches instead of syncing per call."""
    f = jax.jit(lambda t: t + 1)
    t = jnp.zeros((32, 1), jnp.int32)
    sync(f(t))
    n = 64
    best = float("inf")
    for _ in range(3):
        x = t
        t0 = time.perf_counter()
        for _ in range(n):
            x = f(x)
        sync(x)
        best = min(best, time.perf_counter() - t0)
    return best / n * 1000


def composition_main() -> None:
    """`bench.py composition`: the StepPlan composition matrix.

    Sweeps spec-tokens x steps-per-dispatch x pipeline-depth through
    the REAL Scheduler (docs/step-plan.md) on a repetitive workload —
    tiled 4-token prompt patterns, so greedy streams settle into the
    short cycles the n-gram drafter feeds on. Each cell reports
    sustained tokens/sec, the verify accept rate, and the planner's
    degradation counts (any nonzero count means the cell silently
    lost a composition feature — the thing this sweep exists to
    catch). The composed cells (spec>0 x K>1 x depth 1) must beat the
    best single-mechanism cell; perfgate gates every cell under the
    ^composition. bands and --cost-table exports them to the fleet
    simulator."""
    from ome_tpu.engine.core import InferenceEngine
    from ome_tpu.engine.scheduler import Request, Scheduler
    from ome_tpu.models import llama

    cfg = flagship_config()
    SLOTS = int(os.environ.get("OME_BENCH_COMP_SLOTS", "8"))
    NEW = int(os.environ.get("OME_BENCH_COMP_TOKENS", "48"))
    SPECS = tuple(int(x) for x in os.environ.get(
        "OME_BENCH_COMP_SPECS", "0,4").split(","))
    KS = tuple(int(x) for x in os.environ.get(
        "OME_BENCH_COMP_KS", "1,4,8").split(","))
    DEPTHS = tuple(int(x) for x in os.environ.get(
        "OME_BENCH_COMP_DEPTHS", "0,1").split(","))

    log(f"bench: [composition] devices={jax.devices()}")
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    # ONE engine across all cells: each Scheduler brings its own
    # metrics registry and slot bookkeeping, so reusing the engine
    # amortizes the compile cache across the matrix
    eng = InferenceEngine(params, cfg, max_slots=SLOTS,
                          max_seq=CACHE_LEN, prefill_buckets=[16])

    def run_cell(spec, k_, depth):
        sched = Scheduler(eng, overlap=True, pipeline_depth=depth,
                          spec_tokens=spec, steps_per_dispatch=k_)
        sched.start()

        def batch(seed):
            rng = np.random.default_rng(seed)
            reqs = []
            for _ in range(SLOTS):
                pat = rng.integers(0, cfg.vocab_size, size=4)
                ids = [int(x) for x in np.tile(pat, 4)]
                reqs.append(sched.submit(Request(
                    prompt_ids=ids, max_new_tokens=NEW,
                    stop_ids=[])))
            for r in reqs:
                r.done.wait(timeout=600)
            assert all(r.done.is_set() for r in reqs), \
                f"cell spec{spec}_k{k_}_d{depth} stalled"

        batch(3)  # compile + reach the repetitive steady state
        p0 = sched.stats["spec_proposed_tokens_total"]
        a0 = sched.stats["spec_accepted_tokens_total"]
        t0 = time.perf_counter()
        batch(3)  # same prompts: the drafter's n-gram table is hot
        dt = time.perf_counter() - t0
        proposed = sched.stats["spec_proposed_tokens_total"] - p0
        accepted = sched.stats["spec_accepted_tokens_total"] - a0
        degr = dict(sched.degradations)
        sched.stop()
        return {
            "tokens_per_sec": round(SLOTS * NEW / dt, 1),
            "accept_rate": round(accepted / max(proposed, 1), 3),
            "spec": spec, "k": k_, "depth": depth,
            "degraded_steps": sum(degr.values()),
        }, degr

    cells = {}
    for spec in SPECS:
        for k_ in KS:
            for depth in DEPTHS:
                name = f"spec{spec}_k{k_}_d{depth}"
                cell, degr = run_cell(spec, k_, depth)
                cells[name] = cell
                extra = "".join(
                    f" {c}={n}" for c, n in degr.items() if n)
                log(f"bench: [composition] {name}: "
                    f"{cell['tokens_per_sec']:.1f} tok/s, accept "
                    f"{100 * cell['accept_rate']:.0f}%{extra}")
    # a "single-mechanism" cell enables at most one of the three
    # features; the composed cells must beat the best of them
    single = {n: c["tokens_per_sec"] for n, c in cells.items()
              if (c["spec"] > 0) + (c["k"] > 1) + (c["depth"] > 0) <= 1}
    composed = {n: c["tokens_per_sec"] for n, c in cells.items()
                if c["spec"] > 0 and c["k"] > 1 and c["depth"] > 0}
    best_single = max(single.values()) if single else 0.0
    best_composed = max(composed.values()) if composed else 0.0
    if single and composed:
        log(f"bench: [composition] best single-mechanism "
            f"{best_single:.1f} tok/s -> best composed "
            f"{best_composed:.1f} tok/s "
            f"({100 * best_composed / best_single - 100:+.0f}%)")
    print(json.dumps({"composition": {
        "cells": cells,
        "best_single_tokens_per_sec": round(best_single, 1),
        "best_composed_tokens_per_sec": round(best_composed, 1),
        "composed_vs_best_single": round(
            best_composed / max(best_single, 1e-9), 3),
    }}))


def structured_main() -> None:
    """`bench.py structured`: grammar-masked decode vs unmasked.

    Sweeps masked-slot share (0/50/100%) x steps-per-dispatch through
    the REAL Scheduler. Masked slots carry a JsonAutomaton TokenMasker
    (byte tokenizer, shared template so the grammar mask cache engages
    across requests); unmasked slots decode the same repetitive
    workload the composition sweep uses. The headline ratio is the
    100%-masked cell's tokens/sec over the 0%-masked cell's at the
    same K — the device-resident mask table (docs/structured-outputs.md)
    exists to keep that near 1.0, with the host-side `mask_apply`
    phase collapsing to cache lookups. perfgate bands every cell and
    the ratio under ^structured., and --cost-table exports the cells."""
    from ome_tpu.engine import ByteTokenizer
    from ome_tpu.engine.core import InferenceEngine
    from ome_tpu.engine.scheduler import Request, Scheduler
    from ome_tpu.engine.structured import JsonAutomaton, TokenMasker
    from ome_tpu.models import llama

    cfg = flagship_config()
    SLOTS = int(os.environ.get("OME_BENCH_STRUCT_SLOTS", "8"))
    NEW = int(os.environ.get("OME_BENCH_STRUCT_TOKENS", "48"))
    SHARES = tuple(int(x) for x in os.environ.get(
        "OME_BENCH_STRUCT_SHARES", "0,50,100").split(","))
    KS = tuple(int(x) for x in os.environ.get(
        "OME_BENCH_STRUCT_KS", "1,4").split(","))

    log(f"bench: [structured] devices={jax.devices()}")
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(params, cfg, max_slots=SLOTS,
                          max_seq=CACHE_LEN, prefill_buckets=[16])
    tok = ByteTokenizer()
    # the template automaton is pre-advanced into a JSON string: a
    # bare JsonAutomaton completes after one short greedy value
    # (`true`, `-3`) and eos-stops, leaving the cell prefill-bound;
    # inside a string every step is a real free grammar position —
    # long steady-state masked decode, the thing this sweep measures
    template_auto = JsonAutomaton()
    assert template_auto.advance(ord('"'))
    template = TokenMasker(tok, automaton=template_auto)

    def run_cell(share, k_):
        sched = Scheduler(eng, overlap=True, pipeline_depth=1,
                          steps_per_dispatch=k_)
        sched.start()
        n_masked = SLOTS * share // 100

        def batch(seed):
            rng = np.random.default_rng(seed)
            reqs = []
            for i in range(SLOTS):
                if i < n_masked:
                    reqs.append(sched.submit(Request(
                        prompt_ids=tok.encode(f"item {i}: "),
                        max_new_tokens=NEW,
                        masker=template.copy())))
                else:
                    pat = rng.integers(0, cfg.vocab_size, size=4)
                    ids = [int(x) for x in np.tile(pat, 4)]
                    reqs.append(sched.submit(Request(
                        prompt_ids=ids, max_new_tokens=NEW,
                        stop_ids=[])))
            for r in reqs:
                r.done.wait(timeout=600)
            assert all(r.done.is_set() for r in reqs), \
                f"cell share{share}_k{k_} stalled"
            return sum(len(r.output_ids) for r in reqs)

        batch(3)  # compile + warm the grammar mask cache
        best = 0.0
        mask_ms = 0.0
        for _ in range(TRIALS):  # host-noise dominated on CPU
            m0 = sched._ph_mask.sum
            t0 = time.perf_counter()
            produced = batch(3)
            dt = time.perf_counter() - t0
            if produced / dt > best:
                best = produced / dt
                mask_ms = (sched._ph_mask.sum - m0) * 1000
        degr = dict(sched.degradations)
        sched.stop()
        return {
            "tokens_per_sec": round(best, 1),
            "mask_apply_ms": round(mask_ms, 2),
            "share": share, "k": k_,
            "degraded_steps": sum(degr.values()),
        }

    cells = {}
    for share in SHARES:
        for k_ in KS:
            name = f"share{share}_k{k_}"
            cells[name] = run_cell(share, k_)
            log(f"bench: [structured] {name}: "
                f"{cells[name]['tokens_per_sec']:.1f} tok/s, "
                f"mask_apply {cells[name]['mask_apply_ms']:.2f} ms")
    # headline: fully-masked decode speed relative to unmasked at the
    # same K — the acceptance bar for device-resident masking is 0.9
    ratios = [cells[f"share100_k{k_}"]["tokens_per_sec"]
              / max(cells[f"share0_k{k_}"]["tokens_per_sec"], 1e-9)
              for k_ in KS
              if f"share100_k{k_}" in cells and f"share0_k{k_}" in cells]
    ratio = min(ratios) if ratios else 0.0
    mask_build = sum(c["mask_apply_ms"] for c in cells.values()
                     if c["share"] == 100)
    log(f"bench: [structured] structured_vs_unmasked "
        f"{ratio:.3f}, mask_build {mask_build:.2f} ms")
    print(json.dumps({"structured": {
        "cells": cells,
        "structured_vs_unmasked": round(ratio, 3),
        "mask_build_ms": round(mask_build, 2),
    }}))


def flagship_config():
    """~1.9B-parameter dense Llama-class config: big enough that
    decode is genuinely HBM-bound, small enough to fit one v5e chip
    (16G HBM) in bf16 with headroom for the KV cache.
    OME_BENCH_COMP_CONFIG=tiny swaps in the test config for smoke
    runs of the composition sweep off-TPU."""
    from ome_tpu.models import config as cfgs
    if os.environ.get("OME_BENCH_COMP_CONFIG") == "tiny":
        return cfgs.tiny_test().replace(max_seq_len=CACHE_LEN)
    return cfgs.ModelConfig(
        vocab_size=32768, hidden_size=2048, num_layers=24, num_heads=16,
        num_kv_heads=8, head_dim=128, intermediate_size=8192,
        rope_theta=500000.0, max_seq_len=CACHE_LEN)


def main() -> None:
    from ome_tpu.models import llama
    from ome_tpu.models.llama import (_layer, _proj, _rope_frequencies,
                                      apply_rope, attention, dense_mlp,
                                      rms_norm)
    from ome_tpu.models.quant import QTensor, quantize_params, \
        quantized_bytes

    cfg = flagship_config()

    log(f"bench: devices={jax.devices()}")
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    n_params = llama.param_count(params)
    log(f"bench: params={n_params/1e9:.2f}B")
    disp_ms = None  # measured after the first mode's compile+warmup
    # cold-start cost: first mode's prefill + decode compile+warm
    # wall time — the cost table's warmup_ms, which the fleet
    # simulator adds to replica spawn delay (sim/costmodel.py)
    warm_ms = None

    @jax.jit
    def prefill(params, tokens, cache):
        logits, cache = llama.forward(params, cfg, tokens, cache=cache)
        return jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32), cache

    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (BATCH, PREFILL), 0, cfg.vocab_size,
        dtype=jnp.int32)

    def split_layers(p):
        per = [jax.tree.map(lambda a: a[l], p["layers"])
               for l in range(cfg.num_layers)]
        top = {k: v for k, v in p.items() if k != "layers"}
        return per, top

    def head_logits(top, x):
        x = rms_norm(x, top["final_norm"], cfg.rms_norm_eps)
        head = top.get("lm_head")
        head = head.dequant(cfg.dtype) if isinstance(head, QTensor) \
            else head
        return jnp.einsum("bsd,dv->bsv", x, head,
                          preferred_element_type=jnp.float32)

    def embed(top, tok):
        emb = top["embed"]
        return emb.take(tok, cfg.dtype) if isinstance(emb, QTensor) \
            else jnp.take(emb, tok, axis=0).astype(cfg.dtype)

    def one_step(per, top, tok, ks, vs, index):
        """Unrolled decode step over per-layer cache planes."""
        B = tok.shape[0]
        x = embed(top, tok)
        freqs = _rope_frequencies(cfg)
        positions = jnp.broadcast_to(index[None, None], (B, 1))
        kv_len = jnp.broadcast_to(index + 1, (B,))
        nks, nvs = [], []
        for l in range(cfg.num_layers):
            x, nc = _layer(x, per[l], cfg, freqs, positions, kv_len,
                           (ks[l], vs[l]), index)
            nks.append(nc[0])
            nvs.append(nc[1])
        tok = jnp.argmax(head_logits(top, x), axis=-1).astype(jnp.int32)
        return tok, nks, nvs, index + 1

    @jax.jit
    def decode_k(per, top, tok, ks, vs, index):
        def body(carry, _):
            tok, ks, vs, index = carry
            return one_step(per, top, tok, *(ks, vs), index), None

        (tok, ks, vs, index), _ = lax.scan(
            body, (tok, ks, vs, index), None, length=MULTISTEP)
        return tok, ks, vs, index

    def one_step_floor(per, top, tok, ks, vs, index):
        """`one_step` with ONLY the KV-cache attention READ ablated.

        Same per-layer weight projections, same RoPE, same cache-plane
        writes, same sampling head, same carry structure — so `floor_k`
        below compiles to the IDENTICAL dispatch shape as `decode_k`
        (same ~300 buffers in/out, same 8-step scan, same jit-boundary
        cache copy), and `step - floor` isolates exactly the KV-cache
        stream + attention compute. Attention here runs over just the
        freshly written single token (the `cache_kv=None` shape of
        llama._mha), so q/k/v stay live and nothing is DCE'd.

        Round-4 verdict #1: the old floor scanned the STACKED layer
        tree with one step per dispatch, a different dispatch shape
        whose ~8 ms/call of host arg-marshaling landed in `weights_ms`
        and pushed the floor ABOVE the full step."""
        B = tok.shape[0]
        x = embed(top, tok)
        freqs = _rope_frequencies(cfg)
        positions = jnp.broadcast_to(index[None, None], (B, 1))
        nks, nvs = [], []
        for l in range(cfg.num_layers):
            lp = per[l]
            h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
            q = _proj(h, lp["wq"], cfg.dtype,
                      out_dims=(cfg.num_heads, cfg.head_dim))
            k = _proj(h, lp["wk"], cfg.dtype,
                      out_dims=(cfg.num_kv_heads, cfg.head_dim))
            v = _proj(h, lp["wv"], cfg.dtype,
                      out_dims=(cfg.num_kv_heads, cfg.head_dim))
            q = apply_rope(q, positions, freqs)
            k = apply_rope(k, positions, freqs)
            nks.append(lax.dynamic_update_slice(
                ks[l], k.astype(ks[l].dtype), (0, index, 0, 0)))
            nvs.append(lax.dynamic_update_slice(
                vs[l], v.astype(vs[l].dtype), (0, index, 0, 0)))
            # single-key softmax: no cache read; XLA backend — the
            # flash-decode kernel's grid assumes a real cache length
            attn = attention(q, k, v, backend="xla")
            a = _proj(attn, lp["wo"], cfg.dtype, flatten=2)
            x = x + a
            h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
            x = x + dense_mlp(h, lp, cfg)
        tok = jnp.argmax(head_logits(top, x), axis=-1).astype(jnp.int32)
        return tok, nks, nvs, index + 1

    @jax.jit
    def floor_k(per, top, tok, ks, vs, index):
        def body(carry, _):
            tok, ks, vs, index = carry
            return one_step_floor(per, top, tok, ks, vs, index), None

        (tok, ks, vs, index), _ = lax.scan(
            body, (tok, ks, vs, index), None, length=MULTISTEP)
        return tok, ks, vs, index

    def mode_bytes(p) -> int:
        return quantized_bytes(p)

    def run_mode(p, label: str):
        """-> (tok/s, step_ms, weights_ms, attn_ms)."""
        nonlocal disp_ms, warm_ms
        per, top = split_layers(p)
        t0 = time.perf_counter()
        tok, cache = prefill(p, prompt,
                             llama.KVCache.create(cfg, BATCH, CACHE_LEN))
        ks = [cache.k[l] for l in range(cfg.num_layers)]
        vs = [cache.v[l] for l in range(cfg.num_layers)]
        index = cache.index
        st = decode_k(per, top, tok, ks, vs, index)  # compile
        sync(st[0])
        log(f"bench: [{label}] prefill(batch={BATCH}, len={PREFILL}) "
            f"+ compile {time.perf_counter()-t0:.1f}s")
        if warm_ms is None:
            warm_ms = (time.perf_counter() - t0) * 1000
        if disp_ms is None:
            disp_ms = dispatch_ms()
            log(f"bench: dispatch floor {disp_ms:.2f} ms")

        n_disp = (DECODE_STEPS - 1) // MULTISTEP
        steps = n_disp * MULTISTEP
        best = float("inf")
        for _ in range(TRIALS):
            tok, cache = prefill(
                p, prompt, llama.KVCache.create(cfg, BATCH, CACHE_LEN))
            ks = [cache.k[l] for l in range(cfg.num_layers)]
            vs = [cache.v[l] for l in range(cfg.num_layers)]
            st = (tok, ks, vs, cache.index)
            st = decode_k(per, top, *st)  # warm, not timed
            sync(st[0])
            t0 = time.perf_counter()
            for _ in range(n_disp - 1):
                st = decode_k(per, top, *st)
            sync(st[0])
            best = min(best, time.perf_counter() - t0)
        step_ms = best / ((n_disp - 1) * MULTISTEP) * 1000
        tps = BATCH / (step_ms / 1000)

        # weights+sampling floor: floor_k is decode_k with only the
        # KV-cache read ablated, measured over the SAME chained
        # dispatch loop — floor and full step share an identical
        # dispatch shape, so step - floor isolates attention/KV
        fbest = float("inf")
        for _ in range(TRIALS):
            tok2, cache2 = prefill(
                p, prompt, llama.KVCache.create(cfg, BATCH, CACHE_LEN))
            ks2 = [cache2.k[l] for l in range(cfg.num_layers)]
            vs2 = [cache2.v[l] for l in range(cfg.num_layers)]
            st2 = (tok2, ks2, vs2, cache2.index)
            st2 = floor_k(per, top, *st2)  # warm/compile, not timed
            sync(st2[0])
            t0 = time.perf_counter()
            for _ in range(n_disp - 1):
                st2 = floor_k(per, top, *st2)
            sync(st2[0])
            fbest = min(fbest, time.perf_counter() - t0)
        floor_ms = fbest / ((n_disp - 1) * MULTISTEP) * 1000
        weights_ms = max(floor_ms - disp_ms / MULTISTEP, 0.0)
        attn_ms = max(step_ms - floor_ms, 0.0)
        log(f"bench: [{label}] decode {steps} x batch {BATCH}: best-of-"
            f"{TRIALS} {step_ms:.2f} ms/step -> {tps:.1f} tok/s "
            f"(weights {weights_ms:.2f} + attn/kv {attn_ms:.2f} + "
            f"dispatch {disp_ms/MULTISTEP:.2f})")
        return tps, step_ms, weights_ms, attn_ms

    # -- bf16 headline --------------------------------------------------
    bf16_tps, bf16_step, bf16_w, bf16_attn = run_mode(params, "bf16")

    # -- decode-loop step gap: sync fetch vs pipelined offload ----------
    # The serving scheduler's host bubble (the quantity its
    # ome_engine_step_gap_seconds histogram tracks): time from one
    # decode dispatch RETURNING to the next one STARTING. "sync"
    # fetches each dispatch's tokens before dispatching again (the
    # --pipeline-depth 0 loop); "pipelined" starts an async host copy
    # and reads tokens one dispatch LATE (depth 1), so the fetch
    # overlaps device execution instead of serializing with it.
    def step_gap_ms(pipelined: bool) -> float:
        per, top = split_layers(params)
        tok, cache = prefill(params, prompt,
                             llama.KVCache.create(cfg, BATCH, CACHE_LEN))
        ks = [cache.k[l] for l in range(cfg.num_layers)]
        vs = [cache.v[l] for l in range(cfg.num_layers)]
        st = (tok, ks, vs, cache.index)
        st = decode_k(per, top, *st)  # warm, not timed
        sync(st[0])
        n_disp = (DECODE_STEPS - 1) // MULTISTEP
        gaps, disp_end, pending = [], None, None
        for _ in range(n_disp - 1):
            t0 = time.perf_counter()
            if disp_end is not None:
                gaps.append(t0 - disp_end)
            st = decode_k(per, top, *st)
            disp_end = time.perf_counter()
            toks = st[0]
            if pipelined:
                copy = getattr(toks, "copy_to_host_async", None)
                if copy is not None:
                    copy()
                if pending is not None:
                    np.asarray(jax.device_get(pending))
                pending = toks
            else:
                np.asarray(jax.device_get(toks))
        if pending is not None:
            np.asarray(jax.device_get(pending))
        return sum(gaps) / max(len(gaps), 1) * 1000

    gap_sync = step_gap_ms(False)
    gap_pipe = step_gap_ms(True)
    log(f"bench: [bf16] decode {bf16_tps:.1f} tok/s | mean step gap "
        f"{gap_sync:.2f} ms/dispatch sync-fetch -> {gap_pipe:.2f} ms "
        f"pipelined (async token offload, one-dispatch lag)")

    # -- steady-state prefill (TTFT proxy) + MFU ------------------------
    cache2 = llama.KVCache.create(cfg, BATCH, CACHE_LEN)
    prompt2 = jax.random.randint(jax.random.PRNGKey(2), (BATCH, PREFILL),
                                 0, cfg.vocab_size, dtype=jnp.int32)
    sync(prefill(params, prompt2, cache2)[0])
    pbest = float("inf")
    for _ in range(TRIALS):
        # 4 chained prefill dispatches, ONE sync: amortizes the
        # tunnel's result-fetch latency out of the per-call number
        t0 = time.perf_counter()
        for _ in range(4):
            t, _ = prefill(params, prompt2, cache2)
        sync(t)
        pbest = min(pbest, (time.perf_counter() - t0) / 4)
    T = BATCH * PREFILL
    pf_flops = 2 * n_params * T + 2 * cfg.num_layers * BATCH * (
        PREFILL ** 2) * cfg.num_heads * cfg.head_dim
    peak = _lookup(PEAK_TFLOPS) * 1e12
    mfu = pf_flops / pbest / peak
    log(f"bench: steady prefill {pbest*1000:.0f} ms "
        f"({T/pbest:.0f} prefill tok/s, MFU {100*mfu:.1f}%)")
    del cache2, prompt2

    # -- quantized serving paths (engine --quantization int8/int4) -----
    q8 = quantize_params(params, mode="int8")
    q8_bytes = mode_bytes(q8)
    int8_tps, int8_step, int8_w, int8_attn = run_mode(q8, "int8")
    del q8
    q4 = quantize_params(params, mode="int4")
    q4_bytes = mode_bytes(q4)
    int4_tps, int4_step, int4_w, int4_attn = run_mode(q4, "int4")
    del q4
    log(f"bench: int8 {int8_tps:.1f} tok/s "
        f"({100*int8_tps/bf16_tps-100:+.0f}% vs bf16, "
        f"{q8_bytes/1e9:.2f} GB weights) | int4 {int4_tps:.1f} tok/s "
        f"({100*int4_tps/bf16_tps-100:+.0f}%, {q4_bytes/1e9:.2f} GB)")

    # -- paged-KV decode sweep: batch x pool dtype ----------------------
    # Measures the paged KERNEL PATH (ops/paged.py block-table
    # attention + pool scatter) in this bench's unrolled+multistep
    # harness — the shape that amortizes the tunnel dispatch — across
    # batch {64, 128, 256} and pool dtype {bf16, int8}. The serving
    # engine's compiled program (llama.forward_paged: scan over
    # layers, token-exactness in tests/test_paged_kv.py and
    # tests/test_kv_int8.py) shares the kernels but not the unroll;
    # these numbers bound what that program reaches as its dispatch
    # amortization improves. Pool sized to dense-equivalent rows per
    # point, so the int8 column shows the --kv-dtype int8 trade the
    # engine offers: ~half the HBM per slot (per-token row is
    # L*K*(Dk+Dv) int8 bytes + 2*4 f32 scale bytes/head vs
    # L*K*(Dk+Dv)*2 bf16 — a 1.94x ratio at Dh=128) buys roughly
    # double the resident batch at fixed pool bytes, and the sweep
    # shows what that larger batch yields in tok/s.
    def bench_paged(p, PB: int, quantized: bool):
        """-> (tok/s, HBM bytes per decode slot at CACHE_LEN)."""
        from ome_tpu.ops.paged import paged_attention
        bs = 128
        bps = CACHE_LEN // bs               # blocks per slot
        nblk = PB * bps + 1
        per, top = split_layers(p)
        rows = jnp.arange(PB)
        # slot i owns blocks [1 + bps*i, ...] — block 0 is trash
        table = jnp.asarray(
            np.arange(PB * bps).reshape(PB, bps) + 1, jnp.int32)

        def one_step_paged(per, top, tok, ks, vs, kss, vss, index):
            x = embed(top, tok)
            freqs = _rope_frequencies(cfg)
            positions = index[:, None]
            kv_len = index + 1
            blk = table[rows, index // bs]
            off = index % bs
            nks, nvs, nkss, nvss = [], [], [], []
            for l in range(cfg.num_layers):
                lp = per[l]
                h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
                q = _proj(h, lp["wq"], cfg.dtype,
                          out_dims=(cfg.num_heads, cfg.head_dim))
                k = _proj(h, lp["wk"], cfg.dtype,
                          out_dims=(cfg.num_kv_heads, cfg.head_dim))
                v = _proj(h, lp["wv"], cfg.dtype,
                          out_dims=(cfg.num_kv_heads, cfg.head_dim))
                q = apply_rope(q, positions, freqs)
                k = apply_rope(k, positions, freqs)
                if quantized:
                    # per-(row, head) amax/127 symmetric — the same
                    # discipline as llama.forward_paged's append
                    def qrow(x2):
                        xf = x2[:, 0].astype(jnp.float32)
                        amax = jnp.max(jnp.abs(xf), axis=-1)
                        sc = jnp.maximum(amax, 1e-8) / 127.0
                        qv = jnp.clip(jnp.round(xf / sc[..., None]),
                                      -127, 127).astype(jnp.int8)
                        return qv, sc
                    kq, ksc = qrow(k)
                    vq, vsc = qrow(v)
                    kp = ks[l].at[blk, off].set(kq)
                    vp = vs[l].at[blk, off].set(vq)
                    ksp = kss[l].at[blk, :, off].set(ksc)
                    vsp = vss[l].at[blk, :, off].set(vsc)
                else:
                    kp = ks[l].at[blk, off].set(k[:, 0])
                    vp = vs[l].at[blk, off].set(v[:, 0])
                    ksp = vsp = None
                nks.append(kp)
                nvs.append(vp)
                nkss.append(ksp)
                nvss.append(vsp)
                attn = paged_attention(q, kp, vp, table, kv_len,
                                       k_scale=ksp, v_scale=vsp)
                x = x + _proj(attn, lp["wo"], cfg.dtype, flatten=2)
                h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
                x = x + dense_mlp(h, lp, cfg)
            tok = jnp.argmax(head_logits(top, x),
                             axis=-1).astype(jnp.int32)
            return tok, nks, nvs, nkss, nvss, index + 1

        @jax.jit
        def paged_k(per, top, tok, ks, vs, kss, vss, index):
            def body(carry, _):
                return one_step_paged(per, top, *carry), None

            carry, _ = lax.scan(body, (tok, ks, vs, kss, vss, index),
                                None, length=MULTISTEP)
            return carry

        K, Dh = cfg.num_kv_heads, cfg.head_dim
        pool_dt = jnp.int8 if quantized else cfg.dtype
        ks = [jnp.zeros((nblk, bs, K, Dh), pool_dt)
              for _ in range(cfg.num_layers)]
        vs = [jnp.zeros((nblk, bs, K, Dh), pool_dt)
              for _ in range(cfg.num_layers)]
        kss = [jnp.zeros((nblk, K, bs), jnp.float32) if quantized
               else None for _ in range(cfg.num_layers)]
        vss = [jnp.zeros((nblk, K, bs), jnp.float32) if quantized
               else None for _ in range(cfg.num_layers)]
        tok0 = jnp.zeros((PB, 1), jnp.int32)
        index0 = jnp.full((PB,), PREFILL, jnp.int32)
        n_disp = (DECODE_STEPS - 1) // MULTISTEP
        best = float("inf")
        for _ in range(2):
            st = (tok0, ks, vs, kss, vss, index0)
            st = paged_k(per, top, *st)  # compile/warm
            sync(st[0])
            t0 = time.perf_counter()
            for _ in range(n_disp - 1):
                st = paged_k(per, top, *st)
            sync(st[0])
            best = min(best, time.perf_counter() - t0)
        step_ms = best / ((n_disp - 1) * MULTISTEP) * 1000
        itemsize = jnp.dtype(pool_dt).itemsize
        row_bytes = cfg.num_layers * K * 2 * Dh * itemsize
        if quantized:
            row_bytes += cfg.num_layers * K * 2 * 4  # f32 scales
        return PB / (step_ms / 1000), row_bytes * bps * bs

    paged_sweep = {}
    paged_tps = None
    for qlabel, qz in (("bf16", False), ("int8", True)):
        paged_sweep[qlabel] = {}
        for PB in (64, 128, 256):
            try:
                tps, slot_bytes = bench_paged(params, PB, qz)
            except Exception as exc:  # larger points may not fit HBM
                log(f"bench: [paged {qlabel}] batch {PB} skipped: "
                    f"{exc!r}")
                continue
            paged_sweep[qlabel][str(PB)] = {
                "tokens_per_sec": round(tps, 1),
                "hbm_per_slot_bytes": int(slot_bytes),
            }
            log(f"bench: [paged {qlabel}] decode batch {PB}: "
                f"{tps:.1f} tok/s, {slot_bytes/1e6:.1f} MB/slot "
                f"(block-table pool attention)")
            if qlabel == "bf16" and PB == 64:
                paged_tps = tps
    if paged_tps is None:
        raise RuntimeError("paged bf16 batch-64 point failed — the "
                           "headline paged metric has no value")
    try:
        cap_ratio = (paged_sweep["bf16"]["64"]["hbm_per_slot_bytes"]
                     / paged_sweep["int8"]["64"]["hbm_per_slot_bytes"])
        paged_sweep["capacity_ratio_bf16_over_int8"] = round(
            cap_ratio, 3)
        log(f"bench: [paged] int8 pool fits {cap_ratio:.2f}x the "
            f"slots of bf16 at fixed HBM bytes")
    except (KeyError, ZeroDivisionError):
        pass

    # -- self-drafting speculative decode (engine verify path) ----------
    # Measures the SERVING engine's n-gram draft + batched-verify loop
    # (engine/spec.py + InferenceEngine.verify — the --spec-tokens
    # path) against the same engine's plain decode loop, on a
    # high-n-gram-hit workload: after a greedy warmup the random-weight
    # streams settle into short cycles (as repetitive serving traffic
    # does), so the prompt-lookup drafter proposes the continuation
    # and the verify forward accepts most of it — one weight read
    # yields several tokens per slot.
    def bench_spec(p):
        from ome_tpu.engine import spec as spec_drafter
        from ome_tpu.engine.core import InferenceEngine

        K_SPEC = int(os.environ.get("OME_BENCH_SPEC_K", "4"))
        SLOTS = BATCH
        WARM, MEAS = 40, 24  # rows: 17 + 40 + 24 + 5 + 24*5 <= 256
        eng = InferenceEngine(p, cfg, max_slots=SLOTS,
                              max_seq=CACHE_LEN, prefill_buckets=[16])
        state = eng.new_state()
        rng = np.random.default_rng(7)
        streams = []
        for s in range(SLOTS):
            pat = rng.integers(0, cfg.vocab_size, size=4)
            ids = [int(x) for x in np.tile(pat, 4)]  # 16-token prompt
            tok, kv, true_len, bucket = eng.prefill(ids)
            state = eng.insert(state, kv, s, true_len, tok, bucket)
            streams.append(ids + [tok])
        B = SLOTS
        t = np.zeros((B,), np.float32)
        tk = np.zeros((B,), np.int32)
        tp = np.ones((B,), np.float32)
        for _ in range(WARM):  # reach the repetitive steady state
            state, toks = eng.decode(state, t, tk, tp)
            for s, v in enumerate(np.asarray(toks)):
                streams[s].append(int(v))
        # plain decode tok/s, sync fetch per step (depth-0 loop shape)
        t0 = time.perf_counter()
        for _ in range(MEAS):
            state, toks = eng.decode(state, t, tk, tp)
            for s, v in enumerate(np.asarray(toks)):
                streams[s].append(int(v))
        plain_tps = SLOTS * MEAS / (time.perf_counter() - t0)

        def spec_step():
            drafts = np.zeros((B, K_SPEC), np.int32)
            dlen = np.zeros((B,), np.int32)
            for s in range(B):
                d = spec_drafter.propose(streams[s], K_SPEC)
                drafts[s, :d.size] = d
                dlen[s] = d.size
            nonlocal state
            state, out, acc = eng.verify(state, drafts, dlen, t, tk, tp)
            host_out, host_acc = np.asarray(out), np.asarray(acc)
            emitted = 0
            for s in range(B):
                n = int(host_acc[s]) + 1
                streams[s].extend(int(x) for x in host_out[s, :n])
                emitted += n
            return int(dlen.sum()), int(host_acc.sum()), emitted

        spec_step()  # compile the verify program, not timed
        proposed = accepted = emitted = 0
        t0 = time.perf_counter()
        for _ in range(MEAS):
            pr, ac, em = spec_step()
            proposed += pr
            accepted += ac
            emitted += em
        spec_tps = emitted / (time.perf_counter() - t0)
        return plain_tps, spec_tps, accepted / max(proposed, 1), K_SPEC

    spec_plain_tps, spec_tps, spec_rate, spec_k = bench_spec(params)
    log(f"bench: [spec] k={spec_k} batch {BATCH}: plain "
        f"{spec_plain_tps:.1f} tok/s -> spec {spec_tps:.1f} tok/s "
        f"({100*spec_tps/spec_plain_tps-100:+.0f}%, accept rate "
        f"{100*spec_rate:.0f}%)")

    # -- engine multi-token device decode (--steps-per-dispatch K) ------
    # The SERVING engine's fused decode loop (InferenceEngine
    # .decode_multi: lax.fori_loop over {forward, sample, KV append}
    # with on-device stop/budget masking — docs/multi-step-decode.md).
    # The raw decode_k harness above already proves the shape wins;
    # this sweep measures the REAL engine program — jit-boundary state
    # donation, per-iteration PRNG fold, stop-table compare — at
    # K in {1, 4, 8}. Per-token dispatch share falls as disp_ms / K
    # while step_ms approaches the device-bound floor; the scheduler
    # exposes the same knob as --steps-per-dispatch.
    def bench_multistep(p):
        from ome_tpu.engine.core import InferenceEngine

        SLOTS = BATCH
        eng = InferenceEngine(p, cfg, max_slots=SLOTS,
                              max_seq=CACHE_LEN, prefill_buckets=[16])
        state = eng.new_state()
        rng = np.random.default_rng(13)
        for s in range(SLOTS):
            ids = [int(x) for x in
                   rng.integers(0, cfg.vocab_size, size=16)]
            tok, kv, true_len, bucket = eng.prefill(ids)
            state = eng.insert(state, kv, s, true_len, tok, bucket)
        t = np.zeros((SLOTS,), np.float32)         # greedy
        tk = np.zeros((SLOTS,), np.int32)
        tp = np.ones((SLOTS,), np.float32)
        stops = np.full((SLOTS, 1), -1, np.int32)  # never fires
        per_k = {}
        for k_ in (1, 4, 8):
            budget = np.full((SLOTS,), k_, np.int32)
            n_disp = 48 // k_      # same 48 timed tokens per K
            # compile + warm dispatch, not timed (state donation flows
            # through, as in the scheduler's lag queue)
            state, toks, _adv = eng.decode_multi(
                state, t, tk, tp, steps=k_, budget=budget,
                stop_ids=stops)
            sync(toks)
            t0 = time.perf_counter()
            for _ in range(n_disp):
                state, toks, _adv = eng.decode_multi(
                    state, t, tk, tp, steps=k_, budget=budget,
                    stop_ids=stops)
            sync(toks)
            step_ms = (time.perf_counter() - t0) / (n_disp * k_) * 1000
            per_k[k_] = step_ms
            log(f"bench: [multistep] K={k_}: {step_ms:.2f} ms/token -> "
                f"{SLOTS/(step_ms/1000):.1f} tok/s (dispatch share "
                f"{disp_ms/k_:.3f} ms/token)")
        return per_k

    try:
        multistep_ms = bench_multistep(params)
    except Exception as exc:  # keep the headline alive off-TPU
        log(f"bench: [multistep] skipped: {exc!r}")
        multistep_ms = {}

    # -- scheduler step-phase attribution -------------------------------
    # Drives the SERVING scheduler (pipelined decode, depth 1) over the
    # real engine and reads back its ome_engine_step_phase_seconds
    # histograms — the same per-phase attribution an operator scrapes
    # from /metrics, here reduced to a mean-ms-per-step table. The
    # phases partition decode_step + step_gap: dispatch (the compiled
    # decode call), mask_apply (grammar masks; zero in this unmasked
    # workload), device_wait (blocking at the lag-queue token read),
    # host_sample (emit/finish bookkeeping after the read).
    def bench_step_phases(p):
        from ome_tpu.engine.core import InferenceEngine
        from ome_tpu.engine.scheduler import Request, Scheduler

        SLOTS = 8
        eng = InferenceEngine(p, cfg, max_slots=SLOTS,
                              max_seq=CACHE_LEN, prefill_buckets=[16])
        sched = Scheduler(eng, overlap=True, pipeline_depth=1)
        sched.start()
        rng = np.random.default_rng(11)
        reqs = []
        for _ in range(SLOTS):
            ids = [int(x) for x in
                   rng.integers(0, cfg.vocab_size, size=16)]
            reqs.append(sched.submit(
                Request(prompt_ids=ids, max_new_tokens=48)))
        for r in reqs:
            r.done.wait(timeout=300)
        phases = {}
        for name in ("dispatch", "mask_apply", "device_wait",
                     "host_sample"):
            child = sched._h_step_phase.labels(phase=name)
            phases[name] = (child.sum, child.count)
        step_sum = sched._h_decode_step.sum + sched._h_step_gap.sum
        steps = max(sched._h_decode_step.count, 1)
        sched.stop()
        return phases, step_sum, steps

    phase_raw, phase_step_sum, phase_steps = bench_step_phases(params)
    phase_total = sum(s for s, _ in phase_raw.values())
    step_phase_ms = {}
    log(f"bench: [phases] per-step attribution over {phase_steps} "
        f"scheduler steps (ome_engine_step_phase_seconds):")
    log(f"bench:   {'phase':<12} {'mean ms':>9} {'share':>7}")
    for name, (s, _count) in phase_raw.items():
        mean_ms = s / phase_steps * 1000
        step_phase_ms[name] = round(mean_ms, 3)
        share = s / phase_total if phase_total else 0.0
        log(f"bench:   {name:<12} {mean_ms:9.3f} {100*share:6.1f}%")
    phase_cov = phase_total / max(phase_step_sum, 1e-9)
    log(f"bench:   phase sum covers {100*phase_cov:.0f}% of "
        f"decode_step + step_gap")

    # -- rooflines ------------------------------------------------------
    # Per decode step the chip must read all weights once (amortized
    # across the batch) + each sequence's KV cache.
    bw_spec = _lookup(HBM_GBPS)
    bf16_bytes = n_params * 2
    # the achievable anchor IS the weights floor: a weights-shaped
    # stream through the real matmul graph, not a synthetic probe
    bw_ach = bf16_bytes / (max(bf16_w, 1e-3) / 1000) / 1e9
    kv_bytes = (cfg.num_layers * CACHE_LEN * cfg.num_kv_heads * cfg.head_dim
                * 2 * 2)  # k+v, bf16, per sequence, full capacity
    # TRUE bytes moved: the flash-decode kernel DMA-clamps K/V reads to
    # the valid rows (ops/flash.py BlockSpec index clamp), so the
    # effective-bandwidth number uses the AVERAGE valid KV length over
    # the timed window — not cache capacity (round-4 verdict: the
    # anchor must sit at or above what decode itself sustains)
    t_lo = PREFILL + MULTISTEP          # first timed step (post-warm)
    t_hi = PREFILL + MULTISTEP * ((DECODE_STEPS - 1) // MULTISTEP)
    avg_kv = (t_lo + t_hi) / 2
    kv_bytes_true = kv_bytes * avg_kv / CACHE_LEN
    step_bytes = bf16_bytes + BATCH * kv_bytes  # capacity (vs_baseline)
    eff_gbps = (bf16_bytes + BATCH * kv_bytes_true) \
        * bf16_tps / BATCH / 1e9
    roof_spec = bw_spec * 1e9 / step_bytes * BATCH
    roof_ach = bw_ach * 1e9 / step_bytes * BATCH
    vs = bf16_tps / roof_spec
    vs_ach = bf16_tps / roof_ach

    log(f"bench: decode effective {eff_gbps:.0f} GB/s | achievable "
        f"(weights-stream anchor) {bw_ach:.0f} GB/s | spec {bw_spec:.0f}")
    log(f"bench: roofline vs spec {100*vs:.1f}% | vs achievable "
        f"{100*vs_ach:.1f}%")
    multistep_json = {}
    for k_, sm in multistep_ms.items():
        tps_k = BATCH / (sm / 1000)
        multistep_json[str(k_)] = {
            "step_ms": round(sm, 2),
            "tokens_per_sec": round(tps_k, 1),
            "dispatch_share_ms": round(disp_ms / k_, 3),
            "roofline_vs_spec": round(tps_k / roof_spec, 3),
        }
    print(json.dumps({
        "metric": "decode_tokens_per_sec_1.9B_bf16_batch32",
        "value": round(bf16_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs, 3),
        "vs_achievable": round(vs_ach, 3),
        "best_of": TRIALS,
        "int8_tokens_per_sec": round(int8_tps, 1),
        "int4_tokens_per_sec": round(int4_tps, 1),
        "paged_decode_tokens_per_sec_batch64": round(paged_tps, 1),
        "paged_sweep": paged_sweep,
        "spec_decode_tokens_per_sec": round(spec_tps, 1),
        "spec_accept_rate": round(spec_rate, 3),
        "spec_plain_tokens_per_sec": round(spec_plain_tps, 1),
        "spec_k": spec_k,
        "multistep": multistep_json,
        "int4_vs_int8": {
            "int4_tokens_per_sec": round(int4_tps, 1),
            "int8_tokens_per_sec": round(int8_tps, 1),
            "int4_ahead": bool(int4_tps > int8_tps),
            "note": ("int4 must beat int8 (0.5 vs 1 byte/weight of "
                     "HBM traffic); parity of the two step floors "
                     "means the fused kernel gate dropped out — see "
                     "ops/int4_matmul._on_tpu_device (BENCH_r05)"),
        },
        "prefill_ms_batch32x128": round(pbest * 1000, 1),
        "prefill_mfu": round(mfu, 3),
        "dispatch_ms": round(disp_ms, 2),
        "warmup_ms": round(warm_ms or 0.0, 1),
        "step_phase_ms": step_phase_ms,
        "step_phase_coverage": round(phase_cov, 3),
        "decode_step_gap_ms": {"sync": round(gap_sync, 2),
                               "pipelined": round(gap_pipe, 2)},
        "achievable_gbps": round(bw_ach, 1),
        "decode_effective_gbps": round(eff_gbps, 1),
        "decode_ms_breakdown": {
            m: {"step": round(s, 2), "weights_sampling": round(w, 2),
                "attn_kv": round(a, 2),
                "dispatch": round(disp_ms / MULTISTEP, 2)}
            for m, (s, w, a) in {
                "bf16": (bf16_step, bf16_w, bf16_attn),
                "int8": (int8_step, int8_w, int8_attn),
                "int4": (int4_step, int4_w, int4_attn)}.items()},
    }))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "composition":
        composition_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "structured":
        structured_main()
    else:
        main()
