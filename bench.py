#!/usr/bin/env python
"""Driver benchmark: sustained decode throughput of the flagship model.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} plus
context keys (int8/int4 throughput, measured HBM bandwidth, roofline
fractions).

The reference (bcfre/ome) publishes no hardware numbers (BASELINE.md) —
its headline metric is BenchmarkJob *output tokens/sec* against a served
InferenceService (SURVEY.md §6). This bench measures the same quantity
at the layer we own end-to-end on one chip: batched autoregressive
decode tokens/sec of the flagship Llama-class model with a KV cache.

Robustness (round-2 review): every timing is best-of-N trials, so a
single noisy-bandwidth window on the shared/tunneled chip cannot sink
the headline; the quantized paths ship in the parsed JSON, not just
stderr; and the measured-bandwidth anchor is a dedicated HBM
copy microbenchmark (read+write streams, best-of-N) rather than a
reduction sum.

`vs_baseline` is the fraction of the chip's spec HBM-bandwidth roofline
(decode is bandwidth-bound: every generated token must stream all
weights + the KV cache once), so 1.0 == perfect memory-bound decode.
It is kept spec-anchored for round-over-round comparability;
`vs_measured_roofline` reports the same fraction against the measured
copy bandwidth (the environment's real ceiling).
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def sync(x):
    """Force completion. On the axon-tunneled TPU backend
    jax.block_until_ready returns before execution finishes; only a
    device->host fetch truly synchronizes, so time through a fetch."""
    jax.block_until_ready(x)
    return np.asarray(jax.device_get(x))

# Per-chip HBM bandwidth (GB/s) by TPU generation; CPU fallback uses a
# nominal DDR figure so the ratio stays defined in dev environments.
HBM_GBPS = {"v5 lite": 819.0, "v5e": 819.0, "v5p": 2765.0, "v6e": 1640.0,
            "v4": 1228.0, "cpu": 50.0}

BATCH = 32
PREFILL = 128
DECODE_STEPS = 128
CACHE_LEN = PREFILL + DECODE_STEPS
TRIALS = 3


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def device_bandwidth() -> float:
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", d.platform).lower()
    for key, bw in HBM_GBPS.items():
        if key in kind:
            return bw
    return HBM_GBPS["cpu" if d.platform == "cpu" else "v5e"]


def copy_bandwidth() -> float:
    """Best-of-N HBM copy bandwidth (GB/s): y = x + 1 over a 1 GB
    buffer streams 1 GB read + 1 GB write. A dedicated copy benchmark
    (not a reduction) is the conventional STREAM anchor; best-of-N
    because the tunneled chip's effective bandwidth swings run-to-run.

    Caveat (measured, round 3): on the axon tunnel EVERY standalone
    streaming probe tried — XLA elementwise copy, matvec weight read,
    a Pallas DMA copy kernel — reads 10-20 GB/s while the model's own
    decode sustains ~400 GB/s over the same HBM, i.e. the harness
    penalizes single giant ops, not the chip. The caller therefore
    anchors the measured roofline at max(this probe, decode-effective
    bandwidth) so the instrument can't under-read the ceiling."""
    n = int(1e9)
    x = jnp.ones((n,), jnp.int8)
    f = jax.jit(lambda x: x + jnp.int8(1))
    first = jax.jit(lambda y: y.ravel()[0])
    y = f(x)
    # block_until_ready lies on axon; a jitted scalar extract + fetch
    # is the only true sync (an eager y[:1] slice fetches the buffer)
    np.asarray(jax.device_get(first(y)))
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        y = f(x)
        np.asarray(jax.device_get(first(y)))
        best = min(best, time.perf_counter() - t0)
    return 2 * n / best / 1e9


def best_of(trials: int, run) -> float:
    """Min wall-time over `trials` runs of `run()` (run syncs itself)."""
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    from ome_tpu.models import config as cfgs
    from ome_tpu.models import llama

    # ~1.9B-parameter dense Llama-class config: big enough that decode is
    # genuinely HBM-bound, small enough to fit one v5e chip (16G HBM)
    # in bf16 with headroom for the KV cache.
    cfg = cfgs.ModelConfig(
        vocab_size=32768, hidden_size=2048, num_layers=24, num_heads=16,
        num_kv_heads=8, head_dim=128, intermediate_size=8192,
        rope_theta=500000.0, max_seq_len=CACHE_LEN)

    log(f"bench: devices={jax.devices()}")
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    n_params = llama.param_count(params)
    log(f"bench: params={n_params/1e9:.2f}B")

    # NOTE: measured on the axon-tunneled chip, buffer donation and
    # multi-step lax.scan/unrolled decode are all SLOWER than a plain
    # python dispatch loop (donation ~-20%, scan ~-60%); keep the
    # simple form the backend executes best.
    @jax.jit
    def prefill(params, tokens, cache):
        logits, cache = llama.forward(params, cfg, tokens, cache=cache)
        return jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32), cache

    @jax.jit
    def decode(params, tokens, cache):
        logits, cache = llama.forward(params, cfg, tokens, cache=cache)
        return jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32), cache

    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (BATCH, PREFILL), 0, cfg.vocab_size,
        dtype=jnp.int32)

    def decode_toks_per_s(p, label: str) -> float:
        """Compile + warm up, then best-of-TRIALS decode throughput.
        Each trial restarts from a fresh prefilled cache so every trial
        times the identical program state (no write index past
        CACHE_LEN)."""
        t0 = time.perf_counter()
        tok, cache = prefill(p, prompt,
                             llama.KVCache.create(cfg, BATCH, CACHE_LEN))
        tok, cache = decode(p, tok, cache)  # compile decode too
        sync(tok)
        log(f"bench: [{label}] prefill(batch={BATCH}, len={PREFILL}) "
            f"+ compile {time.perf_counter()-t0:.1f}s")
        steps = DECODE_STEPS - 1
        best = float("inf")
        for _ in range(TRIALS):
            tok, cache = prefill(
                p, prompt, llama.KVCache.create(cfg, BATCH, CACHE_LEN))
            tok, cache = decode(p, tok, cache)  # warm, not timed
            sync(tok)
            t0 = time.perf_counter()
            for _ in range(steps):
                tok, cache = decode(p, tok, cache)
            sync(tok)
            best = min(best, time.perf_counter() - t0)
        tps = BATCH * steps / best
        log(f"bench: [{label}] decode {steps} steps x batch {BATCH}: "
            f"best-of-{TRIALS} {best:.2f}s -> {tps:.1f} tok/s")
        return tps

    # -- bf16 headline + steady-state prefill (TTFT proxy) -------------
    toks_per_s = decode_toks_per_s(params, "bf16")

    cache2 = llama.KVCache.create(cfg, BATCH, CACHE_LEN)
    prompt2 = jax.random.randint(jax.random.PRNGKey(2), (BATCH, PREFILL),
                                 0, cfg.vocab_size, dtype=jnp.int32)

    def run_prefill():
        t, _ = prefill(params, prompt2, cache2)
        sync(t)

    ttft = best_of(TRIALS, run_prefill)
    log(f"bench: steady prefill {ttft*1000:.0f} ms "
        f"({BATCH*PREFILL/ttft:.0f} prefill tok/s)")
    del cache2, prompt2

    # -- quantized serving paths (engine --quantization int8/int4) -----
    from ome_tpu.models.quant import quantize_params, quantized_bytes
    q8 = quantize_params(params, mode="int8")
    int8_tps = decode_toks_per_s(q8, "int8")
    q8_bytes = quantized_bytes(q8)
    del q8
    q4 = quantize_params(params, mode="int4")
    int4_tps = decode_toks_per_s(q4, "int4")
    q4_bytes = quantized_bytes(q4)
    del q4
    log(f"bench: int8 {int8_tps:.1f} tok/s "
        f"({100*int8_tps/toks_per_s-100:+.0f}% vs bf16, "
        f"{q8_bytes/1e9:.2f} GB weights) | int4 {int4_tps:.1f} tok/s "
        f"({100*int4_tps/toks_per_s-100:+.0f}%, {q4_bytes/1e9:.2f} GB)")

    # -- rooflines ------------------------------------------------------
    # Per decode step the chip must read all weights once (amortized
    # across the batch) + each sequence's KV cache.
    bw_spec = device_bandwidth()
    bw_copy = copy_bandwidth()
    kv_bytes = (cfg.num_layers * CACHE_LEN * cfg.num_kv_heads * cfg.head_dim
                * 2 * 2)  # k+v, bf16, per sequence
    step_bytes = n_params * 2 + BATCH * kv_bytes
    eff_gbps = step_bytes * toks_per_s / BATCH / 1e9
    roof_spec = bw_spec * 1e9 / step_bytes * BATCH
    vs = toks_per_s / roof_spec

    log(f"bench: decode effective {eff_gbps:.0f} GB/s | HBM copy "
        f"microbench {bw_copy:.0f} GB/s (best-of-5; under-reads on the "
        f"tunnel — see copy_bandwidth) | spec {bw_spec:.0f}")
    log(f"bench: roofline vs spec: {roof_spec:.0f} tok/s -> "
        f"{100*vs:.1f}%")
    print(json.dumps({
        "metric": "decode_tokens_per_sec_1.9B_bf16_batch32",
        "value": round(toks_per_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs, 3),
        "best_of": TRIALS,
        "int8_tokens_per_sec": round(int8_tps, 1),
        "int4_tokens_per_sec": round(int4_tps, 1),
        "prefill_ms_batch32x128": round(ttft * 1000, 1),
        "hbm_copy_gbps": round(bw_copy, 1),
        "decode_effective_gbps": round(eff_gbps, 1),
    }))


if __name__ == "__main__":
    main()
