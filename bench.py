#!/usr/bin/env python
"""Driver benchmark: sustained decode throughput of the flagship model.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference (bcfre/ome) publishes no hardware numbers (BASELINE.md) —
its headline metric is BenchmarkJob *output tokens/sec* against a served
InferenceService (SURVEY.md §6). This bench measures the same quantity
at the layer we own end-to-end on one chip: batched autoregressive
decode tokens/sec of the flagship Llama-class model with a KV cache.

`vs_baseline` is the fraction of the chip's HBM-bandwidth roofline
(decode is bandwidth-bound: every generated token must stream all
weights + the KV cache once), so 1.0 == perfect memory-bound decode.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def sync(x):
    """Force completion. On the axon-tunneled TPU backend
    jax.block_until_ready returns before execution finishes; only a
    device->host fetch truly synchronizes, so time through a fetch."""
    jax.block_until_ready(x)
    return np.asarray(jax.device_get(x))

# Per-chip HBM bandwidth (GB/s) by TPU generation; CPU fallback uses a
# nominal DDR figure so the ratio stays defined in dev environments.
HBM_GBPS = {"v5 lite": 819.0, "v5e": 819.0, "v5p": 2765.0, "v6e": 1640.0,
            "v4": 1228.0, "cpu": 50.0}

BATCH = 32
PREFILL = 128
DECODE_STEPS = 128
CACHE_LEN = PREFILL + DECODE_STEPS


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def device_bandwidth() -> float:
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", d.platform).lower()
    for key, bw in HBM_GBPS.items():
        if key in kind:
            return bw
    return HBM_GBPS["cpu" if d.platform == "cpu" else "v5e"]


def measured_bandwidth() -> float:
    """STREAM-style achievable read bandwidth (GB/s) on this device.

    Roofline analysis conventionally uses *measured* bandwidth; on the
    tunneled chips the achievable figure sits well below the part spec
    (e.g. ~310 GB/s vs 819 on v5e), so the spec-based ratio would
    understate kernel quality by ~2.5x. Both ratios are logged."""
    gb = 2.0
    x = jnp.ones((int(gb * 1e9 / 2),), jnp.bfloat16)
    f = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))
    sync(f(x))
    iters = 8
    t0 = time.perf_counter()
    for _ in range(iters):
        r = f(x)
    sync(r)
    return gb * iters / (time.perf_counter() - t0)


def main() -> None:
    from ome_tpu.models import config as cfgs
    from ome_tpu.models import llama

    # ~1.9B-parameter dense Llama-class config: big enough that decode is
    # genuinely HBM-bound, small enough to fit one v5e chip (16G HBM)
    # in bf16 with headroom for the KV cache.
    cfg = cfgs.ModelConfig(
        vocab_size=32768, hidden_size=2048, num_layers=24, num_heads=16,
        num_kv_heads=8, head_dim=128, intermediate_size=8192,
        rope_theta=500000.0, max_seq_len=CACHE_LEN)

    log(f"bench: devices={jax.devices()}")
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    n_params = llama.param_count(params)
    log(f"bench: params={n_params/1e9:.2f}B")

    cache = llama.KVCache.create(cfg, BATCH, CACHE_LEN)

    # NOTE: measured on the axon-tunneled chip, buffer donation and
    # multi-step lax.scan/unrolled decode are all SLOWER than a plain
    # python dispatch loop (donation ~-20%, scan ~-60%); keep the
    # simple form the backend executes best.
    @jax.jit
    def prefill(params, tokens, cache):
        logits, cache = llama.forward(params, cfg, tokens, cache=cache)
        return jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32), cache

    @jax.jit
    def decode(params, tokens, cache):
        logits, cache = llama.forward(params, cfg, tokens, cache=cache)
        return jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32), cache

    tok = tok_init = jax.random.randint(
        jax.random.PRNGKey(1), (BATCH, PREFILL), 0, cfg.vocab_size,
        dtype=jnp.int32)
    t0 = time.perf_counter()
    tok, cache = prefill(params, tok, cache)
    sync(tok)
    log(f"bench: prefill(batch={BATCH}, len={PREFILL}) + compile "
        f"{time.perf_counter()-t0:.1f}s")
    # steady-state prefill (TTFT proxy at this batch/length): same
    # [BATCH, PREFILL] shape as the compiled program, fresh cache
    prompt2 = jax.random.randint(jax.random.PRNGKey(2), (BATCH, PREFILL),
                                 0, cfg.vocab_size, dtype=jnp.int32)
    cache2 = llama.KVCache.create(cfg, BATCH, CACHE_LEN)
    t0 = time.perf_counter()
    _tok2, cache2 = prefill(params, prompt2, cache2)
    sync(_tok2)
    ttft = time.perf_counter() - t0
    log(f"bench: steady prefill {ttft*1000:.0f} ms "
        f"({BATCH*PREFILL/ttft:.0f} prefill tok/s)")
    del _tok2, cache2, prompt2

    # warmup decode (compile + one synced step)
    tok, cache = decode(params, tok, cache)
    sync(tok)

    t0 = time.perf_counter()
    for _ in range(DECODE_STEPS - 1):
        tok, cache = decode(params, tok, cache)
    sync(tok)
    dt = time.perf_counter() - t0
    steps = DECODE_STEPS - 1
    toks_per_s = BATCH * steps / dt

    # secondary: weight-only int8 serving (models/quant.py) — same
    # model, weights at half the bytes; the serving engine's
    # --quantization int8 path
    from ome_tpu.models.quant import quantize_params
    qparams = quantize_params(params)
    qcache = llama.KVCache.create(cfg, BATCH, CACHE_LEN)
    qtok, qcache = prefill(qparams, tok_init, qcache)
    qtok, qcache = decode(qparams, qtok, qcache)
    sync(qtok)
    t0 = time.perf_counter()
    for _ in range(DECODE_STEPS - 1):
        qtok, qcache = decode(qparams, qtok, qcache)
    sync(qtok)
    qdt = time.perf_counter() - t0
    int8_toks = BATCH * (DECODE_STEPS - 1) / qdt
    log(f"bench: int8 weight-only decode -> {int8_toks:.1f} tok/s "
        f"({100 * int8_toks / toks_per_s - 100:+.0f}% vs bf16)")
    del qparams, qcache

    # Roofline: per decode step the chip must read all weights once
    # (amortized across the batch) + each sequence's KV cache.
    bw_spec = device_bandwidth()
    bw_meas = measured_bandwidth()
    kv_bytes = (cfg.num_layers * CACHE_LEN * cfg.num_kv_heads * cfg.head_dim
                * 2 * 2)  # k+v, bf16, per sequence
    step_bytes = n_params * 2 + BATCH * kv_bytes
    roof_spec = bw_spec * 1e9 / step_bytes * BATCH
    roof_meas = bw_meas * 1e9 / step_bytes * BATCH
    # vs_baseline uses the SPEC roofline: deterministic and comparable
    # across rounds. The measured figure (STREAM-style, highly variable
    # on the shared/tunneled chip: 70-310 GB/s observed) is logged for
    # context — decode's own effective bandwidth (step_bytes/step time)
    # routinely EXCEEDS the microbenchmark, i.e. the model is at this
    # environment's practical memory-bandwidth ceiling.
    vs = toks_per_s / roof_spec
    eff_gbps = step_bytes * steps / dt / 1e9

    log(f"bench: decode {steps} steps x batch {BATCH} in {dt:.2f}s "
        f"-> {toks_per_s:.1f} tok/s (effective {eff_gbps:.0f} GB/s)")
    log(f"bench: roofline vs spec bw ({bw_spec:.0f} GB/s): "
        f"{roof_spec:.0f} tok/s -> {100*vs:.1f}% | STREAM-measured bw "
        f"{bw_meas:.0f} GB/s -> {roof_meas:.0f} tok/s")
    print(json.dumps({
        "metric": "decode_tokens_per_sec_1.9B_bf16_batch32",
        "value": round(toks_per_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
