"""Live HBM accounting: who owns the device memory right now.

`device.memory_stats()` gives the allocator's truth (bytes in use,
peak, limit); the engine knows its own tenants — weights (the
quantizer's byte model), KV cache (pool capacity in paged mode, the
dense slab otherwise), prefix cache (its own byte counter). The
residual is workspace: XLA temp buffers, collectives scratch,
fragmentation. Partitioning the allocator number against the tenants
turns "HBM is 93% full" into "weights 41%, KV 38%, prefix 6%,
workspace 8%" — the first question of every OOM post-mortem.

A new allocator peak records an `hbm_peak` watermark event in the
flight ring, carrying the partition at that moment — so after an
OOM kill the flight dump (or GET /debug/events) shows what grew.

Off-TPU `memory_stats()` is unavailable; the gauges then carry the
tenant model alone (in_use = sum of known tenants, workspace 0) so
dashboards keep a consistent shape in dev environments.
"""

from __future__ import annotations

from typing import Dict, Optional

# fixed tenant enum: gauge children are pre-created for exactly this
# set, so label cardinality is bounded by construction (the
# metrics-label-cardinality lint pattern)
HBM_TENANTS = ("weights", "kv_cache", "prefix_cache", "workspace")


def kv_capacity_bytes(engine) -> int:
    """Device bytes of the engine's KV allocation: the paged pool
    (kv_blocks x kv_block rows) or the dense [L, B, S] slab. Uses
    the same per-row arithmetic as the engine's cache shapes."""
    import jax.numpy as jnp
    cfg = getattr(engine, "cfg", None)
    if cfg is None:
        return 0
    row_fn = getattr(engine, "kv_row_bytes", None)
    if callable(row_fn):
        # the engine's own byte model — int8-pool aware (quantized
        # rows store 1 byte/element + two f32 scales per head)
        row = int(row_fn())
    else:
        itemsize = jnp.dtype(cfg.dtype).itemsize
        row = (cfg.num_layers * cfg.kv_cache_heads
               * (cfg.kv_cache_k_dim + cfg.kv_cache_v_dim) * itemsize)
    if getattr(engine, "kv_block", 0):
        return int(engine.kv_blocks * engine.kv_block * row)
    return int(engine.max_slots * engine.max_seq * row)


class HbmAccountant:
    """Scrape-time HBM gauges partitioned against the known tenants.

    `stats_fn` overrides the `device.memory_stats()` read (tests
    inject allocator numbers; None falls back to the first jax
    device, degrading gracefully when the platform has no stats).
    """

    def __init__(self, registry, weight_bytes: int = 0, device=None,
                 flight=None, stats_fn=None):
        self.weight_bytes = int(weight_bytes)
        self.flight = flight
        self._stats_fn = stats_fn
        self._device = device
        self._last_peak = 0.0
        self._g_in_use = registry.gauge(
            "ome_engine_hbm_bytes_in_use",
            "Device bytes in use (allocator truth on TPU; the tenant "
            "model's sum off-TPU)")
        self._g_limit = registry.gauge(
            "ome_engine_hbm_bytes_limit",
            "Device memory limit reported by the allocator (0 when "
            "unavailable)")
        self._g_peak = registry.gauge(
            "ome_engine_hbm_peak_bytes",
            "Allocator high-water mark; a new peak also records an "
            "hbm_peak flight event with the tenant partition")
        fam = registry.gauge(
            "ome_engine_hbm_tenant_bytes",
            "Device bytes attributed per tenant: weights (quantizer "
            "byte model), kv_cache (pool/slab capacity), prefix_cache "
            "(its byte counter), workspace (the residual)",
            labelnames=("tenant",))
        self._tenants = {t: fam.labels(tenant=t) for t in HBM_TENANTS}

    @classmethod
    def for_engine(cls, engine, registry, flight=None
                   ) -> Optional["HbmAccountant"]:
        """Build an accountant for a real engine; None for fakes and
        wrappers without params/cfg (scheduler tests)."""
        params = getattr(engine, "params", None)
        if params is None or getattr(engine, "cfg", None) is None:
            return None
        try:
            from ..models.quant import quantized_bytes
            wb = quantized_bytes(params)
        except Exception:
            return None
        return cls(registry, weight_bytes=wb, flight=flight)

    def _read_stats(self) -> Optional[Dict[str, float]]:
        if self._stats_fn is not None:
            try:
                return self._stats_fn()
            except Exception:
                return None
        dev = self._device
        if dev is None:
            try:
                import jax
                dev = self._device = jax.devices()[0]
            except Exception:
                return None
        fn = getattr(dev, "memory_stats", None)
        if fn is None:
            return None
        try:
            return fn()
        except Exception:
            return None

    def update(self, engine=None) -> Dict[str, float]:
        """Refresh the gauges (one /metrics scrape). Returns the
        partition dict (tests assert the arithmetic on it)."""
        kv = kv_capacity_bytes(engine) if engine is not None else 0
        pc = getattr(engine, "prefix_cache", None)
        pcb = int(getattr(pc, "bytes", 0) or 0)
        stats = self._read_stats()
        tenant_sum = self.weight_bytes + kv + pcb
        if stats:
            in_use = float(stats.get("bytes_in_use", tenant_sum))
            limit = float(stats.get("bytes_limit", 0) or 0)
            peak = float(stats.get("peak_bytes_in_use", in_use))
        else:
            in_use, limit, peak = float(tenant_sum), 0.0, 0.0
        workspace = max(in_use - tenant_sum, 0.0)
        part = {"bytes_in_use": in_use, "bytes_limit": limit,
                "peak_bytes": peak, "weights": float(self.weight_bytes),
                "kv_cache": float(kv), "prefix_cache": float(pcb),
                "workspace": workspace}
        self._g_in_use.set(in_use)
        self._g_limit.set(limit)
        self._g_peak.set(peak)
        for t in HBM_TENANTS:
            self._tenants[t].set(part[t])
        if peak > self._last_peak:
            # first observation just seats the watermark; every later
            # climb is a real event worth a post-mortem breadcrumb
            if self._last_peak and self.flight is not None:
                self.flight.record(
                    "hbm_peak",
                    peak_bytes=int(peak), bytes_in_use=int(in_use),
                    bytes_limit=int(limit),
                    weights=int(self.weight_bytes), kv_cache=int(kv),
                    prefix_cache=pcb, workspace=int(workspace))
            self._last_peak = peak
        return part
