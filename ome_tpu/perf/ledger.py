"""Program cost ledger: what each compiled device program moves.

The decode roofline in bench.py is a hand-maintained bytes-per-token
model; the compiler already knows the truth. When the engine
dispatches a program for the first time (one ledger entry per
(program, static-args) pair — the jit compile key), the ledger asks
the AOT path for it: `fn.lower(...).compile()` then
`cost_analysis()` (FLOPs, bytes accessed) and `memory_analysis()`
(argument/output/temp bytes). Off-TPU — where a second CPU compile
of a production-sized model would be pure waste and the analysis is
not the one serving runs — the ledger degrades to the analytic
byte model the quantizer already maintains (models/quant.py
`quantized_bytes` + KV-capacity arithmetic), flagged
`source: "model"` so a reader never mistakes an estimate for a
measurement.

Expected ms is the roofline max of the memory and compute terms
against the device spec table bench.py shares from here. The entry
set is bounded by construction: programs are compiled, and
compilation is expensive — a serving process accumulates a handful
of entries, not a stream.

Surfaces: GET /debug/programs (guarded by --debug-endpoints),
`ome_engine_program_flops` / `ome_engine_program_bytes` gauges,
attrs on `engine.decode_chunk` spans, and the POST /debug/profile
response body.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

# Per-chip HBM bandwidth (GB/s) and bf16 peak (TFLOP/s) by
# generation; bench.py imports these so the offline and online
# rooflines can never disagree about the device spec. CPU entries
# keep the ratios defined in dev environments.
DEVICE_HBM_GBPS = {"v5 lite": 819.0, "v5e": 819.0, "v5p": 2765.0,
                   "v6e": 1640.0, "v4": 1228.0, "cpu": 50.0}
DEVICE_PEAK_TFLOPS = {"v5 lite": 197.0, "v5e": 197.0, "v5p": 459.0,
                      "v6e": 918.0, "v4": 275.0, "cpu": 0.2}

LEDGER_MODES = ("auto", "full", "model", "off")

log = logging.getLogger("ome.perf.ledger")


def device_spec(device=None) -> Dict[str, object]:
    """{kind, platform, hbm_gbps, peak_tflops} for `device` (default:
    jax.devices()[0]). Matching mirrors bench.py's table lookup:
    substring on device_kind, platform-keyed fallback."""
    import jax
    if device is None:
        try:
            device = jax.devices()[0]
        except Exception:  # pragma: no cover - no backend at all
            return {"kind": "unknown", "platform": "unknown",
                    "hbm_gbps": DEVICE_HBM_GBPS["cpu"],
                    "peak_tflops": DEVICE_PEAK_TFLOPS["cpu"]}
    kind = str(getattr(device, "device_kind",
                       getattr(device, "platform", "cpu"))).lower()
    platform = str(getattr(device, "platform", "cpu"))

    def _lookup(table):
        for key, val in table.items():
            if key in kind:
                return val
        return table["cpu" if platform == "cpu" else "v5e"]

    return {"kind": kind, "platform": platform,
            "hbm_gbps": _lookup(DEVICE_HBM_GBPS),
            "peak_tflops": _lookup(DEVICE_PEAK_TFLOPS)}


def roofline_ms(flops: float, bytes_moved: float, hbm_gbps: float,
                peak_tflops: float) -> float:
    """Expected program ms at the roofline: the slower of streaming
    `bytes_moved` at spec bandwidth and computing `flops` at peak."""
    mem_s = bytes_moved / max(hbm_gbps * 1e9, 1e-9)
    compute_s = flops / max(peak_tflops * 1e12, 1e-9)
    return max(mem_s, compute_s) * 1000.0


def _on_tpu() -> bool:
    from ..ops.int4_matmul import _on_tpu_device
    return _on_tpu_device()


class ProgramLedger:
    """One entry per compiled engine program, captured at first
    dispatch (the engine calls `capture` immediately before every
    program call; repeats only bump the dispatch count).

    mode: "auto" = full AOT introspection on TPU, analytic model
    off-TPU (TPU-less CI must not pay a second compile of every
    program — and its numbers would describe the CPU fallback, not
    the device serving runs on); "full"/"model" force a path (tests
    force "full" on tiny CPU models); "off" disables capture.
    """

    def __init__(self, mode: str = "auto", registry=None, flight=None):
        if mode not in LEDGER_MODES:
            raise ValueError(
                f"ledger mode {mode!r} not in {LEDGER_MODES}")
        self.mode = mode
        self.flight = flight
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, dict]" = OrderedDict()
        self._last: Optional[dict] = None
        self._g_flops = None
        self._g_bytes = None
        self._spec: Optional[dict] = None
        self._warned = False
        if registry is not None:
            self.bind(registry)

    # -- wiring --------------------------------------------------------

    def bind(self, registry, flight=None) -> None:
        """Attach the serving registry (and optionally the flight
        ring) after construction — the scheduler owns both and the
        engine is built first. Entries captured before the bind are
        exported retroactively."""
        # program label values are compile keys — bounded by
        # construction (entries exist only for compiled programs)
        self._g_flops = registry.gauge(
            "ome_engine_program_flops",
            "FLOPs per dispatch of each compiled engine program, from "
            "XLA cost_analysis (or the analytic model off-TPU)",
            labelnames=("program",))
        self._g_bytes = registry.gauge(
            "ome_engine_program_bytes",
            "HBM bytes moved per dispatch of each compiled engine "
            "program, from XLA cost_analysis (or the analytic model "
            "off-TPU)", labelnames=("program",))
        if flight is not None:
            self.flight = flight
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            self._export(e)

    def device_spec(self) -> Dict[str, object]:
        if self._spec is None:
            self._spec = device_spec()
        return self._spec

    # -- capture -------------------------------------------------------

    def _resolved_mode(self) -> str:
        if self.mode != "auto":
            return self.mode
        return "full" if _on_tpu() else "model"

    def capture(self, name: str, static_desc: str, fn, args,
                static_kwargs: Dict[str, object],
                model: Dict[str, float]) -> Optional[dict]:
        """Record program `name` (e.g. "decode_multi", static args
        described by `static_desc`, e.g. "n=8") about to be
        dispatched as `fn(*args, **static_kwargs)`. `model` is the
        engine's analytic {flops, bytes} estimate — the fallback
        when compiler introspection is off or fails. Returns the
        (shared, mutable) entry; None when the ledger is off."""
        if self.mode == "off":
            return None
        key = f"{name}[{static_desc}]" if static_desc else name
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry["dispatches"] += 1
                self._last = entry
                return entry
        entry = self._build_entry(key, name, static_desc, fn, args,
                                  static_kwargs, model)
        with self._lock:
            entry = self._entries.setdefault(key, entry)
            entry["dispatches"] += 1
            self._last = entry
        self._export(entry)
        if self.flight is not None:
            self.flight.record(
                "program_captured", program=key,
                source=entry["source"],
                expected_ms=entry["expected_ms"],
                bytes=entry["bytes"], flops=entry["flops"])
        return entry

    def _build_entry(self, key, name, static_desc, fn, args,
                     static_kwargs, model) -> dict:
        spec = self.device_spec()
        entry = {
            "program": key,
            "name": name,
            "static": static_desc,
            "source": "model",
            "flops": float(model.get("flops", 0.0)),
            "bytes": float(model.get("bytes", 0.0)),
            "argument_bytes": None,
            "output_bytes": None,
            "temp_bytes": None,
            "device": spec["kind"],
            "dispatches": 0,
            "captured_unix": time.time(),
        }
        if self._resolved_mode() == "full" and fn is not None:
            self._introspect(entry, fn, args, static_kwargs)
        entry["expected_ms"] = roofline_ms(
            entry["flops"], entry["bytes"],
            spec["hbm_gbps"], spec["peak_tflops"])
        return entry

    def _introspect(self, entry, fn, args, static_kwargs) -> None:
        """AOT compiler introspection; any failure leaves the
        analytic-model numbers in place (never break a dispatch over
        observability)."""
        try:
            lowered = fn.lower(*args, **static_kwargs)
        except Exception as e:
            self._warn_once("lower", entry["program"], e)
            return
        ca = None
        try:
            ca = lowered.cost_analysis()
        except Exception:
            pass
        try:
            compiled = lowered.compile()
        except Exception as e:
            # compile failed but the pre-compile HLO cost analysis may
            # still have real numbers — flag the weaker provenance
            if self._apply_cost(entry, ca):
                entry["source"] = "lowered"
            self._warn_once("compile", entry["program"], e)
            return
        try:
            cca = compiled.cost_analysis()
            if isinstance(cca, (list, tuple)):
                cca = cca[0] if cca else None
        except Exception:
            cca = None
        if self._apply_cost(entry, cca):
            entry["source"] = "compiled"
        elif self._apply_cost(entry, ca):
            entry["source"] = "lowered"
        try:
            ma = compiled.memory_analysis()
        except Exception:
            ma = None
        if ma is not None:
            entry["argument_bytes"] = int(
                getattr(ma, "argument_size_in_bytes", 0))
            entry["output_bytes"] = int(
                getattr(ma, "output_size_in_bytes", 0))
            entry["temp_bytes"] = int(
                getattr(ma, "temp_size_in_bytes", 0))

    @staticmethod
    def _apply_cost(entry, analysis) -> bool:
        if not analysis:
            return False
        flops = analysis.get("flops")
        bytes_ = analysis.get("bytes accessed")
        if flops is None and bytes_ is None:
            return False
        if flops is not None:
            entry["flops"] = float(flops)
        if bytes_ is not None:
            entry["bytes"] = float(bytes_)
        return True

    def _warn_once(self, stage, program, exc) -> None:
        if not self._warned:
            self._warned = True
            log.warning("ledger introspection (%s) failed for %s: %s "
                        "— keeping the analytic model estimate",
                        stage, program, exc)

    # -- reads ---------------------------------------------------------

    def last_dispatch(self) -> Optional[dict]:
        """The entry of the most recently captured dispatch — the
        scheduler reads its bytes for the online roofline right after
        the engine call returns."""
        return self._last

    def snapshot(self) -> List[dict]:
        """Entry copies in first-compile order (the /debug/programs
        body)."""
        with self._lock:
            return [dict(e) for e in self._entries.values()]

    def summary(self) -> List[dict]:
        """Compact {program, expected_ms, source} list — rides along
        in the POST /debug/profile response."""
        with self._lock:
            return [{"program": e["program"],
                     "expected_ms": round(e["expected_ms"], 4),
                     "source": e["source"]}
                    for e in self._entries.values()]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def _export(self, entry) -> None:
        if self._g_flops is None:
            return
        self._g_flops.labels(program=entry["program"]).set(
            entry["flops"])
        self._g_bytes.labels(program=entry["program"]).set(
            entry["bytes"])
