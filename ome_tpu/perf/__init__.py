"""Device-performance attribution (docs/perf-attribution.md).

Three always-on, cheap observability surfaces that make ROADMAP
item 2 ("close the decode roofline gap") chaseable from a live
replica instead of from bench.py reruns:

  * ledger  — per-compiled-program cost ledger (FLOPs, bytes,
              expected roofline ms) captured at first dispatch,
              served at GET /debug/programs;
  * hbm     — live HBM occupancy partitioned against the known
              tenants (weights / KV cache / prefix cache /
              workspace), with a new-peak watermark flight event;
  * the scheduler combines the ledger's bytes-per-step with its own
    step timestamps into an online roofline-efficiency signal and a
    slow-step outlier detector (engine/scheduler.py).

scripts/perfgate.py closes the loop offline: it diffs fresh bench.py
output against the checked-in BENCH history and emits the fitted
per-program cost table ROADMAP item 6's fleet simulator consumes.
"""

from .hbm import HBM_TENANTS, HbmAccountant
from .ledger import (DEVICE_HBM_GBPS, DEVICE_PEAK_TFLOPS, ProgramLedger,
                     device_spec, roofline_ms)

__all__ = [
    "DEVICE_HBM_GBPS", "DEVICE_PEAK_TFLOPS", "HBM_TENANTS",
    "HbmAccountant", "ProgramLedger", "device_spec", "roofline_ms",
]
