"""ome_tpu — a TPU-native open model engine.

A from-scratch, TPU-first re-design of the capabilities of OME
(sgl-project/ome, surveyed in SURVEY.md): a model-serving control plane
(models as first-class resources, weighted runtime selection,
accelerator-aware scheduling, single-host / multi-host / PD-disaggregated
deployment patterns, autoscaling, benchmarking) plus a JAX/XLA/Pallas
serving data plane (the part the reference delegates to SGLang/vLLM).

Layout:
  core/        k8s-style object model, in-memory API, workqueue, manager
  apis/        CRD-equivalent typed specs (v1)
  selection/   runtime + accelerator selection engines
  controllers/ reconcilers (InferenceService, BaseModel, BenchmarkJob, AcceleratorClass)
  webhooks/    defaulting / validation / pod mutation (TPU env injection)
  modelagent/  node-side model staging (scout, gopher, parsers, labels)
  hfconfig/    per-architecture HuggingFace config.json parsers
  storage/     storage URI abstraction + providers (+ native C++ chunk downloader)
  models/      JAX model families (flagship: Llama-class decoder)
  ops/         Pallas TPU kernels (flash attention, paged attention, ...)
  parallel/    mesh / sharding / pipeline / ring-attention utilities
  engine/      TPU serving engine (continuous batching, paged KV, sampling)
  train/       sharded training step (for multi-chip validation)
"""

__version__ = "0.1.0"

GROUP = "ome.io"
