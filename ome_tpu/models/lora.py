"""LoRA adapter loading: merged OR multi-adapter serving forms.

The control plane already moves fine-tuned adapters (FineTunedWeight
CRD, agent/serving_agent.py sidecar downloads); this is the engine
side: read a PEFT-format adapter directory (adapter_config.json +
adapter_model.safetensors with lora_A [r, in] / lora_B [out, r]
pairs) and either

  * `merge_lora`: fold `W += (alpha/r) * B @ A` into the converted
    param tree before device upload — ONE adapter at full base-model
    speed (`--adapter <dir>`), or
  * `load_adapter_matrices`: return per-target stacked [L, r, K_in] /
    [L, r, N_out] factor pairs (scaling folded into B, rank
    zero-padded to the engine's slot rank) for MULTI-adapter serving:
    the engine keeps per-adapter factor stacks as extra layer leaves
    and applies per-slot low-rank deltas inside the decode matmuls
    (engine/core.py register_adapter; reference analog:
    internal/ome-agent/serving-agent/serving_agent.go:42-80 staging +
    the engines' punica-style multi-LoRA batching).
"""

from __future__ import annotations

import json
import logging
import os
import re
from typing import Any, Dict

import numpy as np

from .checkpoint import Checkpoint

log = logging.getLogger("ome.lora")

# HF module name -> (our stacked leaf, reshaper from [out, in] delta)
_TARGETS = {
    "q_proj": ("wq", lambda d, cfg: d.T.reshape(
        cfg.hidden_size, cfg.num_heads, cfg.head_dim)),
    "k_proj": ("wk", lambda d, cfg: d.T.reshape(
        cfg.hidden_size, cfg.num_kv_heads, cfg.head_dim)),
    "v_proj": ("wv", lambda d, cfg: d.T.reshape(
        cfg.hidden_size, cfg.num_kv_heads, cfg.head_dim)),
    "o_proj": ("wo", lambda d, cfg: d.T.reshape(
        cfg.num_heads, cfg.head_dim, cfg.hidden_size)),
    "gate_proj": ("w_gate", lambda d, cfg: d.T),
    "up_proj": ("w_up", lambda d, cfg: d.T),
    "down_proj": ("w_down", lambda d, cfg: d.T),
}

_KEY_RE = re.compile(
    r"(?:base_model\.model\.)?model\.layers\.(\d+)\.(?:self_attn|mlp)\."
    r"(\w+_proj)\.lora_(A|B)\.weight")


def _read_adapter(adapter_dir: str):
    """Parse a PEFT dir -> (pairs {(layer, module): {A, B}}, scaling)."""
    with open(os.path.join(adapter_dir, "adapter_config.json")) as f:
        acfg = json.load(f)
    cfg_rank = acfg.get("r", 8)
    alpha = acfg.get("lora_alpha", cfg_rank)
    rslora = bool(acfg.get("use_rslora", False))

    ckpt = Checkpoint(adapter_dir)
    pairs: Dict[tuple, Dict[str, np.ndarray]] = {}
    unmatched = []
    for key in ckpt.keys():
        m = _KEY_RE.fullmatch(key)
        if not m:
            unmatched.append(key)
            continue
        layer, module, ab = int(m.group(1)), m.group(2), m.group(3)
        pairs.setdefault((layer, module), {})[ab] = \
            ckpt.read(key).astype(np.float32)
    if unmatched:
        # silently dropping deltas would serve a subtly wrong model
        raise ValueError(
            f"adapter carries weights this merge does not cover "
            f"(supported targets: {sorted(_TARGETS)}): "
            f"{unmatched[:5]}{'...' if len(unmatched) > 5 else ''}")
    for (layer, module), mats in sorted(pairs.items()):
        if "A" not in mats or "B" not in mats:
            raise ValueError(f"adapter incomplete for layer {layer} "
                             f"{module}: needs both lora_A and lora_B")
        rank = mats["A"].shape[0]
        if mats["B"].shape[1] != rank:
            raise ValueError(
                f"layer {layer} {module}: lora_A rank {rank} != "
                f"lora_B rank {mats['B'].shape[1]}")
        if rank != cfg_rank:
            raise ValueError(
                f"layer {layer} {module}: tensor rank {rank} != "
                f"adapter_config r={cfg_rank}")
    if not pairs:
        raise ValueError(f"no LoRA weights recognized in {adapter_dir}")
    scaling = alpha / (cfg_rank ** 0.5 if rslora else cfg_rank)
    return pairs, cfg_rank, alpha, scaling


# multi-LoRA factor layout per target: flattened contraction width K
# and output width N of the stacked leaf ([L, r, K] A / [L, r, N] B)
def _target_dims(cfg) -> Dict[str, tuple]:
    D, H, K, Dh, F = (cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads,
                      cfg.head_dim, cfg.intermediate_size)
    return {
        "wq": (D, H * Dh), "wk": (D, K * Dh), "wv": (D, K * Dh),
        "wo": (H * Dh, D),
        "w_gate": (D, F), "w_up": (D, F), "w_down": (F, D),
    }


def load_adapter_matrices(adapter_dir: str, cfg,
                          rank_pad: int) -> Dict[str, tuple]:
    """PEFT dir -> {leaf: (A [L, rank_pad, K], B [L, rank_pad, N])}
    float32, scaling folded into B, zero rows pad rank to `rank_pad`
    (zero factors = no delta, so padding and untouched layers are
    exact no-ops)."""
    pairs, rank, _alpha, scaling = _read_adapter(adapter_dir)
    if rank > rank_pad:
        raise ValueError(f"adapter rank {rank} exceeds the engine's "
                         f"LoRA slot rank {rank_pad} "
                         f"(--lora-rank at startup)")
    L = cfg.num_layers
    dims = _target_dims(cfg)
    out: Dict[str, list] = {}
    for (layer, module), mats in sorted(pairs.items()):
        leaf, _ = _TARGETS[module]
        if leaf not in dims:
            raise ValueError(f"unknown adapter target {module}")
        if layer >= L:
            raise ValueError(f"adapter layer {layer} out of range "
                             f"(model has {L})")
        Kd, Nd = dims[leaf]
        if mats["A"].shape[1] != Kd or mats["B"].shape[0] != Nd:
            raise ValueError(
                f"layer {layer} {module}: adapter dims "
                f"{mats['B'].shape[0]}x{mats['A'].shape[1]} != model "
                f"{Nd}x{Kd}")
        if leaf not in out:
            out[leaf] = [np.zeros((L, rank_pad, Kd), np.float32),
                         np.zeros((L, rank_pad, Nd), np.float32)]
        out[leaf][0][layer, :rank] = mats["A"]
        out[leaf][1][layer, :rank] = scaling * mats["B"].T
    return {k: (a, b) for k, (a, b) in out.items()}


def merge_lora(params: Dict[str, Any], cfg, adapter_dir: str) -> int:
    """Fold the adapter into `params` (numpy tree, pre-device-put).

    Returns the number of (layer, module) pairs merged. Raises on rank
    mismatches or targets the model doesn't have.
    """
    pairs, rank, alpha, scaling = _read_adapter(adapter_dir)

    merged = 0
    layers = params["layers"]
    writable: set = set()  # stacked leaves copied once, not per layer
    for (layer, module), mats in sorted(pairs.items()):
        leaf_name, reshape = _TARGETS[module]
        if leaf_name not in layers:
            raise ValueError(f"model has no {leaf_name} for adapter "
                             f"target {module}")
        delta = scaling * (mats["B"] @ mats["A"])  # [out, in]
        if leaf_name not in writable:
            layers[leaf_name] = np.array(layers[leaf_name])
            writable.add(leaf_name)
        leaf = layers[leaf_name]
        leaf[layer] = (np.asarray(leaf[layer], np.float32)
                       + reshape(delta, cfg)).astype(leaf.dtype)
        merged += 1
    if merged == 0:
        raise ValueError(f"no LoRA weights recognized in {adapter_dir}")
    log.info("merged %d LoRA deltas (r=%d, alpha=%s) from %s",
             merged, rank, alpha, adapter_dir)
    return merged
