"""LoRA adapter loading: merge PEFT adapters into base weights.

The control plane already moves fine-tuned adapters (FineTunedWeight
CRD, agent/serving_agent.py sidecar downloads); this is the engine
side: read a PEFT-format adapter directory (adapter_config.json +
adapter_model.safetensors with lora_A [r, in] / lora_B [out, r]
pairs) and fold `W += (alpha/r) * B @ A` into the converted param
tree before device upload. Merge-at-load serves ONE adapter at full
base-model speed — the TPU-friendly choice for static shapes (the
reference's runtimes likewise pass a merged or single-adapter path to
their engines).
"""

from __future__ import annotations

import json
import logging
import os
import re
from typing import Any, Dict

import numpy as np

from .checkpoint import Checkpoint

log = logging.getLogger("ome.lora")

# HF module name -> (our stacked leaf, reshaper from [out, in] delta)
_TARGETS = {
    "q_proj": ("wq", lambda d, cfg: d.T.reshape(
        cfg.hidden_size, cfg.num_heads, cfg.head_dim)),
    "k_proj": ("wk", lambda d, cfg: d.T.reshape(
        cfg.hidden_size, cfg.num_kv_heads, cfg.head_dim)),
    "v_proj": ("wv", lambda d, cfg: d.T.reshape(
        cfg.hidden_size, cfg.num_kv_heads, cfg.head_dim)),
    "o_proj": ("wo", lambda d, cfg: d.T.reshape(
        cfg.num_heads, cfg.head_dim, cfg.hidden_size)),
    "gate_proj": ("w_gate", lambda d, cfg: d.T),
    "up_proj": ("w_up", lambda d, cfg: d.T),
    "down_proj": ("w_down", lambda d, cfg: d.T),
}

_KEY_RE = re.compile(
    r"(?:base_model\.model\.)?model\.layers\.(\d+)\.(?:self_attn|mlp)\."
    r"(\w+_proj)\.lora_(A|B)\.weight")


def merge_lora(params: Dict[str, Any], cfg, adapter_dir: str) -> int:
    """Fold the adapter into `params` (numpy tree, pre-device-put).

    Returns the number of (layer, module) pairs merged. Raises on rank
    mismatches or targets the model doesn't have.
    """
    with open(os.path.join(adapter_dir, "adapter_config.json")) as f:
        acfg = json.load(f)
    cfg_rank = acfg.get("r", 8)
    alpha = acfg.get("lora_alpha", cfg_rank)
    rslora = bool(acfg.get("use_rslora", False))

    ckpt = Checkpoint(adapter_dir)
    pairs: Dict[tuple, Dict[str, np.ndarray]] = {}
    unmatched = []
    for key in ckpt.keys():
        m = _KEY_RE.fullmatch(key)
        if not m:
            unmatched.append(key)
            continue
        layer, module, ab = int(m.group(1)), m.group(2), m.group(3)
        pairs.setdefault((layer, module), {})[ab] = \
            ckpt.read(key).astype(np.float32)
    if unmatched:
        # silently dropping deltas would serve a subtly wrong model
        raise ValueError(
            f"adapter carries weights this merge does not cover "
            f"(supported targets: {sorted(_TARGETS)}): "
            f"{unmatched[:5]}{'...' if len(unmatched) > 5 else ''}")

    merged = 0
    layers = params["layers"]
    writable: set = set()  # stacked leaves copied once, not per layer
    for (layer, module), mats in sorted(pairs.items()):
        if "A" not in mats or "B" not in mats:
            raise ValueError(f"adapter incomplete for layer {layer} "
                             f"{module}: needs both lora_A and lora_B")
        rank = mats["A"].shape[0]
        if mats["B"].shape[1] != rank:
            raise ValueError(
                f"layer {layer} {module}: lora_A rank {rank} != "
                f"lora_B rank {mats['B'].shape[1]}")
        if rank != cfg_rank:
            raise ValueError(
                f"layer {layer} {module}: tensor rank {rank} != "
                f"adapter_config r={cfg_rank}")
        # PEFT scaling: alpha/r, or alpha/sqrt(r) with rsLoRA
        scaling = alpha / (rank ** 0.5 if rslora else rank)
        leaf_name, reshape = _TARGETS[module]
        if leaf_name not in layers:
            raise ValueError(f"model has no {leaf_name} for adapter "
                             f"target {module}")
        delta = scaling * (mats["B"] @ mats["A"])  # [out, in]
        if leaf_name not in writable:
            layers[leaf_name] = np.array(layers[leaf_name])
            writable.add(leaf_name)
        leaf = layers[leaf_name]
        leaf[layer] = (np.asarray(leaf[layer], np.float32)
                       + reshape(delta, cfg)).astype(leaf.dtype)
        merged += 1
    if merged == 0:
        raise ValueError(f"no LoRA weights recognized in {adapter_dir}")
    log.info("merged %d LoRA deltas (r=%d, alpha=%s) from %s",
             merged, rank, alpha, adapter_dir)
    return merged
