"""Checkpoint IO: safetensors parsing + HF-weights -> JAX param trees.

The reference operator never touches weights numerically — it stages
files on nodes and lets SGLang/vLLM load them (gopher.go download
paths, SURVEY.md §2.6). This repo owns a serving engine, so it owns
the conversion from HuggingFace safetensors checkpoints to the stacked
per-layer param pytree that models/llama.py scans over.

Pure-numpy safetensors reader/writer (no torch, no safetensors pip
package): the format is an 8-byte LE header length + JSON header of
{name: {dtype, shape, data_offsets}} + raw little-endian tensor bytes.
bf16 rides ml_dtypes (a JAX dependency). Reads are lazy and per-tensor
(seek + read) so a 70B checkpoint never needs 2x RAM; multi-shard
checkpoints resolve through model.safetensors.index.json exactly like
huggingface_hub does.

Name mapping covers the Llama superset the model implements: llama /
mistral / qwen2 (attention bias) / qwen3 (qk-norm) / gemma2 (softcap)
dense models, and mixtral / qwen2-moe / deepseek-style MoE with shared
experts.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

try:  # ml_dtypes ships with jax
    import ml_dtypes
    _BF16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BF16 = None

_DTYPES = {
    "F64": np.dtype(np.float64), "F32": np.dtype(np.float32),
    "F16": np.dtype(np.float16), "I64": np.dtype(np.int64),
    "I32": np.dtype(np.int32), "I16": np.dtype(np.int16),
    "I8": np.dtype(np.int8), "U8": np.dtype(np.uint8),
    "BOOL": np.dtype(np.bool_),
}
if _BF16 is not None:
    _DTYPES["BF16"] = _BF16
_DTYPE_NAMES = {v: k for k, v in _DTYPES.items()}


class SafetensorsError(Exception):
    pass


class SafetensorsFile:
    """Lazy reader for one .safetensors file."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            (hlen,) = struct.unpack("<Q", f.read(8))
            if hlen > 100 * 1024 * 1024:
                raise SafetensorsError(f"{path}: implausible header size")
            header = json.loads(f.read(hlen))
        self._data_start = 8 + hlen
        self._meta = header.pop("__metadata__", {})
        self._tensors: Dict[str, Tuple[np.dtype, tuple, int, int]] = {}
        for name, info in header.items():
            dt = _DTYPES.get(info["dtype"])
            if dt is None:
                raise SafetensorsError(
                    f"{path}: unsupported dtype {info['dtype']} for {name}")
            start, end = info["data_offsets"]
            self._tensors[name] = (dt, tuple(info["shape"]), start, end)

    def keys(self) -> List[str]:
        return list(self._tensors)

    def shape(self, name: str) -> tuple:
        return self._tensors[name][1]

    def read(self, name: str) -> np.ndarray:
        dt, shape, start, end = self._tensors[name]
        with open(self.path, "rb") as f:
            f.seek(self._data_start + start)
            buf = f.read(end - start)
        n = int(np.prod(shape)) if shape else 1
        if len(buf) != n * dt.itemsize:
            raise SafetensorsError(f"{self.path}: short read for {name}")
        return np.frombuffer(buf, dtype=dt).reshape(shape)


def save_safetensors(path: str, tensors: Dict[str, np.ndarray],
                     metadata: Optional[Dict[str, str]] = None) -> None:
    """Write a safetensors file (used by tests, replica, and export)."""
    header: Dict[str, Any] = {}
    if metadata:
        header["__metadata__"] = metadata
    offset = 0
    arrays = []
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        dt = _DTYPE_NAMES.get(arr.dtype)
        if dt is None:
            raise SafetensorsError(f"unsupported dtype {arr.dtype}")
        nbytes = arr.nbytes
        header[name] = {"dtype": dt, "shape": list(arr.shape),
                        "data_offsets": [offset, offset + nbytes]}
        arrays.append(arr)
        offset += nbytes
    hbytes = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(hbytes)))
        f.write(hbytes)
        for arr in arrays:
            f.write(arr.tobytes())


class Checkpoint:
    """A model directory's full weight set (single- or multi-shard)."""

    def __init__(self, model_dir: str):
        self.model_dir = model_dir
        index = os.path.join(model_dir, "model.safetensors.index.json")
        self._files: Dict[str, SafetensorsFile] = {}
        self._where: Dict[str, str] = {}
        if os.path.exists(index):
            with open(index) as f:
                weight_map = json.load(f)["weight_map"]
            for name, fname in weight_map.items():
                self._where[name] = fname
        else:
            shards = sorted(fn for fn in os.listdir(model_dir)
                            if fn.endswith(".safetensors"))
            if not shards:
                raise SafetensorsError(
                    f"no .safetensors files in {model_dir}")
            for fname in shards:
                for name in self._file(fname).keys():
                    self._where[name] = fname

    def _file(self, fname: str) -> SafetensorsFile:
        if fname not in self._files:
            self._files[fname] = SafetensorsFile(
                os.path.join(self.model_dir, fname))
        return self._files[fname]

    def keys(self) -> List[str]:
        return list(self._where)

    def __contains__(self, name: str) -> bool:
        return name in self._where

    def read(self, name: str) -> np.ndarray:
        if name not in self._where:
            raise KeyError(name)
        return self._file(self._where[name]).read(name)


# -- HF -> llama.py param tree ---------------------------------------------


def _np_dtype(dtype) -> np.dtype:
    import jax.numpy as jnp
    if dtype in (jnp.bfloat16, "bfloat16"):
        return _BF16
    return np.dtype(dtype)


class _Stacker:
    """Fills [L, ...] stacked arrays one layer at a time (no 2x peak)."""

    def __init__(self, num_layers: int, dtype: np.dtype):
        self.L = num_layers
        self.dtype = dtype
        self.out: Dict[str, np.ndarray] = {}

    def put(self, key: str, layer: int, arr: np.ndarray,
            dtype: Optional[np.dtype] = None) -> None:
        dt = dtype or self.dtype
        if key not in self.out:
            self.out[key] = np.empty((self.L,) + arr.shape, dt)
        self.out[key][layer] = arr.astype(dt)


def convert_llama(ckpt: Checkpoint, cfg, dtype=None) -> Dict[str, Any]:
    """Map HF checkpoint names/layouts onto the llama.py param tree.

    HF linear weights are [out, in] (y = W x); the model's einsums take
    [in, out]-shaped factors, so every projection transposes, and
    attention projections reshape the fused head dim into [heads, Dh].

    DeepSeek (MLA) checkpoints additionally split kv_b_proj into the
    absorbed-path factors w_uk/w_uv, and route the first_k_dense
    leading layers into a separate "dense_layers" stack.
    """
    np_dt = _np_dtype(dtype or "bfloat16")
    L, D, H, K, Dh = (cfg.num_layers, cfg.hidden_size, cfg.num_heads,
                      cfg.num_kv_heads, cfg.head_dim)
    mla = getattr(cfg, "mla", False)
    n_dense = cfg.first_k_dense if (cfg.is_moe
                                    and cfg.first_k_dense) else 0
    st_main = _Stacker(L - n_dense, np_dt)
    st_dense = _Stacker(n_dense, np_dt) if n_dense else None

    def take(name: str) -> np.ndarray:
        if name not in ckpt and name.startswith("model."):
            # bare AutoModel checkpoints (MistralModel/Qwen2Model
            # embedding repos) drop the "model." prefix
            name = name[len("model."):]
        return ckpt.read(name).astype(np.float32)

    def linear_in_out(name: str) -> np.ndarray:
        return take(name).T  # [out,in] -> [in,out]

    for li in range(L):
        p = f"model.layers.{li}."
        if li < n_dense:
            st, i = st_dense, li
        else:
            st, i = st_main, li - n_dense
        layer_is_moe = cfg.is_moe and li >= n_dense
        layernorm = getattr(cfg, "norm_type", "rmsnorm") == "layernorm"
        st.put("attn_norm", i, take(p + "input_layernorm.weight"))
        if layernorm:  # phimoe: torch LayerNorm biases ride along
            st.put("attn_norm_bias", i,
                   take(p + "input_layernorm.bias"))
        if getattr(cfg, "post_block_norms", False):
            # gemma2 block: post_attention_layernorm normalizes the
            # attention OUTPUT (pre-residual); the MLP pre-norm is
            # pre_feedforward_layernorm
            st.put("attn_post_norm", i,
                   take(p + "post_attention_layernorm.weight"))
            st.put("mlp_norm", i,
                   take(p + "pre_feedforward_layernorm.weight"))
            st.put("mlp_post_norm", i,
                   take(p + "post_feedforward_layernorm.weight"))
        elif getattr(cfg, "parallel_block", False):
            pass  # command-r: one shared input norm feeds attn AND mlp
        else:
            st.put("mlp_norm", i,
                   take(p + "post_attention_layernorm.weight"))
            if layernorm:
                st.put("mlp_norm_bias", i,
                       take(p + "post_attention_layernorm.bias"))
        if mla:
            qk = cfg.qk_nope_head_dim + cfg.qk_rope_head_dim
            r, vd = cfg.kv_lora_rank, cfg.v_head_dim
            if cfg.q_lora_rank:
                st.put("wq_a", i,
                       linear_in_out(p + "self_attn.q_a_proj.weight"))
                st.put("q_a_norm", i,
                       take(p + "self_attn.q_a_layernorm.weight"))
                st.put("wq_b", i,
                       take(p + "self_attn.q_b_proj.weight").T.reshape(
                           cfg.q_lora_rank, H, qk))
            else:
                st.put("wq", i,
                       take(p + "self_attn.q_proj.weight").T.reshape(
                           D, H, qk))
            st.put("wkv_a", i, linear_in_out(
                p + "self_attn.kv_a_proj_with_mqa.weight"))
            st.put("kv_a_norm", i,
                   take(p + "self_attn.kv_a_layernorm.weight"))
            # kv_b_proj [H*(nope+v), r] carries both absorbed factors
            kv_b = take(p + "self_attn.kv_b_proj.weight").reshape(
                H, cfg.qk_nope_head_dim + vd, r)
            st.put("w_uk", i, kv_b[:, :cfg.qk_nope_head_dim])
            st.put("w_uv", i,
                   kv_b[:, cfg.qk_nope_head_dim:].transpose(0, 2, 1))
            st.put("wo", i,
                   take(p + "self_attn.o_proj.weight").T.reshape(
                       H, vd, D))
        elif p + "self_attn.qkv_proj.weight" in ckpt:
            # phi3: fused qkv — rows are [H*Dh | K*Dh | K*Dh]
            qkv = take(p + "self_attn.qkv_proj.weight")
            st.put("wq", i, qkv[:H * Dh].T.reshape(D, H, Dh))
            st.put("wk", i,
                   qkv[H * Dh:(H + K) * Dh].T.reshape(D, K, Dh))
            st.put("wv", i, qkv[(H + K) * Dh:].T.reshape(D, K, Dh))
            st.put("wo", i,
                   take(p + "self_attn.o_proj.weight").T.reshape(H, Dh, D))
        else:
            st.put("wq", i,
                   take(p + "self_attn.q_proj.weight").T.reshape(D, H, Dh))
            st.put("wk", i,
                   take(p + "self_attn.k_proj.weight").T.reshape(D, K, Dh))
            st.put("wv", i,
                   take(p + "self_attn.v_proj.weight").T.reshape(D, K, Dh))
            st.put("wo", i,
                   take(p + "self_attn.o_proj.weight").T.reshape(H, Dh, D))
        if getattr(cfg, "attn_bias", False):
            st.put("bq", i,
                   take(p + "self_attn.q_proj.bias").reshape(H, Dh))
            st.put("bk", i,
                   take(p + "self_attn.k_proj.bias").reshape(K, Dh))
            st.put("bv", i,
                   take(p + "self_attn.v_proj.bias").reshape(K, Dh))
            if p + "self_attn.o_proj.bias" in ckpt:
                st.put("bo", i, take(p + "self_attn.o_proj.bias"))
        if getattr(cfg, "attn_sinks", False):
            st.put("sinks", i, take(p + "self_attn.sinks"),
                   dtype=np.dtype(np.float32))
        if cfg.qk_norm:
            st.put("q_norm", i, take(p + "self_attn.q_norm.weight"))
            st.put("k_norm", i, take(p + "self_attn.k_norm.weight"))
        if layer_is_moe and p + "mlp.experts.gate_up_proj" in ckpt:
            # gpt_oss: fused per-expert parameters, stored [in, out]
            # already (bmm layout); gate/up are INTERLEAVED on the
            # last dim, router is a biased linear
            st.put("router", i, linear_in_out(p + "mlp.router.weight"))
            st.put("router_b", i, take(p + "mlp.router.bias"),
                   dtype=np.dtype(np.float32))
            gu = take(p + "mlp.experts.gate_up_proj")    # [E, D, 2I]
            st.put("we_gate", i, gu[..., ::2])
            st.put("we_up", i, gu[..., 1::2])
            gub = take(p + "mlp.experts.gate_up_proj_bias")  # [E, 2I]
            st.put("we_gate_b", i, gub[..., ::2])
            st.put("we_up_b", i, gub[..., 1::2])
            st.put("we_down", i, take(p + "mlp.experts.down_proj"))
            st.put("we_down_b", i,
                   take(p + "mlp.experts.down_proj_bias"))
        elif layer_is_moe:
            # router: mixtral block_sparse_moe.gate / qwen-moe+deepseek
            # mlp.gate
            for rn in ("block_sparse_moe.gate.weight", "mlp.gate.weight"):
                if p + rn in ckpt:
                    st.put("router", i, linear_in_out(p + rn))
                    break
            else:
                raise SafetensorsError(f"no MoE router for layer {li}")
            if getattr(cfg, "router_bias", False):
                # selection bias stays fp32: bf16 rounding could flip
                # expert choices
                st.put("router_bias", i,
                       take(p + "mlp.gate.e_score_correction_bias"),
                       dtype=np.dtype(np.float32))
            gates, ups, downs = [], [], []
            for e in range(cfg.num_experts):
                if f"{p}block_sparse_moe.experts.{e}.w1.weight" in ckpt:
                    en = f"{p}block_sparse_moe.experts.{e}."
                    g, u, d = en + "w1.weight", en + "w3.weight", \
                        en + "w2.weight"
                else:
                    en = f"{p}mlp.experts.{e}."
                    g, u, d = en + "gate_proj.weight", \
                        en + "up_proj.weight", en + "down_proj.weight"
                gates.append(linear_in_out(g))
                ups.append(linear_in_out(u))
                downs.append(linear_in_out(d))
            st.put("we_gate", i, np.stack(gates))
            st.put("we_up", i, np.stack(ups))
            st.put("we_down", i, np.stack(downs))
            if cfg.num_shared_experts > 0:
                for sn in ("mlp.shared_experts.", "mlp.shared_expert."):
                    if p + sn + "gate_proj.weight" in ckpt:
                        st.put("ws_gate", i,
                               linear_in_out(p + sn + "gate_proj.weight"))
                        st.put("ws_up", i,
                               linear_in_out(p + sn + "up_proj.weight"))
                        st.put("ws_down", i,
                               linear_in_out(p + sn + "down_proj.weight"))
                        break
        elif p + "mlp.gate_up_proj.weight" in ckpt:
            # phi3: fused gate|up rows (Phi3MLP chunks in halves)
            guw = take(p + "mlp.gate_up_proj.weight")
            half = guw.shape[0] // 2
            st.put("w_gate", i, guw[:half].T)
            st.put("w_up", i, guw[half:].T)
            st.put("w_down", i, linear_in_out(p + "mlp.down_proj.weight"))
        else:
            st.put("w_gate", i, linear_in_out(p + "mlp.gate_proj.weight"))
            st.put("w_up", i, linear_in_out(p + "mlp.up_proj.weight"))
            st.put("w_down", i, linear_in_out(p + "mlp.down_proj.weight"))

    params: Dict[str, Any] = {
        "embed": take("model.embed_tokens.weight").astype(np_dt),
        "final_norm": take("model.norm.weight").astype(np_dt),
        "layers": st_main.out,
    }
    if getattr(cfg, "norm_type", "rmsnorm") == "layernorm":
        params["final_norm_bias"] = take("model.norm.bias").astype(np_dt)
    if st_dense is not None:
        params["dense_layers"] = st_dense.out
    if not cfg.tie_word_embeddings:
        if "lm_head.weight" in ckpt:
            params["lm_head"] = linear_in_out(
                "lm_head.weight").astype(np_dt)
        # some checkpoints omit lm_head despite tie=False in config:
        # fall back to tied embeddings (forward() handles the absence)
    if getattr(cfg, "lm_head_bias", False) and "lm_head.bias" in ckpt:
        params["lm_head_bias"] = take("lm_head.bias").astype(np.float32)
    return params


# architectures whose math models/llama.py implements faithfully; a
# config.json outside this list loads only with allow_unsupported
# (e.g. Mllama adds cross-attention vision layers — loading it here
# would produce garbage silently)
SUPPORTED_ARCHITECTURES = frozenset({
    "LlamaForCausalLM", "MistralForCausalLM", "Qwen2ForCausalLM",
    "Qwen3ForCausalLM", "MixtralForCausalLM", "Gemma2ForCausalLM",
    # MLA family (models/mla.py): DeepSeek-V2/V3; Kimi-K2 ships the
    # DeepseekV3ForCausalLM architecture
    "DeepseekV2ForCausalLM", "DeepseekV3ForCausalLM",
    # round 5 (r4 verdict #5): phi3 (fused qkv/gate_up), Phi-3.5-MoE
    # (LayerNorm + sparsemixer), command-r (parallel block, interleaved
    # rope, logit scale), gpt-oss (sinks, clamped-GLU biased experts)
    "Phi3ForCausalLM", "PhimoeForCausalLM", "PhiMoEForCausalLM",
    "CohereForCausalLM", "Cohere2ForCausalLM", "GptOssForCausalLM",
    # decoder embedding models (engine/embed.py): bare AutoModel
    # checkpoints whose tensors lack the "model." prefix
    "MistralModel", "Qwen2Model", "Qwen3Model",
})


def load_params(model_dir: str, cfg=None, dtype=None,
                device_put: bool = True, allow_unsupported: bool = False,
                ) -> Tuple[Dict[str, Any], Any]:
    """Load (params, cfg) from a HF model directory.

    cfg defaults to ModelConfig.from_hf_config(config.json). With
    device_put the numpy tree is transferred to the default device as
    one jnp tree (the sharded path goes through parallel/sharding.py
    with the numpy tree instead).
    """
    from .config import ModelConfig
    if cfg is None:
        with open(os.path.join(model_dir, "config.json")) as f:
            hf = json.load(f)
        archs = hf.get("architectures") or []
        if not allow_unsupported and archs and \
                not set(archs) & SUPPORTED_ARCHITECTURES:
            raise SafetensorsError(
                f"architecture {archs} is not faithfully implemented by "
                f"models/llama.py (supported: "
                f"{sorted(SUPPORTED_ARCHITECTURES)}); pass "
                f"allow_unsupported=True to force-load")
        cfg = ModelConfig.from_hf_config(hf)
    ckpt = Checkpoint(model_dir)
    params = convert_llama(ckpt, cfg, dtype=dtype)
    if dtype is not None:
        cfg = cfg.replace(dtype=dtype)  # compute dtype follows weights
    if device_put:
        import jax
        params = jax.tree.map(lambda a: jax.device_put(a), params)
    return params, cfg
