"""Weight-only quantization (int8 per-channel, int4 groupwise) for serving.

Decode is HBM-bandwidth-bound: every generated token streams all
weights once (bench.py roofline). Symmetric per-output-channel int8
halves the bytes per step vs bf16; groupwise int4 halves them again.
XLA fuses the dequant (nibble unpack, convert, scale) into the matmul
operand read, so the MXU still computes in bf16 while HBM traffic
drops 2x/4x. This is the runtime analog of the reference catalog's
int4/fp8 model-format entries (model.go:262-268) for checkpoints that
ship full precision.

int4 packing is TPU-deliberate: two nibbles per int8 byte, paired as
[first half | second half] of the WHOLE packing axis (byte j holds
rows j and K/2+j), so dequant is two arithmetic shifts + ONE
concatenate — no stride-2 interleave, which XLA:TPU cannot fuse into
the matmul read (measured 1.8x slower on v5e) — and the fused Pallas
kernel (ops/int4_matmul.py) reads each half's matching x slice and
scale rows as CONTIGUOUS blocks (group-interleaved pairing forced a
strided in-kernel shuffle that crashed or starved Mosaic). Scales are
per-(group x output-channel), GPTQ-style, groups contiguous along the
axis.

QTensor is a registered pytree (scan/jit/shard-friendly), dequantized
at use by models/llama.py's weight accessor `_w`.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class QTensor:
    """Quantized weight + broadcastable f32 scale.

    bits=8: `q` int8 in the original shape, `s` with contraction dims
    of size 1 (per-output-channel).
    bits=4: `q` int8 carrying two nibbles, with the packing axis
    halved; `s` with the packing axis sized n_groups and other
    contraction dims 1.
    """

    q: jax.Array
    s: jax.Array
    bits: int = 8            # static
    # static: packing/group axis for bits=4, stored NEGATIVE (offset
    # from the last dim) so it survives lax.scan slicing layer leaves
    # off the stacked [L, ...] tree and gather prepending index dims
    axis: int = -1

    def dequant(self, dtype=jnp.bfloat16) -> jax.Array:
        if self.bits == 8:
            return (self.q.astype(jnp.float32) * self.s).astype(dtype)
        return _unpack4(self.q, self.s, self.axis).astype(dtype)

    def take(self, idx: jax.Array, dtype=jnp.bfloat16) -> jax.Array:
        """Row gather (embedding lookup) without full dequant."""
        rows = jnp.take(self.q, idx, axis=0)
        scales = jnp.take(self.s, idx, axis=0)
        if self.bits == 8:
            return (rows.astype(jnp.float32) * scales).astype(dtype)
        return _unpack4(rows, scales, self.axis).astype(dtype)

    @property
    def shape(self):
        if self.bits == 8:
            return self.q.shape
        sh = list(self.q.shape)
        sh[self.axis] *= 2
        return tuple(sh)

    @property
    def size(self):
        n = 1
        for d in self.shape:
            n *= d
        return n


jax.tree_util.register_dataclass(
    QTensor, data_fields=("q", "s"), meta_fields=("bits", "axis"))


def _unpack4(q: jax.Array, s: jax.Array, axis: int) -> jax.Array:
    """Dequantize half-packed int4: q [..., K/2, ...] -> f32 [..., K, ...].

    Byte j holds original rows j (low nibble) and K/2+j (high nibble),
    so unpack is one concatenate of the two nibble planes along the
    axis; s has n_groups contiguous groups along the axis.
    """
    axis = axis % q.ndim
    n_groups = s.shape[axis]
    pre, post = q.shape[:axis], q.shape[axis + 1:]
    lo = jnp.left_shift(q, 4) >> 4                # sign-extended nibble
    hi = q >> 4                                   # arithmetic shift
    full = jnp.concatenate([lo, hi], axis=axis).astype(jnp.float32)
    K = 2 * q.shape[axis]
    gsize = K // n_groups
    fr = full.reshape(pre + (n_groups, gsize) + post)
    sr = s.reshape(s.shape[:axis] + (n_groups, 1) + s.shape[axis + 1:])
    out = fr * sr
    return out.reshape(pre + (K,) + post)


def quantize_tensor(w: jax.Array, contract_axes) -> QTensor:
    """Per-output-channel symmetric int8: scales span `contract_axes`
    (the dims the matmul sums over), so each output channel gets its
    own dynamic range."""
    w32 = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=tuple(contract_axes),
                   keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / s), -127, 127).astype(jnp.int8)
    return QTensor(q=q, s=s, bits=8)


def quantize_tensor_fp8(w: jax.Array, contract_axes) -> QTensor:
    """Per-output-channel scaled float8_e4m3: same byte footprint as
    int8 but a floating 4-bit mantissa — the v6e-native weight format
    (v6e converts fp8 in the MXU datapath; on v5e it lowers to the
    same convert+scale XLA fuses for int8). Scale to the e4m3 max so
    the channel's range uses the format's full span."""
    w32 = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=tuple(contract_axes),
                   keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 448.0  # e4m3 finite max
    q = (w32 / s).astype(jnp.float8_e4m3fn)
    return QTensor(q=q, s=s, bits=8)


def quantize_tensor_int4(w: jax.Array, contract_axes,
                         group: int = 128) -> QTensor:
    """Groupwise symmetric int4, concat-packed along the first
    contraction axis. Falls back to one group when the axis doesn't
    split evenly into even-sized groups."""
    axis = contract_axes[0]
    w32 = jnp.asarray(w, jnp.float32)
    K = w32.shape[axis]
    if K % group == 0 and group % 2 == 0:
        n_groups = K // group
    elif K % 2 == 0:
        n_groups = 1  # axis too small/ragged for groups: one scale
    else:
        raise ValueError(f"int4 needs an even packing dim, got {K}")
    gsize = K // n_groups
    pre, post = w32.shape[:axis], w32.shape[axis + 1:]
    wg = w32.reshape(pre + (n_groups, gsize) + post)
    # scales span the group slice plus the OTHER contraction dims
    other = tuple(a + 1 if a > axis else a
                  for a in contract_axes[1:])
    amax = jnp.max(jnp.abs(wg), axis=(axis + 1,) + other, keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 7.0
    qg = jnp.clip(jnp.round(wg / s), -7, 7).astype(jnp.int8)
    qfull = qg.reshape(pre + (K,) + post)
    lo, hi = jnp.split(qfull, 2, axis=axis)       # halves of the AXIS
    packed = (hi << 4) | (lo & 0x0F)              # [., K/2, .]
    s = jnp.squeeze(s, axis=axis + 1)             # [., n_groups, .(1s)]
    return QTensor(q=packed, s=s, bits=4, axis=axis - w32.ndim)


# contraction axes per stacked-layer leaf ([L, ...]; axis 0 = layer)
_LAYER_CONTRACT = {
    "wq": (1,), "wk": (1,), "wv": (1,),   # [L, D, H, Dh]: sum over D
    "wo": (2, 1),                          # [L, H, Dh, D]: sum over H,Dh
    "w_gate": (1,), "w_up": (1,),          # [L, D, F]
    "w_down": (1,),                        # [L, F, D]
    "we_gate": (2,), "we_up": (2,),        # [L, E, D, F]
    "we_down": (2,),                       # [L, E, F, D]
    "ws_gate": (1,), "ws_up": (1,), "ws_down": (1,),
    # MLA projections (models/mla.py); norms/biases stay fp
    "wq_a": (1,),                          # [L, D, q_rank]
    "wq_b": (1,),                          # [L, q_rank, H, qk]
    "wkv_a": (1,),                         # [L, D, r+rope]
    "w_uk": (2,),                          # [L, H, nope, r]
    "w_uv": (2,),                          # [L, H, r, v]
}
_TOP_CONTRACT = {
    "embed": (1,),     # per-ROW scales: rows are both lookup outputs
    "lm_head": (0,),   # [D, V]: sum over D
}


def quantize_params(params: Dict[str, Any], mode: str = "int8",
                    group: int = 128) -> Dict[str, Any]:
    """Quantize the big matmul weights; norms/biases/router stay full
    precision (tiny, and routing is precision-sensitive).

    mode="int8": per-output-channel symmetric int8 everywhere.
    mode="fp8": per-output-channel scaled float8_e4m3 everywhere —
    the catalog's fp8 model-format analog (model.go:262-268) for
    full-precision checkpoints, v6e-targeted (same bytes as int8;
    v6e's MXU consumes fp8 natively).
    mode="int4": groupwise int4 for the layer matmuls; embed/lm_head
    stay int8 (their error feeds every position — the GPTQ convention
    of keeping embeddings at higher precision), and so do the
    down-projections (w_down/ws_down): their packing axis F is the
    tp-sharded row dim (parallel/sharding._LAYER_RULES), and nibble
    pairs spanning device shards would force GSPMD to all-gather the
    weight every step — worse than the bytes saved. wo also stays
    int8: its pack axis (Dh) sits under the H head dim, so the
    half-packed flattened layout the fused kernel streams can't stay
    contiguous for it.
    """
    if mode not in ("int8", "int4", "fp8"):
        raise ValueError(f"unknown quantization mode {mode!r}")
    int4 = mode == "int4"
    base_q = quantize_tensor_fp8 if mode == "fp8" else quantize_tensor
    _INT8_ONLY = {"w_down", "ws_down", "wo"}
    log = logging.getLogger("ome.models.quant")

    def q_layer(k: str, v):
        if k not in _LAYER_CONTRACT:
            return v
        axes = _LAYER_CONTRACT[k]
        if int4 and k not in _INT8_ONLY:
            try:
                return quantize_tensor_int4(v, axes, group=group)
            except ValueError as e:
                log.info("int4: %s falls back to int8 (%s)", k, e)
                return quantize_tensor(v, axes)
        return base_q(v, axes)

    out: Dict[str, Any] = {}
    for name, leaf in params.items():
        if name in ("layers", "dense_layers"):
            out[name] = {k: q_layer(k, v) for k, v in leaf.items()}
        elif name in _TOP_CONTRACT:
            out[name] = base_q(leaf, _TOP_CONTRACT[name])
        else:
            out[name] = leaf
    return out


def quantized_bytes(params: Dict[str, Any]) -> int:
    """Weight bytes per full read (the decode-roofline numerator)."""
    total = 0
    for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QTensor)):
        if isinstance(leaf, QTensor):
            total += leaf.q.size + leaf.s.size * 4
        else:
            total += leaf.size * leaf.dtype.itemsize
    return total
