"""Weight-only int8 quantization for serving.

Decode is HBM-bandwidth-bound: every generated token streams all
weights once (bench.py roofline). Symmetric per-output-channel int8
halves the bytes per step vs bf16 — XLA fuses the int8->bf16 convert
and scale multiply into the matmul operand read, so the MXU still
computes in bf16 while HBM traffic drops ~2x. This is the runtime
analog of the reference catalog's int4/fp8 model-format entries
(model.go:262-268) for checkpoints that ship full-precision.

QTensor is a registered pytree (scan/jit/shard-friendly): `q` int8
plus a per-output-channel `s` scale, dequantized at use by
models/llama.py's weight accessor.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QTensor:
    """Symmetric int8 weight + broadcastable f32 scale."""

    q: jax.Array          # int8, original shape
    s: jax.Array          # f32, shape with contraction dims = 1

    def dequant(self, dtype=jnp.bfloat16) -> jax.Array:
        return (self.q.astype(jnp.float32) * self.s).astype(dtype)

    def take(self, idx: jax.Array, dtype=jnp.bfloat16) -> jax.Array:
        """Row gather (embedding lookup) without full dequant."""
        rows = jnp.take(self.q, idx, axis=0).astype(jnp.float32)
        scales = jnp.take(self.s, idx, axis=0)
        return (rows * scales).astype(dtype)

    @property
    def shape(self):
        return self.q.shape

    @property
    def size(self):
        return self.q.size


def quantize_tensor(w: jax.Array, contract_axes) -> QTensor:
    """Per-output-channel symmetric int8: scales span `contract_axes`
    (the dims the matmul sums over), so each output channel gets its
    own dynamic range."""
    w32 = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=tuple(contract_axes),
                   keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / s), -127, 127).astype(jnp.int8)
    return QTensor(q=q, s=s)


# contraction axes per stacked-layer leaf ([L, ...]; axis 0 = layer)
_LAYER_CONTRACT = {
    "wq": (1,), "wk": (1,), "wv": (1,),   # [L, D, H, Dh]: sum over D
    "wo": (1, 2),                          # [L, H, Dh, D]: sum over H,Dh
    "w_gate": (1,), "w_up": (1,),          # [L, D, F]
    "w_down": (1,),                        # [L, F, D]
    "we_gate": (2,), "we_up": (2,),        # [L, E, D, F]
    "we_down": (2,),                       # [L, E, F, D]
    "ws_gate": (1,), "ws_up": (1,), "ws_down": (1,),
}
_TOP_CONTRACT = {
    "embed": (1,),     # per-ROW scales: rows are both lookup outputs
    "lm_head": (0,),   # [D, V]: sum over D
}


def quantize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """int8-quantize the big matmul weights; norms/biases/router stay
    full precision (tiny, and routing is precision-sensitive)."""
    out: Dict[str, Any] = {}
    for name, leaf in params.items():
        if name == "layers":
            out["layers"] = {
                k: (quantize_tensor(v, _LAYER_CONTRACT[k])
                    if k in _LAYER_CONTRACT else v)
                for k, v in leaf.items()
            }
        elif name in _TOP_CONTRACT:
            out[name] = quantize_tensor(leaf, _TOP_CONTRACT[name])
        else:
            out[name] = leaf
    return out


def quantized_bytes(params: Dict[str, Any]) -> int:
    """Weight bytes per full read (the decode-roofline numerator)."""
    total = 0
    for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QTensor)):
        if isinstance(leaf, QTensor):
            total += leaf.q.size + leaf.s.size * 4
        else:
            total += leaf.size * leaf.dtype.itemsize
    return total
