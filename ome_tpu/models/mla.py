"""Multi-head Latent Attention (DeepSeek-V2/V3, Kimi-K2).

The reference ships first-class DeepSeek support throughout
(/root/reference/pkg/hfutil/modelconfig/deepseek_v3.go, the srt PD
runtime YAMLs) but delegates the math to SGLang; here it is
implemented TPU-first:

  * the KV cache stores per-token LATENTS — `kv_a_proj` output
    (kv_lora_rank) + the shared rope key (qk_rope_head_dim) — instead
    of per-head K/V. For DeepSeek-V3 that is 576 values/token vs
    128 heads x 2 x 192 = 49k for naive MHA caching: an ~85x cut in
    the decode step's KV bytes, which is exactly what the
    bandwidth-bound TPU decode roofline wants (bench.py).
  * decode uses the ABSORBED-weight path: q_nope is projected through
    w_uk into latent space once per step, scores and the attention-
    weighted sum run entirely against the latent cache, and w_uv
    lifts the result back per head — no materialized K/V at decode.
  * prefill materializes per-head K/V from the latents with two
    einsums (compute-bound anyway) and reuses plain masked SDPA.

RoPE on the rope dims uses the interleaved-pair convention of the HF
reference (modeling_deepseek_v2.apply_rotary_emb /
v3.apply_rotary_pos_emb_interleave); attention scores are permutation-
invariant to the pair layout, so logits match both variants.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .config import ModelConfig

Params = dict


def yarn_frequencies(cfg: ModelConfig, d: int):
    """Rope inverse frequencies + cos/sin attention factor.

    Plain RoPE unless cfg.rope_scaling is YaRN, in which case the
    published YaRN recipe applies (frequency interpolation below the
    beta_slow boundary, extrapolation above beta_fast, a linear ramp
    between — and the mscale attention factor on cos/sin), matching
    transformers' _compute_yarn_parameters as DeepSeek configures it
    (dim = qk_rope_head_dim).
    """
    import math
    half = d // 2
    pos_freqs = cfg.rope_theta ** (jnp.arange(half, dtype=jnp.float32)
                                   * 2 / d)
    inv_freq = 1.0 / pos_freqs
    rs = cfg.rope_scaling or {}
    if rs.get("rope_type", rs.get("type")) != "yarn":
        return inv_freq, 1.0
    factor = rs.get("factor", 1.0)
    beta_fast = rs.get("beta_fast") or 32
    beta_slow = rs.get("beta_slow") or 1
    orig = (rs.get("original_max_position_embeddings")
            or cfg.max_seq_len)

    def correction_dim(n_rot):
        return (d * math.log(orig / (n_rot * 2 * math.pi))
                / (2 * math.log(cfg.rope_theta)))

    low = max(math.floor(correction_dim(beta_fast)), 0)
    high = min(math.ceil(correction_dim(beta_slow)), d - 1)
    if low == high:
        high += 0.001
    ramp = jnp.clip((jnp.arange(half, dtype=jnp.float32) - low)
                    / (high - low), 0, 1)
    extrapolation_factor = 1.0 - ramp
    inv_freq = (inv_freq / factor * ramp
                + inv_freq * extrapolation_factor)

    def get_mscale(scale, m=1.0):
        return 0.1 * m * math.log(scale) + 1.0 if scale > 1 else 1.0

    att = rs.get("attention_factor")
    if att is None:
        mscale, mscale_all = rs.get("mscale"), rs.get("mscale_all_dim")
        if mscale and mscale_all:
            att = get_mscale(factor, mscale) / get_mscale(factor,
                                                          mscale_all)
        else:
            att = get_mscale(factor)
    return inv_freq, float(att)


def rope_interleaved(x: jax.Array, positions: jax.Array,
                     cfg: ModelConfig) -> jax.Array:
    """Rotate interleaved pairs: (x[2j], x[2j+1]) by pos * inv_freq_j,
    with YaRN frequency remapping + mscale when configured.

    x: [B, S, N, D] (N may be 1 for the shared MQA rope key)."""
    d = x.shape[-1]
    freqs, att = yarn_frequencies(cfg, d)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,d/2]
    cos = jnp.cos(angles)[:, :, None, :] * att
    sin = jnp.sin(angles)[:, :, None, :] * att
    xf = x.astype(jnp.float32)
    x0 = xf[..., 0::2]
    x1 = xf[..., 1::2]
    out0 = x0 * cos - x1 * sin
    out1 = x0 * sin + x1 * cos
    # scores are invariant to pair ordering as long as q and k agree,
    # so emit [evens | odds] (a cheap concat, no re-interleave)
    return jnp.concatenate([out0, out1], axis=-1).astype(x.dtype)


def _masked_softmax(scores: jax.Array, q_pos: jax.Array,
                    k_pos: jax.Array,
                    kv_len: Optional[jax.Array]) -> jax.Array:
    """scores [B, H, S, T]; causal + kv-length masking, fp32 softmax."""
    mask = k_pos[None, None, None, :] <= q_pos[:, None, :, None]
    if kv_len is not None:
        mask &= k_pos[None, None, None, :] < kv_len[:, None, None, None]
    scores = jnp.where(mask, scores, -1e30)
    return jax.nn.softmax(scores.astype(jnp.float32), axis=-1)


def mla_attention(h: jax.Array, lp: Params, cfg: ModelConfig,
                  positions: jax.Array,
                  kv_len: Optional[jax.Array],
                  cache_kv: Optional[Tuple[jax.Array, jax.Array]],
                  cache_index: Optional[jax.Array]):
    """One MLA attention block (pre-normed input h [B, S, D]).

    Returns (attn_out [B, S, D], new_cache_kv or None). The cache's k
    plane holds latents [B, Smax, 1, kv_lora_rank + rope]; the v plane
    is zero-width (cfg.kv_cache_v_dim == 0).
    """
    B, S, _ = h.shape
    Hn = cfg.num_heads
    nope, rope = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    r = cfg.kv_lora_rank

    from .llama import _w, rms_norm  # shared weight accessor / norm

    # -- queries -------------------------------------------------------
    if cfg.q_lora_rank:
        ql = jnp.einsum("bsd,dr->bsr", h, _w(lp, "wq_a", cfg.dtype))
        ql = rms_norm(ql, lp["q_a_norm"], cfg.rms_norm_eps)
        q = jnp.einsum("bsr,rhk->bshk", ql, _w(lp, "wq_b", cfg.dtype))
    else:
        q = jnp.einsum("bsd,dhk->bshk", h, _w(lp, "wq", cfg.dtype))
    q_nope, q_pe = q[..., :nope], q[..., nope:]
    q_pe = rope_interleaved(q_pe, positions, cfg)

    # -- latent K/V ----------------------------------------------------
    ckv = jnp.einsum("bsd,dr->bsr", h, _w(lp, "wkv_a", cfg.dtype))
    c, k_pe = ckv[..., :r], ckv[..., r:]
    c = rms_norm(c, lp["kv_a_norm"], cfg.rms_norm_eps)
    k_pe = rope_interleaved(k_pe[:, :, None, :], positions,
                            cfg)[:, :, 0]
    latent = jnp.concatenate([c, k_pe], axis=-1)[:, :, None, :]

    if cache_kv is not None:
        ck_cache, cv_cache = cache_kv
        if cache_index.ndim == 1:
            upd = jax.vmap(
                lambda cc, u, i: lax.dynamic_update_slice(
                    cc, u.astype(cc.dtype), (i, 0, 0)))
            ck_cache = upd(ck_cache, latent, cache_index)
        else:
            ck_cache = lax.dynamic_update_slice(
                ck_cache, latent.astype(ck_cache.dtype),
                (0, cache_index, 0, 0))
        new_cache = (ck_cache, cv_cache)
        full = ck_cache[:, :, 0]                     # [B, T, r+rope]
        k_pos = jnp.arange(full.shape[1], dtype=jnp.int32)
    else:
        new_cache = None
        full = latent[:, :, 0]                       # [B, S, r+rope]
        k_pos = None
    c_all, kpe_all = full[..., :r], full[..., r:]
    scale = cfg.mla_scale

    if S == 1 and cache_kv is not None:
        # -- absorbed decode: never leave latent space -----------------
        w_uk = _w(lp, "w_uk", cfg.dtype)             # [H, nope, r]
        w_uv = _w(lp, "w_uv", cfg.dtype)             # [H, r, v_dim]
        q_lat = jnp.einsum("bshn,hnr->bshr", q_nope, w_uk)
        scores = (jnp.einsum("bshr,btr->bhst", q_lat, c_all)
                  + jnp.einsum("bshp,btp->bhst", q_pe, kpe_all)) * scale
        attn = _masked_softmax(scores, positions, k_pos, kv_len)
        out_lat = jnp.einsum("bhst,btr->bshr",
                             attn.astype(c_all.dtype), c_all)
        attn_out = jnp.einsum("bshr,hrv->bshv", out_lat, w_uv)
    else:
        # -- prefill: materialize per-head K/V from the latents --------
        k_nope = jnp.einsum("btr,hnr->bthn", c_all,
                            _w(lp, "w_uk", cfg.dtype))
        v = jnp.einsum("btr,hrv->bthv", c_all,
                       _w(lp, "w_uv", cfg.dtype))
        k = jnp.concatenate(
            [k_nope, jnp.broadcast_to(
                kpe_all[:, :, None, :],
                (*k_nope.shape[:3], rope)).astype(k_nope.dtype)],
            axis=-1)
        qf = jnp.concatenate([q_nope, q_pe.astype(q_nope.dtype)],
                             axis=-1)
        scores = jnp.einsum("bshk,bthk->bhst", qf, k) * scale
        if k_pos is None:
            k_pos_eff = positions[0]                 # plain causal
        else:
            k_pos_eff = k_pos
        attn = _masked_softmax(scores, positions, k_pos_eff, kv_len)
        attn_out = jnp.einsum("bhst,bthv->bshv",
                              attn.astype(v.dtype), v)

    out = jnp.einsum("bshv,hvd->bsd", attn_out, _w(lp, "wo", cfg.dtype))
    return out, new_cache
