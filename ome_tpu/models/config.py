"""Model configuration for the JAX data plane.

The reference operator parses HF config.json into metadata
(pkg/hfutil/modelconfig) and delegates math to SGLang/vLLM; here the data
plane is in-repo, so the same parsed config drives real JAX models.
Covers the Llama family superset: GQA, RoPE scaling, tied embeddings,
MoE (Mixtral/Qwen-MoE/DeepSeek-style) and sliding-window knobs.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax.numpy as jnp


@dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    intermediate_size: int = 14336
    rope_theta: float = 500000.0
    rope_scaling: Optional[Dict[str, Any]] = None
    rms_norm_eps: float = 1e-5
    max_seq_len: int = 8192
    tie_word_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    # MoE (0 experts -> dense MLP)
    num_experts: int = 0
    experts_per_token: int = 0
    moe_intermediate_size: int = 0
    num_shared_experts: int = 0
    # "dense" computes every expert (GSPMD-shardable everywhere);
    # "ragged" sorts tokens by expert and runs grouped matmuls
    # (lax.ragged_dot) — O(k/E) of the dense FLOPs, the serving path
    moe_impl: str = "dense"
    # MLA (DeepSeek-V2/V3, Kimi-K2): compressed-KV attention — the KV
    # cache stores per-token latents [kv_lora_rank + qk_rope_head_dim]
    # instead of per-head K/V (models/mla.py)
    mla: bool = False
    q_lora_rank: Optional[int] = None
    kv_lora_rank: int = 0
    qk_nope_head_dim: int = 0
    qk_rope_head_dim: int = 0
    v_head_dim: int = 0
    first_k_dense: int = 0     # leading dense layers before MoE blocks
    # routing flavor: "mixtral" (softmax over the selected top-k),
    # "softmax_v2" (full softmax, optional group-limited greedy),
    # "sigmoid_v3" (sigmoid + selection bias + top-2-sum group scores)
    router_scoring: str = "mixtral"
    n_group: int = 0
    topk_group: int = 0
    routed_scaling_factor: float = 1.0
    norm_topk_prob: bool = False
    router_bias: bool = False  # e_score_correction_bias tensor present
    # attention extras
    sliding_window: Optional[int] = None
    attn_logit_softcap: Optional[float] = None
    qk_norm: bool = False
    attn_bias: bool = False  # qwen2-style q/k/v projection biases
    # gemma2-family block shape (models/llama.py pair-scan path)
    mlp_activation: str = "silu"      # "silu" | "gelu_tanh"
    alt_sliding_window: bool = False  # periodic sliding/global layers
    sliding_pattern: int = 2          # period P: every P-th is global
    rope_skip_global: bool = False    # cohere2: global layers are NoPE
    query_scale: Optional[float] = None  # overrides head_dim**-0.5
    post_block_norms: bool = False    # post-attn/post-mlp RMSNorms
    embed_scale: bool = False         # x *= sqrt(hidden) after embed
    unit_offset_norm: bool = False    # RMSNorm scales by (1 + w)
    final_logit_softcap: Optional[float] = None
    # round-5 architecture breadth (r4 verdict #5)
    # "rmsnorm" | "layernorm" (torch LayerNorm, affine+bias: phimoe) |
    # "layernorm_nobias" (mean-centered, weight-only: command-r)
    norm_type: str = "rmsnorm"
    parallel_block: bool = False   # command-r: x + attn(n(x)) + mlp(n(x))
    logit_scale: Optional[float] = None  # command-r final-logit mult
    rope_interleaved: bool = False  # command-r even/odd pair rotation
    attn_sinks: bool = False       # gpt_oss per-head learned sink logit
    lm_head_bias: bool = False     # phimoe
    router_jitter: float = 0.0     # phimoe sparsemixer threshold eps
    moe_activation: str = "silu"   # "silu" | "gptoss_glu" (clamped)
    moe_bias: bool = False         # gpt_oss expert + router biases

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    # KV-cache geometry (engine + KVCache.create): MLA caches ONE
    # latent "head" of kv_lora_rank+rope dims and no separate V rows
    @property
    def kv_cache_heads(self) -> int:
        return 1 if self.mla else self.num_kv_heads

    @property
    def kv_cache_k_dim(self) -> int:
        if self.mla:
            return self.kv_lora_rank + self.qk_rope_head_dim
        return self.head_dim

    @property
    def kv_cache_v_dim(self) -> int:
        return 0 if self.mla else self.head_dim

    @property
    def mla_scale(self) -> float:
        """qk_head_dim**-0.5, yarn-mscale-corrected when rope_scaling
        carries mscale_all_dim (DeepseekV3Attention.__init__)."""
        s = (self.qk_nope_head_dim + self.qk_rope_head_dim) ** -0.5
        rs = self.rope_scaling or {}
        mscale_all = rs.get("mscale_all_dim", 0)
        if mscale_all:
            factor = rs.get("factor", 1.0)
            if factor > 1.0:
                import math
                m = 0.1 * mscale_all * math.log(factor) + 1.0
                s *= m * m
        return s

    def replace(self, **kw) -> "ModelConfig":
        return dataclasses.replace(self, **kw)

    @classmethod
    def from_hf_config(cls, cfg: Dict[str, Any]) -> "ModelConfig":
        """Build from a HuggingFace config.json dict (llama/qwen2/qwen3/
        mistral/mixtral families — the set models/checkpoint.py
        SUPPORTED_ARCHITECTURES accepts)."""
        hidden = cfg.get("hidden_size", 4096)
        heads = cfg.get("num_attention_heads", 32)
        archs = cfg.get("architectures") or [""]
        arch = archs[0]
        sc_raw = cfg.get("rope_scaling")
        if sc_raw and sc_raw.get("rope_type",
                                 sc_raw.get("type")) == "su":
            # normalize early Phi-3's original spelling ONCE so every
            # downstream reader (_rope_frequencies, the attention
            # factor, mla) sees the canonical name
            cfg = dict(cfg, rope_scaling=dict(sc_raw,
                                              rope_type="longrope"))
        deepseek = arch.startswith("Deepseek")
        mla_kw = {}
        if deepseek:
            # DeepSeek-V2/V3 family (Kimi-K2 ships the V3 architecture):
            # MLA attention + first-k-dense MoE + its routing flavor
            v3 = arch.startswith("DeepseekV3")
            mla_kw = dict(
                mla=True,
                q_lora_rank=cfg.get("q_lora_rank"),
                kv_lora_rank=cfg.get("kv_lora_rank", 512),
                qk_nope_head_dim=cfg.get("qk_nope_head_dim", 128),
                qk_rope_head_dim=cfg.get("qk_rope_head_dim", 64),
                v_head_dim=cfg.get("v_head_dim", 128),
                first_k_dense=cfg.get("first_k_dense_replace", 0),
                router_scoring="sigmoid_v3" if v3 else "softmax_v2",
                n_group=cfg.get("n_group", 0) or 0,
                topk_group=cfg.get("topk_group", 0) or 0,
                routed_scaling_factor=cfg.get("routed_scaling_factor",
                                              1.0),
                norm_topk_prob=bool(cfg.get("norm_topk_prob", v3)),
                router_bias=v3,
            )
            if not v3 and cfg.get("topk_method") == "greedy":
                mla_kw["n_group"] = 0  # V2-lite: plain greedy top-k
        # qwen2 uses qkv biases (not spelled out in its config.json);
        # qwen3 replaces them with per-head q/k RMS norms
        attn_bias = cfg.get("attention_bias",
                            cfg.get("qkv_bias", arch.startswith("Qwen2")))
        gemma2 = arch == "Gemma2ForCausalLM"
        qscale = None
        if gemma2 and cfg.get("query_pre_attn_scalar"):
            qscale = cfg["query_pre_attn_scalar"] ** -0.5
        # yarn/longrope multiply cos AND sin by an attention factor;
        # q and k both scale, so logits scale by att^2 — fold it into
        # the query scale (KV cache stays unscaled). MLA models apply
        # their own mscale (models/mla.py) and skip this.
        if not deepseek:
            att = _rope_attention_factor(
                cfg.get("rope_scaling"),
                cfg.get("max_position_embeddings", 8192))
            if att != 1.0:
                head_dim = cfg.get("head_dim") or hidden // heads
                qscale = (qscale if qscale is not None
                          else head_dim ** -0.5) * att * att
        extra = {}
        if arch in ("PhimoeForCausalLM", "PhiMoEForCausalLM"):
            # the official Phi-3.5-MoE repo ships the capital-E
            # spelling; the transformers class uses Phimoe
            # Phi-3.5-MoE: torch LayerNorm (bias) everywhere, optional
            # lm_head bias, sparsemixer top-2 routing
            # (cite ref: pkg/hfutil/modelconfig parses phimoe configs)
            extra = dict(norm_type="layernorm",
                         lm_head_bias=bool(cfg.get("lm_head_bias")),
                         router_scoring="sparsemixer",
                         router_jitter=cfg.get("router_jitter_noise",
                                               0.01) or 0.0)
        elif arch == "Cohere2ForCausalLM":
            # command-r7b / command-a: the cohere parallel block plus
            # a period-4 sliding pattern whose global layers skip RoPE
            # (cite ref: pkg/hfutil/modelconfig parses cohere2)
            extra = dict(norm_type="layernorm_nobias",
                         parallel_block=True,
                         logit_scale=cfg.get("logit_scale", 1.0),
                         rope_interleaved=True,
                         rms_norm_eps=cfg.get("layer_norm_eps", 1e-5),
                         alt_sliding_window=True,
                         sliding_pattern=cfg.get(
                             "sliding_window_pattern", 4),
                         rope_skip_global=True)
        elif arch in ("CohereForCausalLM", "CohereModel"):
            # command-r: weight-only mean-centered LayerNorm, PARALLEL
            # attn+MLP residual off one shared norm, interleaved rope,
            # logit scaling, per-head q/k norms on R+
            # (cite ref: pkg/hfutil/modelconfig/commandr.go)
            extra = dict(norm_type="layernorm_nobias",
                         parallel_block=True,
                         logit_scale=cfg.get("logit_scale", 1.0),
                         rope_interleaved=True,
                         qk_norm=bool(cfg.get("use_qk_norm")),
                         rms_norm_eps=cfg.get("layer_norm_eps", 1e-5))
        elif arch == "GptOssForCausalLM":
            # gpt-oss: attention sinks, alternating sliding layers,
            # top-4 softmax router with bias, clamped-GLU experts with
            # biases (cite ref: pkg/hfutil/modelconfig/gpt_oss.go)
            extra = dict(attn_sinks=True, alt_sliding_window=True,
                         router_bias=True, moe_bias=True,
                         moe_activation="gptoss_glu",
                         moe_intermediate_size=cfg.get(
                             "intermediate_size", 4 * hidden))
        kw = dict(
            vocab_size=cfg.get("vocab_size", 32000),
            hidden_size=hidden,
            num_layers=cfg.get("num_hidden_layers", 32),
            num_heads=heads,
            num_kv_heads=cfg.get("num_key_value_heads", heads),
            head_dim=cfg.get("head_dim") or hidden // heads,
            intermediate_size=cfg.get("intermediate_size", 4 * hidden),
            rope_theta=cfg.get("rope_theta", 10000.0),
            rope_scaling=cfg.get("rope_scaling"),
            rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
            max_seq_len=cfg.get("max_position_embeddings", 8192),
            tie_word_embeddings=cfg.get("tie_word_embeddings", False),
            num_experts=cfg.get("num_local_experts",
                                cfg.get("num_experts",
                                        cfg.get("n_routed_experts", 0))) or 0,
            experts_per_token=cfg.get("num_experts_per_tok", 0) or 0,
            moe_intermediate_size=cfg.get("moe_intermediate_size", 0) or 0,
            num_shared_experts=cfg.get("n_shared_experts", 0) or 0,
            sliding_window=cfg.get("sliding_window")
            if cfg.get("use_sliding_window", True) else None,
            attn_logit_softcap=cfg.get("attn_logit_softcapping"),
            qk_norm=arch.startswith("Qwen3"),
            attn_bias=bool(attn_bias),
            mlp_activation="gelu_tanh" if gemma2 else "silu",
            alt_sliding_window=gemma2,
            query_scale=qscale,
            post_block_norms=gemma2,
            embed_scale=gemma2,
            unit_offset_norm=gemma2,
            final_logit_softcap=cfg.get("final_logit_softcapping"),
        )
        kw.update(mla_kw)
        kw.update(extra)  # per-architecture overrides win
        return cls(**kw)


def _rope_attention_factor(sc: Optional[Dict[str, Any]],
                           max_pos: int) -> float:
    """cos/sin attention factor of yarn/longrope scaling (transformers
    _compute_{yarn,longrope}_parameters)."""
    if not sc:
        return 1.0
    import math
    t = sc.get("rope_type", sc.get("type"))
    if t == "yarn":
        att = sc.get("attention_factor")
        if att is not None:
            return float(att)
        f = sc.get("factor", 1.0)
        return 0.1 * math.log(f) + 1.0 if f > 1 else 1.0
    if t == "longrope":
        att = sc.get("attention_factor")
        if att is not None:
            return float(att)
        orig = sc.get("original_max_position_embeddings") or max_pos
        s = max_pos / orig
        if s <= 1.0:
            return 1.0
        return math.sqrt(1.0 + math.log(s) / math.log(orig))
    return 1.0


# -- presets ---------------------------------------------------------------

def llama3_8b() -> ModelConfig:
    return ModelConfig(vocab_size=128256, hidden_size=4096, num_layers=32,
                       num_heads=32, num_kv_heads=8, head_dim=128,
                       intermediate_size=14336, rope_theta=500000.0,
                       max_seq_len=8192)


def llama3_70b() -> ModelConfig:
    return ModelConfig(vocab_size=128256, hidden_size=8192, num_layers=80,
                       num_heads=64, num_kv_heads=8, head_dim=128,
                       intermediate_size=28672, rope_theta=500000.0,
                       max_seq_len=8192)


def qwen25_05b() -> ModelConfig:
    return ModelConfig(vocab_size=151936, hidden_size=896, num_layers=24,
                       num_heads=14, num_kv_heads=2, head_dim=64,
                       intermediate_size=4864, rope_theta=1000000.0,
                       tie_word_embeddings=True, max_seq_len=32768)


def mixtral_8x7b() -> ModelConfig:
    return ModelConfig(vocab_size=32000, hidden_size=4096, num_layers=32,
                       num_heads=32, num_kv_heads=8, head_dim=128,
                       intermediate_size=14336, rope_theta=1000000.0,
                       num_experts=8, experts_per_token=2,
                       moe_intermediate_size=14336, max_seq_len=32768)


def tiny_test(moe: bool = False) -> ModelConfig:
    """Structurally-faithful small config for tests and dry runs."""
    return ModelConfig(vocab_size=512, hidden_size=128, num_layers=4,
                       num_heads=8, num_kv_heads=4, head_dim=16,
                       intermediate_size=256, max_seq_len=256,
                       rope_theta=10000.0,
                       num_experts=8 if moe else 0,
                       experts_per_token=2 if moe else 0,
                       moe_intermediate_size=128 if moe else 0)
