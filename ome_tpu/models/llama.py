"""Flagship Llama-family decoder in pure-functional JAX.

TPU-first design choices (vs. the torch modules the reference's engines
wrap): parameters are a pytree of stacked per-layer arrays scanned with
`lax.scan` (one compiled layer body, natural fit for pipeline stages),
bf16 weights with fp32 softmax/norm accumulation, static shapes
everywhere, and attention dispatched through ome_tpu.ops so the Pallas
flash kernel is used on TPU with an XLA fallback on the CPU test mesh.

Covers dense Llama/Mistral/Qwen2 (qkv bias)/Qwen3 (qk-norm) models,
the Mixtral-style top-k MoE variant (dense or ragged dispatch), and
the gemma2 block shape (GeGLU, post-block (1+w) norms, alternating
sliding-window/global attention via a layer-pair scan, softcaps).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import attention
from .config import ModelConfig
from .quant import QTensor

Params = Dict[str, Any]


def _w(p: Params, name: str, dtype=None) -> jax.Array:
    """Weight accessor: dequantizes int8 QTensor leaves at use (XLA
    fuses the convert+scale into the consuming matmul's operand read,
    so quantized serving streams int8 bytes from HBM). dtype is the
    compute dtype (cfg.dtype); defaults to bfloat16."""
    w = p[name]
    if isinstance(w, QTensor):
        return w.dequant(dtype or jnp.bfloat16)
    return w


def _proj(x: jax.Array, w, dtype, out_dims=None, flatten: int = 1):
    """Contract x's trailing `flatten` dims with weight `w`.

    int4 QTensor leaves route through the fused Pallas kernel
    (ops/int4_matmul.py) so the nibble unpack happens in VMEM and HBM
    streams packed bytes; everything else (bf16, int8, unsupported
    shapes, non-TPU) takes the dequant + einsum path, which XLA fuses
    for int8. Callers must only pass weights whose dims up to and
    including the pack axis are contraction dims (wq/wk/wv/wo,
    w_gate/w_up — not expert-stacked or per-head-factored leaves).
    """
    import math
    lead = x.shape[:-flatten]
    K = math.prod(x.shape[len(lead):])
    x2 = x.reshape(*lead, K)
    y = None
    if isinstance(w, QTensor) and w.bits == 4:
        from ..ops.int4_matmul import int4_matmul
        y = int4_matmul(x2, w, dtype or jnp.bfloat16)
    if y is None:
        wd = w.dequant(dtype or jnp.bfloat16) \
            if isinstance(w, QTensor) else w
        y = jnp.einsum("...k,kn->...n", x2, wd.reshape(K, -1))
    if out_dims:
        y = y.reshape(*y.shape[:-1], *out_dims)
    return y


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    """Fixed-capacity per-layer KV cache.

    k, v: [L, B, S_max, K, Dh]; index: next-write position — scalar
    int32 (shared by the whole batch: training-style chunked prefill)
    or [B] int32 (per-slot write positions: the serving engine's
    continuous-batching decode, where every slot is at a different
    sequence length).
    """

    k: jax.Array
    v: jax.Array
    index: jax.Array

    @classmethod
    def create(cls, cfg: ModelConfig, batch: int, max_seq: Optional[int] = None,
               dtype=None) -> "KVCache":
        S = max_seq or cfg.max_seq_len
        dtype = dtype or cfg.dtype
        # MLA caches one latent "head" of kv_lora_rank+rope dims and a
        # zero-width v plane (models/mla.py); dense models cache K/V
        K, Dk, Dv = (cfg.kv_cache_heads, cfg.kv_cache_k_dim,
                     cfg.kv_cache_v_dim)
        L = cfg.num_layers
        return cls(k=jnp.zeros((L, batch, S, K, Dk), dtype),
                   v=jnp.zeros((L, batch, S, K, Dv), dtype),
                   index=jnp.zeros((), jnp.int32))


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedKVCache:
    """Block-pool KV cache for the serving engine's paged decode.

    k, v: [L, N, block, K, Dh] POOLS of N fixed-size blocks shared by
    every decode slot; `table`: [B, M] int32 block table mapping each
    slot's sequence block j to a pool block id (0 is the reserved
    trash block — unallocated entries point there and kv_len masking
    makes it unreachable for reads); `index`: [B] per-slot lengths.
    HBM is sized by total tokens in flight (N * block) instead of
    B * S_max — the vLLM/SGLang PagedAttention idea with TPU-static
    shapes (ops/paged.py).
    """

    k: jax.Array
    v: jax.Array
    index: jax.Array
    table: jax.Array
    # int8 pools only: per-(row, head) f32 dequant scales, stored
    # S-minor ([L, N, K, block]) so each block's [K, block] scale
    # plane is lane-aligned for the Pallas kernel (ops/flash.py
    # quantize_kv_block layout); None for bf16 pools
    k_scale: jax.Array = None
    v_scale: jax.Array = None

    @classmethod
    def create(cls, cfg: ModelConfig, batch: int, n_blocks: int,
               block: int, max_blocks: int,
               dtype=None) -> "PagedKVCache":
        dtype = dtype or cfg.dtype
        K, Dk, Dv = (cfg.kv_cache_heads, cfg.kv_cache_k_dim,
                     cfg.kv_cache_v_dim)
        L = cfg.num_layers
        quantized = jnp.dtype(dtype) == jnp.int8

        def scale():
            # distinct buffers per plane: donation refuses aliases
            return (jnp.zeros((L, n_blocks, K, block), jnp.float32)
                    if quantized else None)
        return cls(k=jnp.zeros((L, n_blocks, block, K, Dk), dtype),
                   v=jnp.zeros((L, n_blocks, block, K, Dv), dtype),
                   index=jnp.zeros((batch,), jnp.int32),
                   table=jnp.zeros((batch, max_blocks), jnp.int32),
                   k_scale=scale(), v_scale=scale())


# -- init ------------------------------------------------------------------


def _init_layer_block(rng: jax.Array, cfg: ModelConfig, L: int,
                      moe: bool) -> Params:
    """One stacked block of L structurally-identical layers."""
    D, H, K, Dh, F = (cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads,
                      cfg.head_dim, cfg.intermediate_size)
    keys = iter(jax.random.split(rng, 24))
    depth = cfg.num_layers

    def norm(shape, key, std=0.02):
        return (jax.random.normal(key, shape, jnp.float32) * std).astype(cfg.dtype)

    def norm_scale(*shape):
        # unit-offset (gemma) norms store scale-1: zeros == identity
        fill = jnp.zeros if cfg.unit_offset_norm else jnp.ones
        return fill(shape, cfg.dtype)

    layers: Params = {
        "attn_norm": norm_scale(L, D),
        "mlp_norm": norm_scale(L, D),
    }
    if cfg.mla:
        qk = cfg.qk_nope_head_dim + cfg.qk_rope_head_dim
        r = cfg.kv_lora_rank
        if cfg.q_lora_rank:
            layers["wq_a"] = norm((L, D, cfg.q_lora_rank), next(keys))
            layers["q_a_norm"] = norm_scale(L, cfg.q_lora_rank)
            layers["wq_b"] = norm((L, cfg.q_lora_rank, H, qk), next(keys))
        else:
            layers["wq"] = norm((L, D, H, qk), next(keys))
        layers["wkv_a"] = norm((L, D, r + cfg.qk_rope_head_dim),
                               next(keys))
        layers["kv_a_norm"] = norm_scale(L, r)
        layers["w_uk"] = norm((L, H, cfg.qk_nope_head_dim, r), next(keys))
        layers["w_uv"] = norm((L, H, r, cfg.v_head_dim), next(keys))
        layers["wo"] = norm((L, H, cfg.v_head_dim, D), next(keys),
                            std=0.02 / (2 * depth) ** 0.5)
    else:
        layers.update({
            "wq": norm((L, D, H, Dh), next(keys)),
            "wk": norm((L, D, K, Dh), next(keys)),
            "wv": norm((L, D, K, Dh), next(keys)),
            "wo": norm((L, H, Dh, D), next(keys),
                       std=0.02 / (2 * depth) ** 0.5),
        })
    if cfg.qk_norm:
        layers["q_norm"] = norm_scale(L, Dh)
        layers["k_norm"] = norm_scale(L, Dh)
    if cfg.attn_bias:
        layers["bq"] = jnp.zeros((L, H, Dh), cfg.dtype)
        layers["bk"] = jnp.zeros((L, K, Dh), cfg.dtype)
        layers["bv"] = jnp.zeros((L, K, Dh), cfg.dtype)
    if cfg.post_block_norms:
        layers["attn_post_norm"] = norm_scale(L, D)
        layers["mlp_post_norm"] = norm_scale(L, D)
    if moe:
        E, Fm = cfg.num_experts, cfg.moe_intermediate_size or F
        layers.update({
            "router": norm((L, D, E), next(keys)),
            "we_gate": norm((L, E, D, Fm), next(keys)),
            "we_up": norm((L, E, D, Fm), next(keys)),
            "we_down": norm((L, E, Fm, D), next(keys),
                            std=0.02 / (2 * depth) ** 0.5),
        })
        if cfg.router_bias:
            layers["router_bias"] = jnp.zeros((L, E), jnp.float32)
        if cfg.num_shared_experts > 0:
            Fs = Fm * cfg.num_shared_experts
            layers.update({
                "ws_gate": norm((L, D, Fs), next(keys)),
                "ws_up": norm((L, D, Fs), next(keys)),
                "ws_down": norm((L, Fs, D), next(keys),
                                std=0.02 / (2 * depth) ** 0.5),
            })
    else:
        layers.update({
            "w_gate": norm((L, D, F), next(keys)),
            "w_up": norm((L, D, F), next(keys)),
            "w_down": norm((L, F, D), next(keys),
                           std=0.02 / (2 * depth) ** 0.5),
        })
    return layers


def init_params(rng: jax.Array, cfg: ModelConfig) -> Params:
    """Initialize parameters (normal init scaled like Llama pretraining).

    MoE models with first_k_dense (DeepSeek) get a separate
    "dense_layers" block for the leading dense-MLP layers.
    """
    D = cfg.hidden_size
    k_top, k_dense, k_moe = jax.random.split(rng, 3)
    keys = iter(jax.random.split(k_top, 4))

    def norm(shape, key, std=0.02):
        return (jax.random.normal(key, shape, jnp.float32) * std).astype(cfg.dtype)

    n_dense = cfg.first_k_dense if cfg.is_moe else 0
    params: Params = {
        "embed": norm((cfg.vocab_size, D), next(keys)),
        "layers": _init_layer_block(k_moe, cfg, cfg.num_layers - n_dense,
                                    cfg.is_moe),
        "final_norm": (jnp.zeros if cfg.unit_offset_norm
                       else jnp.ones)((D,), cfg.dtype),
    }
    if n_dense:
        params["dense_layers"] = _init_layer_block(k_dense, cfg, n_dense,
                                                   moe=False)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = norm((D, cfg.vocab_size), next(keys))
    return params


def param_count(params: Params) -> int:
    return sum(p.size for p in jax.tree.leaves(params))


# -- building blocks -------------------------------------------------------


def rms_norm(x: jax.Array, scale: jax.Array, eps: float,
             unit_offset: bool = False) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    y = x.astype(jnp.float32) * lax.rsqrt(var + eps)
    w = scale.astype(jnp.float32)
    if unit_offset:  # gemma convention: weight stored as (scale - 1)
        w = 1.0 + w
    return (y * w).astype(x.dtype)


def layer_norm(x: jax.Array, scale: jax.Array,
               bias: Optional[jax.Array], eps: float) -> jax.Array:
    """Mean-centered LayerNorm in fp32. bias=None is the command-r
    (CohereLayerNorm) weight-only form; with bias it is torch
    LayerNorm (phimoe)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps) * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def block_norm(x: jax.Array, lp: Params, name: str,
               cfg: ModelConfig) -> jax.Array:
    """Per-block norm dispatched on cfg.norm_type; layernorm biases
    ride as `name`_bias leaves."""
    if cfg.norm_type == "rmsnorm":
        return rms_norm(x, lp[name], cfg.rms_norm_eps,
                        cfg.unit_offset_norm)
    bias = lp.get(name + "_bias") if cfg.norm_type == "layernorm" \
        else None
    return layer_norm(x, lp[name], bias, cfg.rms_norm_eps)


def _rope_frequencies(cfg: ModelConfig) -> jax.Array:
    half = cfg.head_dim // 2
    freqs = 1.0 / cfg.rope_theta ** (jnp.arange(half, dtype=jnp.float32) / half)
    sc = cfg.rope_scaling
    rtype = sc.get("rope_type", sc.get("type")) if sc else None
    if rtype not in (None, "default", "llama3", "yarn", "longrope",
                     "linear"):
        # silently unscaled frequencies serve wrong logits past the
        # original window — refuse instead (r5 review)
        raise ValueError(f"unsupported rope_scaling type {rtype!r}")
    if rtype == "yarn":
        # gpt-oss/qwen long-context; the cos/sin attention factor is
        # folded into query_scale at config parse (logits scale by
        # att^2 — equivalent, and the KV cache stays unscaled)
        from .mla import yarn_frequencies
        freqs, _ = yarn_frequencies(cfg, cfg.head_dim)
    elif rtype == "longrope":
        # phi3 family: per-dim extension factors; long list when the
        # deployed window exceeds the original training window
        orig = sc.get("original_max_position_embeddings",
                      cfg.max_seq_len)
        which = "long_factor" if cfg.max_seq_len > orig \
            else "short_factor"
        ext = jnp.asarray(sc[which], jnp.float32)
        freqs = freqs / ext
    elif rtype == "linear":
        freqs = freqs / sc.get("factor", 1.0)
    if rtype == "llama3":
        # Llama-3.1 NTK-by-parts frequency remapping
        factor = sc.get("factor", 8.0)
        lo = sc.get("low_freq_factor", 1.0)
        hi = sc.get("high_freq_factor", 4.0)
        orig = sc.get("original_max_position_embeddings", 8192)
        wavelen = 2 * jnp.pi / freqs
        ramp = (orig / wavelen - lo) / (hi - lo)
        ramp = jnp.clip(ramp, 0.0, 1.0)
        smoothed = freqs * (ramp + (1 - ramp) / factor)
        freqs = jnp.where(wavelen < orig / hi, freqs,          # high freq: keep
                          jnp.where(wavelen > orig / lo,
                                    freqs / factor,            # low freq: scale
                                    smoothed))                 # medium: blend
    return freqs


def apply_rope(x: jax.Array, positions: jax.Array, freqs: jax.Array,
               interleaved: bool = False) -> jax.Array:
    """RoPE. x: [B, S, N, Dh]. Default is rotate-half (HF Llama
    convention); `interleaved` pairs even/odd dims (command-r's
    repeat_interleave convention)."""
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    xf = x.astype(jnp.float32)
    if interleaved:
        x1, x2 = xf[..., ::2], xf[..., 1::2]
        out = jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                        axis=-1).reshape(x.shape)
    else:
        x1, x2 = jnp.split(xf, 2, axis=-1)
        out = jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _activate(gate: jax.Array, cfg: Optional[ModelConfig]) -> jax.Array:
    if cfg is not None and cfg.mlp_activation == "gelu_tanh":
        return jax.nn.gelu(gate, approximate=True)
    return jax.nn.silu(gate)


def _lora_delta(x: jax.Array, lp: Params, name: str,
                adapter_ids: Optional[jax.Array], flatten: int = 1):
    """Per-slot low-rank delta for the projection `name`.

    lp[name+"_lora_a"]: [n_slots, r, K], lp[..._b]: [n_slots, r, N] —
    per-layer slices of the engine's adapter stacks (scaling already
    folded into B; slot 0 is all-zero = base model). adapter_ids: [B].
    Returns [B, S, N] in x.dtype, or None when multi-LoRA is off.
    """
    a = lp.get(name + "_lora_a")
    if a is None or adapter_ids is None:
        return None
    b = lp.get(name + "_lora_b")
    import math
    B = x.shape[0]
    K = math.prod(x.shape[x.ndim - flatten:])
    x2 = x.reshape(B, -1, K)
    asel = jnp.take(a, adapter_ids, axis=0)          # [B, r, K]
    bsel = jnp.take(b, adapter_ids, axis=0)          # [B, r, N]
    h = jnp.einsum("bsk,brk->bsr", x2, asel.astype(x2.dtype))
    return jnp.einsum("bsr,brn->bsn", h, bsel.astype(x2.dtype))


def _proj_lora(x: jax.Array, lp: Params, name: str,
               adapter_ids: Optional[jax.Array], dtype,
               out_dims=None, flatten: int = 1):
    """_proj + the slot's adapter delta (multi-LoRA serving)."""
    y = _proj(x, lp[name], dtype, flatten=flatten)
    d = _lora_delta(x, lp, name, adapter_ids, flatten=flatten)
    if d is not None:
        y = y + d.reshape(y.shape)
    if out_dims:
        y = y.reshape(*y.shape[:-1], *out_dims)
    return y


def dense_mlp(x: jax.Array, p: Params,
              cfg: Optional[ModelConfig] = None,
              adapter_ids: Optional[jax.Array] = None) -> jax.Array:
    dt = cfg.dtype if cfg else None
    gate = _proj_lora(x, p, "w_gate", adapter_ids, dt)
    up = _proj_lora(x, p, "w_up", adapter_ids, dt)
    return _proj_lora(_activate(gate, cfg) * up, p, "w_down",
                      adapter_ids, dt)


def _route(x: jax.Array, p: Params, cfg: ModelConfig):
    """Router: top-k expert ids + weights (fp32 routing).

    Three flavors (cfg.router_scoring):
      * "mixtral"    — softmax over the selected top-k logits
        (Mixtral/Qwen-MoE);
      * "softmax_v2" — full softmax scores, optional group-limited
        greedy selection (DeepseekV2TopkRouter);
      * "sigmoid_v3" — sigmoid scores, a selection-only correction
        bias, groups scored by their top-2 sum
        (DeepseekV3TopkRouter.get_topk_indices).
    """
    router_logits = jnp.einsum("bsd,de->bse", x,
                               p["router"]).astype(jnp.float32)
    k = cfg.experts_per_token
    if cfg.router_scoring == "mixtral":
        if cfg.moe_bias and "router_b" in p:
            # gpt_oss router: logits carry a bias BEFORE selection
            router_logits = router_logits + p["router_b"]
        weights, idx = lax.top_k(router_logits, k)
        return jax.nn.softmax(weights, axis=-1), idx  # [B,S,k] x2
    if cfg.router_scoring == "sparsemixer":
        return _route_sparsemixer(router_logits, cfg)
    if cfg.router_scoring == "sigmoid_v3":
        scores = jax.nn.sigmoid(router_logits)
        choice = scores + p["router_bias"] if "router_bias" in p \
            else scores
        def group_reduce(g):  # a group's merit: sum of its best two
            return jnp.sum(lax.top_k(g, 2)[0], axis=-1)
    else:  # softmax_v2
        scores = jax.nn.softmax(router_logits, axis=-1)
        choice = scores
        def group_reduce(g):
            return jnp.max(g, axis=-1)
    if cfg.n_group > 1 and 0 < cfg.topk_group < cfg.n_group:
        B, S, E = choice.shape
        g = choice.reshape(B, S, cfg.n_group, E // cfg.n_group)
        _, gidx = lax.top_k(group_reduce(g), cfg.topk_group)
        gmask = jnp.sum(jax.nn.one_hot(gidx, cfg.n_group,
                                       dtype=jnp.float32), axis=-2) > 0
        choice = jnp.where(
            jnp.repeat(gmask, E // cfg.n_group, axis=-1), choice, 0.0)
    _, idx = lax.top_k(choice, k)
    weights = jnp.take_along_axis(scores, idx, axis=-1)
    if cfg.norm_topk_prob:
        weights = weights / (jnp.sum(weights, axis=-1, keepdims=True)
                             + 1e-20)
        if cfg.router_scoring == "softmax_v2":
            # HF DeepseekV2MoE applies routed_scaling_factor only in
            # the non-normalized branch; V3 (sigmoid) scales always
            return weights, idx
    return weights * cfg.routed_scaling_factor, idx


def _route_sparsemixer(scores: jax.Array, cfg: ModelConfig):
    """Phi-3.5-MoE inference-time sparsemixer (PhimoeSparseMoeBlock):
    top-1 twice with a jitter-eps sparsity mask; each multiplier is
    the pick's softmax weight over ITS masked logits (not normalized
    across the two picks)."""
    eps = cfg.router_jitter

    def pick(masked_from: jax.Array):
        # threshold mask uses the ORIGINAL scores in the numerator and
        # |scores| clamped to the candidate max as the denominator
        m = jnp.max(masked_from, axis=-1, keepdims=True)
        idx = jnp.argmax(masked_from, axis=-1)
        factor = jnp.maximum(jnp.abs(scores), m)
        drop = (m - scores) / factor > 2 * eps
        masked = jnp.where(drop, -jnp.inf, masked_from)
        gates = jax.nn.softmax(masked, axis=-1)
        w = jnp.take_along_axis(gates, idx[..., None], -1)[..., 0]
        return w, idx

    w1, i1 = pick(scores)
    masked_scores = jnp.where(
        jax.nn.one_hot(i1, scores.shape[-1], dtype=bool), -jnp.inf,
        scores)
    w2, i2 = pick(masked_scores)
    return (jnp.stack([w1, w2], axis=-1),
            jnp.stack([i1, i2], axis=-1).astype(jnp.int32))


def _moe_act(gate: jax.Array, up: jax.Array,
             cfg: ModelConfig) -> jax.Array:
    if cfg.moe_activation == "gptoss_glu":
        # GptOssExperts: clamped GLU — gate capped at +limit, up at
        # +-limit, glu = gate * sigmoid(1.702 * gate), out = (up+1)*glu
        gate = jnp.clip(gate, None, 7.0)
        up = jnp.clip(up, -7.0, 7.0)
        return (up + 1.0) * (gate * jax.nn.sigmoid(gate * 1.702))
    return _activate(gate, cfg) * up


def moe_mlp_dense(x: jax.Array, p: Params, cfg: ModelConfig) -> jax.Array:
    """Top-k MoE computing EVERY expert and mixing by router weight.

    O(E) FLOPs but fully static shapes and trivially GSPMD-shardable
    (experts on the tp/ep axis) — the training/pipeline path.
    """
    weights, idx = _route(x, p, cfg)
    gate = jnp.einsum("bsd,edf->bsef", x, _w(p, "we_gate", cfg.dtype))
    up = jnp.einsum("bsd,edf->bsef", x, _w(p, "we_up", cfg.dtype))
    if cfg.moe_bias:
        gate = gate + p["we_gate_b"]
        up = up + p["we_up_b"]
    h = _moe_act(gate, up, cfg)
    expert_out = jnp.einsum("bsef,efd->bsed", h,
                            _w(p, "we_down", cfg.dtype))  # [B,S,E,D]
    if cfg.moe_bias:
        # gpt_oss scales (out + down_bias) by the routing weight
        expert_out = expert_out + p["we_down_b"][None, None]
    onehot = jax.nn.one_hot(idx, cfg.num_experts, dtype=weights.dtype)  # [B,S,k,E]
    mix = jnp.einsum("bske,bsk->bse", onehot, weights)  # [B,S,E]
    return jnp.einsum("bsed,bse->bsd", expert_out,
                      mix.astype(expert_out.dtype))


def moe_mlp_ragged(x: jax.Array, p: Params, cfg: ModelConfig) -> jax.Array:
    """Dropless ragged dispatch: sort token-expert pairs by expert and
    run grouped matmuls (lax.ragged_dot -> TPU grouped GEMM).

    O(k/E) of the dense path's expert FLOPs with NO capacity dropping —
    static [T*k] shapes, so it jits cleanly. The sort/gather/scatter
    costs bandwidth proportional to activations (tiny next to expert
    weights), which is the right trade on TPU where the MoE block is
    weight-bound. Serving-path default (models/config.py moe_impl).
    """
    B, S, D = x.shape
    k, E = cfg.experts_per_token, cfg.num_experts
    T = B * S
    weights, idx = _route(x, p, cfg)
    xf = x.reshape(T, D)
    expert_ids = idx.reshape(T * k)
    order = jnp.argsort(expert_ids)                      # stable
    token_of = order // k                                # source token
    xs = jnp.take(xf, token_of, axis=0)                  # [T*k, D]
    group_sizes = jnp.bincount(expert_ids, length=E).astype(jnp.int32)
    gate = lax.ragged_dot(xs, _w(p, "we_gate", cfg.dtype), group_sizes)
    up = lax.ragged_dot(xs, _w(p, "we_up", cfg.dtype), group_sizes)
    if cfg.moe_bias:
        gate = gate + jnp.take(p["we_gate_b"], expert_ids[order],
                               axis=0)
        up = up + jnp.take(p["we_up_b"], expert_ids[order], axis=0)
    h = _moe_act(gate, up, cfg)  # same dtype flow as the dense path
    out_sorted = lax.ragged_dot(h, _w(p, "we_down", cfg.dtype), group_sizes)  # [T*k, D]
    if cfg.moe_bias:
        out_sorted = out_sorted + jnp.take(p["we_down_b"],
                                           expert_ids[order], axis=0)
    w_sorted = jnp.take(weights.reshape(T * k), order, axis=0)
    contrib = out_sorted * w_sorted[:, None].astype(out_sorted.dtype)
    out = jnp.zeros((T, D), contrib.dtype).at[token_of].add(contrib)
    return out.reshape(B, S, D).astype(x.dtype)


def moe_mlp(x: jax.Array, p: Params, cfg: ModelConfig) -> jax.Array:
    """Top-k MoE block (Mixtral/Qwen-MoE/DeepSeek-style)."""
    if cfg.moe_impl == "ragged":
        out = moe_mlp_ragged(x, p, cfg)
    else:
        out = moe_mlp_dense(x, p, cfg)
    if cfg.num_shared_experts > 0:
        # DeepSeek-MoE shared experts: always-active dense branch
        shared = {"w_gate": p["ws_gate"], "w_up": p["ws_up"],
                  "w_down": p["ws_down"]}  # dense_mlp dequantizes via _w
        out = out + dense_mlp(x, shared)
    return out


# -- forward ---------------------------------------------------------------


_WINDOW_FROM_CFG = object()  # sentinel: per-layer override unset


def _layer(x: jax.Array, lp: Params, cfg: ModelConfig, freqs: jax.Array,
           positions: jax.Array, kv_len: Optional[jax.Array],
           cache_kv: Optional[Tuple[jax.Array, jax.Array]],
           cache_index: Optional[jax.Array],
           window=_WINDOW_FROM_CFG, moe: Optional[bool] = None,
           adapter_ids: Optional[jax.Array] = None,
           use_rope: bool = True):
    """One transformer block. cache_kv: ([B,Smax,K,Dh], [B,Smax,K,Dh]).
    `window` overrides cfg.sliding_window (the gemma2 pair-scan passes
    the per-layer value; None = global attention). `moe` overrides
    cfg.is_moe (DeepSeek's first_k_dense leading dense layers).
    `adapter_ids` ([B]) selects each slot's LoRA delta (multi-adapter
    serving; None = no adapter stacks present)."""
    if window is _WINDOW_FROM_CFG:
        window = cfg.sliding_window
    uo = cfg.unit_offset_norm
    h = block_norm(x, lp, "attn_norm", cfg)
    if cfg.mla:
        from .mla import mla_attention
        a, new_cache = mla_attention(h, lp, cfg, positions, kv_len,
                                     cache_kv, cache_index)
    else:
        a, new_cache = _mha(h, lp, cfg, freqs, positions, kv_len,
                            cache_kv, cache_index, window, uo,
                            adapter_ids, use_rope=use_rope)
    use_moe = cfg.is_moe if moe is None else moe
    if cfg.parallel_block:
        # command-r: attention and MLP both read the SAME normed
        # input and add into one residual (CohereDecoderLayer)
        mlp_out = moe_mlp(h, lp, cfg) if use_moe \
            else dense_mlp(h, lp, cfg, adapter_ids)
        return x + a + mlp_out, new_cache
    if cfg.post_block_norms:
        a = rms_norm(a, lp["attn_post_norm"], cfg.rms_norm_eps, uo)
    x = x + a

    h = block_norm(x, lp, "mlp_norm", cfg)
    mlp_out = moe_mlp(h, lp, cfg) if use_moe \
        else dense_mlp(h, lp, cfg, adapter_ids)
    if cfg.post_block_norms:
        mlp_out = rms_norm(mlp_out, lp["mlp_post_norm"],
                           cfg.rms_norm_eps, uo)
    return x + mlp_out, new_cache


def _qkv(h: jax.Array, lp: Params, cfg: ModelConfig, freqs: jax.Array,
         positions: jax.Array, uo: bool,
         adapter_ids: Optional[jax.Array] = None, rope: bool = True):
    """Projected + biased + normed + roped q/k/v — shared between the
    dense (_mha) and paged (forward_paged) attention paths.
    `rope=False` is cohere2's NoPE global layers."""
    q = _proj_lora(h, lp, "wq", adapter_ids, cfg.dtype,
                   out_dims=(cfg.num_heads, cfg.head_dim))
    k = _proj_lora(h, lp, "wk", adapter_ids, cfg.dtype,
                   out_dims=(cfg.num_kv_heads, cfg.head_dim))
    v = _proj_lora(h, lp, "wv", adapter_ids, cfg.dtype,
                   out_dims=(cfg.num_kv_heads, cfg.head_dim))
    if cfg.attn_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    if cfg.qk_norm:
        if cfg.norm_type == "layernorm_nobias":
            # command-r-plus: per-(head, dim) weighted LayerNorm
            q = layer_norm(q, lp["q_norm"], None, cfg.rms_norm_eps)
            k = layer_norm(k, lp["k_norm"], None, cfg.rms_norm_eps)
        else:
            q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps, uo)
            k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps, uo)
    if rope:
        q = apply_rope(q, positions, freqs, cfg.rope_interleaved)
        k = apply_rope(k, positions, freqs, cfg.rope_interleaved)
    return q, k, v


def _mha(h: jax.Array, lp: Params, cfg: ModelConfig, freqs: jax.Array,
         positions: jax.Array, kv_len, cache_kv, cache_index, window,
         uo: bool, adapter_ids: Optional[jax.Array] = None,
         use_rope: bool = True):
    """Standard multi-head (GQA) attention on the pre-normed input."""
    q, k, v = _qkv(h, lp, cfg, freqs, positions, uo, adapter_ids,
                   rope=use_rope)

    if cache_kv is not None:
        ck, cv = cache_kv
        if cache_index.ndim == 1:
            # per-slot write positions (continuous batching): vmap the
            # update over the batch so each slot writes at its own length
            upd = jax.vmap(
                lambda c, u, i: lax.dynamic_update_slice(
                    c, u.astype(c.dtype), (i, 0, 0)))
            ck = upd(ck, k, cache_index)
            cv = upd(cv, v, cache_index)
        else:
            ck = lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                          (0, cache_index, 0, 0))
            cv = lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                          (0, cache_index, 0, 0))
        k_full, v_full = ck, cv
        new_cache = (ck, cv)
    else:
        k_full, v_full = k, v
        new_cache = None

    attn = attention(q, k_full, v_full, positions=positions, kv_len=kv_len,
                     sliding_window=window, scale=cfg.query_scale,
                     logit_softcap=cfg.attn_logit_softcap,
                     sinks=lp.get("sinks") if cfg.attn_sinks else None)
    a = _proj_lora(attn, lp, "wo", adapter_ids, cfg.dtype, flatten=2)
    if "bo" in lp:  # phimoe/gpt_oss: o_proj carries a bias too
        a = a + lp["bo"]
    return a, new_cache


def forward(params: Params, cfg: ModelConfig, tokens: jax.Array,
            positions: Optional[jax.Array] = None,
            cache: Optional[KVCache] = None,
            adapter_ids: Optional[jax.Array] = None,
            ) -> Tuple[jax.Array, Optional[KVCache]]:
    """Run the decoder.

    tokens: [B, S] int32. positions: [B, S] (defaults to arange).
    With `cache`, K/V are written at cache.index and attention spans the
    cache (serving decode/chunked prefill); without, plain causal prefill.
    `adapter_ids` ([B] int32) selects each row's LoRA adapter slot when
    the params carry multi-adapter factor stacks (engine/core.py).
    Returns (logits [B, S, vocab], updated cache or None).
    """
    B, S = tokens.shape
    if positions is None:
        base = jnp.arange(S, dtype=jnp.int32)[None, :]
        if cache is not None:
            idx = cache.index
            base = base + (idx[:, None] if idx.ndim == 1 else idx)
        positions = jnp.broadcast_to(base, (B, S))
    emb = params["embed"]
    x = emb.take(tokens, cfg.dtype) if isinstance(emb, QTensor) \
        else jnp.take(emb, tokens, axis=0).astype(cfg.dtype)
    if cfg.embed_scale:  # gemma: normalizer in the compute dtype
        x = x * jnp.asarray(cfg.hidden_size ** 0.5, cfg.dtype)
    freqs = _rope_frequencies(cfg)

    kv_len = jnp.broadcast_to(cache.index + S, (B,)) \
        if cache is not None else None
    index = cache.index if cache is not None else None

    if cfg.alt_sliding_window:
        x, new_cache = _alt_window_scan(params, cfg, x, freqs, positions,
                                        kv_len, cache, adapter_ids)
    else:
        # DeepSeek first_k_dense: leading dense-MLP layers scan as
        # their own block; the cache's layer dim covers both blocks
        n_dense = cfg.first_k_dense if "dense_layers" in params else 0

        def scan_block(x, block, ck, cv, moe):
            def body(x, per_layer):
                lp, layer_cache = per_layer
                x, nc = _layer(x, lp, cfg, freqs, positions, kv_len,
                               layer_cache, index, moe=moe,
                               adapter_ids=adapter_ids)
                return x, nc

            carry_cache = (ck, cv) if cache is not None else None
            x, nc = lax.scan(body, x, (block, carry_cache))
            return x, nc

        if cache is not None:
            dk, dv = cache.k[:n_dense], cache.v[:n_dense]
            mk, mv = cache.k[n_dense:], cache.v[n_dense:]
        else:
            dk = dv = mk = mv = None
        if n_dense:
            x, dnc = scan_block(x, params["dense_layers"], dk, dv,
                                moe=False)
        x, mnc = scan_block(x, params["layers"], mk, mv, moe=None)
        if cache is not None:
            nk, nv = mnc
            if n_dense:
                nk = jnp.concatenate([dnc[0], nk], axis=0)
                nv = jnp.concatenate([dnc[1], nv], axis=0)
            new_cache = KVCache(k=nk, v=nv, index=cache.index + S)
        else:
            new_cache = None

    return _final_logits(params, cfg, x), new_cache


def forward_paged(params: Params, cfg: ModelConfig, tokens: jax.Array,
                  cache: PagedKVCache,
                  adapter_ids: Optional[jax.Array] = None,
                  ) -> Tuple[jax.Array, PagedKVCache]:
    """Short-sequence decode over a paged (block-pool) KV cache.

    tokens: [B, S] with small S — 1 for plain decode, k+1 for a
    speculative verify step (engine/core.py). Each slot writes its S
    new K/V rows into pool blocks `table[b, (index[b]+s) // block]`
    at offsets `(index[b]+s) % block` (the engine pre-allocates the
    covering blocks), then attends over its block chain with
    per-query causal masking (ops/paged.py). Standard GQA models
    only — MLA, MoE, and sliding-window variants keep the dense path
    (the engine guards). cite: vLLM PagedAttention, which the
    reference consumes via its SGLang/vLLM runtimes (SURVEY.md L0,
    /root/reference/config/runtimes/srt/*); here it is in-repo and
    TPU-static.
    """
    from ..ops.paged import paged_attention, paged_attention_multi
    B, S = tokens.shape
    bs = cache.k.shape[2]
    M = cache.table.shape[1]
    positions = cache.index[:, None] + jnp.arange(S,
                                                  dtype=jnp.int32)[None, :]
    kv_len = cache.index + 1
    emb = params["embed"]
    x = emb.take(tokens, cfg.dtype) if isinstance(emb, QTensor) \
        else jnp.take(emb, tokens, axis=0).astype(cfg.dtype)
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.hidden_size ** 0.5, cfg.dtype)
    freqs = _rope_frequencies(cfg)
    uo = cfg.unit_offset_norm
    rows = jnp.arange(B)
    # clamp keeps a finished slot whose length outgrew its table row
    # in-bounds; its row points at the trash block by then
    blk = cache.table[rows[:, None],
                      jnp.minimum(positions // bs, M - 1)]  # [B, S]
    off = positions % bs
    quantized = cache.k_scale is not None

    def _append(pool, scale_pool, rows_new):
        """Write S fresh [B, K, D] rows into the pool; int8 pools
        quantize per (row, head) on the way in (amax/127 symmetric,
        the ops/flash.py quantize_kv_block discipline) and store the
        f32 scale at the same (block, offset). The S writes per slot
        land on consecutive rows (distinct (block, offset) pairs), so
        the unrolled scatter order doesn't matter; trash-block
        collisions between inactive slots are never read back."""
        if quantized:
            amax = jnp.max(jnp.abs(rows_new.astype(jnp.float32)),
                           axis=-1)                        # [B, S, K]
            sc = jnp.maximum(amax, 1e-8) / 127.0
            rows_new = jnp.clip(
                jnp.round(rows_new.astype(jnp.float32)
                          / sc[..., None]),
                -127, 127).astype(jnp.int8)
        for s in range(S):
            pool = pool.at[blk[:, s], off[:, s]].set(
                rows_new[:, s].astype(pool.dtype))
            if quantized:
                scale_pool = scale_pool.at[blk[:, s], :,
                                           off[:, s]].set(sc[:, s])
        return pool, scale_pool

    def body(x, per):
        if quantized:
            lp, kp, vp, ksp, vsp = per
        else:
            lp, kp, vp = per
            ksp = vsp = None
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps, uo)
        q, k, v = _qkv(h, lp, cfg, freqs, positions, uo, adapter_ids)
        kp, ksp = _append(kp, ksp, k)
        vp, vsp = _append(vp, vsp, v)
        if S == 1:
            attn = paged_attention(q, kp, vp, cache.table, kv_len,
                                   scale=cfg.query_scale,
                                   logit_softcap=cfg.attn_logit_softcap,
                                   k_scale=ksp, v_scale=vsp)
        else:
            attn = paged_attention_multi(
                q, kp, vp, cache.table, positions,
                scale=cfg.query_scale,
                logit_softcap=cfg.attn_logit_softcap,
                k_scale=ksp, v_scale=vsp)
        a = _proj_lora(attn, lp, "wo", adapter_ids, cfg.dtype,
                       flatten=2)
        if cfg.post_block_norms:
            a = rms_norm(a, lp["attn_post_norm"], cfg.rms_norm_eps, uo)
        x = x + a
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps, uo)
        mlp_out = dense_mlp(h, lp, cfg, adapter_ids)
        if cfg.post_block_norms:
            mlp_out = rms_norm(mlp_out, lp["mlp_post_norm"],
                               cfg.rms_norm_eps, uo)
        out = (x + mlp_out, ((kp, vp, ksp, vsp) if quantized
                             else (kp, vp)))
        return out

    if quantized:
        x, (nk, nv, nks, nvs) = lax.scan(
            body, x, (params["layers"], cache.k, cache.v,
                      cache.k_scale, cache.v_scale))
    else:
        x, (nk, nv) = lax.scan(body, x,
                               (params["layers"], cache.k, cache.v))
        nks = nvs = None
    new_cache = PagedKVCache(k=nk, v=nv, index=cache.index + S,
                             table=cache.table,
                             k_scale=nks, v_scale=nvs)
    return _final_logits(params, cfg, x), new_cache


def _final_logits(params: Params, cfg: ModelConfig,
                  x: jax.Array) -> jax.Array:
    """Final norm + LM head — shared by forward and forward_paged."""
    x = block_norm(x, params, "final_norm", cfg)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"]
        head = head.dequant(cfg.dtype).T if isinstance(head, QTensor) \
            else head.T
    elif isinstance(head, QTensor):
        head = head.dequant(cfg.dtype)
    logits = jnp.einsum("bsd,dv->bsv", x, head,
                        preferred_element_type=jnp.float32)
    if "lm_head_bias" in params:
        logits = logits + params["lm_head_bias"]
    if cfg.logit_scale is not None:
        logits = logits * cfg.logit_scale
    if cfg.final_logit_softcap:
        logits = jnp.tanh(logits / cfg.final_logit_softcap) \
            * cfg.final_logit_softcap
    return logits


def _alt_window_scan(params: Params, cfg: ModelConfig, x: jax.Array,
                     freqs, positions, kv_len, cache: Optional[KVCache],
                     adapter_ids: Optional[jax.Array] = None):
    """Scan over layer GROUPS of `cfg.sliding_pattern` (P): layers
    with (i+1) % P != 0 use the sliding window, every P-th layer is
    global. gemma2/gpt-oss: P=2; command-r7b/command-a (cohere2):
    P=4, and the global layers additionally skip RoPE
    (cfg.rope_skip_global — Cohere2Attention applies rotary only on
    sliding layers). The unrolled group body keeps every variant
    static — one compiled body, no dynamic masks."""
    L, P = cfg.num_layers, cfg.sliding_pattern
    assert L % P == 0, \
        f"alternating sliding window needs depth % {P} == 0"

    def group(a):
        return a.reshape(L // P, P, *a.shape[1:])

    layers_g = jax.tree.map(group, params["layers"])
    index = cache.index if cache is not None else None

    def body(x, per):
        lp_g, c_g = per
        nks, nvs = [], []
        for j in range(P):
            lp = jax.tree.map(lambda a: a[j], lp_g)
            cj = (c_g[0][j], c_g[1][j]) if c_g is not None else None
            is_global = (j + 1) % P == 0
            x, nc = _layer(
                x, lp, cfg, freqs, positions, kv_len, cj, index,
                window=None if is_global else cfg.sliding_window,
                adapter_ids=adapter_ids,
                use_rope=not (is_global and cfg.rope_skip_global))
            if nc is not None:
                nks.append(nc[0])
                nvs.append(nc[1])
        if not nks:
            return x, None
        return x, (jnp.stack(nks), jnp.stack(nvs))

    if cache is not None:
        x, (nk, nv) = lax.scan(
            body, x, (layers_g, (group(cache.k), group(cache.v))))
        S = positions.shape[1]
        new_cache = KVCache(k=nk.reshape(cache.k.shape),
                            v=nv.reshape(cache.v.shape),
                            index=cache.index + S)
    else:
        x, _ = lax.scan(body, x, (layers_g, None))
        new_cache = None
    return x, new_cache


def loss_fn(params: Params, cfg: ModelConfig, tokens: jax.Array,
            targets: jax.Array, mask: Optional[jax.Array] = None) -> jax.Array:
    """Next-token cross-entropy (fp32 logits), for the training step."""
    logits, _ = forward(params, cfg, tokens)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)
    return jnp.mean(nll)
