"""Serving agent — fine-tuned-weight sidecar.

Re-designs internal/ome-agent/serving-agent (serving_agent.go:42-80):
watches a fine-tuned-weight info file (a mounted ConfigMap entry in the
reference, updated when an adapter is attached to the service),
downloads the referenced adapter archive and unpacks it next to the
base weights so the engine can hot-load it. The reference uses fsnotify
on the mount; a poll of (mtime, size) is equivalent for ConfigMap
mounts, which kubelet updates atomically via symlink swap.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import tempfile
import threading
import zipfile
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from ..storage.hub import HubClient
from ..storage.providers import open_storage
from ..storage.uri import StorageType, parse_storage_uri

log = logging.getLogger("ome.agent.serving")


@dataclass
class AdapterInfo:
    """Schema of the info file: one JSON object per adapter."""

    name: str
    storage_uri: str
    revision: str = ""

    @classmethod
    def parse_file(cls, path: str) -> Dict[str, "AdapterInfo"]:
        with open(path) as f:
            data = json.load(f)
        entries = data if isinstance(data, list) else [data]
        out = {}
        for e in entries:
            info = cls(name=e["name"], storage_uri=e["storageUri"],
                       revision=e.get("revision", ""))
            out[info.name] = info
        return out


class ServingAgent:
    def __init__(self, info_file: str, adapters_dir: str,
                 hub: Optional[HubClient] = None,
                 endpoints: Optional[Dict[str, str]] = None,
                 poll_interval: float = 2.0,
                 on_change: Optional[Callable[[str], None]] = None,
                 engine_url: Optional[str] = None):
        self.info_file = info_file
        self.adapters_dir = adapters_dir
        self.hub = hub or HubClient()
        self.endpoints = endpoints or {}
        self.poll_interval = poll_interval
        self.on_change = on_change
        # engine hot-load hook: after staging adapter <name> at
        # <adapters_dir>/<name>, POST it to the co-located engine's
        # /v1/adapters (DELETE on unload) so multi-LoRA slots track
        # the FineTunedWeight attachment without a restart
        self.engine_url = engine_url.rstrip("/") if engine_url else None
        self.loaded: Dict[str, AdapterInfo] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one reconciliation pass ---------------------------------------

    def sync(self) -> bool:
        """Reconcile adapters_dir against the info file; True if changed."""
        if not os.path.exists(self.info_file):
            return False
        try:
            want = AdapterInfo.parse_file(self.info_file)
        except (ValueError, KeyError) as e:
            log.warning("bad adapter info file %s: %s", self.info_file, e)
            return False
        changed = False
        for name, info in want.items():
            cur = self.loaded.get(name)
            if cur and (cur.storage_uri, cur.revision) == (
                    info.storage_uri, info.revision):
                continue
            self._load(info)
            self.loaded[name] = info
            changed = True
        for name in list(self.loaded):
            if name not in want:
                self._unload(name)
                changed = True
        return changed

    def _load(self, info: AdapterInfo):
        comps = parse_storage_uri(info.storage_uri)
        target = os.path.join(self.adapters_dir, info.name)
        with tempfile.TemporaryDirectory(prefix="ome-adapter-") as stage:
            if comps.type == StorageType.HUGGINGFACE:
                files = self.hub.snapshot_download(
                    comps.repo_id, stage,
                    revision=comps.revision or info.revision or "main")
            else:
                storage = open_storage(comps, self.endpoints)
                files = storage.download(stage, comps.prefix)
            os.makedirs(target, exist_ok=True)
            troot = os.path.realpath(target)
            for f in files:
                if f.endswith(".zip"):
                    with zipfile.ZipFile(f) as z:  # adapter archives
                        for m in z.namelist():
                            # zip-slip guard: resolve both sides
                            p = os.path.realpath(os.path.join(troot, m))
                            if os.path.commonpath([p, troot]) != troot:
                                raise ValueError(
                                    f"zip entry escapes target: {m!r}")
                        z.extractall(troot)
                else:
                    rel = os.path.relpath(f, stage)
                    dst = os.path.join(target, rel)
                    os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
                    # shutil.move: stage (tmpfs) and adapters_dir (PVC)
                    # are usually different filesystems — os.replace
                    # would fail with EXDEV
                    shutil.move(f, dst)
        log.info("adapter %s loaded from %s", info.name, info.storage_uri)
        self._notify_engine("load", info.name, target)
        if self.on_change:
            self.on_change(info.name)

    def _unload(self, name: str):
        shutil.rmtree(os.path.join(self.adapters_dir, name),
                      ignore_errors=True)
        self.loaded.pop(name, None)
        log.info("adapter %s unloaded", name)
        self._notify_engine("unload", name, None)
        if self.on_change:
            self.on_change(name)

    def _notify_engine(self, action: str, name: str,
                       path: Optional[str]):
        if not self.engine_url:
            return
        import urllib.error
        import urllib.request
        try:
            if action == "load":
                req = urllib.request.Request(
                    self.engine_url + "/v1/adapters",
                    data=json.dumps({"name": name,
                                     "path": path}).encode(),
                    headers={"Content-Type": "application/json"})
            else:
                req = urllib.request.Request(
                    self.engine_url + f"/v1/adapters/{name}",
                    method="DELETE")
            with urllib.request.urlopen(req, timeout=60) as resp:
                resp.read()
            log.info("engine %s adapter %s ok", action, name)
        except (urllib.error.URLError, OSError) as e:
            # staging succeeded; the engine can still pick the adapter
            # up on restart — don't fail the sync loop
            log.warning("engine %s adapter %s failed: %s", action,
                        name, e)

    # -- watch loop ----------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._run,
                                        name="ome-serving-agent",
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _run(self):
        last_sig = object()  # sentinel: never equal on first pass
        while not self._stop.is_set():
            try:
                st = os.stat(self.info_file)
                sig = (st.st_mtime_ns, st.st_size)
            except OSError:
                sig = None
            if sig != last_sig:
                try:
                    self.sync()
                    # only remember the signature on success, so a
                    # transient download failure is retried next poll
                    last_sig = sig
                except Exception:  # noqa: BLE001 — keep watching
                    log.exception("adapter sync failed; will retry")
            self._stop.wait(self.poll_interval)
