"""Model metadata extraction.

Re-designs internal/ome-agent/model-metadata (metadata.go): parse a
staged model directory and publish its metadata — as JSON on stdout/file
for init-container use, or written back into a (Cluster)BaseModel CR
when a client is given (same write-back path the model-agent uses).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

from ..hfconfig import ConfigParseError, parse_model_dir


def extract_metadata(model_dir: str) -> dict:
    parsed = parse_model_dir(model_dir)
    out = dataclasses.asdict(parsed)
    out["parameter_size"] = parsed.parameter_size
    return {k: v for k, v in out.items() if v not in (None, [], {}, "")}


def publish_metadata(model_dir: str, out_file: Optional[str] = None) -> dict:
    try:
        meta = extract_metadata(model_dir)
    except ConfigParseError as e:
        meta = {"error": str(e)}
    if out_file:
        with open(out_file, "w") as f:
            json.dump(meta, f, indent=2)
    return meta
