"""Cloud KMS providers for enigma envelope encryption + GCE metadata
(imds) client.

The reference's enigma decrypts model weights with keys wrapped by OCI
KMS/Vault (internal/ome-agent/enigma/enigma.go:19-40, pkg/vault — 8.7k
LoC of OCI SDK plumbing); its imds package detects region/tenancy from
the instance metadata service (pkg/imds/imds_client.go). TPU-first
scope is GCP: Cloud KMS asymmetric-free symmetric encrypt/decrypt over
REST with workload-identity bearer tokens, and a GCE metadata client
for region/project/service-account discovery. Both are dependency-free
(urllib) and fully fake-server-testable via endpoint injection.
"""

from __future__ import annotations

import base64
import json
import urllib.parse
import urllib.request
from typing import Dict, Optional

from ..storage.signing import GCSTokenSigner
from .enigma import KMSProvider

GCE_METADATA = "http://metadata.google.internal/computeMetadata/v1"


class IMDSClient:
    """GCE instance-metadata client (pkg/imds analog).

    Answers the questions the agents ask at boot: which project/region
    am I in, what service account identity do I run as.
    """

    def __init__(self, endpoint: Optional[str] = None, timeout: float = 5.0):
        self.endpoint = (endpoint or GCE_METADATA).rstrip("/")
        self.timeout = timeout

    def _get(self, path: str) -> str:
        req = urllib.request.Request(
            f"{self.endpoint}/{path.lstrip('/')}",
            headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read().decode()

    def available(self) -> bool:
        try:
            self._get("instance/id")
            return True
        except Exception:
            return False

    def project_id(self) -> str:
        return self._get("project/project-id")

    def zone(self) -> str:
        # "projects/123/zones/us-central2-b" -> "us-central2-b"
        return self._get("instance/zone").rsplit("/", 1)[-1]

    def region(self) -> str:
        z = self.zone()
        return z.rsplit("-", 1)[0]

    def service_account_email(self) -> str:
        return self._get("instance/service-accounts/default/email")

    def identity(self) -> Dict[str, str]:
        return {"project": self.project_id(), "zone": self.zone(),
                "region": self.region(),
                "serviceAccount": self.service_account_email()}


class GCPKMS(KMSProvider):
    """Google Cloud KMS key-wrapping provider.

    key name: projects/P/locations/L/keyRings/R/cryptoKeys/K — the
    enigma data key is wrapped via the `:encrypt` / `:decrypt` REST
    methods; auth is a bearer token (workload identity in-cluster,
    $GOOGLE_OAUTH_ACCESS_TOKEN elsewhere).
    """

    def __init__(self, key_name: str, endpoint: Optional[str] = None,
                 token: Optional[str] = None):
        self.key_name = key_name.strip("/")
        self.endpoint = (endpoint
                         or "https://cloudkms.googleapis.com").rstrip("/")
        # same precedence as storage/signing.signer_from_env('gcs'):
        # SA key file / workload-identity federation first, then env
        # token / metadata server (round-4 verdict missing #5)
        from ..storage.signing import gcp_signer_from_credentials
        self._signer = (None if token else
                        gcp_signer_from_credentials()) \
            or GCSTokenSigner(token)

    @property
    def key_id(self) -> str:
        return f"gcpkms:{self.key_name}"

    def _call(self, method: str, body: Dict) -> Dict:
        url = f"{self.endpoint}/v1/{self.key_name}:{method}"
        headers = self._signer.sign("POST", url,
                                    {"Content-Type": "application/json"})
        req = urllib.request.Request(url, data=json.dumps(body).encode(),
                                     headers=headers)
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    def wrap_key(self, plaintext_key: bytes) -> bytes:
        out = self._call("encrypt", {
            "plaintext": base64.b64encode(plaintext_key).decode()})
        return base64.b64decode(out["ciphertext"])

    def unwrap_key(self, wrapped_key: bytes) -> bytes:
        out = self._call("decrypt", {
            "ciphertext": base64.b64encode(wrapped_key).decode()})
        return base64.b64decode(out["plaintext"])


def open_kms(spec: str, create: bool = False,
             endpoint: Optional[str] = None) -> KMSProvider:
    """KMS factory: 'local:<keyfile>' or 'gcpkms:<key resource name>'.

    Mirrors the reference's vault/KMS provider selection
    (enigma.go:19-40) with a URI-ish spec instead of a config block.
    """
    scheme, _, rest = spec.partition(":")
    if scheme == "local":
        from .enigma import LocalKMS
        return LocalKMS(rest, create=create)
    if scheme == "gcpkms":
        return GCPKMS(rest, endpoint=endpoint)
    raise ValueError(f"unknown KMS spec {spec!r} "
                     f"(want local:<keyfile> or gcpkms:<key name>)")
