"""Enigma — encrypted model distribution.

Re-designs internal/ome-agent/enigma (enigma.go:19-40: model weight
decryption backed by OCI KMS / Vault secrets): envelope encryption for
model directories. A per-model data key encrypts file contents with
AES-256-GCM in framed chunks; the data key itself is wrapped by a KMS
provider. Providers: LocalKMS (keyfile — dev/test and air-gapped
clusters) and the KMSProvider interface cloud backends implement
(wrap/unwrap only — the data path never talks to the cloud).
"""

from __future__ import annotations

import abc
import hashlib
import json
import os
import secrets
import struct
from typing import Optional

from cryptography.hazmat.primitives.ciphers.aead import AESGCM

MAGIC = b"OMEENC1\n"
CHUNK = 4 << 20  # plaintext bytes per GCM frame
ENC_SUFFIX = ".enc"


class EnigmaError(Exception):
    pass


class KMSProvider(abc.ABC):
    """Wraps/unwraps data keys (the only cloud-touching surface)."""

    @abc.abstractmethod
    def wrap_key(self, plaintext_key: bytes) -> bytes:
        ...

    @abc.abstractmethod
    def unwrap_key(self, wrapped_key: bytes) -> bytes:
        ...

    @property
    @abc.abstractmethod
    def key_id(self) -> str:
        ...


class LocalKMS(KMSProvider):
    """Keyfile-backed KMS: wraps data keys with a master AES-GCM key."""

    def __init__(self, keyfile: str, create: bool = False):
        if create and not os.path.exists(keyfile):
            os.makedirs(os.path.dirname(keyfile) or ".", exist_ok=True)
            fd = os.open(keyfile, os.O_WRONLY | os.O_CREAT | os.O_EXCL,
                         0o600)
            with os.fdopen(fd, "wb") as f:
                f.write(secrets.token_bytes(32))
        with open(keyfile, "rb") as f:
            self._master = f.read()
        if len(self._master) != 32:
            raise EnigmaError(f"{keyfile}: master key must be 32 bytes")
        self._key_id = f"local:{os.path.abspath(keyfile)}"

    @property
    def key_id(self) -> str:
        return self._key_id

    def wrap_key(self, plaintext_key: bytes) -> bytes:
        nonce = secrets.token_bytes(12)
        return nonce + AESGCM(self._master).encrypt(nonce, plaintext_key,
                                                    b"ome-data-key")

    def unwrap_key(self, wrapped_key: bytes) -> bytes:
        nonce, ct = wrapped_key[:12], wrapped_key[12:]
        try:
            return AESGCM(self._master).decrypt(nonce, ct, b"ome-data-key")
        except Exception as e:
            raise EnigmaError(f"data key unwrap failed: {e}") from e


def encrypt_file(src: str, dst: str, data_key: bytes,
                 kms: KMSProvider) -> None:
    """MAGIC + header(json) + frames of [len u32][nonce 12][ciphertext]."""
    header = json.dumps({
        "v": 1, "alg": "aes-256-gcm", "chunk": CHUNK,
        "key_id": kms.key_id,
        "wrapped_key": kms.wrap_key(data_key).hex(),
        "orig_name": os.path.basename(src),
        "orig_size": os.path.getsize(src),
    }).encode()
    aes = AESGCM(data_key)
    # every frame's AAD binds the (plaintext) header — orig_name,
    # orig_size, wrapped key — so header tampering, cross-file frame
    # splicing and truncation-with-resize all fail authentication
    aad_base = hashlib.sha256(header).digest()
    tmp = dst + ".part"
    os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
    with open(src, "rb") as fin, open(tmp, "wb") as fout:
        fout.write(MAGIC)
        fout.write(struct.pack("<I", len(header)))
        fout.write(header)
        seq = 0
        while True:
            block = fin.read(CHUNK)
            if not block:
                break
            nonce = secrets.token_bytes(12)
            ct = aes.encrypt(nonce, block,
                             aad_base + struct.pack("<Q", seq))
            fout.write(struct.pack("<I", len(ct)) + nonce + ct)
            seq += 1
    os.replace(tmp, dst)


def decrypt_file(src: str, dst: str, kms: KMSProvider) -> None:
    with open(src, "rb") as fin:
        if fin.read(len(MAGIC)) != MAGIC:
            raise EnigmaError(f"{src}: not an enigma file")
        (hlen,) = struct.unpack("<I", fin.read(4))
        header_raw = fin.read(hlen)
        header = json.loads(header_raw)
        aad_base = hashlib.sha256(header_raw).digest()
        data_key = kms.unwrap_key(bytes.fromhex(header["wrapped_key"]))
        aes = AESGCM(data_key)
        tmp = dst + ".part"
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        with open(tmp, "wb") as fout:
            seq = 0
            while True:
                raw = fin.read(4)
                if not raw:
                    break
                (clen,) = struct.unpack("<I", raw)
                nonce = fin.read(12)
                ct = fin.read(clen)
                try:
                    fout.write(aes.decrypt(
                        nonce, ct, aad_base + struct.pack("<Q", seq)))
                except Exception as e:
                    raise EnigmaError(
                        f"{src}: frame {seq} auth failed: {e}") from e
                seq += 1
        if os.path.getsize(tmp) != header["orig_size"]:
            raise EnigmaError(f"{src}: size mismatch after decrypt")
        os.replace(tmp, dst)


def encrypt_dir(src_dir: str, dst_dir: str, kms: KMSProvider,
                data_key: Optional[bytes] = None) -> int:
    """Encrypt every file; returns count. One data key per model dir."""
    data_key = data_key or secrets.token_bytes(32)
    n = 0
    for root, _, files in os.walk(src_dir):
        for fn in files:
            src = os.path.join(root, fn)
            rel = os.path.relpath(src, src_dir)
            encrypt_file(src, os.path.join(dst_dir, rel + ENC_SUFFIX),
                         data_key, kms)
            n += 1
    return n


def decrypt_dir(src_dir: str, dst_dir: str, kms: KMSProvider) -> int:
    n = 0
    for root, _, files in os.walk(src_dir):
        for fn in files:
            if not fn.endswith(ENC_SUFFIX):
                continue
            src = os.path.join(root, fn)
            rel = os.path.relpath(src, src_dir)[:-len(ENC_SUFFIX)]
            decrypt_file(src, os.path.join(dst_dir, rel), kms)
            n += 1
    return n
