"""ome-agent CLI — the swiss-army-knife binary.

Re-designs cmd/ome-agent (main.go:27-35 cobra subcommands): argparse
subcommands over the same capabilities — `enigma` encrypt/decrypt,
`replica`, `serving-agent`, `model-metadata`, `hf-download`.
Run as `python -m ome_tpu.agent <subcommand>`.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import time


def _cmd_enigma(args) -> int:
    from .cloudkms import open_kms
    from .enigma import decrypt_dir, encrypt_dir
    if not args.kms and not args.keyfile:
        print("enigma: one of --kms or --keyfile is required",
              file=sys.stderr)
        return 2
    spec = args.kms or f"local:{args.keyfile}"
    kms = open_kms(spec, create=args.mode == "encrypt")
    if args.mode == "encrypt":
        n = encrypt_dir(args.input, args.output, kms)
    else:
        n = decrypt_dir(args.input, args.output, kms)
    print(json.dumps({"mode": args.mode, "files": n,
                      "output": args.output}))
    return 0


def _cmd_replica(args) -> int:
    from ..storage.hub import HubClient
    from .replica import Replicator
    hub = HubClient(endpoint=args.hf_endpoint) if args.hf_endpoint \
        else HubClient()
    rep = Replicator(hub=hub, pvc_mount_root=args.pvc_mount_root,
                     workers=args.workers)
    res = rep.replicate(args.source, args.target)
    print(json.dumps({"source": res.source, "target": res.target,
                      "files": res.files, "bytes": res.bytes}))
    return 0


def _cmd_serving_agent(args) -> int:
    from .serving_agent import ServingAgent
    agent = ServingAgent(args.info_file, args.adapters_dir,
                         poll_interval=args.poll_interval,
                         engine_url=args.engine_url)
    if args.once:
        agent.sync()
        return 0
    agent.start()
    try:
        while True:
            time.sleep(60)
    except KeyboardInterrupt:
        agent.stop()
    return 0


def _cmd_model_metadata(args) -> int:
    from .metadata import publish_metadata
    meta = publish_metadata(args.model_dir, args.out_file)
    print(json.dumps(meta, indent=2))
    return 0 if "error" not in meta else 1


def _cmd_hf_download(args) -> int:
    from ..storage.hub import HubClient
    hub = HubClient(endpoint=args.endpoint) if args.endpoint \
        else HubClient()
    files = hub.snapshot_download(args.repo_id, args.target_dir,
                                  revision=args.revision,
                                  workers=args.workers)
    print(json.dumps({"repo": args.repo_id, "files": len(files)}))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ome-agent",
        description="model lifecycle agent (enigma/replica/"
                    "serving-agent/model-metadata/hf-download)")
    p.add_argument("-v", "--verbose", action="store_true")
    sub = p.add_subparsers(dest="command", required=True)

    e = sub.add_parser("enigma", help="encrypt/decrypt model weights")
    e.add_argument("mode", choices=["encrypt", "decrypt"])
    e.add_argument("--input", required=True)
    e.add_argument("--output", required=True)
    e.add_argument("--keyfile", help="shorthand for --kms local:<file>")
    e.add_argument("--kms", default=None,
                   help="KMS spec: local:<keyfile> | gcpkms:<key name>")
    e.set_defaults(fn=_cmd_enigma)

    r = sub.add_parser("replica", help="replicate a model between stores")
    r.add_argument("--source", required=True, help="source storage uri")
    r.add_argument("--target", required=True, help="target storage uri")
    r.add_argument("--pvc-mount-root", default="/mnt/pvc")
    r.add_argument("--workers", type=int, default=4)
    r.add_argument("--hf-endpoint", default="")
    r.set_defaults(fn=_cmd_replica)

    s = sub.add_parser("serving-agent",
                       help="fine-tuned-adapter sidecar")
    s.add_argument("--info-file", required=True)
    s.add_argument("--adapters-dir", required=True)
    s.add_argument("--poll-interval", type=float, default=2.0)
    s.add_argument("--engine-url", default=None,
                   help="co-located engine base URL; staged adapters "
                        "hot-load via POST /v1/adapters (multi-LoRA)")
    s.add_argument("--once", action="store_true",
                   help="sync once and exit")
    s.set_defaults(fn=_cmd_serving_agent)

    m = sub.add_parser("model-metadata",
                       help="extract model metadata to JSON")
    m.add_argument("--model-dir", required=True)
    m.add_argument("--out-file", default=None)
    m.set_defaults(fn=_cmd_model_metadata)

    h = sub.add_parser("hf-download", help="snapshot-download a repo")
    h.add_argument("--repo-id", required=True)
    h.add_argument("--target-dir", required=True)
    h.add_argument("--revision", default="main")
    h.add_argument("--workers", type=int, default=4)
    h.add_argument("--endpoint", default="")
    h.set_defaults(fn=_cmd_hf_download)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    try:
        return args.fn(args)
    except Exception as e:  # noqa: BLE001 — CLI surface
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
