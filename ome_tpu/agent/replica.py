"""Replica — model replication between storage backends.

Re-designs internal/ome-agent/replica (replica/replicator/*.go: the
hf→oci, hf→pvc, oci↔oci/pvc, pvc↔pvc matrix): one replicator over the
uniform Storage interface instead of one Go type per (src, dst) pair —
any parseable storage URI can be a source, and any non-hf URI a
destination. Downloads stage through a local dir (the hub client and
object stores already resume + verify) and uploads stream back out.
"""

from __future__ import annotations

import logging
import os
import shutil
import tempfile
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..storage.hub import HubClient
from ..storage.providers import open_storage
from ..storage.uri import StorageComponents, StorageType, parse_storage_uri

log = logging.getLogger("ome.agent.replica")


@dataclass
class ReplicationResult:
    source: str
    target: str
    files: int
    bytes: int


class Replicator:
    def __init__(self, hub: Optional[HubClient] = None,
                 endpoints: Optional[Dict[str, str]] = None,
                 pvc_mount_root: str = "/mnt/pvc", workers: int = 4):
        self.hub = hub or HubClient()
        self.endpoints = endpoints or {}
        self.pvc_mount_root = pvc_mount_root
        self.workers = workers

    # -- staging -------------------------------------------------------

    def _fetch(self, comps: StorageComponents, stage: str) -> List[str]:
        if comps.type == StorageType.HUGGINGFACE:
            return self.hub.snapshot_download(
                comps.repo_id, stage, revision=comps.revision,
                workers=self.workers)
        storage = open_storage(comps, self.endpoints, self.pvc_mount_root)
        return storage.download(stage, comps.prefix, workers=self.workers)

    def _push(self, comps: StorageComponents, stage: str) -> List[str]:
        if comps.type == StorageType.HUGGINGFACE:
            raise ValueError("hf:// is read-only; cannot be a target")
        # local/pvc roots are baked into the provider by open_storage;
        # only object stores carry a non-empty key prefix
        storage = open_storage(comps, self.endpoints, self.pvc_mount_root)
        return storage.upload(stage, comps.prefix)

    # -- public --------------------------------------------------------

    def replicate(self, source_uri: str, target_uri: str,
                  stage_dir: Optional[str] = None) -> ReplicationResult:
        src = parse_storage_uri(source_uri)
        dst = parse_storage_uri(target_uri)
        own_stage = stage_dir is None
        stage = stage_dir or tempfile.mkdtemp(prefix="ome-replica-")
        try:
            files = self._fetch(src, stage)
            total = sum(os.path.getsize(f) for f in files
                        if os.path.isfile(f))
            pushed = self._push(dst, stage)
            log.info("replicated %s -> %s: %d files, %d bytes",
                     source_uri, target_uri, len(pushed), total)
            return ReplicationResult(source=source_uri, target=target_uri,
                                     files=len(pushed), bytes=total)
        finally:
            if own_stage:
                shutil.rmtree(stage, ignore_errors=True)
