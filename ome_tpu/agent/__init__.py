"""ome-agent: model lifecycle tooling (internal/ome-agent analog).

Subsystems: enigma (encrypted model distribution), replica (cross-store
replication), serving-agent (fine-tuned-adapter sidecar),
model-metadata (config extraction). CLI: `python -m ome_tpu.agent`.

Re-exports resolve lazily so each subcommand only imports what it needs
(e.g. model-metadata in a minimal init-container never pulls in
enigma's `cryptography` dependency).
"""

_EXPORTS = {
    "EnigmaError": "enigma", "KMSProvider": "enigma", "LocalKMS": "enigma",
    "decrypt_dir": "enigma", "decrypt_file": "enigma",
    "encrypt_dir": "enigma", "encrypt_file": "enigma",
    "extract_metadata": "metadata", "publish_metadata": "metadata",
    "ReplicationResult": "replica", "Replicator": "replica",
    "AdapterInfo": "serving_agent", "ServingAgent": "serving_agent",
}

__all__ = list(_EXPORTS)


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    return getattr(importlib.import_module(f".{mod}", __name__), name)
