"""Chaos soak harness: randomized fault schedules, real invariants.

PRs 1 and 5 built the failure-handling ingredients — deterministic
fault injection (faults.py), scheduler crash recovery, the durable
request journal, graceful drain, drain-aware routing, and now the PD
prefill pool with failover — but each is tested in isolation. This
module composes them: it stands up a real topology (router + prefill/
decode/unified engine SUBPROCESSES), drives a mixed workload (greedy +
temperature sampling, speculative tokens, paged-KV pressure), injects
a seed-derived schedule of fault points and process-level kills
(SIGKILL mid-decode, SIGTERM drain, prefill-peer death mid-handoff),
and then asserts the system-level invariants that individual tests
cannot:

  1. **No accepted request is lost.** After recovery + journal drain,
     every journaled admit is tombstoned: the client got an answer,
     or the respawned process resumed and finished the request.
  2. **Greedy streams are byte-identical** to a fault-free oracle run
     of the same (prompt, max_tokens) — failover, restart-resume,
     preemption, and speculation may not change emitted bytes.
  3. **KV block-pool conservation** (the PagedAttention discipline):
     at quiescence, free + slot-owned blocks account for the whole
     pool (`ome_engine_kv_conservation_ok` — the prefix cache holds
     separate device buffers, outside the pool by design). With the
     host-DRAM prefix tier enabled (the default topology passes
     ``--prefix-cache-host-mb``), the same gauge also folds in the
     two-tier accounting check (PrefixCache.tier_conservation: device
     trie + host LRU bytes exact, no double residency, host budget
     respected), and the harness additionally asserts the exported
     ``ome_engine_prefix_host_bytes`` gauge never exceeds the
     configured budget. SIGKILL mid-swap is covered by invariant 2:
     a killed engine respawns with a COLD host tier, so resumed
     greedy streams must come out byte-identical via the recompute
     fallback — which is exactly what the byte-compare proves.
  4. **/metrics stays consistent**: counters are monotone within one
     process incarnation, and draining gauges return to zero once the
     episode's drains complete.
  5. **No admitted class starves** (multi-tenancy,
     docs/multi-tenancy.md): every priority class with journaled
     admits also finishes requests, and in a noisy-neighbor episode
     the interactive class is never shed (429) — admission must shed
     the lowest class first.
  6. **Weighted shares hold**: over contended polls (two or more
     classes active with at least one queued), every class with
     QUEUED demand decodes at least a tolerance fraction of its
     weighted-fair entitlement (read from
     ``ome_engine_class_tokens_total``); classes that are merely
     demand-limited are out of scope.
  7. **No request is lost fleet-wide** (router HA,
     docs/router-ha.md): every workload request driven through the
     N-router ingress ends with exactly ONE outcome — a client that
     fails over to a surviving router after a transport failure
     never observes a duplicate and is never silently dropped
     (request durability below the routers is invariant 1, checked
     across every engine journal regardless of which router admitted
     the request).
  8. **Breaker observations outlive the replica that made them**:
     the backend records a victim router served in its last pre-kill
     gossip snapshot are held by every surviving router within one
     anti-entropy round of the kill (LWW stamps at least as new), so
     the fleet does not re-learn a dead backend the hard way.

Invariants 5 and 6 get their workload from the ``--noisy-neighbor``
episode kind: a seeded best-effort (batch-class) flood of at least
``--flood-factor``x the topology's slot capacity, steady interactive
traffic throughout, and a mid-episode SIGKILL of a serving engine.

Invariants 7 and 8 get theirs from the ``--router-loss`` episode
kind (requires ``--routers N``, N >= 2): N asyncio routers front the
same engine pool and gossip observations to each other
(router/gossip.py), the seeded schedule arms a keyed
``router_forward`` fault on one victim router so it accumulates real
breaker state, the harness snapshots the victim's /gossip/state,
waits one anti-entropy round, SIGKILLs it mid-replay, and the
workload client fails over across the surviving fronts.

Every schedule derives from ``random.Random(f"{seed}:{episode}")`` —
a violation prints the seed, the exact schedule, and a one-command
replay line. The runner REFUSES to start if any fault point it would
inject is missing from the documented catalog in
docs/failure-semantics.md (reusing scripts/check_fault_points.py), so
the harness and the failure-contract docs cannot drift apart.

CLI (also exposed as ``scripts/chaos_soak.py``)::

    python -m ome_tpu.chaos --seed 7 --episodes 50
    python -m ome_tpu.chaos --seed 7 --episode 23   # replay one

This module imports no jax: the subprocess children re-enter through
``--serve-child``, which forces the virtual CPU platform in-process
(the image's sitecustomize pins the TPU backend, so env vars alone
are not enough) before handing argv to the real entrypoints.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import pathlib
import random
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .priority import (DEFAULT_CLASS_WEIGHTS, PRIORITY_CLASSES,
                       highest_class)

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
CATALOG_DOC = REPO_ROOT / "docs" / "failure-semantics.md"

# fault points the schedule generator may draw from, by role. Kept
# deliberately clear of journal_* faults: a degraded journal cannot
# honor invariant 1, so journal durability faults stay in their own
# unit tests (tests/test_journal.py).
ENGINE_FAULT_MENU = ("engine_step",)
PD_FAULT_MENU = ("pd_peer_connect", "pd_fetch", "pd_deserialize",
                 "pd_insert")
ROUTER_FAULT_MENU = ("router_forward",)

# invariant 6 (weighted shares): a class's share of contended-window
# tokens must stay above this fraction of its weighted entitlement;
# the window itself must hold at least this many tokens to be judged
SHARE_TOLERANCE = 0.35
MIN_CONTENDED_TOKENS = 30.0

# router health-loop cadence inside chaos topologies; gossip pulls
# run on the same cadence, so invariant 8 (breaker convergence) gives
# survivors one such round plus the slack to adopt the victim's state
ROUTER_HEALTH_INTERVAL = 1.0
GOSSIP_ROUND_SLACK = 1.5


class ChaosError(RuntimeError):
    """Harness refusal or setup failure (not an invariant violation)."""


# -- fault-catalog preflight -----------------------------------------


def _load_check_fault_points():
    path = REPO_ROOT / "scripts" / "check_fault_points.py"
    spec = importlib.util.spec_from_file_location(
        "_chaos_check_fault_points", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def preflight_fault_points(specs: Sequence[str],
                           doc: Optional[pathlib.Path] = None) -> None:
    """Refuse to run a schedule that injects any fault point absent
    from the documented catalog — the same source of truth
    scripts/check_fault_points.py enforces in CI."""
    from . import faults
    points = set()
    for spec in specs:
        if spec:
            points |= faults.spec_points(spec)
    if not points:
        return
    cfp = _load_check_fault_points()
    catalog = cfp.catalog_points(doc or CATALOG_DOC)
    missing = sorted(points - catalog)
    if missing:
        raise ChaosError(
            "refusing to run: fault point(s) not in the "
            f"failure-semantics catalog: {', '.join(missing)} "
            f"(document them in {CATALOG_DOC.name} first)")


# -- subprocess management -------------------------------------------


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(url: str, payload: Optional[dict] = None,
          timeout: float = 10.0,
          headers: Optional[Dict[str, str]] = None
          ) -> Tuple[int, object]:
    """GET (payload None) or POST json; returns (status, parsed body).
    Raises URLError/OSError on transport failure."""
    data = None
    hdrs = dict(headers) if headers else {}
    if payload is not None:
        data = json.dumps(payload).encode()
        hdrs["Content-Type"] = "application/json"
    req = urllib.request.Request(url, data=data, headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            status = resp.status
    except urllib.error.HTTPError as e:
        raw = e.read()
        status = e.code
        e.close()
    try:
        return status, json.loads(raw)
    except ValueError:
        return status, raw


class ManagedProc:
    """One child process (engine or router) the harness can kill,
    drain, and respawn. `incarnation` increments per start() so
    metrics samples from different lives are never compared."""

    def __init__(self, name: str, role: str, args: List[str],
                 port: int, log_path: pathlib.Path):
        self.name = name
        self.role = role          # "engine" | "router"
        self.args = args          # argv AFTER the role token
        self.port = port
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self.incarnation = 0

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self, faults_spec: Optional[str] = None) -> None:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["OME_CHAOS_CPU"] = "1"
        env["PYTHONPATH"] = str(REPO_ROOT) + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else "")
        env.pop("OME_FAULTS", None)  # faults only via explicit argv
        args = list(self.args)
        if faults_spec:
            args += ["--faults", faults_spec]
        cmd = [sys.executable, "-m", "ome_tpu.chaos", "--serve-child",
               self.role] + args
        self.incarnation += 1
        log_fh = open(self.log_path, "a", encoding="utf-8")
        log_fh.write(f"\n==== incarnation {self.incarnation}: "
                     f"{' '.join(cmd)}\n")
        log_fh.flush()
        self.proc = subprocess.Popen(
            cmd, cwd=str(REPO_ROOT), env=env, stdout=log_fh,
            stderr=subprocess.STDOUT, start_new_session=True)
        log_fh.close()  # the child owns the fd now

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def kill(self) -> None:
        if self.alive():
            self.proc.kill()
            self.proc.wait()

    def term(self) -> None:
        if self.alive():
            self.proc.send_signal(signal.SIGTERM)

    def wait_exit(self, timeout: float = 30.0) -> None:
        if self.proc is None:
            return
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()

    def stop(self) -> None:
        if self.alive():
            self.term()
            self.wait_exit(10.0)
        self.kill()

    def tail(self, n: int = 25) -> str:
        try:
            lines = self.log_path.read_text(
                encoding="utf-8", errors="replace").splitlines()
            return "\n".join(lines[-n:])
        except OSError:
            return "<no log>"

    def wait_ready(self, timeout: float = 120.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not self.alive():
                raise ChaosError(
                    f"{self.name} exited during startup (rc="
                    f"{self.proc.returncode}); log tail:\n"
                    f"{self.tail()}")
            try:
                status, _ = _http(self.url + "/health", timeout=2.0)
                if status == 200:
                    return
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(0.25)
        raise ChaosError(f"{self.name} not ready after {timeout}s; "
                         f"log tail:\n{self.tail()}")


def _serve_child(argv: List[str]) -> int:
    """Re-entry point for harness subprocesses: force the virtual CPU
    platform IN-PROCESS (sitecustomize pins the TPU backend; env vars
    don't stick), then hand argv to the real entrypoint."""
    if not argv:
        raise SystemExit("--serve-child needs a role: engine|router")
    role, rest = argv[0], argv[1:]
    if os.environ.get("OME_CHAOS_CPU"):
        sys.path.insert(0, str(REPO_ROOT))
        from __graft_entry__ import _force_cpu_devices
        _force_cpu_devices(int(os.environ.get("OME_CHAOS_CPU_N", "1")))
    if role == "engine":
        from .engine import serve
        return serve.main(rest)
    if role == "router":
        # every chaos topology fronts with the asyncio data path
        # (router/aserver.py); the threaded server remains for
        # in-process tests, but the deployable ingress is async
        from .router import aserver
        return aserver.main(rest)
    raise SystemExit(f"unknown --serve-child role {role!r}")


# -- metrics scraping ------------------------------------------------


def scrape_metrics(url: str, timeout: float = 5.0) -> Dict[str, float]:
    """Parse a Prometheus text exposition into {'name{labels}': value}."""
    status, body = _http(url + "/metrics", timeout=timeout)
    if status != 200:
        raise ChaosError(f"/metrics answered {status} at {url}")
    if isinstance(body, bytes):
        body = body.decode("utf-8", errors="replace")
    elif not isinstance(body, str):
        body = json.dumps(body)
    out: Dict[str, float] = {}
    for line in body.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        try:
            out[key] = float(val)
        except ValueError:
            continue
    return out


class MetricsWatch:
    """Background /metrics poller asserting counter monotonicity
    within each process incarnation. Samples that straddle a restart
    (incarnation changed while scraping) are discarded."""

    def __init__(self, procs: Sequence[ManagedProc],
                 interval: float = 0.5):
        self.procs = list(procs)
        self.interval = interval
        self.violations: List[str] = []
        self._last: Dict[Tuple[str, int], Dict[str, float]] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5.0)

    def poll_once(self):
        for p in self.procs:
            inc = p.incarnation
            if not p.alive():
                continue
            try:
                sample = scrape_metrics(p.url, timeout=2.0)
            except (ChaosError, urllib.error.URLError, OSError):
                continue
            if p.incarnation != inc or not p.alive():
                continue  # straddled a restart: not comparable
            prev = self._last.get((p.name, inc))
            if prev is not None:
                for key, val in sample.items():
                    name = key.split("{", 1)[0]
                    if not name.endswith("_total"):
                        continue
                    before = prev.get(key)
                    if before is not None and val < before:
                        self.violations.append(
                            f"counter regression on {p.name} "
                            f"(incarnation {inc}): {key} "
                            f"{before} -> {val}")
            self._last[(p.name, inc)] = sample

    def _run(self):
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self.interval)


class ShareSampler:
    """Background poller feeding invariant 6 (weighted shares).

    Each poll reads the per-class token counters and queue-depth
    gauges on every serving engine. A poll is CONTENDED on an engine
    when at least two classes are active (queued, or decoded tokens
    since the previous poll) and at least one of them is queued —
    i.e. the weighted scheduler actually had an allocation decision to
    make. Within a contended poll, only classes with QUEUED demand are
    judged: a class that is not queueing is demand-limited, not
    starved, and must not be held to its entitlement (the interactive
    trickle often has exactly one in-flight request). For each queued
    class the poll accumulates the tokens it actually decoded
    (``got``) and its weight share of the poll's total token delta
    (``entitled``); counter resets (restarts) re-base via the
    (name, incarnation) key, same discipline as MetricsWatch."""

    def __init__(self, procs: Sequence[ManagedProc],
                 interval: float = 0.25):
        self.procs = list(procs)
        self.interval = interval
        self.got: Dict[str, float] = {c: 0.0
                                      for c in PRIORITY_CLASSES}
        self.entitled: Dict[str, float] = {c: 0.0
                                           for c in PRIORITY_CLASSES}
        self.contended_polls = 0
        self._last: Dict[Tuple[str, int], Dict[str, float]] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    @staticmethod
    def _per_class(sample: Dict[str, float], family: str
                   ) -> Dict[str, float]:
        return {c: sample.get(f'{family}{{class="{c}"}}', 0.0)
                for c in PRIORITY_CLASSES}

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5.0)

    def poll_once(self):
        for p in self.procs:
            inc = p.incarnation
            if not p.alive():
                continue
            try:
                sample = scrape_metrics(p.url, timeout=2.0)
            except (ChaosError, urllib.error.URLError, OSError):
                continue
            if p.incarnation != inc or not p.alive():
                continue
            toks = self._per_class(sample,
                                   "ome_engine_class_tokens_total")
            depth = self._per_class(sample,
                                    "ome_engine_class_queue_depth")
            prev = self._last.get((p.name, inc))
            self._last[(p.name, inc)] = toks
            if prev is None:
                continue
            delta = {c: max(0.0, toks[c] - prev[c])
                     for c in PRIORITY_CLASSES}
            active = {c for c in PRIORITY_CLASSES
                      if depth[c] > 0 or delta[c] > 0}
            queued = {c for c in PRIORITY_CLASSES if depth[c] > 0}
            if len(active) >= 2 and queued:
                self.contended_polls += 1
                total_delta = sum(delta.values())
                if total_delta <= 0:
                    continue
                wsum = sum(DEFAULT_CLASS_WEIGHTS.get(c, 1)
                           for c in active)
                for c in queued:
                    self.got[c] += delta[c]
                    self.entitled[c] += total_delta * (
                        DEFAULT_CLASS_WEIGHTS.get(c, 1) / wsum)

    def _run(self):
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self.interval)


# -- journal inspection ----------------------------------------------


def journal_live_entries(path: pathlib.Path) -> Dict[int, dict]:
    """Admitted-but-untombstoned requests in a journal file; a torn
    final line (crash mid-append) is skipped, like replay does."""
    live: Dict[int, dict] = {}
    if not path.exists():
        return live
    for line in path.read_text(encoding="utf-8",
                               errors="replace").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn tail
        t, jid = rec.get("t"), rec.get("jid")
        if t == "admit":
            live[jid] = rec
        elif t == "prog" and jid in live:
            live[jid].setdefault("toks", []).extend(rec.get("toks", []))
        elif t == "fin":
            live.pop(jid, None)
    return live


# -- workload --------------------------------------------------------


@dataclass
class ChaosRequest:
    prompt: str
    max_tokens: int
    temperature: float
    top_k: int = 0
    top_p: float = 1.0
    delay: float = 0.0
    # priority class (ome_tpu/priority.py); None = engine default
    priority: Optional[str] = None
    # filled by the client thread:
    status: Optional[int] = None
    text: Optional[str] = None
    finish_reason: Optional[str] = None
    error: Optional[str] = None
    # fleet-outcome bookkeeping (invariant 7): complete HTTP
    # responses received and transport-failure failovers taken
    answers: int = 0
    failovers: int = 0

    def payload(self) -> dict:
        out = {"prompt": self.prompt, "max_tokens": self.max_tokens,
               "temperature": self.temperature, "top_k": self.top_k,
               "top_p": self.top_p}
        if self.priority:
            out["priority"] = self.priority
        return out

    def headers(self) -> Dict[str, str]:
        # the header path is what the router forwards verbatim, so
        # noisy-neighbor episodes exercise it alongside the payload
        # field (the engine lets the header win)
        return ({"X-OME-Priority": self.priority}
                if self.priority else {})


def requests_from_trace(path: pathlib.Path,
                        prompt_seed: int = 0) -> List[ChaosRequest]:
    """Trace-driven episodes (--trace): replace the seeded synthetic
    workload with a replay trace (autoscale/trace.py — a saved trace
    file or an engine reqlog), keeping its inter-arrival gaps as the
    per-request start delays. The fault/kill schedule stays seeded,
    so one production trace can soak under many chaos schedules."""
    from .autoscale import trace as trace_mod
    try:
        tr = trace_mod.load_trace(path)
    except (KeyError, ValueError):
        tr = trace_mod.load_reqlog(path)
    if not tr:
        raise ChaosError(f"no replayable records in {path}")
    return [ChaosRequest(prompt=r.prompt_text(prompt_seed),
                         max_tokens=r.max_tokens,
                         temperature=r.temperature,
                         delay=r.arrival,
                         priority=r.priority)
            for r in tr]


def _gen_workload(rng: random.Random, n: int,
                  spread: float) -> List[ChaosRequest]:
    out = []
    for _ in range(n):
        prompt = "".join(rng.choice("abcdefgh ") for _ in
                         range(rng.randint(4, 12)))
        greedy = rng.random() < 0.6
        out.append(ChaosRequest(
            prompt=prompt,
            max_tokens=rng.randint(6, 20),
            temperature=0.0 if greedy else rng.choice((0.7, 1.0)),
            top_k=0 if greedy else rng.choice((0, 20)),
            top_p=1.0 if greedy else rng.choice((1.0, 0.9)),
            delay=rng.uniform(0.0, spread)))
    return out


def _gen_noisy_workload(rng: random.Random, topo: "Topology",
                        spread: float,
                        flood_factor: int) -> List[ChaosRequest]:
    """Noisy-neighbor workload: a batch-class flood of at least
    ``flood_factor``x the topology's concurrent-slot capacity lands in
    the first 40% of the episode, while a steady trickle of
    interactive requests spans the whole spread. Everything is greedy
    so invariant 2 (byte-identity vs the oracle) still applies to the
    tenant traffic under preemption and weighted scheduling."""
    serving = max(1, topo.decode + topo.unified)
    capacity = max(1, topo.max_slots) * serving
    flood_n = max(flood_factor * capacity, 2 * flood_factor)
    out = []
    for _ in range(flood_n):
        prompt = "".join(rng.choice("abcdefgh ") for _ in
                         range(rng.randint(4, 12)))
        out.append(ChaosRequest(
            prompt=prompt,
            max_tokens=rng.randint(8, 16),
            temperature=0.0,
            delay=rng.uniform(0.0, spread * 0.4),
            priority="batch"))
    n_interactive = max(4, capacity + 2)
    for i in range(n_interactive):
        prompt = "".join(rng.choice("abcdefgh ") for _ in
                         range(rng.randint(3, 8)))
        at = spread * (i + 0.5) / n_interactive
        out.append(ChaosRequest(
            prompt=prompt,
            max_tokens=rng.randint(4, 8),
            temperature=0.0,
            delay=max(0.0, at + rng.uniform(-0.1, 0.1)),
            priority=highest_class()))
    return out


def _drive(urls, reqs: Sequence[ChaosRequest],
           timeout: float = 60.0) -> None:
    """Send every request against the router front on client threads,
    honoring per-request start delays; blocks until all have an
    outcome. `urls` is one front URL or a list of N router replicas:
    requests spread across the fronts round-robin, and a TRANSPORT
    failure (connection refused/reset — no HTTP response at all)
    fails over to the next front. An HTTP error status is an answer,
    not a failover: retrying a request the router already answered is
    how clients manufacture duplicates (invariant 7)."""
    if isinstance(urls, str):
        urls = [urls]

    def one(i: int, r: ChaosRequest):
        time.sleep(r.delay)
        last = None
        for k in range(len(urls)):
            url = urls[(i + k) % len(urls)]
            try:
                status, body = _http(url + "/v1/completions",
                                     r.payload(), timeout=timeout,
                                     headers=r.headers())
            except Exception as e:  # noqa: BLE001 — a dead router
                last = f"{type(e).__name__}: {e}"  # is expected chaos
                r.failovers += 1
                continue
            r.answers += 1
            r.status = status
            if status == 200 and isinstance(body, dict):
                choice = (body.get("choices") or [{}])[0]
                r.text = choice.get("text")
                r.finish_reason = choice.get("finish_reason")
            else:
                r.error = str(body)[:200]
            return
        r.error = last or "no router front reachable"

    threads = [threading.Thread(target=one, args=(i, r), daemon=True)
               for i, r in enumerate(reqs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout + 30.0)


# -- the episode -----------------------------------------------------


@dataclass
class Topology:
    """Subprocess layout for one episode."""

    prefill: int = 2
    decode: int = 2
    unified: int = 0
    router: bool = True
    # router replicas fronting the pool; >1 turns on gossip peering
    # between them (router_loss episodes require >= 2)
    routers: int = 1
    kv_block: int = 16
    kv_blocks: int = 40
    max_slots: int = 2
    # host-DRAM prefix tier budget (MB) for every engine; 0 disables.
    # On by default so soaks exercise spill/swap-in under kills —
    # the tier is value-neutral (recompute fallback), so invariant 2
    # must hold with it on.
    prefix_host_mb: int = 4
    spec_tokens: int = 0
    pd_local_fallback: bool = False
    drain_grace: float = 4.0

    def engine_count(self) -> int:
        return self.prefill + self.decode + self.unified


@dataclass
class Episode:
    seed: int
    index: int
    topo: Topology
    kind: str = "mixed"        # "mixed" | "noisy" | "router_loss"
    requests: List[ChaosRequest] = field(default_factory=list)
    fault_specs: Dict[str, str] = field(default_factory=dict)
    events: List[Tuple[float, str, str]] = field(default_factory=list)
    violations: List[str] = field(default_factory=list)

    def schedule(self) -> dict:
        return {"seed": self.seed, "episode": self.index,
                "kind": self.kind,
                "faults": self.fault_specs,
                "events": [{"at": round(at, 3), "action": act,
                            "target": tgt}
                           for at, act, tgt in self.events],
                "requests": len(self.requests)}

    def replay_command(self) -> str:
        extra = ""
        if self.kind == "noisy":
            extra = " --noisy-neighbor"
        elif self.kind == "router_loss":
            extra = f" --router-loss --routers {self.topo.routers}"
        return (f"python scripts/chaos_soak.py --seed {self.seed} "
                f"--episode {self.index}{extra}")


def _plan_episode(seed: int, index: int, topo: Topology, n_requests: int,
                  spread: float,
                  workload: Optional[Sequence[ChaosRequest]] = None,
                  kind: str = "mixed",
                  flood_factor: int = 5) -> Episode:
    """Everything random in an episode comes from this ONE generator
    seeded by (seed, index) — the whole schedule replays from the two
    numbers a violation prints. A --trace workload substitutes the
    requests (fresh copies: episodes mutate outcome fields) but NOT
    the fault/kill schedule, which stays seed-derived."""
    rng = random.Random(f"{seed}:{index}")
    ep = Episode(seed=seed, index=index, topo=topo, kind=kind)
    if workload is not None:
        ep.requests = [ChaosRequest(
            prompt=r.prompt, max_tokens=r.max_tokens,
            temperature=r.temperature, top_k=r.top_k, top_p=r.top_p,
            delay=r.delay, priority=r.priority) for r in workload]
    elif kind == "noisy":
        ep.requests = _gen_noisy_workload(rng, topo, spread,
                                          flood_factor)
    else:
        ep.requests = _gen_workload(rng, n_requests, spread)

    decode_names = [f"decode{i}" for i in range(topo.decode)]
    unified_names = [f"unified{i}" for i in range(topo.unified)]
    prefill_names = [f"prefill{i}" for i in range(topo.prefill)]

    if kind == "noisy":
        # overload IS the chaos: no injected fault points, just one
        # seeded mid-episode SIGKILL of a serving engine so the
        # isolation invariants must survive kill-and-resume too
        serving = decode_names + unified_names
        ep.events.append((rng.uniform(0.35, 0.6) * spread, "sigkill",
                          rng.choice(serving)))
        return ep

    if kind == "router_loss":
        # the chaos IS losing one of N router replicas mid-replay. A
        # keyed router_forward fault first makes the victim accumulate
        # real breaker observations to gossip ("{serving0}" is
        # substituted with the first serving engine's URL at start
        # time — backend ports are not known at plan time); the
        # harness then snapshots the victim's /gossip/state, waits
        # one anti-entropy round so peers pull it, and SIGKILLs the
        # victim while the workload fails over across survivors
        victim = f"router{rng.randint(0, topo.routers - 1)}"
        ep.fault_specs[victim] = (
            "router_forward|{serving0}"
            f".raise@1:{rng.randint(3, 5)}")
        ep.events.append((rng.uniform(0.25, 0.5) * spread,
                          "sigkill_router", victim))
        return ep

    # fault-point schedules: at most one rule per serving proc so an
    # episode stays interpretable; hits land in the episode's early
    # request volume
    for name in decode_names:
        if rng.random() < 0.7:
            point = rng.choice(PD_FAULT_MENU + ENGINE_FAULT_MENU)
            ep.fault_specs[name] = \
                f"{point}.raise@{rng.randint(1, 4)}"
    for name in unified_names:
        if rng.random() < 0.5:
            ep.fault_specs[name] = \
                f"engine_step.raise@{rng.randint(2, 6)}"
    if topo.router and rng.random() < 0.3:
        ep.fault_specs["router"] = \
            f"router_forward.raise@{rng.randint(1, 3)}"

    # process-level events: kills and drains at seeded offsets
    serving = decode_names + unified_names
    n_events = rng.randint(0, 2) if serving else 0
    for _ in range(n_events):
        action = rng.choice(("sigkill", "sigterm"))
        ep.events.append((rng.uniform(0.5, spread),
                          action, rng.choice(serving)))
    if prefill_names and rng.random() < 0.6:
        # prefill-peer death mid-handoff: the decode pool must fail
        # over (or fall back locally) without a scheduler restart
        ep.events.append((rng.uniform(0.2, spread * 0.7),
                          "kill_prefill", rng.choice(prefill_names)))
    ep.events.sort(key=lambda e: e[0])
    return ep


class ChaosRunner:
    """Owns the topology's processes and the per-soak oracle engine;
    runs episodes and evaluates invariants."""

    def __init__(self, topo: Topology, base_dir: pathlib.Path,
                 model_dir: Optional[str] = None,
                 keep_logs: bool = False,
                 journal_drain_timeout: float = 90.0,
                 force_violation: bool = False):
        self.topo = topo
        self.base = base_dir
        self.base.mkdir(parents=True, exist_ok=True)
        self.keep_logs = keep_logs
        self.journal_drain_timeout = journal_drain_timeout
        # append a synthetic violation to every episode so the bundle
        # pipeline (flight dumps + merged trace) can be exercised
        # end-to-end without waiting for a real invariant to break
        self.force_violation = force_violation
        # empty model dir + --random-weights = the deterministic
        # tiny_test config with ByteTokenizer: every engine in the
        # topology (and the oracle) inits IDENTICAL weights from
        # PRNGKey(0), which is what makes invariant 2 meaningful
        self.model_dir = model_dir or str(self._ensure_model_dir())
        self.oracle: Optional[ManagedProc] = None
        self._oracle_cache: Dict[Tuple[str, int], Tuple[str, str]] = {}

    def _ensure_model_dir(self) -> pathlib.Path:
        d = self.base / "model"
        d.mkdir(parents=True, exist_ok=True)
        return d

    # -- oracle ------------------------------------------------------

    def _engine_args(self, port: int, topo: Topology,
                     journal_dir: Optional[pathlib.Path] = None,
                     role: Optional[str] = None,
                     prefill_urls: Sequence[str] = (),
                     reqlog: Optional[pathlib.Path] = None,
                     span_log: Optional[pathlib.Path] = None,
                     flight_dump_dir: Optional[pathlib.Path] = None,
                     debug: bool = False) -> List[str]:
        args = ["--model-dir", self.model_dir, "--random-weights",
                "--dtype", "float32", "--host", "127.0.0.1",
                "--port", str(port),
                "--max-slots", str(topo.max_slots),
                "--prefix-cache-mb", "8",
                "--drain-grace", str(topo.drain_grace)]
        if topo.prefix_host_mb:
            args += ["--prefix-cache-host-mb",
                     str(topo.prefix_host_mb)]
        if topo.kv_block:
            args += ["--kv-block", str(topo.kv_block),
                     "--kv-blocks", str(topo.kv_blocks)]
        if topo.spec_tokens and role != "prefill":
            args += ["--spec-tokens", str(topo.spec_tokens)]
        if role == "prefill":
            args += ["--disaggregation-mode", "prefill"]
        elif role == "decode":
            args += ["--disaggregation-mode", "decode",
                     "--pd-attempt-timeout", "15"]
            for u in prefill_urls:
                args += ["--prefill-url", u]
            if topo.pd_local_fallback:
                args += ["--pd-local-fallback"]
        if journal_dir is not None:
            args += ["--journal", str(journal_dir),
                     "--journal-fsync", "always"]
        if reqlog is not None:
            args += ["--request-log", str(reqlog)]
        # timeline + flight-recorder capture for the violation bundle:
        # every serving child spans its requests and exposes the
        # guarded /debug/events tail (the oracle stays bare)
        if span_log is not None:
            args += ["--span-log", str(span_log)]
        if flight_dump_dir is not None:
            args += ["--flight-dump-dir", str(flight_dump_dir)]
        if debug:
            args += ["--debug-endpoints"]
        return args

    def start_oracle(self) -> ManagedProc:
        """One fault-free unified engine, alive for the whole soak:
        the reference every greedy response is byte-compared against."""
        if self.oracle is not None and self.oracle.alive():
            return self.oracle
        port = free_port()
        topo = Topology(prefill=0, decode=0, unified=1, router=False,
                        kv_block=self.topo.kv_block,
                        kv_blocks=max(self.topo.kv_blocks, 64),
                        max_slots=self.topo.max_slots,
                        spec_tokens=0)
        self.oracle = ManagedProc(
            "oracle", "engine",
            self._engine_args(port, topo), port,
            self.base / "oracle.log")
        self.oracle.start()
        self.oracle.wait_ready()
        return self.oracle

    def oracle_text(self, prompt: str, max_tokens: int
                    ) -> Tuple[str, str]:
        key = (prompt, max_tokens)
        if key not in self._oracle_cache:
            oracle = self.start_oracle()
            status, body = _http(
                oracle.url + "/v1/completions",
                {"prompt": prompt, "max_tokens": max_tokens,
                 "temperature": 0.0}, timeout=60.0)
            if status != 200 or not isinstance(body, dict):
                raise ChaosError(
                    f"oracle answered {status}: {str(body)[:200]}")
            choice = body["choices"][0]
            self._oracle_cache[key] = (choice.get("text"),
                                       choice.get("finish_reason"))
        return self._oracle_cache[key]

    def close(self):
        if self.oracle is not None:
            self.oracle.stop()

    # -- one episode -------------------------------------------------

    def run_episode(self, ep: Episode) -> Episode:
        preflight_fault_points(list(ep.fault_specs.values()))
        topo = ep.topo
        epdir = self.base / f"ep{ep.index}"
        epdir.mkdir(parents=True, exist_ok=True)

        prefills = []
        for i in range(topo.prefill):
            port = free_port()
            name = f"prefill{i}"
            prefills.append(ManagedProc(
                name, "engine",
                self._engine_args(port, topo, role="prefill",
                                  span_log=epdir / f"{name}.spans.jsonl",
                                  flight_dump_dir=epdir, debug=True),
                port, epdir / f"{name}.log"))
        prefill_urls = [p.url for p in prefills]

        serving = []
        journals: Dict[str, pathlib.Path] = {}
        for i in range(topo.decode):
            port = free_port()
            name = f"decode{i}"
            jdir = epdir / f"journal-{name}"
            journals[name] = jdir / "requests.jsonl"
            serving.append(ManagedProc(
                name, "engine",
                self._engine_args(port, topo, journal_dir=jdir,
                                  role="decode",
                                  prefill_urls=prefill_urls,
                                  reqlog=epdir / f"{name}.reqlog",
                                  span_log=epdir / f"{name}.spans.jsonl",
                                  flight_dump_dir=epdir, debug=True),
                port, epdir / f"{name}.log"))
        for i in range(topo.unified):
            port = free_port()
            name = f"unified{i}"
            jdir = epdir / f"journal-{name}"
            journals[name] = jdir / "requests.jsonl"
            serving.append(ManagedProc(
                name, "engine",
                self._engine_args(port, topo, journal_dir=jdir,
                                  reqlog=epdir / f"{name}.reqlog",
                                  span_log=epdir / f"{name}.spans.jsonl",
                                  flight_dump_dir=epdir, debug=True),
                port, epdir / f"{name}.log"))

        routers: List[ManagedProc] = []
        if topo.router:
            n_routers = max(1, topo.routers)
            rports = [free_port() for _ in range(n_routers)]
            for i, rport in enumerate(rports):
                name = "router" if n_routers == 1 else f"router{i}"
                rargs = ["--bind", "127.0.0.1", "--port", str(rport),
                         "--policy", "round_robin",
                         "--health-interval",
                         str(ROUTER_HEALTH_INTERVAL),
                         "--replica-id", name,
                         "--debug-endpoints",
                         "--span-log",
                         str(epdir / f"{name}.spans.jsonl")]
                for s in serving:
                    rargs += ["--backend", s.url]
                for other in rports:
                    if other != rport:
                        rargs += ["--gossip-peer",
                                  f"http://127.0.0.1:{other}"]
                routers.append(ManagedProc(
                    name, "router", rargs, rport,
                    epdir / f"{name}.log"))

        procs = prefills + serving + routers
        by_name = {p.name: p for p in procs}
        watch = None
        sampler = None
        try:
            for p in prefills + serving:
                p.start(ep.fault_specs.get(p.name))
            for p in prefills + serving:
                p.wait_ready()
            for r in routers:
                r.start(self._router_faults(ep, r.name, serving))
            for r in routers:
                r.wait_ready()

            watch = MetricsWatch(procs).start()
            if ep.kind == "noisy":
                sampler = ShareSampler(serving).start()
            fronts = [r.url for r in routers] or [serving[0].url]

            # workload client threads + the kill/term schedule run
            # concurrently — that's the "mid-handoff" in the ISSUE
            driver = threading.Thread(
                target=_drive, args=(fronts, ep.requests), daemon=True)
            t0 = time.monotonic()
            driver.start()
            killed: List[ManagedProc] = []
            for at, action, target in ep.events:
                delay = t0 + at - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                victim = by_name.get(target)
                if victim is None or not victim.alive():
                    continue
                if action == "sigkill_router":
                    # invariant 8 setup: capture what the victim knew,
                    # give peers one anti-entropy round to pull it,
                    # THEN kill — survivors must hold that state
                    snap = None
                    try:
                        status, body = _http(
                            victim.url + "/gossip/state", timeout=5.0)
                        if status == 200 and isinstance(body, dict):
                            snap = body
                    except (urllib.error.URLError, OSError):
                        pass
                    time.sleep(ROUTER_HEALTH_INTERVAL
                               + GOSSIP_ROUND_SLACK)
                    victim.kill()
                    self._check_breaker_convergence(
                        ep, victim.name, snap,
                        [r for r in routers
                         if r is not victim and r.alive()])
                elif action == "sigkill" or action == "kill_prefill":
                    victim.kill()
                else:
                    victim.term()
                    victim.wait_exit(topo.drain_grace + 20.0)
                killed.append(victim)
            driver.join(180.0)

            # recovery: every killed/drained proc respawns FAULT-FREE
            # (the schedule already fired; replay must re-run it, not
            # the respawn), then resumes its journal
            for victim in killed:
                victim.wait_exit(5.0)
                victim.start()
            for victim in killed:
                victim.wait_ready()

            self._await_journal_drain(ep, journals, by_name)
            if sampler is not None:
                sampler.stop()
                sampler.poll_once()
            self._check_journals(ep, journals)
            self._check_fleet_outcomes(ep)
            self._check_class_starvation(ep, journals)
            self._check_greedy(ep)
            self._check_kv_conservation(ep, serving)
            self._check_draining_zero(ep, routers)
            if sampler is not None:
                self._check_weighted_shares(ep, sampler)
            watch.stop()
            watch.poll_once()
            ep.violations.extend(watch.violations)
            watch = None
            if self.force_violation:
                ep.violations.append(
                    "forced violation (--force-violation)")
            if ep.violations:
                # grab the bundle while the children are still alive —
                # /debug/events only answers from a live process
                self.collect_bundle(ep, epdir, procs)
        finally:
            if watch is not None:
                watch.stop()
            if sampler is not None:
                sampler.stop()
            for p in procs:
                p.stop()
        return ep

    @staticmethod
    def _router_faults(ep: Episode, name: str,
                       serving: Sequence[ManagedProc]
                       ) -> Optional[str]:
        """A router's fault spec with plan-time placeholders bound to
        the ports this episode actually got ("{serving0}" = first
        serving engine's URL, the backend the victim's keyed
        router_forward rule fails against)."""
        spec = ep.fault_specs.get(name)
        if spec and serving:
            spec = spec.replace("{serving0}", serving[0].url)
        return spec

    # -- violation bundle --------------------------------------------

    def collect_bundle(self, ep: Episode, epdir: pathlib.Path,
                       procs: Sequence[ManagedProc]
                       ) -> Optional[pathlib.Path]:
        """Violation replay bundle under ``<epdir>/bundle``: the
        schedule + violations, a flight-recorder dump per live engine
        child (via the guarded ``/debug/events`` tail), any crash
        auto-dumps the children already wrote into the episode dir,
        and every span log merged into one exported Perfetto trace
        (telemetry/export.py). Best-effort by design — a half-dead
        topology must not turn a violation report into a second
        failure."""
        bundle = epdir / "bundle"
        try:
            bundle.mkdir(parents=True, exist_ok=True)
        except OSError:
            return None

        flight_paths: List[pathlib.Path] = []
        for p in procs:
            if p.role != "engine" or not p.alive():
                continue
            try:
                status, doc = _http(p.url + "/debug/events?n=0",
                                    timeout=5.0)
            except (urllib.error.URLError, OSError):
                continue
            if status != 200 or not isinstance(doc, dict):
                continue
            # shape the endpoint doc like a FlightRecorder.dump()
            # file so the exporter (and a human) reads both the same
            doc.setdefault("pid", p.proc.pid if p.proc else 0)
            doc.setdefault("reason", "chaos_violation")
            doc["component"] = p.name
            path = bundle / f"flight-{p.name}.json"
            try:
                path.write_text(
                    json.dumps(doc, separators=(",", ":"),
                               default=str) + "\n", encoding="utf-8")
            except OSError:
                continue
            flight_paths.append(path)
        # crash recovery inside a child auto-dumps into the episode
        # dir (--flight-dump-dir): fold those lives in too
        flight_paths.extend(sorted(epdir.glob("flight-*.json")))

        # per-router replica state (breaker/gossip/stream view): what
        # each surviving front believed when the invariant broke
        for p in procs:
            if p.role != "router" or not p.alive():
                continue
            try:
                status, doc = _http(p.url + "/debug/state",
                                    timeout=5.0)
            except (urllib.error.URLError, OSError):
                continue
            if status != 200 or not isinstance(doc, dict):
                continue
            try:
                (bundle / f"router-state-{p.name}.json").write_text(
                    json.dumps(doc, indent=2, default=str) + "\n",
                    encoding="utf-8")
            except OSError:
                continue

        span_paths = sorted(epdir.glob("*.spans.jsonl"))
        try:
            from .telemetry import export as trace_export
            spans = trace_export.load_spans(span_paths)
            flights = trace_export.load_flight_dumps(flight_paths)
            doc = trace_export.build_trace(spans, flights)
            (bundle / "trace.json").write_text(
                json.dumps(doc, separators=(",", ":")) + "\n",
                encoding="utf-8")
        except Exception as e:  # noqa: BLE001 — see docstring
            ep.violations.append(
                f"bundle: trace export failed: "
                f"{type(e).__name__}: {e}")
        try:
            (bundle / "violation.json").write_text(
                json.dumps({"schedule": ep.schedule(),
                            "violations": ep.violations,
                            "replay": ep.replay_command(),
                            "span_logs": [str(s) for s in span_paths],
                            "flight_dumps": [str(f)
                                             for f in flight_paths]},
                           indent=2) + "\n", encoding="utf-8")
        except OSError:
            return None
        print(f"[chaos] violation bundle: {bundle}", flush=True)
        return bundle

    # -- invariants --------------------------------------------------

    def _await_journal_drain(self, ep: Episode,
                             journals: Dict[str, pathlib.Path],
                             by_name: Dict[str, ManagedProc]) -> None:
        deadline = time.monotonic() + self.journal_drain_timeout
        while time.monotonic() < deadline:
            leftover = {name: journal_live_entries(path)
                        for name, path in journals.items()}
            if not any(leftover.values()):
                return
            # a proc that crashed OUTSIDE the schedule (startup race,
            # OOM) would wedge this wait — surface it instead
            for name in leftover:
                p = by_name.get(name)
                if p is not None and not p.alive():
                    ep.violations.append(
                        f"{name} died outside the schedule with "
                        f"{len(leftover[name])} journaled request(s) "
                        f"unresumed; log tail:\n{p.tail()}")
                    return
            time.sleep(0.5)
        # timed out: _check_journals reports the specifics

    def _check_journals(self, ep: Episode,
                        journals: Dict[str, pathlib.Path]) -> None:
        """Invariant 1: journal ⊕ responses cover all admits — after
        recovery + resume, no admit record is left untombstoned."""
        for name, path in journals.items():
            live = journal_live_entries(path)
            if live:
                ep.violations.append(
                    f"request-loss: {name} journal has "
                    f"{len(live)} admitted request(s) never finished "
                    f"(jids {sorted(live)[:8]})")

    def _check_class_starvation(self, ep: Episode,
                                journals: Dict[str, pathlib.Path]
                                ) -> None:
        """Invariant 5: no admitted class starves. Per class, admits
        across the topology's journals must be matched by finishes —
        a class-wide zero means the weighted scheduler never ran that
        class at all (individual stragglers are invariant 1's job).
        In a noisy-neighbor episode, additionally: the interactive
        class is never shed (429). Admission sheds the lowest class
        first, and the episode's interactive demand is modest by
        construction, so any interactive 429 is a shedding-order
        violation."""
        admits: Dict[str, int] = {}
        fins: Dict[str, int] = {}
        for path in journals.values():
            if not path.exists():
                continue
            cls_of: Dict[int, str] = {}
            for line in path.read_text(encoding="utf-8",
                                       errors="replace").splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail
                t, jid = rec.get("t"), rec.get("jid")
                if t == "admit":
                    cls = rec.get("cls", "standard")
                    cls_of[jid] = cls
                    admits[cls] = admits.get(cls, 0) + 1
                elif t == "fin" and jid in cls_of:
                    cls = cls_of[jid]
                    fins[cls] = fins.get(cls, 0) + 1
        for cls in sorted(admits):
            if admits[cls] and not fins.get(cls):
                ep.violations.append(
                    f"class starvation: class {cls!r} admitted "
                    f"{admits[cls]} request(s) but finished none")
        if ep.kind != "noisy":
            return
        shed = [r for r in ep.requests
                if r.priority == highest_class() and r.status == 429]
        if shed:
            ep.violations.append(
                f"shedding-order violation: {len(shed)} interactive "
                f"request(s) got 429 during a batch flood — admission "
                f"must shed the lowest class first")

    def _check_weighted_shares(self, ep: Episode,
                               sampler: ShareSampler) -> None:
        """Invariant 6: a class with QUEUED demand during contended
        polls must decode at least SHARE_TOLERANCE of its weighted
        entitlement over those polls. Judging only queued classes
        keeps demand-limited traffic out of scope (an interactive
        trickle with one in-flight request is not starved just
        because batch fills the other slots), while a queued class
        that the scheduler ignores sits near 0% and is caught. The
        floor is loose on purpose: sampling is coarse (0.25s polls vs
        per-step allocation) and slot granularity skews short
        windows."""
        for cls in PRIORITY_CLASSES:
            entitled = sampler.entitled[cls]
            if entitled < MIN_CONTENDED_TOKENS:
                continue  # not enough queued demand to judge
            got = sampler.got[cls]
            if got < entitled * SHARE_TOLERANCE:
                ep.violations.append(
                    f"weighted-share violation: class {cls!r} "
                    f"decoded {int(got)} tokens against a weighted "
                    f"entitlement of {int(entitled)} while queued "
                    f"(floor {SHARE_TOLERANCE:.0%}, "
                    f"{sampler.contended_polls} contended polls)")

    def _check_greedy(self, ep: Episode) -> None:
        """Invariant 2: greedy completions match the fault-free
        oracle byte-for-byte. Only cleanly finished responses compare
        — errored/timed-out/shutdown requests are covered by the
        journal invariant instead."""
        for r in ep.requests:
            if r.temperature != 0.0 or r.status != 200:
                continue
            if r.finish_reason not in ("stop", "length"):
                continue
            want_text, want_fin = self.oracle_text(r.prompt,
                                                   r.max_tokens)
            if r.text != want_text or r.finish_reason != want_fin:
                ep.violations.append(
                    "greedy divergence: prompt "
                    f"{r.prompt!r} max_tokens={r.max_tokens}: got "
                    f"{r.text!r} ({r.finish_reason}), oracle "
                    f"{want_text!r} ({want_fin})")

    def _check_kv_conservation(self, ep: Episode,
                               serving: Sequence[ManagedProc]) -> None:
        """Invariant 3: at quiescence every paged pool conserves
        blocks (free + owned = total − trash block); the gauge is
        computed per scrape by Scheduler.update_gauges."""
        if not ep.topo.kv_block:
            return
        for p in serving:
            if not p.alive():
                continue
            try:
                sample = scrape_metrics(p.url)
            except (ChaosError, urllib.error.URLError, OSError) as e:
                ep.violations.append(
                    f"kv-conservation: cannot scrape {p.name}: {e}")
                continue
            ok = sample.get("ome_engine_kv_conservation_ok")
            if ok is not None and ok != 1.0:
                ep.violations.append(
                    f"kv-conservation violated on {p.name}: free="
                    f"{sample.get('ome_engine_kv_blocks_free')} "
                    f"owned={sample.get('ome_engine_kv_blocks_owned')} "
                    f"host_bytes="
                    f"{sample.get('ome_engine_prefix_host_bytes')}")
            # host-tier budget from the exported gauge: the in-process
            # tier_conservation check already folds into the gauge
            # above; this asserts the same bound end to end through
            # /metrics, the surface an operator actually alerts on
            host = sample.get("ome_engine_prefix_host_bytes")
            budget = ep.topo.prefix_host_mb * (1 << 20)
            if host is not None and budget and host > budget:
                ep.violations.append(
                    f"host-tier over budget on {p.name}: "
                    f"ome_engine_prefix_host_bytes={int(host)} > "
                    f"{budget}")

    def _check_draining_zero(self, ep: Episode,
                             routers: Sequence[ManagedProc]) -> None:
        """Invariant 4b: once the episode's drains finish, every live
        router's draining gauge returns to zero (the health loop
        re-probes at --health-interval)."""
        for router in routers:
            if not router.alive():
                continue
            deadline = time.monotonic() + 15.0
            last = None
            while time.monotonic() < deadline:
                try:
                    sample = scrape_metrics(router.url)
                except (ChaosError, urllib.error.URLError, OSError):
                    last = None
                    break
                last = sample.get("ome_router_backends_draining", 0.0)
                if not last:
                    break
                time.sleep(1.0)
            if last:
                ep.violations.append(
                    f"draining gauge stuck on {router.name}: "
                    f"ome_router_backends_draining={last} after "
                    f"episode end")

    def _check_fleet_outcomes(self, ep: Episode) -> None:
        """Invariant 7: every workload request ends with exactly one
        outcome fleet-wide. The failover client records how many
        complete HTTP responses it observed; more than one is a
        duplicate (a client retried a request some router had already
        answered), zero with no recorded transport error is a silent
        drop. Failing over only on transport failure — never on an
        HTTP status — is what makes both impossible by construction;
        this check pins that contract against client regressions."""
        for i, r in enumerate(ep.requests):
            if r.answers > 1:
                ep.violations.append(
                    f"fleet outcome: request {i} observed "
                    f"{r.answers} answers across router fronts "
                    f"(duplicate)")
            if r.answers == 0 and r.error is None:
                ep.violations.append(
                    f"fleet outcome: request {i} vanished — no "
                    f"response and no transport error recorded")

    def _check_breaker_convergence(
            self, ep: Episode, victim_name: str,
            snap: Optional[dict],
            survivors: Sequence[ManagedProc]) -> None:
        """Invariant 8: every real observation (stamp > 0) the victim
        router served in its last pre-kill gossip snapshot is held by
        every surviving router within one anti-entropy round of the
        kill — held meaning the survivor's record for that backend
        carries an LWW stamp at least as new (its own fresher
        observation also satisfies the invariant)."""
        if not survivors:
            return
        if not isinstance(snap, dict):
            ep.violations.append(
                f"gossip convergence: no pre-kill snapshot from "
                f"{victim_name} (/gossip/state unreachable)")
            return
        needed = {
            url: rec
            for url, rec in (snap.get("backends") or {}).items()
            if isinstance(rec, dict) and rec.get("stamp", 0) > 0}
        # say what the invariant is judging so a clean episode is
        # auditable as non-vacuous from the soak log alone
        print(f"[chaos] invariant 8: {victim_name} served "
              f"{len(needed)} real observation(s); checking "
              f"{len(survivors)} survivor(s)", flush=True)
        if not needed:
            return
        pending = {(s.name, url) for s in survivors for url in needed}
        states: Dict[str, dict] = {}
        deadline = time.monotonic() + ROUTER_HEALTH_INTERVAL \
            + GOSSIP_ROUND_SLACK
        while pending and time.monotonic() < deadline:
            for s in survivors:
                if not s.alive():
                    pending -= {(s.name, u) for u in needed}
                    continue
                try:
                    status, body = _http(s.url + "/gossip/state",
                                         timeout=3.0)
                except (urllib.error.URLError, OSError):
                    continue
                if status != 200 or not isinstance(body, dict):
                    continue
                have = body.get("backends") or {}
                states[s.name] = have
                for url, rec in needed.items():
                    mine = have.get(url)
                    if isinstance(mine, dict) and \
                            (mine.get("stamp", 0.0),
                             mine.get("origin", "")) >= \
                            (rec.get("stamp", 0.0),
                             rec.get("origin", "")):
                        pending.discard((s.name, url))
            if pending:
                time.sleep(0.25)
        for name, url in sorted(pending):
            want = needed[url]
            have = (states.get(name) or {}).get(url)
            ep.violations.append(
                f"gossip convergence: {name} did not adopt "
                f"{victim_name}'s observation of {url} within one "
                f"anti-entropy round (want stamp >= "
                f"{want.get('stamp')} origin {want.get('origin')!r}, "
                f"have {have and have.get('stamp')})")


# -- weight-plane kill episode (docs/model-fleet.md) -----------------


def _hash_tree(root: pathlib.Path) -> Dict[str, str]:
    import hashlib
    out: Dict[str, str] = {}
    for p in sorted(root.rglob("*")):
        if p.is_file() and not p.name.startswith(".ome_fetch_"):
            out[str(p.relative_to(root))] = hashlib.sha256(
                p.read_bytes()).hexdigest()
    return out


def run_weight_kill_episode(seed: int, base_dir: pathlib.Path, *,
                            n_objects: int = 24, obj_kb: int = 8,
                            slow_s: float = 0.05,
                            timeout: float = 120.0) -> List[str]:
    """SIGKILL the model agent mid-download; assert the weight plane's
    failure contract (docs/model-fleet.md):

      1. the serving path NEVER holds a partial tree — until a
         complete publish it does not exist at all, and is never
         ``is_published``;
      2. every object the staging manifest recorded before the kill
         has its staged bytes intact (size + sha256 match) — the
         ledger never gets ahead of the disk;
      3. the re-run RESUMES: every object recorded before the kill is
         skipped (``resumed`` counts them all), the tree publishes,
         and the published bytes are identical to the source.

    The kill lands deterministically mid-download by pacing each
    object with a ``weight_fetch.slow`` rule and waiting until the
    manifest has recorded a seed-derived number of objects — not by
    racing a wall-clock sleep against process startup. Returns the
    violation list (empty = episode clean).
    """
    from .modelagent import weightplane

    preflight_fault_points([f"weight_fetch.slow={slow_s}@1:1"])
    rng = random.Random(seed)
    violations: List[str] = []
    base_dir = pathlib.Path(base_dir)
    src = base_dir / "source"
    target = base_dir / "served" / "model"
    target.parent.mkdir(parents=True, exist_ok=True)

    # seed-derived source tree: sizes and bytes reproduce per seed
    src.mkdir(parents=True, exist_ok=True)
    for i in range(n_objects):
        size = obj_kb * 1024 + rng.randrange(obj_kb * 1024)
        (src / f"shard-{i:03d}.bin").write_bytes(
            rng.getrandbits(8 * size).to_bytes(size, "little"))
    src_hashes = _hash_tree(src)
    kill_after = rng.randint(max(2, n_objects // 4),
                             max(3, n_objects // 2))

    argv = [sys.executable, "-m", "ome_tpu.modelagent.weightplane",
            "--source", f"local://{src}", "--target", str(target),
            "--name", f"chaos-seed{seed}", "--workers", "2",
            "--faults", f"weight_fetch.slow={slow_s}@1:{n_objects}"]
    log_path = base_dir / "agent.log"
    staging = pathlib.Path(weightplane.staging_dir(str(target)))
    with open(log_path, "ab") as lf:
        proc = subprocess.Popen(argv, stdout=lf, stderr=lf,
                                cwd=str(REPO_ROOT))
    deadline = time.monotonic() + timeout
    try:
        while True:
            m = weightplane.FetchManifest.load(str(staging))
            if m is not None and len(m.objects) >= kill_after:
                break
            if proc.poll() is not None:
                violations.append(
                    f"agent exited (rc={proc.returncode}) before the "
                    f"kill threshold ({kill_after} objects) — the "
                    "episode never got to kill mid-download")
                return violations
            if time.monotonic() > deadline:
                violations.append(
                    f"manifest never reached {kill_after} objects "
                    f"within {timeout:g}s")
                return violations
            # the serving path must not flicker into existence while
            # the download is in flight
            if target.exists():
                violations.append(
                    "serving path exists mid-download (invariant 1)")
            time.sleep(0.01)
        proc.kill()
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    # invariant 1: nothing partial at the serving path
    if target.exists():
        violations.append("serving path exists after mid-download "
                          "SIGKILL (invariant 1)")
    if weightplane.is_published(str(target)):
        violations.append("partial tree reads as published "
                          "(invariant 1)")

    # invariant 2: the manifest never gets ahead of the disk
    m = weightplane.FetchManifest.load(str(staging))
    if m is None or not m.objects:
        violations.append("no staging manifest survived the kill")
        return violations
    if m.complete:
        violations.append("staging manifest marked complete before "
                          "publish (invariant 1)")
    recorded = dict(m.objects)
    from .storage.base import sha256_file
    for rel, rec in recorded.items():
        p = staging / rel
        if not p.is_file():
            violations.append(f"manifest records {rel} but the staged "
                              "file is missing (invariant 2)")
        elif p.stat().st_size != rec["size"] \
                or sha256_file(str(p)) != rec["sha256"]:
            violations.append(f"staged {rel} does not match its "
                              "manifest record (invariant 2)")

    # invariant 3: the re-run resumes from verified objects and
    # publishes a byte-identical tree
    rerun = subprocess.run(
        [sys.executable, "-m", "ome_tpu.modelagent.weightplane",
         "--source", f"local://{src}", "--target", str(target),
         "--name", f"chaos-seed{seed}", "--workers", "2"],
        capture_output=True, text=True, timeout=timeout,
        cwd=str(REPO_ROOT))
    if rerun.returncode != 0:
        violations.append(f"re-run failed (rc={rerun.returncode}): "
                          f"{rerun.stdout[-300:]}{rerun.stderr[-300:]}")
        return violations
    stats = json.loads(rerun.stdout.strip().splitlines()[-1])
    if stats.get("resumed", 0) != len(recorded):
        violations.append(
            f"re-run resumed {stats.get('resumed')} objects, expected "
            f"every one of the {len(recorded)} recorded before the "
            "kill (invariant 3)")
    if not weightplane.is_published(str(target)):
        violations.append("re-run did not publish (invariant 3)")
    if staging.exists():
        violations.append("staging dir survived publish (invariant 3)")
    if _hash_tree(target) != src_hashes:
        violations.append("published tree is not byte-identical to "
                          "the source (invariant 3)")
    return violations


# -- soak entry ------------------------------------------------------


def run_soak(seed: int, episodes: Sequence[int], topo: Topology,
             base_dir: pathlib.Path, n_requests: int, spread: float,
             keep_logs: bool = False,
             journal_drain_timeout: float = 90.0,
             force_violation: bool = False,
             workload: Optional[Sequence[ChaosRequest]] = None,
             kind: str = "mixed", flood_factor: int = 5,
             override_events: Optional[Sequence[Tuple[float, str, str]]]
             = None) -> int:
    from .telemetry import Registry
    registry = Registry()
    c_episodes = registry.counter("ome_chaos_episodes_total",
                                  "Chaos episodes completed")
    c_requests = registry.counter("ome_chaos_requests_total",
                                  "Chaos workload requests driven")
    c_violations = registry.counter(
        "ome_chaos_invariant_failures_total",
        "Invariant violations detected across the soak")
    runner = ChaosRunner(topo, base_dir, keep_logs=keep_logs,
                         journal_drain_timeout=journal_drain_timeout,
                         force_violation=force_violation)
    failed = []
    try:
        for index in episodes:
            ep = _plan_episode(seed, index, topo, n_requests, spread,
                               workload=workload, kind=kind,
                               flood_factor=flood_factor)
            if override_events is not None:
                # a down-converted sim schedule is authoritative: its
                # kills replace the seed-derived events, and the
                # fault-point specs (sim transport points have no
                # subprocess analog) are cleared
                ep.events = [tuple(e) for e in override_events]
                ep.fault_specs = {}
            print(f"[chaos] episode {index} ({ep.kind}): "
                  f"{len(ep.requests)} requests, faults="
                  f"{ep.fault_specs or '{}'}, events="
                  f"{[(round(a, 2), b, c) for a, b, c in ep.events]}",
                  flush=True)
            runner.run_episode(ep)
            c_episodes.inc()
            c_requests.inc(len(ep.requests))
            if ep.violations:
                c_violations.inc(len(ep.violations))
                failed.append(ep)
                print(f"[chaos] EPISODE {index} FAILED "
                      f"({len(ep.violations)} violation(s)):",
                      flush=True)
                for v in ep.violations:
                    print(f"  - {v}", flush=True)
                print("[chaos] schedule: "
                      + json.dumps(ep.schedule()), flush=True)
                print(f"[chaos] replay: {ep.replay_command()}",
                      flush=True)
            else:
                print(f"[chaos] episode {index} OK", flush=True)
    finally:
        runner.close()
    total = len(list(episodes))
    print(f"[chaos] soak done: {total - len(failed)}/{total} episodes "
          f"clean, {int(c_violations.value)} violation(s)", flush=True)
    if failed:
        print("[chaos] replay failing episodes with:", flush=True)
        for ep in failed:
            print(f"  {ep.replay_command()}", flush=True)
    return 1 if failed else 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="chaos_soak",
        description="Seed-replayable chaos soak over a router + "
                    "prefill/decode/unified engine topology with "
                    "invariant checking (docs/README.md). Subprocess "
                    "re-entry: --serve-child {engine,router} ARGS...")
    p.add_argument("--seed", type=int, default=0,
                   help="schedule seed; a violation's printed "
                        "(seed, episode) pair replays exactly")
    p.add_argument("--episodes", type=int, default=5,
                   help="number of episodes (0..N-1) to run")
    p.add_argument("--episode", type=int, default=None,
                   help="run exactly ONE episode index (replay mode)")
    p.add_argument("--prefill", type=int, default=2,
                   help="prefill engines in the PD pool")
    p.add_argument("--decode", type=int, default=2,
                   help="PD decode engines behind the router")
    p.add_argument("--unified", type=int, default=0,
                   help="monolithic (non-PD) engines behind the router")
    p.add_argument("--no-router", action="store_true",
                   help="drive the first serving engine directly")
    p.add_argument("--routers", type=int, default=1,
                   help="router replicas fronting the pool; >1 peers "
                        "them with anti-entropy gossip and spreads "
                        "the workload across the fronts with "
                        "client-side failover")
    p.add_argument("--requests", type=int, default=10,
                   help="workload requests per episode")
    p.add_argument("--spread", type=float, default=4.0,
                   help="seconds the workload (and fault events) are "
                        "spread over")
    p.add_argument("--trace", default=None,
                   help="replay-driven episodes: drive each episode "
                        "with this trace (autoscale save_trace JSONL "
                        "or engine reqlog) instead of the synthetic "
                        "workload; the fault/kill schedule stays "
                        "seed-derived, and --spread grows to cover "
                        "the trace duration")
    p.add_argument("--schedule", default=None,
                   help="fidelity spot-check: down-convert a "
                        "simulator FaultSchedule JSON "
                        "(sim/faultplan.py) onto this topology — its "
                        "kill events become SIGKILLs of the real "
                        "serving engines (round-robin), its seed "
                        "drives the workload, and the SAME "
                        "invariants are checked; runs one episode")
    p.add_argument("--kv-block", type=int, default=16,
                   help="paged-KV block size for the engines (0 = "
                        "dense; disables the conservation invariant)")
    p.add_argument("--kv-blocks", type=int, default=40,
                   help="paged-KV pool size (small = pool pressure)")
    p.add_argument("--max-slots", type=int, default=2)
    p.add_argument("--prefix-host-mb", type=int, default=4,
                   help="host-DRAM prefix-cache tier budget (MB) on "
                        "every engine (0 disables); the conservation "
                        "invariant then covers both tiers and kills "
                        "exercise the recompute fallback")
    p.add_argument("--spec-tokens", type=int, default=0,
                   help="speculative draft tokens on decode/unified "
                        "engines (greedy stays byte-identical)")
    p.add_argument("--pd-local-fallback", action="store_true",
                   help="decode engines compute prefill locally when "
                        "the whole prefill pool is down")
    p.add_argument("--drain-grace", type=float, default=4.0)
    p.add_argument("--journal-drain-timeout", type=float, default=90.0,
                   help="seconds to wait after recovery for resumed "
                        "requests to tombstone their journal entries")
    p.add_argument("--base-dir", default=None,
                   help="scratch directory for logs/journals "
                        "(default: a fresh temp dir)")
    p.add_argument("--keep-logs", action="store_true",
                   help="do not delete the scratch directory")
    p.add_argument("--force-violation", action="store_true",
                   help="append a synthetic violation to every "
                        "episode, exercising the replay bundle "
                        "(flight dumps + merged trace) end to end")
    p.add_argument("--noisy-neighbor", action="store_true",
                   help="noisy-neighbor episodes: a batch-class "
                        "flood of --flood-factor x slot capacity "
                        "plus steady interactive traffic and one "
                        "mid-episode SIGKILL, checked against the "
                        "multi-tenant isolation invariants (no "
                        "admitted class starves, weighted shares "
                        "hold, interactive never shed)")
    p.add_argument("--flood-factor", type=int, default=5,
                   help="noisy-neighbor flood size as a multiple of "
                        "the topology's concurrent slot capacity")
    p.add_argument("--router-loss", action="store_true",
                   help="router-loss episodes (requires --routers "
                        ">= 2): arm a keyed router_forward fault on "
                        "one victim router, snapshot its gossip "
                        "state, SIGKILL it mid-replay, and check the "
                        "fleet invariants (exactly one outcome per "
                        "request, survivors adopt the victim's "
                        "breaker observations within one "
                        "anti-entropy round)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "--serve-child":
        return _serve_child(argv[1:])
    args = build_parser().parse_args(argv)
    topo = Topology(prefill=args.prefill, decode=args.decode,
                    unified=args.unified, router=not args.no_router,
                    routers=args.routers,
                    kv_block=args.kv_block, kv_blocks=args.kv_blocks,
                    max_slots=args.max_slots,
                    prefix_host_mb=args.prefix_host_mb,
                    spec_tokens=args.spec_tokens,
                    pd_local_fallback=args.pd_local_fallback,
                    drain_grace=args.drain_grace)
    if topo.engine_count() == 0:
        build_parser().error("topology has no serving engines")
    if topo.decode and not topo.prefill:
        build_parser().error("--decode engines need a --prefill pool "
                             "(or use --unified engines)")
    if args.router_loss and (args.no_router or topo.routers < 2):
        build_parser().error("--router-loss needs --routers >= 2 "
                             "(a victim plus survivors)")
    if args.router_loss and args.noisy_neighbor:
        build_parser().error("--router-loss and --noisy-neighbor are "
                             "separate episode kinds")
    if args.base_dir:
        base = pathlib.Path(args.base_dir)
        cleanup = False
    else:
        import tempfile
        base = pathlib.Path(tempfile.mkdtemp(prefix="ome-chaos-"))
        cleanup = not args.keep_logs
    episodes = ([args.episode] if args.episode is not None
                else list(range(args.episodes)))
    workload = None
    spread = args.spread
    if args.trace:
        workload = requests_from_trace(pathlib.Path(args.trace))
        # kill/drain events must land inside the replayed traffic
        spread = max(spread, max(r.delay for r in workload))
    seed = args.seed
    override_events = None
    if args.schedule:
        from .sim.faultplan import FaultSchedule, to_chaos_events
        sched = FaultSchedule.load(args.schedule)
        serving = ([f"decode{i}" for i in range(topo.decode)]
                   + [f"unified{i}" for i in range(topo.unified)])
        override_events = to_chaos_events(sched, serving, spread)
        seed = sched.seed
        episodes = [args.episode if args.episode is not None else 0]
        print(f"[chaos] schedule {args.schedule}: "
              f"{len(override_events)} kill(s) down-converted onto "
              f"{len(serving)} serving engine(s), seed {seed}",
              flush=True)
    try:
        rc = run_soak(seed, episodes, topo, base,
                      n_requests=args.requests, spread=spread,
                      keep_logs=args.keep_logs,
                      journal_drain_timeout=args.journal_drain_timeout,
                      force_violation=args.force_violation,
                      workload=workload,
                      kind=("router_loss" if args.router_loss
                            else "noisy" if args.noisy_neighbor
                            else "mixed"),
                      flood_factor=args.flood_factor,
                      override_events=override_events)
    finally:
        if cleanup:
            import shutil
            shutil.rmtree(base, ignore_errors=True)
        else:
            print(f"[chaos] logs kept under {base}", flush=True)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
