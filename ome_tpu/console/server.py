"""Console REST API.

Mirrors the reference backend's routes (web-console/backend/cmd/api/
main.go:56-145):

  GET    /api/v1/namespaces
  GET    /api/v1/models[?namespace=]         (cluster + namespaced)
  GET    /api/v1/runtimes[?namespace=]
  GET    /api/v1/services[?namespace=]
  POST   /api/v1/services                    (create isvc, admission-checked)
  DELETE /api/v1/services/{ns}/{name}
  GET    /api/v1/accelerators
  POST   /api/v1/validate                    (admission dry-run, no persist)
  GET    /api/v1/huggingface?q=              (hub model search proxy)
  GET    /                                   (single-page UI)

Works against InMemoryClient or KubeClient — the console only speaks
the shared client interface.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..apis import v1
from ..webhooks.admission import (AdmissionError, default_inference_service,
                                  validate_inference_service)
from .ui import INDEX_HTML

log = logging.getLogger("ome.console")

HF_API_DEFAULT = "https://huggingface.co"


def _summary(obj) -> dict:
    d = obj.to_dict()
    d["kind"] = type(obj).KIND
    return d


class ConsoleServer:
    def __init__(self, client, host: str = "0.0.0.0", port: int = 0,
                 hf_endpoint: Optional[str] = None):
        self.client = client
        self.hf_endpoint = (hf_endpoint or HF_API_DEFAULT).rstrip("/")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, code: int, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.send_header("Access-Control-Allow-Origin", "*")
                self.end_headers()
                self.wfile.write(body)

            def _html(self, body: bytes):
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _query(self):
                return {k: vs[0] for k, vs in urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query).items()}

            def do_GET(self):
                path = urllib.parse.urlparse(self.path).path
                q = self._query()
                ns = q.get("namespace")
                try:
                    if path in ("/", "/index.html"):
                        return self._html(INDEX_HTML.encode())
                    if path == "/healthz":
                        return self._json(200, {"status": "ok"})
                    if path == "/api/v1/namespaces":
                        return self._json(200, outer.namespaces())
                    if path == "/api/v1/models":
                        items = [_summary(m) for m in outer.client.list(
                            v1.ClusterBaseModel)]
                        items += [_summary(m) for m in outer.client.list(
                            v1.BaseModel, namespace=ns)]
                        return self._json(200, {"items": items})
                    if path == "/api/v1/runtimes":
                        items = [_summary(r) for r in outer.client.list(
                            v1.ClusterServingRuntime)]
                        items += [_summary(r) for r in outer.client.list(
                            v1.ServingRuntime, namespace=ns)]
                        return self._json(200, {"items": items})
                    if path == "/api/v1/services":
                        items = [_summary(s) for s in outer.client.list(
                            v1.InferenceService, namespace=ns)]
                        return self._json(200, {"items": items})
                    if path == "/api/v1/accelerators":
                        items = [_summary(a) for a in outer.client.list(
                            v1.AcceleratorClass)]
                        return self._json(200, {"items": items})
                    if path == "/api/v1/huggingface":
                        return self._json(200, outer.hf_search(
                            q.get("q", ""), int(q.get("limit", "10"))))
                    return self._json(404, {"error": "not found"})
                except Exception as e:
                    log.exception("GET %s failed", path)
                    return self._json(500, {"error": str(e)})

            def do_POST(self):
                path = urllib.parse.urlparse(self.path).path
                n = int(self.headers.get("Content-Length") or 0)
                try:
                    body = json.loads(self.rfile.read(n) or b"{}")
                except ValueError as e:
                    return self._json(400, {"error": f"bad json: {e}"})
                try:
                    if path == "/api/v1/validate":
                        ok, msgs = outer.validate(body)
                        return self._json(200, {"valid": ok,
                                                "messages": msgs})
                    if path == "/api/v1/services":
                        created, errs = outer.create_service(body)
                        if errs:
                            return self._json(422, {"errors": errs})
                        return self._json(201, _summary(created))
                    return self._json(404, {"error": "not found"})
                except Exception as e:
                    log.exception("POST %s failed", path)
                    return self._json(500, {"error": str(e)})

            def do_DELETE(self):
                parts = [p for p in urllib.parse.urlparse(self.path)
                         .path.split("/") if p]
                if len(parts) == 5 and parts[:3] == ["api", "v1",
                                                     "services"]:
                    _, _, _, ns, name = parts
                    from ..core.errors import NotFoundError
                    try:
                        outer.client.delete(v1.InferenceService, name, ns)
                        return self._json(200, {"deleted": f"{ns}/{name}"})
                    except NotFoundError:
                        return self._json(404, {"error": "not found"})
                return self._json(404, {"error": "not found"})

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- handlers ------------------------------------------------------

    def namespaces(self) -> dict:
        seen = set()
        for cls in (v1.InferenceService, v1.BaseModel, v1.ServingRuntime,
                    v1.BenchmarkJob):
            for obj in self.client.list(cls):
                if obj.metadata.namespace:
                    seen.add(obj.metadata.namespace)
        return {"items": sorted(seen) or ["default"]}

    def validate(self, body: dict):
        isvc = v1.InferenceService.from_dict(body)
        try:
            default_inference_service(self.client, isvc)
            validate_inference_service(self.client, isvc)
            return True, []
        except AdmissionError as e:
            return False, e.messages

    def create_service(self, body: dict):
        isvc = v1.InferenceService.from_dict(body)
        if not isvc.metadata.namespace:
            isvc.metadata.namespace = "default"
        try:
            default_inference_service(self.client, isvc)
            validate_inference_service(self.client, isvc)
        except AdmissionError as e:
            return None, e.messages
        return self.client.create(isvc), []

    def hf_search(self, query: str, limit: int = 10) -> dict:
        url = (f"{self.hf_endpoint}/api/models?"
               + urllib.parse.urlencode({"search": query, "limit": limit}))
        try:
            with urllib.request.urlopen(url, timeout=15) as resp:
                models = json.loads(resp.read())
        except Exception as e:
            return {"items": [], "error": f"hub unreachable: {e}"}
        return {"items": [{
            "id": m.get("modelId") or m.get("id"),
            "downloads": m.get("downloads"),
            "likes": m.get("likes"),
            "pipeline_tag": m.get("pipeline_tag"),
        } for m in models]}

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "ConsoleServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="ome-console", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def main(argv=None) -> int:
    import argparse

    from ..cmd.manager import build_client
    p = argparse.ArgumentParser(prog="ome-console")
    p.add_argument("--port", type=int, default=8090)
    p.add_argument("--bind", default="0.0.0.0")
    p.add_argument("--kubeconfig", default=None)
    p.add_argument("--kube-server", default=None)
    p.add_argument("--in-cluster", action="store_true")
    p.add_argument("--hf-endpoint", default=None)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    client = build_client(args)
    srv = ConsoleServer(client, host=args.bind, port=args.port,
                        hf_endpoint=args.hf_endpoint).start()
    log.info("console on :%d", srv.port)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
