"""Web console: REST backend + single-page UI.

Re-designs web-console/ (backend: Go/gin over informers at
web-console/backend/cmd/api/main.go:56-145; frontend: React). Same
API surface, served by one stdlib HTTP server over either client
substrate; the UI is a dependency-free single HTML file.
"""

from .server import ConsoleServer  # noqa: F401
