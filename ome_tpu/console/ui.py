"""Single-file console UI (the reference ships a React app; this is a
dependency-free equivalent covering the same workflows: browse models/
runtimes/services/accelerators, inspect status, validate + create an
InferenceService, search the HF hub)."""

INDEX_HTML = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>OME-TPU Console</title>
<style>
  :root { --bg:#0e1116; --panel:#161b24; --line:#283042; --fg:#dbe2ef;
          --dim:#8b96ab; --acc:#4f8cff; --ok:#3fb68b; --bad:#e0635f; }
  * { box-sizing: border-box; }
  body { margin:0; background:var(--bg); color:var(--fg);
         font:14px/1.5 system-ui, sans-serif; }
  header { padding:14px 22px; border-bottom:1px solid var(--line);
           display:flex; gap:18px; align-items:baseline; }
  header h1 { font-size:17px; margin:0; }
  header nav a { color:var(--dim); margin-right:14px; cursor:pointer;
                 text-decoration:none; }
  header nav a.active { color:var(--acc); }
  main { padding:20px 22px; max-width:1100px; }
  table { width:100%; border-collapse:collapse; background:var(--panel);
          border:1px solid var(--line); border-radius:8px; }
  th, td { text-align:left; padding:8px 12px;
           border-bottom:1px solid var(--line); font-size:13px; }
  th { color:var(--dim); font-weight:500; }
  .ok { color:var(--ok); } .bad { color:var(--bad); }
  textarea { width:100%; height:220px; background:var(--panel);
             color:var(--fg); border:1px solid var(--line);
             border-radius:8px; padding:10px; font:12px monospace; }
  button { background:var(--acc); color:#fff; border:0; padding:8px 14px;
           border-radius:6px; cursor:pointer; margin-right:8px; }
  input { background:var(--panel); color:var(--fg); padding:8px;
          border:1px solid var(--line); border-radius:6px; width:320px; }
  pre { background:var(--panel); border:1px solid var(--line);
        border-radius:8px; padding:12px; overflow:auto; font-size:12px; }
</style>
</head>
<body>
<header>
  <h1>OME-TPU</h1>
  <nav id="nav"></nav>
</header>
<main id="main"></main>
<script>
const TABS = ["services","models","runtimes","accelerators","create","hub"];
let tab = "services";
const $ = (h) => { const d = document.createElement("div");
                   d.innerHTML = h; return d; };
const get = (p) => fetch(p).then(r => r.json());

function nav() {
  document.getElementById("nav").innerHTML = TABS.map(t =>
    `<a class="${t===tab?'active':''}" onclick="go('${t}')">${t}</a>`
  ).join("");
}
function go(t) { tab = t; nav(); render(); }

function rows(items, cols) {
  return `<table><tr>${cols.map(c=>`<th>${c[0]}</th>`).join("")}</tr>` +
    items.map(i=>`<tr>${cols.map(c=>`<td>${c[1](i)??""}</td>`).join("")}
    </tr>`).join("") + "</table>";
}
const meta = i => i.metadata || {};
const ready = s => { const c = (s.status?.conditions||[])
    .find(c=>c.type==="Ready");
  return c?.status==="True" ? '<span class="ok">Ready</span>'
                            : '<span class="bad">NotReady</span>'; };

async function render() {
  const m = document.getElementById("main");
  if (tab === "services") {
    const d = await get("/api/v1/services");
    m.replaceChildren($(rows(d.items, [
      ["namespace", i=>meta(i).namespace], ["name", i=>meta(i).name],
      ["model", i=>i.spec?.model?.name], ["mode",
        i=>i.status?.deploymentMode], ["url", i=>i.status?.url],
      ["status", ready]])));
  } else if (tab === "models") {
    const d = await get("/api/v1/models");
    m.replaceChildren($(rows(d.items, [
      ["kind", i=>i.kind], ["name", i=>meta(i).name],
      ["architecture", i=>i.spec?.modelArchitecture],
      ["params", i=>i.spec?.modelParameterSize],
      ["storage", i=>i.spec?.storage?.storageUri],
      ["state", i=>i.status?.lifecycle]])));
  } else if (tab === "runtimes") {
    const d = await get("/api/v1/runtimes");
    m.replaceChildren($(rows(d.items, [
      ["name", i=>meta(i).name],
      ["formats", i=>(i.spec?.supportedModelFormats||[])
         .map(f=>f.modelArchitecture||f.name).join(", ")],
      ["sizeRange", i=>{const r=i.spec?.modelSizeRange;
         return r?`${r.min||""}-${r.max||""}`:""}],
      ["accelerators", i=>(i.spec?.acceleratorRequirements?
         .acceleratorClasses||[]).join(", ")]])));
  } else if (tab === "accelerators") {
    const d = await get("/api/v1/accelerators");
    m.replaceChildren($(rows(d.items, [
      ["name", i=>meta(i).name], ["family", i=>i.spec?.family],
      ["topology", i=>i.spec?.topology?.shape],
      ["memoryGB", i=>i.spec?.capabilities?.memoryGb],
      ["nodes", i=>i.status?.nodeCount]])));
  } else if (tab === "create") {
    m.replaceChildren($(`
      <p>InferenceService JSON (validated by the admission chain):</p>
      <textarea id="spec">{
  "metadata": {"name": "my-svc", "namespace": "default"},
  "spec": {"model": {"name": ""}, "engine": {}}
}</textarea><br>
      <button onclick="validate()">Validate</button>
      <button onclick="create()">Create</button>
      <pre id="out"></pre>`));
  } else if (tab === "hub") {
    m.replaceChildren($(`
      <p><input id="q" placeholder="search huggingface models">
      <button onclick="hub()">Search</button></p><div id="hubout"></div>`));
  }
}
async function validate() {
  const body = document.getElementById("spec").value;
  const r = await fetch("/api/v1/validate", {method:"POST", body});
  document.getElementById("out").textContent =
    JSON.stringify(await r.json(), null, 2);
}
async function create() {
  const body = document.getElementById("spec").value;
  const r = await fetch("/api/v1/services", {method:"POST", body});
  document.getElementById("out").textContent =
    JSON.stringify(await r.json(), null, 2);
}
async function hub() {
  const q = document.getElementById("q").value;
  const d = await get("/api/v1/huggingface?q=" + encodeURIComponent(q));
  document.getElementById("hubout").replaceChildren($(rows(d.items, [
    ["model", i=>i.id], ["downloads", i=>i.downloads],
    ["likes", i=>i.likes], ["task", i=>i.pipeline_tag]])));
}
nav(); render();
</script>
</body>
</html>
"""
