"""HuggingFace config.json parsers (pkg/hfutil/modelconfig analog)."""

from .parser import (ConfigParseError, FamilyHandler, ParsedModelConfig,
                     parse_config, parse_model_dir, supported_model_types)
