"""HuggingFace config.json parsing — architecture registry.

Re-designs pkg/hfutil/modelconfig (SURVEY.md §2.7: ~45 per-architecture
parsers implementing the HuggingFaceModel interface,
modelconfig/interface.go:16-47). Instead of one Go file per family,
a registry maps model_type → a FamilyHandler that supplies capability
flags and a parameter-count formula; dense-transformer families share
the generic estimator and only structurally different families (MoE
variants, MLA, SSM, encoder-decoder, encoders, diffusion) override it.

When a safetensors index is available the exact parameter count comes
from its total_size instead of the formula (the reference parses
weights metadata the same way).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..apis.v1 import ModelCapability, format_parameter_size


@dataclass
class ParsedModelConfig:
    model_type: str = ""
    architecture: str = ""
    parameter_count: int = 0
    context_length: int = 0
    quantization: Optional[str] = None
    capabilities: List[str] = field(default_factory=list)
    torch_dtype: str = "bfloat16"
    hidden_size: int = 0
    num_layers: int = 0
    num_experts: int = 0
    vision: bool = False
    raw: Dict = field(default_factory=dict)

    @property
    def parameter_size(self) -> str:
        return format_parameter_size(float(self.parameter_count))

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0


class ConfigParseError(ValueError):
    pass


def _g(cfg: Dict, *keys, default=0):
    for k in keys:
        if cfg.get(k) is not None:
            return cfg[k]
    return default


# -- generic dense-transformer estimator -----------------------------------


def dense_params(cfg: Dict) -> int:
    """Llama-family superset: embeddings + per-layer GQA attention +
    gated MLP + norms (+ biases where the family uses them)."""
    V = _g(cfg, "vocab_size", default=32000)
    D = _g(cfg, "hidden_size", "n_embd", "d_model", default=4096)
    L = _g(cfg, "num_hidden_layers", "n_layer", "num_layers", default=32)
    H = _g(cfg, "num_attention_heads", "n_head", default=32)
    K = _g(cfg, "num_key_value_heads", default=H) or H
    Dh = _g(cfg, "head_dim", default=D // max(H, 1))
    F = _g(cfg, "intermediate_size", "n_inner", "ffn_dim",
           default=4 * D)
    attn = D * H * Dh + 2 * D * K * Dh + H * Dh * D
    if _g(cfg, "attention_bias", "qkv_bias", default=False):
        attn += (H + 2 * K) * Dh + D
    gates = 3 if _g(cfg, "hidden_act", "activation_function",
                    default="silu") in ("silu", "swiglu", "gelu_pytorch_tanh",
                                        "gelu") else 2
    mlp = gates * D * F
    norms = 2 * D
    embed = V * D
    if not _g(cfg, "tie_word_embeddings", default=False):
        embed *= 2
    return embed + L * (attn + mlp + norms) + D


def moe_params(cfg: Dict) -> int:
    """Mixtral/Qwen-MoE-style: every layer's MLP replaced by E experts
    + router (+ optional shared experts)."""
    D = _g(cfg, "hidden_size", default=4096)
    L = _g(cfg, "num_hidden_layers", default=32)
    E = _g(cfg, "num_local_experts", "num_experts", "n_routed_experts")
    Fm = _g(cfg, "moe_intermediate_size",
            default=_g(cfg, "intermediate_size", default=4 * D))
    shared = _g(cfg, "n_shared_experts", "num_shared_experts", default=0)
    dense = dense_params(cfg)
    F = _g(cfg, "intermediate_size", default=4 * D)
    dense_mlp = 3 * D * F * L
    expert_mlp = L * (E * 3 * D * Fm + D * E + shared * 3 * D * Fm)
    return dense - dense_mlp + expert_mlp


def deepseek_params(cfg: Dict) -> int:
    """DeepSeek-V2/V3 MLA + MoE with dense first-k layers."""
    V = _g(cfg, "vocab_size", default=102400)
    D = _g(cfg, "hidden_size", default=5120)
    L = _g(cfg, "num_hidden_layers", default=60)
    H = _g(cfg, "num_attention_heads", default=128)
    q_lora = _g(cfg, "q_lora_rank", default=0)
    kv_lora = _g(cfg, "kv_lora_rank", default=512)
    qk_nope = _g(cfg, "qk_nope_head_dim", default=128)
    qk_rope = _g(cfg, "qk_rope_head_dim", default=64)
    v_dim = _g(cfg, "v_head_dim", default=128)
    qk_dim = qk_nope + qk_rope
    if q_lora:
        attn = D * q_lora + q_lora * H * qk_dim
    else:
        attn = D * H * qk_dim
    attn += D * (kv_lora + qk_rope) + kv_lora * H * (qk_nope + v_dim)
    attn += H * v_dim * D
    F = _g(cfg, "intermediate_size", default=12288)
    Fm = _g(cfg, "moe_intermediate_size", default=1536)
    E = _g(cfg, "n_routed_experts", default=0)
    shared = _g(cfg, "n_shared_experts", default=0)
    first_dense = _g(cfg, "first_k_dense_replace", default=0 if E else L)
    moe_layers = L - first_dense if E else 0
    dense_layers = L - moe_layers
    mlp_dense = 3 * D * F
    mlp_moe = E * 3 * D * Fm + D * E + shared * 3 * D * Fm
    total = 2 * V * D + D
    total += L * (attn + 2 * D)
    total += dense_layers * mlp_dense + moe_layers * mlp_moe
    return total


def mamba_params(cfg: Dict) -> int:
    V = _g(cfg, "vocab_size", default=50280)
    D = _g(cfg, "hidden_size", "d_model", default=2560)
    L = _g(cfg, "num_hidden_layers", "n_layer", default=64)
    expand = _g(cfg, "expand", default=2)
    state = _g(cfg, "state_size", "d_state", default=16)
    conv = _g(cfg, "conv_kernel", "d_conv", default=4)
    Di = expand * D
    per_layer = (2 * D * Di          # in_proj
                 + Di * conv         # conv1d
                 + Di * (2 * state)  # x_proj (B,C)
                 + Di                # dt
                 + Di * state        # A
                 + Di * D + D)       # out_proj + norm
    return V * D + L * per_layer + D


def encdec_params(cfg: Dict) -> int:
    """T5-style encoder-decoder."""
    V = _g(cfg, "vocab_size", default=32128)
    D = _g(cfg, "d_model", "hidden_size", default=768)
    Le = _g(cfg, "num_layers", default=12)
    Ld = _g(cfg, "num_decoder_layers", default=Le)
    F = _g(cfg, "d_ff", "intermediate_size", default=4 * D)
    attn = 4 * D * D
    enc = Le * (attn + 2 * D * F + 2 * D)
    dec = Ld * (2 * attn + 2 * D * F + 3 * D)
    return V * D + enc + dec


# -- registry ---------------------------------------------------------------

TEXT_GEN = [ModelCapability.TEXT_GENERATION.value,
            ModelCapability.CHAT.value]
EMBED = [ModelCapability.TEXT_EMBEDDINGS.value]


@dataclass
class FamilyHandler:
    model_type: str
    params: Callable[[Dict], int] = dense_params
    capabilities: List[str] = field(default_factory=lambda: list(TEXT_GEN))
    vision: bool = False
    context_keys: tuple = ("max_position_embeddings",)
    # nested sub-config holding the text model (VLM composites)
    text_config_key: Optional[str] = None


_REGISTRY: Dict[str, FamilyHandler] = {}


def register(handler: FamilyHandler):
    _REGISTRY[handler.model_type] = handler


def _vlm(model_type: str, text_key: str = "text_config") -> FamilyHandler:
    return FamilyHandler(
        model_type, params=dense_params,
        capabilities=TEXT_GEN + [ModelCapability.VISION.value],
        vision=True, text_config_key=text_key)


for _t in ("llama", "mistral", "qwen2", "qwen3", "gemma", "gemma2",
           "phi", "phi3", "stablelm", "internlm2", "baichuan", "yi",
           "olmo", "olmo2", "granite", "starcoder2", "gpt_neox", "mpt",
           "falcon", "exaone", "nemotron", "glm", "glm4", "chatglm",
           "smollm", "gpt_bigcode"):
    register(FamilyHandler(_t))
register(FamilyHandler("gpt2", context_keys=("n_positions", "n_ctx")))
register(FamilyHandler("gemma3_text"))
register(FamilyHandler("cohere"))   # command-r
register(FamilyHandler("cohere2"))
for _t in ("mixtral", "qwen2_moe", "qwen3_moe", "phimoe", "dbrx",
           "jamba", "olmoe", "arctic", "gpt_oss", "grok-1", "minimax",
           "granitemoe"):
    register(FamilyHandler(_t, params=moe_params))
for _t in ("deepseek", "deepseek_v2", "deepseek_v3", "kimi_k2",
           "minicpm3"):
    register(FamilyHandler(_t, params=deepseek_params))
register(FamilyHandler("llama4", params=moe_params,
                       text_config_key="text_config",
                       capabilities=TEXT_GEN
                       + [ModelCapability.VISION.value], vision=True))
for _t, _k in (("qwen2_vl", None), ("qwen2_5_vl", None),
               ("mllama", "text_config"), ("llava", "text_config"),
               ("paligemma", "text_config"), ("gemma3", "text_config"),
               ("idefics3", "text_config"), ("internvl_chat", "llm_config"),
               ("pixtral", "text_config"), ("mistral3", "text_config")):
    register(_vlm(_t, _k) if _k else FamilyHandler(
        _t, capabilities=TEXT_GEN + [ModelCapability.VISION.value],
        vision=True))
for _t in ("bert", "roberta", "xlm-roberta", "distilbert", "nomic_bert",
           "modernbert"):
    register(FamilyHandler(_t, capabilities=list(EMBED)))
register(FamilyHandler("t5", params=encdec_params,
                       capabilities=[ModelCapability.TEXT_GENERATION.value],
                       context_keys=("n_positions",)))
register(FamilyHandler("mamba", params=mamba_params))
register(FamilyHandler("falcon_mamba", params=mamba_params))


# -- entry points -----------------------------------------------------------


def detect_quantization(cfg: Dict) -> Optional[str]:
    q = cfg.get("quantization_config") or {}
    method = q.get("quant_method")
    if method == "fp8":
        return "fbgemm_fp8" if q.get("modules_to_not_convert") else "fp8"
    if method in ("gptq", "awq"):
        bits = q.get("bits", 4)
        return f"int{bits}"
    if method == "bitsandbytes":
        return "int8" if q.get("load_in_8bit") else "int4"
    if method in ("mxfp4", "compressed-tensors"):
        return method
    return None


def safetensors_param_count(model_dir: str, dtype: str) -> Optional[int]:
    """Exact count from model.safetensors.index.json total_size."""
    idx = os.path.join(model_dir, "model.safetensors.index.json")
    if not os.path.exists(idx):
        return None
    try:
        with open(idx) as f:
            meta = json.load(f).get("metadata", {})
        total = meta.get("total_size")
    except (ValueError, OSError):
        return None
    if not total:
        return None
    bytes_per = {"float32": 4, "float16": 2, "bfloat16": 2,
                 "int8": 1, "fp8": 1, "float8_e4m3fn": 1}.get(dtype, 2)
    return int(total) // bytes_per


def parse_config(cfg: Dict, model_dir: Optional[str] = None,
                 ) -> ParsedModelConfig:
    if "_class_name" in cfg or "_diffusers_version" in cfg:
        return _parse_diffusion(cfg)
    model_type = cfg.get("model_type", "")
    archs = cfg.get("architectures") or []
    handler = _REGISTRY.get(model_type)
    if handler is None:
        # fall back on the architecture name's family, then generic
        for t, h in _REGISTRY.items():
            if archs and archs[0].lower().startswith(t.replace("_", "")):
                handler = h
                break
    if handler is None:
        handler = FamilyHandler(model_type or "unknown")

    text_cfg = cfg
    if handler.text_config_key and handler.text_config_key in cfg:
        text_cfg = {**cfg[handler.text_config_key]}
        text_cfg.setdefault("model_type", model_type)

    dtype = cfg.get("torch_dtype") or text_cfg.get("torch_dtype") \
        or "bfloat16"
    count = None
    if model_dir:
        count = safetensors_param_count(model_dir, dtype)
    if count is None:
        count = handler.params(text_cfg)

    ctx = 0
    for k in handler.context_keys + ("max_position_embeddings",):
        v = text_cfg.get(k) or cfg.get(k)
        if v:
            ctx = int(v)
            break

    return ParsedModelConfig(
        model_type=model_type,
        architecture=archs[0] if archs else "",
        parameter_count=int(count),
        context_length=ctx,
        quantization=detect_quantization(cfg),
        capabilities=list(handler.capabilities),
        torch_dtype=str(dtype),
        hidden_size=_g(text_cfg, "hidden_size", "d_model", "n_embd"),
        num_layers=_g(text_cfg, "num_hidden_layers", "n_layer",
                      "num_layers"),
        num_experts=_g(text_cfg, "num_local_experts", "num_experts",
                       "n_routed_experts"),
        vision=handler.vision,
        raw=cfg)


def _parse_diffusion(cfg: Dict) -> ParsedModelConfig:
    """model_index.json (diffusers pipelines: SD/SDXL/Flux...)."""
    cls = cfg.get("_class_name", "DiffusionPipeline")
    return ParsedModelConfig(
        model_type="diffusion",
        architecture=cls,
        capabilities=[ModelCapability.IMAGE_GENERATION.value],
        raw=cfg)


def parse_model_dir(model_dir: str) -> ParsedModelConfig:
    """Find + parse config.json or model_index.json
    (config_parser.go:51-124 behavior)."""
    for name in ("config.json", "model_index.json"):
        p = os.path.join(model_dir, name)
        if os.path.exists(p):
            with open(p) as f:
                try:
                    cfg = json.load(f)
                except ValueError as e:
                    raise ConfigParseError(f"{p}: invalid JSON: {e}")
            return parse_config(cfg, model_dir=model_dir)
    raise ConfigParseError(
        f"no config.json or model_index.json under {model_dir!r}")


def supported_model_types() -> List[str]:
    return sorted(_REGISTRY)
