"""Scout — model watcher deciding what this node stages.

Re-designs pkg/modelagent/scout.go:49-745: handles (Cluster)BaseModel
add/update/delete events, checks the model's StorageSpec node
constraints (nodeSelector / nodeAffinity) against this node's labels
(scout.go:499-652 shouldDownloadModel), and enqueues Gopher tasks.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

from ..apis import v1
from ..core.client import Event, InMemoryClient
from ..core.k8s import Node
from ..core.serde import to_dict
from .gopher import Gopher, GopherTask, TaskType

log = logging.getLogger("ome.modelagent.scout")


def node_matches_storage(storage: Optional[v1.StorageSpec],
                         node: Node) -> bool:
    """shouldDownloadModel: empty constraints mean every node stages."""
    if storage is None:
        return True
    if storage.node_selector:
        if not all(node.metadata.labels.get(k) == val
                   for k, val in storage.node_selector.items()):
            return False
    aff = storage.node_affinity
    if aff:
        terms = (aff.get("required", aff) or {}).get(
            "nodeSelectorTerms", [])
        if terms:
            for term in terms:
                ok = True
                for e in term.get("matchExpressions", []):
                    key = e.get("key")
                    op = e.get("operator", "In")
                    have = node.metadata.labels.get(key)
                    values = e.get("values", [])
                    if op == "In":
                        ok = ok and have in values
                    elif op == "NotIn":
                        ok = ok and have not in values
                    elif op == "Exists":
                        ok = ok and have is not None
                    elif op == "DoesNotExist":
                        ok = ok and have is None
                if ok:
                    return True
            return False
    return True


class Scout:
    def __init__(self, client: InMemoryClient, gopher: Gopher,
                 node_name: str):
        self.client = client
        self.gopher = gopher
        self.node_name = node_name
        self._cancel: Optional[Callable[[], None]] = None
        # last download-relevant spec per model, so self-inflicted CR
        # updates (config parse-back) don't re-trigger downloads — the
        # reference's UpdateFunc diffs old/new specs the same way
        # (scout.go:170-230)
        self._seen: dict = {}

    def start(self):
        # seed: existing models reconcile on boot (informer initial list)
        for cls in (v1.BaseModel, v1.ClusterBaseModel):
            for m in self.client.list(cls):
                self._handle(m, deleted=False)
        self._cancel = self.client.watch(self._on_event)

    def stop(self):
        if self._cancel:
            self._cancel()

    def _on_event(self, ev: Event):
        if not isinstance(ev.obj, (v1.BaseModel, v1.ClusterBaseModel)):
            return
        self._handle(ev.obj, deleted=(ev.type == "Deleted"))

    def _handle(self, model, deleted: bool):
        node = self.client.try_get(Node, self.node_name)
        if node is None:
            return
        kind = type(model).KIND
        task_kw = dict(model_kind=kind,
                       model_namespace=model.metadata.namespace,
                       model_name=model.metadata.name)
        key = (kind, model.metadata.namespace, model.metadata.name)
        if deleted or model.metadata.deletion_timestamp \
                or model.spec.disabled:
            self._seen.pop(key, None)
            # spec rides along so _delete removes a custom storage.path
            self.gopher.enqueue(GopherTask(type=TaskType.DELETE,
                                           spec=model.spec, **task_kw))
            return
        sig = repr(to_dict(model.spec.storage))
        if self._seen.get(key) == sig:
            return  # spec unchanged (e.g. our own config parse-back)
        self._seen[key] = sig
        if not node_matches_storage(model.spec.storage, node):
            log.debug("%s/%s: node constraints exclude %s",
                      kind, model.metadata.name, self.node_name)
            return
        self.gopher.enqueue(GopherTask(type=TaskType.DOWNLOAD,
                                       spec=model.spec, **task_kw))
