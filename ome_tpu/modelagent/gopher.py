"""Gopher — the model download worker pool.

Re-designs pkg/modelagent/gopher.go:240-1442: a queue of tasks
(Download / Delete) drained by worker threads; per-storage-type
download paths (HF hub with chunk-dedup via the native CDC store,
object stores, PVC/local), post-download verification, config.json
parsing written back to the model CR, then node label + per-node
ConfigMap status updates.
"""

from __future__ import annotations

import enum
import logging
import os
import queue
import random
import shutil
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from .. import constants
from ..apis import v1
from ..core.client import InMemoryClient
from ..core.errors import ConflictError
from ..hfconfig import ConfigParseError, parse_model_dir
from ..storage.base import verify_tree
from ..storage.hub import HubClient
from ..storage.providers import open_storage
from ..storage.uri import StorageType, parse_storage_uri
from ..storage.xet import ChunkStore, DedupStats
from . import weightplane
from .metrics import METRICS
from .reconcilers import ConfigMapReconciler, NodeLabelReconciler

log = logging.getLogger("ome.modelagent.gopher")


class TaskType(str, enum.Enum):
    DOWNLOAD = "Download"
    DELETE = "Delete"


@dataclass
class GopherTask:
    type: TaskType
    model_kind: str  # BaseModel | ClusterBaseModel
    model_namespace: str
    model_name: str
    spec: Optional[v1.BaseModelSpec] = None


@dataclass
class Gopher:
    client: InMemoryClient
    node_name: str
    models_root: str = "/mnt/models"
    hub: Optional[HubClient] = None
    chunk_store: Optional[ChunkStore] = None
    download_retries: int = 3
    num_workers: int = 2
    endpoints: Dict[str, str] = field(default_factory=dict)
    # injectable for tests: backoff sleeps and their jitter source
    sleep: Callable[[float], None] = time.sleep
    rng: Optional[random.Random] = None

    def __post_init__(self):
        self.tasks: "queue.Queue[Optional[GopherTask]]" = queue.Queue()
        self.labels = NodeLabelReconciler(self.client, self.node_name)
        self.status_cm = ConfigMapReconciler(self.client, self.node_name)
        self._threads = []
        self._stop = threading.Event()
        if self.rng is None:
            self.rng = random.Random()

    # -- lifecycle -----------------------------------------------------

    def start(self):
        for i in range(self.num_workers):
            t = threading.Thread(target=self._worker,
                                 name=f"gopher-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self, timeout: float = 5.0):
        """Bounded shutdown: set the stop flag, wake every worker with
        one sentinel each, then join with a deadline shared across
        threads. A worker mid-download finishes (or fails) its current
        task and exits on its next queue poll; stop() itself never
        blocks past ``timeout``."""
        self._stop.set()
        for _ in self._threads:
            self.tasks.put(None)
        deadline = time.monotonic() + timeout
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        self._threads = [t for t in self._threads if t.is_alive()]

    def enqueue(self, task: GopherTask):
        self.tasks.put(task)

    def drain(self):
        """Synchronously process queued tasks (test/deterministic mode)."""
        while True:
            try:
                task = self.tasks.get_nowait()
            except queue.Empty:
                return
            try:
                if task is not None:
                    self.process(task)
            finally:
                self.tasks.task_done()

    def _worker(self):
        # Every successful get() is matched by exactly one task_done()
        # — including sentinels — so queue.join() accounting stays
        # exact. The timed get() means a worker parked on an empty
        # queue still notices _stop even if another worker consumed
        # its sentinel.
        while True:
            try:
                task = self.tasks.get(timeout=0.2)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            try:
                if task is None:
                    return
                self.process(task)
            except Exception:
                log.exception("task %s %s failed unexpectedly",
                              task.type, task.model_name)
            finally:
                self.tasks.task_done()

    # -- task processing (gopher.go:240+) ------------------------------

    def model_dir(self, task: GopherTask) -> str:
        if task.spec is not None and task.spec.storage is not None \
                and task.spec.storage.path:
            return task.spec.storage.path
        return os.path.join(self.models_root, task.model_name)

    def process(self, task: GopherTask):
        if task.type == TaskType.DELETE:
            self._delete(task)
            return
        self._set_state(task, constants.MODEL_STATUS_UPDATING)
        try:
            target = self._download(task)
            self._parse_and_update_cr(task, target)
        except Exception as e:  # noqa: BLE001 — any failure marks the node
            log.warning("download %s failed: %s", task.model_name, e)
            METRICS.inc("downloads_failed_total")
            self._set_state(task, constants.MODEL_STATUS_FAILED,
                            {"error": str(e)[:500]})
            return
        METRICS.inc("downloads_success_total")
        self._set_state(task, constants.MODEL_STATUS_READY)

    def _set_state(self, task: GopherTask, state: str,
                   extra: Optional[Dict] = None):
        self.labels.reconcile(task.model_kind, task.model_name, state)
        self.status_cm.set_status(task.model_kind, task.model_namespace,
                                  task.model_name, state, extra)

    def _delete(self, task: GopherTask):
        target = self.model_dir(task)
        for tree in (target, weightplane.staging_dir(target),
                     target.rstrip("/") + ".trash"):
            if os.path.isdir(tree):
                shutil.rmtree(tree, ignore_errors=True)
        self.labels.reconcile(task.model_kind, task.model_name, None)
        self.status_cm.remove(task.model_kind, task.model_namespace,
                              task.model_name)

    # -- download paths ------------------------------------------------

    def _download(self, task: GopherTask) -> str:
        spec = task.spec
        if spec is None or spec.storage is None \
                or not spec.storage.storage_uri:
            raise ValueError(f"model {task.model_name} has no storage uri")
        target = self.model_dir(task)
        if spec.storage.download_policy == v1.DownloadPolicy.REUSE \
                and weightplane.is_published(target):
            # ReuseIfExists (model.go:150-156) — only a tree the
            # weight plane published complete counts; a partial tree
            # from a killed download must be re-fetched, not served
            return target

        comps = parse_storage_uri(spec.storage.storage_uri)
        last: Optional[Exception] = None
        for attempt in range(self.download_retries):
            if attempt:
                self.sleep(weightplane.backoff_delay(attempt - 1,
                                                     self.rng))
            try:
                if comps.type == StorageType.HUGGINGFACE:
                    self._download_hf(comps, target)
                else:
                    # local/pvc roots are baked into the provider; only
                    # object stores carry a key prefix
                    storage = open_storage(comps, self.endpoints)
                    prefix = comps.prefix
                    expected = storage.list(prefix)
                    if not expected:
                        raise IOError(
                            f"{spec.storage.storage_uri}: no objects found")
                    weightplane.fetch_and_publish(
                        storage, prefix, expected, target,
                        name=task.model_name, retries=1)
                METRICS.inc("verifications_total")
                return target
            except Exception as e:  # noqa: BLE001
                last = e
                log.warning("attempt %d/%d for %s failed: %s",
                            attempt + 1, self.download_retries,
                            task.model_name, e)
        raise last  # type: ignore[misc]

    def _download_hf(self, comps, target: str):
        # The hub client has its own resumable transfer; the weight
        # plane stages, hashes and atomically publishes its output so
        # the serving path keeps the same never-partial contract.
        hub = self.hub or HubClient()
        staging = weightplane.staging_dir(target)
        t0 = time.monotonic()
        files = hub.snapshot_download(comps.repo_id, staging,
                                      revision=comps.revision)
        expected = hub.expected_objects(comps.repo_id, comps.revision)
        bad = verify_tree(staging, [o for o in expected if o.size])
        if bad:
            raise IOError(f"verification failed: {bad[:3]}")
        weightplane.seal_tree(staging,
                              fetch_seconds=time.monotonic() - t0)
        weightplane.publish(target, name=comps.repo_id)
        # feed the dedup store so future revisions reuse local chunks
        if self.chunk_store is not None:
            stats = DedupStats()
            for f in files:
                rel = os.path.relpath(f, staging)
                key = f"{comps.repo_id}@{comps.revision}/{rel}"
                manifest = self.chunk_store.ingest(
                    os.path.join(target, rel), stats)
                self.chunk_store.save_manifest(key, manifest)
            METRICS.observe("dedup_ratio", stats.dedup_ratio)

    # -- config parse-back (gopher.go:207, config_parser.go:51) --------

    def _parse_and_update_cr(self, task: GopherTask, target: str):
        try:
            parsed = parse_model_dir(target)
        except ConfigParseError as e:
            log.info("no parseable config for %s: %s", task.model_name, e)
            return
        cls = (v1.BaseModel if task.model_kind == "BaseModel"
               else v1.ClusterBaseModel)
        for _ in range(4):
            obj = self.client.try_get(cls, task.model_name,
                                      task.model_namespace)
            if obj is None:
                return
            spec = obj.spec
            before = repr(spec)
            if parsed.architecture:
                spec.model_architecture = parsed.architecture
            if parsed.parameter_count:
                spec.model_parameter_size = parsed.parameter_size
            if parsed.context_length:
                spec.max_tokens = parsed.context_length
            if parsed.capabilities and not spec.model_capabilities:
                spec.model_capabilities = list(parsed.capabilities)
            if parsed.quantization and spec.quantization is None:
                try:
                    spec.quantization = v1.ModelQuantization(
                        parsed.quantization)
                except ValueError:
                    pass
            if repr(spec) == before:
                return  # nothing new parsed — avoid a no-op update event
            try:
                self.client.update(obj)
                return
            except ConflictError:
                continue
