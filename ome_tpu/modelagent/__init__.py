"""Node data plane: model staging agent (pkg/modelagent analog)."""

from .gopher import Gopher, GopherTask, TaskType
from .metrics import METRICS, Metrics
from .reconcilers import ConfigMapReconciler, NodeLabelReconciler
from .scout import Scout, node_matches_storage
