"""Node-side label + status-ConfigMap reconcilers.

Re-designs pkg/modelagent/node_label_reconciler.go (idempotent
models.ome.io/<kind>.<name>=<state> node labels consumed by the
controller's model-ready scheduling constraint) and
configmap_reconciler.go:90-560 (per-node ConfigMap in the operator
namespace feeding the BaseModel controller's aggregation).
"""

from __future__ import annotations

import json
import threading
from typing import Dict, Optional

from .. import constants
from ..controllers.basemodel import (MODEL_STATUS_CM_LABEL, model_key,
                                     node_status_cm_name)
from ..core.client import InMemoryClient
from ..core.errors import ConflictError, NotFoundError
from ..core.k8s import ConfigMap, Node
from ..core.meta import ObjectMeta


class NodeLabelReconciler:
    def __init__(self, client: InMemoryClient, node_name: str):
        self.client = client
        self.node_name = node_name

    def reconcile(self, model_kind: str, model_name: str,
                  state: Optional[str]) -> None:
        """Set (or clear, state=None) the model label on this node."""
        label = constants.model_ready_label(model_kind, model_name)
        for _ in range(4):  # retry on rv conflict
            node = self.client.try_get(Node, self.node_name)
            if node is None:
                return
            current = node.metadata.labels.get(label)
            if state is None:
                if current is None:
                    return
                node.metadata.labels.pop(label, None)
            else:
                if current == state:
                    return
                node.metadata.labels[label] = state
            try:
                self.client.update(node, bump_generation=False)
                return
            except ConflictError:
                continue


class ConfigMapReconciler:
    """Per-node model status ConfigMap with a write-through cache that
    survives agent restarts by re-reading the live object."""

    def __init__(self, client: InMemoryClient, node_name: str,
                 namespace: str = constants.OPERATOR_NAMESPACE):
        self.client = client
        self.node_name = node_name
        self.namespace = namespace
        self._lock = threading.Lock()
        self._cache: Optional[Dict[str, str]] = None

    @property
    def cm_name(self) -> str:
        return node_status_cm_name(self.node_name)

    def _load(self) -> Dict[str, str]:
        cm = self.client.try_get(ConfigMap, self.cm_name, self.namespace)
        return dict(cm.data) if cm is not None else {}

    def _flush(self, data: Dict[str, str]) -> None:
        for _ in range(4):
            cm = self.client.try_get(ConfigMap, self.cm_name,
                                     self.namespace)
            if cm is None:
                self.client.create(ConfigMap(
                    metadata=ObjectMeta(
                        name=self.cm_name, namespace=self.namespace,
                        labels={MODEL_STATUS_CM_LABEL: "true"}),
                    data=dict(data)))
                return
            cm.data = dict(data)
            try:
                self.client.update(cm)
                return
            except ConflictError:
                continue

    def set_status(self, model_kind: str, model_namespace: str,
                   model_name: str, state: str,
                   extra: Optional[Dict] = None) -> None:
        key = model_key(model_kind, model_namespace, model_name)
        entry = {"state": state, **(extra or {})}
        with self._lock:
            if self._cache is None:
                self._cache = self._load()
            self._cache[key] = json.dumps(entry, sort_keys=True)
            self._flush(self._cache)

    def remove(self, model_kind: str, model_namespace: str,
               model_name: str) -> None:
        key = model_key(model_kind, model_namespace, model_name)
        with self._lock:
            if self._cache is None:
                self._cache = self._load()
            if self._cache.pop(key, None) is not None:
                self._flush(self._cache)
