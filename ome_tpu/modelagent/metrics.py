"""Model-agent metrics (modelagent/metrics.go:50-160 analog): Prometheus
text-format counters/gauges without a client-library dependency."""

from __future__ import annotations

import threading
from typing import Dict

PREFIX = "model_agent"


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}

    def inc(self, name: str, amount: float = 1.0):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount

    def observe(self, name: str, value: float):
        with self._lock:
            self._gauges[name] = value

    def get(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, self._gauges.get(name, 0.0))

    def render(self) -> str:
        """Prometheus exposition format."""
        with self._lock:
            lines = []
            for k, v in sorted(self._counters.items()):
                lines.append(f"# TYPE {PREFIX}_{k} counter")
                lines.append(f"{PREFIX}_{k} {v}")
            for k, v in sorted(self._gauges.items()):
                lines.append(f"# TYPE {PREFIX}_{k} gauge")
                lines.append(f"{PREFIX}_{k} {v}")
            return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {**self._counters, **self._gauges}

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._gauges.clear()


METRICS = Metrics()
