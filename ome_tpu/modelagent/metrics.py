"""Model-agent metrics (modelagent/metrics.go:50-160 analog).

Now a thin shim over the shared telemetry registry
(ome_tpu/telemetry/) so the model-agent's exposition gets the same
`# HELP`/`# TYPE` correctness, `_total` counter enforcement, and
naming lint as the engine and router — while gopher/cmd callers keep
the original short-name `Metrics` API (`inc`/`observe`/`get`/
`render`/`snapshot`/`reset`).
"""

from __future__ import annotations

import threading
from typing import Dict

from ..telemetry import Counter, Gauge, Registry

PREFIX = "model_agent"


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()  # guards family creation/reset
        self._registry = Registry()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}

    @property
    def registry(self) -> Registry:
        return self._registry

    def inc(self, name: str, amount: float = 1.0):
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._registry.counter(f"{PREFIX}_{name}")
                self._counters[name] = c
        c.inc(amount)

    def observe(self, name: str, value: float):
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._registry.gauge(f"{PREFIX}_{name}")
                self._gauges[name] = g
        g.set(value)

    def get(self, name: str) -> float:
        with self._lock:
            fam = self._counters.get(name) or self._gauges.get(name)
        return fam.value if fam is not None else 0.0

    def render(self) -> str:
        """Prometheus exposition format (registry-backed)."""
        return self._registry.render()

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {name: fam.value
                    for d in (self._counters, self._gauges)
                    for name, fam in d.items()}

    def reset(self):
        # registries are append-only by design; reset (tests only)
        # swaps in a fresh one
        with self._lock:
            self._registry = Registry()
            self._counters.clear()
            self._gauges.clear()


METRICS = Metrics()
