"""Hardened weight plane: resumable, digest-verified, atomically
published model weight fetches (docs/model-fleet.md).

The failure contract, in download order:

  * a fetch only ever writes under ``<target>.staging/``; the serving
    path ``<target>`` appears in one ``os.rename`` after every object
    verified — a reader (an engine booting, the REUSE policy) never
    observes a partial tree at the serving path;
  * every verified object is recorded ``{name, size, sha256}`` in the
    staging manifest, which is fsynced before the next record, so a
    SIGKILL mid-download resumes from verified objects instead of
    restarting — resumed objects are re-hashed against the recorded
    digest, so a truncated or corrupted staged file is re-fetched,
    never trusted;
  * the manifest travels with the published tree with
    ``complete=true`` — that marker (not "directory is non-empty") is
    what ``DownloadPolicy.REUSE`` accepts as an existing download;
  * attempts are separated by jittered exponential backoff.

The manifest also accumulates fetch wall time and byte totals across
attempts; the published ``fetch_bps`` is what a serving engine
advertises on /ready so the router's cold-start Retry-After math uses
measured — not guessed — fetch throughput.

Fault points (docs/failure-semantics.md): ``weight_fetch`` (per
object, key=relative object name), ``weight_verify`` (key=relative
object name), ``model_publish`` (key=model name).
"""

from __future__ import annotations

import argparse
import concurrent.futures as cf
import json
import logging
import os
import shutil
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .. import faults
from ..storage.base import (ObjectInfo, ProgressFn, Storage, safe_join,
                            sha256_file)
from .metrics import METRICS

log = logging.getLogger("ome.modelagent.weightplane")

MANIFEST_NAME = ".ome_fetch_manifest.json"
MANIFEST_SCHEMA = 1

# Retry-After math falls back to this when a tree predates manifests
# (or was published by the HF path with hub-side timing unavailable).
DEFAULT_FETCH_BPS = 256e6


class WeightVerifyError(IOError):
    """A fetched object's size or digest does not match."""


class PublishError(IOError):
    """The staging -> serving rename failed; staging is left intact."""


def _fsync_path(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str):
    try:
        _fsync_path(path)
    except OSError:
        pass  # some filesystems refuse O_RDONLY fsync on dirs


def staging_dir(target: str) -> str:
    return target.rstrip("/") + ".staging"


@dataclass
class FetchManifest:
    """Per-object verification ledger for one model tree.

    Lives at ``<staging>/.ome_fetch_manifest.json`` during a fetch and
    is published with the tree. ``objects`` maps relative object name
    to ``{"size": int, "sha256": hex}``; a name is only present after
    its bytes were hashed and the staged file fsynced, so every record
    can be trusted across a SIGKILL.
    """

    objects: Dict[str, Dict] = field(default_factory=dict)
    complete: bool = False
    total_bytes: int = 0
    fetch_seconds: float = 0.0
    attempts: int = 0

    @classmethod
    def load(cls, tree: str) -> Optional["FetchManifest"]:
        path = os.path.join(tree, MANIFEST_NAME)
        try:
            with open(path, "r", encoding="utf-8") as f:
                raw = json.load(f)
        except (OSError, ValueError):
            return None
        if raw.get("schema_version") != MANIFEST_SCHEMA:
            return None
        return cls(objects=dict(raw.get("objects", {})),
                   complete=bool(raw.get("complete", False)),
                   total_bytes=int(raw.get("total_bytes", 0)),
                   fetch_seconds=float(raw.get("fetch_seconds", 0.0)),
                   attempts=int(raw.get("attempts", 0)))

    def save(self, tree: str):
        """Atomic + durable: tmp file, fsync, rename, fsync dir."""
        path = os.path.join(tree, MANIFEST_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"schema_version": MANIFEST_SCHEMA,
                       "complete": self.complete,
                       "total_bytes": self.total_bytes,
                       "fetch_seconds": self.fetch_seconds,
                       "attempts": self.attempts,
                       "objects": self.objects}, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(tree)

    def record(self, name: str, size: int, sha256: str):
        self.objects[name] = {"size": size, "sha256": sha256}

    def verified(self, name: str, size: int) -> bool:
        rec = self.objects.get(name)
        return rec is not None and rec.get("size") == size

    def fetch_bps(self) -> float:
        if self.total_bytes and self.fetch_seconds > 0:
            return self.total_bytes / self.fetch_seconds
        return 0.0


def is_published(target: str) -> bool:
    """True only for a tree the weight plane published complete — the
    REUSE completeness check (a non-empty directory is NOT enough:
    that is exactly the partial tree a killed download leaves)."""
    if not os.path.isdir(target):
        return False
    m = FetchManifest.load(target)
    return m is not None and m.complete


def published_manifest(target: str) -> Optional[FetchManifest]:
    m = FetchManifest.load(target)
    return m if m is not None and m.complete else None


def published_fetch_bps(target: str) -> float:
    """Measured fetch throughput of a published tree (0 if unknown)."""
    m = published_manifest(target)
    return m.fetch_bps() if m is not None else 0.0


def backoff_delay(attempt: int, rng, base: float = 0.5,
                  cap: float = 30.0) -> float:
    """Jittered exponential backoff: full jitter over [base/2, d] with
    d = min(cap, base * 2^attempt)."""
    d = min(cap, base * (2.0 ** attempt))
    lo = min(base / 2.0, d)
    return lo + (d - lo) * rng.random()


# Family suffixes + help live in module dicts and declarations go
# through the ``f"ome_modelagent_{key}"`` idiom so the catalog-drift
# lint can statically extract every name against observability.md.
_COUNTER_HELP = {
    "fetch_attempts_total":
        "weight-plane fetch attempts (one per try, not per object)",
    "fetch_retries_total":
        "fetch attempts after the first (backoff-separated)",
    "objects_verified_total":
        "objects fetched, hashed and recorded in the fetch manifest",
    "objects_resumed_total":
        "objects skipped on resume because their staged bytes "
        "matched the manifest digest",
    "verify_failures_total":
        "weight-plane attempts that failed fetching or verifying an "
        "object",
    "fetch_bytes_total":
        "bytes fetched and verified by the weight plane",
    "publishes_total":
        "complete model trees atomically promoted to the serving "
        "path",
}
_GAUGE_HELP = {
    "fetch_throughput_bps":
        "measured fetch throughput of the last completed fetch "
        "(bytes/second, manifest-accumulated)",
}


def declare_families():
    """Register every weight-plane family (idempotent) so /metrics
    exposes them before first use."""
    reg = METRICS.registry
    for _ckey in _COUNTER_HELP:
        reg.counter(f"ome_modelagent_{_ckey}",
                    help=_COUNTER_HELP[_ckey])
    for _gkey in _GAUGE_HELP:
        reg.gauge(f"ome_modelagent_{_gkey}", help=_GAUGE_HELP[_gkey])


def _counter(key: str):
    # METRICS.reset() (tests) swaps registries — resolve the family
    # against the CURRENT registry per call, never cache it.
    return METRICS.registry.counter("ome_modelagent_" + key,
                                    help=_COUNTER_HELP[key])


def _gauge(key: str):
    return METRICS.registry.gauge("ome_modelagent_" + key,
                                  help=_GAUGE_HELP[key])


def _rel_name(o: ObjectInfo, prefix: str) -> str:
    return o.name[len(prefix):].lstrip("/") if prefix else o.name


def fetch_tree(storage: Storage, prefix: str,
               expected: List[ObjectInfo], target: str, *,
               workers: int = 4,
               progress: Optional[ProgressFn] = None,
               clock: Callable[[], float] = time.monotonic) -> Dict:
    """One fetch attempt into ``staging_dir(target)``.

    Objects already recorded in the staging manifest are re-hashed and
    skipped when intact; the rest are fetched in parallel, hashed,
    fsynced, and recorded one at a time (a single writer folds worker
    results into the manifest, so a crash never loses more than the
    in-flight objects). Raises on the first failed object after
    letting already-completed workers be recorded. Does NOT publish.
    """
    staging = staging_dir(target)
    os.makedirs(staging, exist_ok=True)
    manifest = FetchManifest.load(staging) or FetchManifest()
    manifest.attempts += 1
    manifest.complete = False
    _counter("fetch_attempts_total").inc()

    todo: List[ObjectInfo] = []
    resumed = 0
    for o in expected:
        rel = _rel_name(o, prefix)
        dst = safe_join(staging, rel)
        if manifest.verified(rel, o.size) and os.path.exists(dst) \
                and os.path.getsize(dst) == o.size \
                and sha256_file(dst) == manifest.objects[rel]["sha256"]:
            resumed += 1
            if progress:
                progress(o.name, o.size, o.size)
            continue
        todo.append(o)
    if resumed:
        _counter("objects_resumed_total").inc(resumed)

    t0 = clock()

    def fetch_one(o: ObjectInfo):
        rel = _rel_name(o, prefix)
        dst = safe_join(staging, rel)
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        faults.fire("weight_fetch", key=rel)
        part = dst + ".part"
        storage.get_to_file(o.name, part, progress=progress,
                            total=o.size, etag=o.etag)
        got = os.path.getsize(part)
        digest = sha256_file(part)
        faults.fire("weight_verify", key=rel,
                    exc=WeightVerifyError)
        if o.size and got != o.size:
            os.unlink(part)  # a ranged resume must not trust it
            raise WeightVerifyError(
                f"{rel}: size {got} != expected {o.size}")
        os.replace(part, dst)
        _fsync_path(dst)
        return rel, got, digest

    fetched = 0
    first_err: Optional[BaseException] = None
    if todo:
        with cf.ThreadPoolExecutor(max_workers=workers) as ex:
            futs = [ex.submit(fetch_one, o) for o in todo]
            for fut in cf.as_completed(futs):
                try:
                    rel, size, digest = fut.result()
                except BaseException as e:  # noqa: BLE001 — record, then re-raise
                    if first_err is None:
                        first_err = e
                        for other in futs:
                            other.cancel()
                    continue
                manifest.record(rel, size, digest)
                manifest.save(staging)
                fetched += 1
                _counter("objects_verified_total").inc()
                _counter("fetch_bytes_total").inc(size)
    manifest.fetch_seconds += max(0.0, clock() - t0)
    manifest.save(staging)
    if first_err is not None:
        _counter("verify_failures_total").inc()
        raise first_err

    manifest.total_bytes = sum(o.size for o in expected)
    manifest.save(staging)
    bps = manifest.fetch_bps()
    if bps:
        _gauge("fetch_throughput_bps").set(bps)
    return {"fetched": fetched, "resumed": resumed,
            "bytes": manifest.total_bytes,
            "seconds": manifest.fetch_seconds, "bps": bps}


def seal_tree(staging: str, *,
              fetch_seconds: float = 0.0) -> FetchManifest:
    """Build a complete manifest over an already-materialized staging
    tree (the HF hub path downloads via its own resumable client, so
    the weight plane hashes the result rather than the transfer)."""
    manifest = FetchManifest.load(staging) or FetchManifest()
    total = 0
    for root, _, files in os.walk(staging):
        for fn in files:
            if fn == MANIFEST_NAME or fn.endswith(".part") \
                    or fn.endswith(".tmp"):
                continue
            p = os.path.join(root, fn)
            rel = os.path.relpath(p, staging)
            size = os.path.getsize(p)
            faults.fire("weight_verify", key=rel,
                        exc=WeightVerifyError)
            manifest.record(rel, size, sha256_file(p))
            total += size
    manifest.total_bytes = total
    if fetch_seconds:
        manifest.fetch_seconds += fetch_seconds
    manifest.save(staging)
    return manifest


def publish(target: str, *, name: str = "") -> None:
    """Atomically promote ``staging_dir(target)`` to ``target``.

    Marks the staging manifest complete (fsynced), then renames the
    whole tree into place — the only write the serving path ever
    sees. A pre-existing tree at ``target`` (a partial left by code
    that predates the weight plane) is moved aside first and deleted
    only after the rename lands.
    """
    staging = staging_dir(target)
    manifest = FetchManifest.load(staging)
    if manifest is None or not manifest.objects:
        raise PublishError(f"{staging}: no verified manifest to publish")
    faults.fire("model_publish", key=name or os.path.basename(target),
                exc=PublishError)
    manifest.complete = True
    manifest.save(staging)
    trash = target.rstrip("/") + ".trash"
    if os.path.isdir(trash):
        shutil.rmtree(trash, ignore_errors=True)
    if os.path.exists(target):
        os.rename(target, trash)
    try:
        os.rename(staging, target)
    except OSError:
        # roll the old tree back so the serving path is never empty
        if os.path.isdir(trash) and not os.path.exists(target):
            os.rename(trash, target)
        raise
    _fsync_dir(os.path.dirname(os.path.abspath(target)) or ".")
    if os.path.isdir(trash):
        shutil.rmtree(trash, ignore_errors=True)
    _counter("publishes_total").inc()


def fetch_and_publish(storage: Storage, prefix: str,
                      expected: List[ObjectInfo], target: str, *,
                      name: str = "", workers: int = 4,
                      retries: int = 1, rng=None,
                      sleep: Callable[[float], None] = time.sleep,
                      progress: Optional[ProgressFn] = None,
                      clock: Callable[[], float] = time.monotonic
                      ) -> Dict:
    """Fetch + verify + publish with jittered backoff between
    attempts. Returns the last attempt's stats dict with
    ``published=True``."""
    import random
    rng = rng or random.Random()
    last: Optional[Exception] = None
    for attempt in range(max(1, retries)):
        if attempt:
            _counter("fetch_retries_total").inc()
            sleep(backoff_delay(attempt - 1, rng))
        try:
            stats = fetch_tree(storage, prefix, expected, target,
                               workers=workers, progress=progress,
                               clock=clock)
            publish(target, name=name)
            stats["published"] = True
            return stats
        except Exception as e:  # noqa: BLE001 — every attempt may retry
            last = e
            log.warning("fetch attempt %d/%d for %s failed: %s",
                        attempt + 1, max(1, retries), target, e)
    raise last  # type: ignore[misc]


def main(argv=None) -> int:
    """Subprocess entrypoint for the chaos harness: fetch a storage
    URI into a target dir and print one JSON stats line. The harness
    SIGKILLs this process mid-download and asserts the serving path
    never holds a partial tree, then re-runs it to observe resume."""
    from ..storage.providers import open_storage
    from ..storage.uri import parse_storage_uri

    p = argparse.ArgumentParser(
        prog="weightplane",
        description="hardened model weight fetch (chaos/soak entry)")
    p.add_argument("--source", required=True,
                   help="storage uri, e.g. local:///path")
    p.add_argument("--target", required=True)
    p.add_argument("--name", default="model")
    p.add_argument("--retries", type=int, default=1)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--faults", default="",
                   help="fault spec (faults.py grammar)")
    args = p.parse_args(argv)
    if args.faults:
        faults.install(args.faults)
    comps = parse_storage_uri(args.source)
    storage = open_storage(comps, {})
    expected = storage.list(comps.prefix)
    if not expected:
        print(json.dumps({"error": "no objects"}))
        return 2
    try:
        stats = fetch_and_publish(storage, comps.prefix, expected,
                                  args.target, name=args.name,
                                  workers=args.workers,
                                  retries=args.retries)
    except Exception as e:  # noqa: BLE001 — report, nonzero exit
        print(json.dumps({"error": str(e)[:500], "published": False}))
        return 1
    print(json.dumps(stats))
    return 0


if __name__ == "__main__":  # pragma: no cover — subprocess entry
    sys.exit(main())
