"""Lease-based leader election (client-go leaderelection equivalent).

The reference manager runs with leader election on a coordination/v1
Lease (cmd/manager/main.go:181-196, `LeaderElection: true`). Same
protocol here: acquire the Lease if unheld or expired, renew on an
interval, yield (and call on_stopped_leading) if a renewal fails past
the deadline. Works against either client substrate.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from datetime import datetime, timedelta, timezone
from typing import Callable, Optional

from .errors import AlreadyExistsError, ConflictError, NotFoundError
from .k8s import Lease, LeaseSpec
from .meta import ObjectMeta

log = logging.getLogger("ome.leaderelect")

_FMT = "%Y-%m-%dT%H:%M:%SZ"


def _now() -> datetime:
    return datetime.now(timezone.utc)


def _stamp(t: datetime) -> str:
    return t.strftime(_FMT)


def _parse(s: Optional[str]) -> Optional[datetime]:
    if not s:
        return None
    return datetime.strptime(s, _FMT).replace(tzinfo=timezone.utc)


class LeaderElector:
    def __init__(self, client, lease_name: str = "ome-manager-leader",
                 namespace: str = "ome",
                 identity: Optional[str] = None,
                 lease_duration: float = 15.0,
                 renew_interval: float = 5.0,
                 on_started_leading: Optional[Callable[[], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None):
        self.client = client
        self.lease_name = lease_name
        self.namespace = namespace
        self.identity = identity or f"ome-{uuid.uuid4().hex[:8]}"
        self.lease_duration = lease_duration
        self.renew_interval = renew_interval
        self.on_started_leading = on_started_leading or (lambda: None)
        self.on_stopped_leading = on_stopped_leading or (lambda: None)
        self.is_leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one protocol step ---------------------------------------------

    def try_acquire_or_renew(self) -> bool:
        now = _now()
        try:
            lease = self.client.get(Lease, self.lease_name, self.namespace)
        except NotFoundError:
            lease = Lease(
                metadata=ObjectMeta(name=self.lease_name,
                                    namespace=self.namespace),
                spec=LeaseSpec(
                    holder_identity=self.identity,
                    lease_duration_seconds=int(self.lease_duration),
                    acquire_time=_stamp(now), renew_time=_stamp(now),
                    lease_transitions=0))
            try:
                self.client.create(lease)
                return True
            except AlreadyExistsError:
                return False

        held_by_us = lease.spec.holder_identity == self.identity
        renew = _parse(lease.spec.renew_time)
        expired = renew is None or now - renew > timedelta(
            seconds=lease.spec.lease_duration_seconds
            or self.lease_duration)
        if not held_by_us and not expired:
            return False
        if not held_by_us:
            lease.spec.holder_identity = self.identity
            lease.spec.acquire_time = _stamp(now)
            lease.spec.lease_transitions = \
                (lease.spec.lease_transitions or 0) + 1
        lease.spec.renew_time = _stamp(now)
        lease.spec.lease_duration_seconds = int(self.lease_duration)
        try:
            self.client.update(lease)
            return True
        except (ConflictError, NotFoundError):
            return False

    # -- run loop ------------------------------------------------------

    def run(self):
        """Block until leadership is acquired, then keep renewing until
        stop() or a lost lease (on_stopped_leading fires, loop exits)."""
        while not self._stop.is_set():
            if self.try_acquire_or_renew():
                break
            if self._stop.wait(self.renew_interval):
                return
        if self._stop.is_set():
            return
        self.is_leader = True
        log.info("acquired leadership as %s", self.identity)
        self.on_started_leading()
        last_renew = time.monotonic()
        while not self._stop.wait(self.renew_interval):
            if self.try_acquire_or_renew():
                last_renew = time.monotonic()
            elif time.monotonic() - last_renew > self.lease_duration:
                log.warning("lost leadership (%s)", self.identity)
                break
        self.is_leader = False
        self.on_stopped_leading()

    def start(self) -> "LeaderElector":
        self._thread = threading.Thread(target=self.run,
                                        name="leader-elect", daemon=True)
        self._thread.start()
        return self

    def stop(self, release: bool = True):
        was_leader = self.is_leader  # run() clears it on the way out
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if release and was_leader:
            try:
                lease = self.client.get(Lease, self.lease_name,
                                        self.namespace)
                if lease.spec.holder_identity == self.identity:
                    lease.spec.holder_identity = None
                    self.client.update(lease)
            except Exception:
                pass
            self.is_leader = False
