"""In-process fake kube-apiserver (envtest equivalent).

The reference boots a real kube-apiserver binary via envtest for its
webhook/controller integration suites (pkg/testing/envtest_setup.go:
22-45). This repo's equivalent is an HTTP facade over the
InMemoryClient: the same REST paths, JSON bodies, status codes,
optimistic-concurrency conflicts, status subresource, label selectors
and chunked watch streams KubeClient speaks against a real cluster —
so KubeClient + controllers can be integration-tested end-to-end over
real HTTP with no cluster.
"""

from __future__ import annotations

import json
import queue
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple, Type
from urllib.parse import parse_qs, urlparse

from .client import Event, InMemoryClient
from .errors import AlreadyExistsError, ConflictError, NotFoundError
from .kubeclient import kind_registry
from .meta import Resource, plural_of


class FakeKubeApiServer:
    def __init__(self, client: Optional[InMemoryClient] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.client = client or InMemoryClient()
        self._registry = kind_registry()
        # (group-or-core, plural) -> class
        self._routes: Dict[Tuple[str, str], Type[Resource]] = {}
        for cls in self._registry.values():
            api_version = cls.API_VERSION
            group = api_version.split("/")[0] if "/" in api_version else ""
            self._routes[(group, plural_of(cls))] = cls
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, code: int, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _status_err(self, code: int, reason: str, message: str):
                self._json(code, {"kind": "Status", "apiVersion": "v1",
                                  "status": "Failure", "reason": reason,
                                  "code": code, "message": message})

            def _route(self):
                """Parse path -> (cls, namespace, name, subresource)."""
                parts = [p for p in urlparse(self.path).path.split("/")
                         if p]
                # /api/v1/... or /apis/{group}/{version}/...
                if not parts:
                    return None
                if parts[0] == "api" and len(parts) >= 2:
                    group, rest = "", parts[2:]
                elif parts[0] == "apis" and len(parts) >= 3:
                    group, rest = parts[1], parts[3:]
                else:
                    return None
                ns = ""
                if len(rest) >= 2 and rest[0] == "namespaces":
                    ns, rest = rest[1], rest[2:]
                if not rest:
                    return None
                plural, rest = rest[0], rest[1:]
                cls = outer._routes.get((group, plural))
                if cls is None:
                    return None
                name = rest[0] if rest else ""
                sub = rest[1] if len(rest) > 1 else ""
                return cls, ns, name, sub

            def _body(self):
                n = int(self.headers.get("Content-Length") or 0)
                return json.loads(self.rfile.read(n)) if n else None

            def do_GET(self):
                if urlparse(self.path).path == "/healthz":
                    return self._json(200, {"status": "ok"})
                r = self._route()
                if r is None:
                    return self._status_err(404, "NotFound", self.path)
                cls, ns, name, _sub = r
                q = parse_qs(urlparse(self.path).query)
                if name:
                    try:
                        obj = outer.client.get(cls, name, ns)
                    except NotFoundError as e:
                        return self._status_err(404, "NotFound", str(e))
                    return self._json(200, obj.to_dict())
                if q.get("watch", ["false"])[0] == "true":
                    return self._watch(cls, ns, q)
                selector = None
                if q.get("labelSelector"):
                    selector = dict(
                        kv.split("=", 1)
                        for kv in q["labelSelector"][0].split(","))
                items = outer.client.list(
                    cls, namespace=ns or None, label_selector=selector)
                self._json(200, {
                    "kind": f"{cls.KIND}List",
                    "apiVersion": cls.API_VERSION,
                    "metadata": {
                        "resourceVersion": str(outer.client._rv)},
                    "items": [o.to_dict() for o in items]})

            def _watch(self, cls, ns, q):
                events: "queue.Queue[Optional[Event]]" = queue.Queue()
                since = int(q.get("resourceVersion", ["0"])[0] or 0)

                def on_event(ev: Event):
                    if type(ev.obj).KIND != cls.KIND:
                        return
                    if ns and cls.NAMESPACED \
                            and ev.obj.metadata.namespace != ns:
                        return
                    if int(ev.obj.metadata.resource_version or 0) <= since:
                        return
                    events.put(ev)

                cancel = outer.client.watch(on_event)
                # replay the current state newer than `since` AFTER
                # subscribing: a real apiserver replays history from the
                # given resourceVersion, so events landing between the
                # client's list and this stream opening must not be lost
                # (duplicates are fine — controllers are level-triggered)
                for obj in outer.client.list(cls, namespace=ns or None):
                    if int(obj.metadata.resource_version or 0) > since:
                        events.put(Event("Modified", obj))
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    while not outer._stopping.is_set():
                        try:
                            ev = events.get(timeout=0.2)
                        except queue.Empty:
                            continue
                        line = json.dumps({
                            "type": {"Added": "ADDED",
                                     "Modified": "MODIFIED",
                                     "Deleted": "DELETED"}[ev.type],
                            "object": ev.obj.to_dict()}).encode() + b"\n"
                        self.wfile.write(
                            f"{len(line):x}\r\n".encode() + line + b"\r\n")
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass
                finally:
                    cancel()

            def do_POST(self):
                path = urlparse(self.path).path
                m = re.fullmatch(
                    r"/api/v1/namespaces/([^/]+)/events", path)
                if m:  # corev1 Events sink (best-effort recorder)
                    data = self._body()
                    with outer.client._lock:
                        outer.client._recorded_events.append(data)
                    return self._json(201, data)
                r = self._route()
                if r is None:
                    return self._status_err(404, "NotFound", self.path)
                cls, ns, _name, _sub = r
                data = self._body()
                try:
                    obj = cls.from_dict(data)
                    if ns:
                        obj.metadata.namespace = ns
                    created = outer.client.create(obj)
                except AlreadyExistsError as e:
                    return self._status_err(409, "AlreadyExists", str(e))
                self._json(201, created.to_dict())

            def do_PUT(self):
                r = self._route()
                if r is None:
                    return self._status_err(404, "NotFound", self.path)
                cls, ns, name, sub = r
                obj = cls.from_dict(self._body())
                if ns:
                    obj.metadata.namespace = ns
                obj.metadata.name = obj.metadata.name or name
                try:
                    if sub == "status":
                        updated = outer.client.update_status(obj)
                    else:
                        updated = outer.client.update(obj)
                except NotFoundError as e:
                    return self._status_err(404, "NotFound", str(e))
                except ConflictError as e:
                    return self._status_err(409, "Conflict", str(e))
                self._json(200, updated.to_dict())

            def do_DELETE(self):
                r = self._route()
                if r is None:
                    return self._status_err(404, "NotFound", self.path)
                cls, ns, name, _sub = r
                try:
                    outer.client.delete(cls, name, ns)
                except NotFoundError as e:
                    return self._status_err(404, "NotFound", str(e))
                self._json(200, {"kind": "Status", "status": "Success"})

        self._stopping = threading.Event()
        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="fake-apiserver", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stopping.set()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
