"""Object metadata and condition types (apimachinery equivalents).

Mirrors the subset of k8s.io/apimachinery used by the reference operator:
ObjectMeta (labels/annotations/ownerRefs/finalizers/resourceVersion),
Knative-style Conditions used throughout InferenceServiceStatus
(/root/reference/pkg/apis/ome/v1beta1/inference_service_status.go).
"""

from __future__ import annotations

import dataclasses
import datetime
from dataclasses import dataclass, field
from typing import ClassVar, Dict, List, Optional

from . import serde


def now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: Optional[bool] = None
    block_owner_deletion: Optional[bool] = None


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    uid: str = ""
    resource_version: str = ""
    generation: int = 0
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    owner_references: List[OwnerReference] = field(default_factory=list)
    finalizers: List[str] = field(default_factory=list)
    creation_timestamp: Optional[str] = None
    deletion_timestamp: Optional[str] = None


@dataclass
class Condition:
    """Knative-ish condition (type/status/reason/message/severity)."""

    type: str = ""
    status: str = "Unknown"  # True | False | Unknown
    reason: Optional[str] = None
    message: Optional[str] = None
    severity: Optional[str] = None
    last_transition_time: Optional[str] = None

    def is_true(self) -> bool:
        return self.status == "True"


def set_condition(conditions: List[Condition], cond: Condition) -> List[Condition]:
    """Upsert a condition by type, bumping lastTransitionTime on status change."""
    out = []
    replaced = False
    for c in conditions:
        if c.type == cond.type:
            if cond.last_transition_time is None:
                # preserve the transition time while status is stable
                cond.last_transition_time = (c.last_transition_time
                                             if c.status == cond.status
                                             else now())
            out.append(cond)
            replaced = True
        else:
            out.append(c)
    if not replaced:
        if cond.last_transition_time is None:
            cond.last_transition_time = now()
        out.append(cond)
    return out


def get_condition(conditions: List[Condition], ctype: str) -> Optional[Condition]:
    for c in conditions:
        if c.type == ctype:
            return c
    return None


@dataclass
class Resource:
    """Base for all API objects. Subclasses set KIND / API_VERSION /
    NAMESPACED class vars and declare `spec` / `status` dataclass fields."""

    KIND: ClassVar[str] = ""
    API_VERSION: ClassVar[str] = "ome.io/v1"
    NAMESPACED: ClassVar[bool] = True
    PLURAL: ClassVar[str] = ""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def key(self) -> str:
        if type(self).NAMESPACED:
            return f"{self.metadata.namespace}/{self.metadata.name}"
        return self.metadata.name

    def deepcopy(self):
        return serde.deepcopy_resource(self)

    def to_dict(self) -> dict:
        d = {"apiVersion": type(self).API_VERSION, "kind": type(self).KIND}
        d.update(serde.to_dict(self))
        return d

    @classmethod
    def from_dict(cls, data: dict):
        data = dict(data)
        data.pop("apiVersion", None)
        data.pop("kind", None)
        return serde.from_dict(cls, data)


def plural_of(cls) -> str:
    return cls.PLURAL or cls.KIND.lower() + "s"
