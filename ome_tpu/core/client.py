"""In-memory API store + client.

Plays two roles, mirroring how the reference tests and runs:
  * the `fake.NewClientBuilder` fake client used across the reference's
    controller suites (SURVEY.md §4) — our controller tests run against it;
  * a standalone "API server" for running the whole control plane without
    a kube cluster (watch streams, resourceVersion conflicts, finalizer
    semantics, owner-reference garbage collection).
"""

from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Tuple, Type

from .errors import ConflictError, AlreadyExistsError, NotFoundError
from .meta import Resource, now


@dataclass
class Event:
    type: str  # Added | Modified | Deleted
    obj: Resource


class InMemoryClient:
    """Thread-safe typed object store with watch support."""

    def __init__(self, initial: Iterable[Resource] = ()):  # noqa: D401
        self._lock = threading.RLock()
        self._store: Dict[Tuple[str, str, str], Resource] = {}
        self._rv = 0
        self._watchers: List[Callable[[Event], None]] = []
        self._recorded_events: List[dict] = []  # EventRecorder sink
        for obj in initial:
            self.create(obj.deepcopy())

    # -- helpers -------------------------------------------------------

    def _key(self, cls: Type[Resource], namespace: str, name: str):
        return (cls.KIND, namespace if cls.NAMESPACED else "", name)

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _notify(self, ev: Event):
        for w in list(self._watchers):
            w(ev)

    # -- CRUD ----------------------------------------------------------

    def create(self, obj: Resource) -> Resource:
        with self._lock:
            k = self._key(type(obj), obj.metadata.namespace, obj.metadata.name)
            if k in self._store:
                raise AlreadyExistsError(f"{type(obj).KIND} {obj.key()} already exists")
            obj = obj.deepcopy()
            obj.metadata.uid = obj.metadata.uid or str(uuid.uuid4())
            obj.metadata.resource_version = self._next_rv()
            obj.metadata.creation_timestamp = obj.metadata.creation_timestamp or now()
            obj.metadata.generation = 1
            self._store[k] = obj
            self._notify(Event("Added", obj.deepcopy()))
            return obj.deepcopy()

    def get(self, cls: Type[Resource], name: str, namespace: str = "") -> Resource:
        with self._lock:
            k = self._key(cls, namespace, name)
            if k not in self._store:
                raise NotFoundError(f"{cls.KIND} {namespace}/{name} not found")
            return self._store[k].deepcopy()

    def try_get(self, cls: Type[Resource], name: str, namespace: str = "") -> Optional[Resource]:
        try:
            return self.get(cls, name, namespace)
        except NotFoundError:
            return None

    def list(self, cls: Type[Resource], namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None) -> List[Resource]:
        with self._lock:
            out = []
            for (kind, ns, _), obj in self._store.items():
                if kind != cls.KIND:
                    continue
                if namespace is not None and cls.NAMESPACED and ns != namespace:
                    continue
                if label_selector and any(
                        obj.metadata.labels.get(k) != v for k, v in label_selector.items()):
                    continue
                out.append(obj.deepcopy())
            out.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
            return out

    def update(self, obj: Resource, bump_generation: bool = True) -> Resource:
        with self._lock:
            k = self._key(type(obj), obj.metadata.namespace, obj.metadata.name)
            cur = self._store.get(k)
            if cur is None:
                raise NotFoundError(f"{type(obj).KIND} {obj.key()} not found")
            if (obj.metadata.resource_version
                    and obj.metadata.resource_version != cur.metadata.resource_version):
                raise ConflictError(
                    f"{type(obj).KIND} {obj.key()}: resourceVersion conflict "
                    f"({obj.metadata.resource_version} != {cur.metadata.resource_version})")
            obj = obj.deepcopy()
            obj.metadata.uid = cur.metadata.uid
            obj.metadata.creation_timestamp = cur.metadata.creation_timestamp
            obj.metadata.resource_version = self._next_rv()
            if bump_generation:
                obj.metadata.generation = cur.metadata.generation + 1
            else:
                obj.metadata.generation = cur.metadata.generation
            self._store[k] = obj
            self._notify(Event("Modified", obj.deepcopy()))
            # finalizer-aware delete completion
            if obj.metadata.deletion_timestamp and not obj.metadata.finalizers:
                self._finish_delete(k, obj)
            return obj.deepcopy()

    def update_status(self, obj: Resource) -> Resource:
        """Status().Update() equivalent — does not bump generation."""
        return self.update(obj, bump_generation=False)

    def delete(self, obj_or_cls, name: str = None, namespace: str = "") -> None:
        with self._lock:
            if isinstance(obj_or_cls, Resource):
                cls, name, namespace = type(obj_or_cls), obj_or_cls.metadata.name, obj_or_cls.metadata.namespace
            else:
                cls = obj_or_cls
            k = self._key(cls, namespace, name)
            cur = self._store.get(k)
            if cur is None:
                raise NotFoundError(f"{cls.KIND} {namespace}/{name} not found")
            if cur.metadata.finalizers:
                if not cur.metadata.deletion_timestamp:
                    cur.metadata.deletion_timestamp = now()
                    cur.metadata.resource_version = self._next_rv()
                    self._notify(Event("Modified", cur.deepcopy()))
                return
            self._finish_delete(k, cur)

    def _finish_delete(self, k, cur: Resource):
        self._store.pop(k, None)
        self._notify(Event("Deleted", cur.deepcopy()))
        self._garbage_collect(cur)

    def _garbage_collect(self, owner: Resource):
        """k8s-style GC: drop the dead owner's references; an object is
        cascade-deleted only once its last owner reference is gone."""
        doomed = []
        for key, obj in list(self._store.items()):
            refs = obj.metadata.owner_references
            remaining = [r for r in refs if r.uid != owner.metadata.uid]
            if len(remaining) == len(refs):
                continue
            if remaining:
                obj.metadata.owner_references = remaining
                obj.metadata.resource_version = self._next_rv()
                self._notify(Event("Modified", obj.deepcopy()))
            else:
                doomed.append((key, obj))
        for key, obj in doomed:
            obj.metadata.finalizers = []
            self._finish_delete(key, obj)

    # -- watch ---------------------------------------------------------

    def watch(self, handler: Callable[[Event], None]) -> Callable[[], None]:
        with self._lock:
            self._watchers.append(handler)
            def cancel():
                with self._lock:
                    if handler in self._watchers:
                        self._watchers.remove(handler)
            return cancel

    # -- event recorder (corev1 Events) --------------------------------

    def record_event(self, obj: Resource, event_type: str, reason: str, message: str):
        with self._lock:
            self._recorded_events.append({
                "involvedObject": f"{type(obj).KIND}/{obj.key()}",
                "type": event_type, "reason": reason, "message": message,
                "timestamp": now(),
            })

    @property
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._recorded_events)


def set_controller_reference(owner: Resource, controlled: Resource):
    """controllerutil.SetControllerReference equivalent."""
    from .meta import OwnerReference
    for ref in controlled.metadata.owner_references:
        if ref.uid == owner.metadata.uid:
            return
    controlled.metadata.owner_references.append(OwnerReference(
        api_version=type(owner).API_VERSION, kind=type(owner).KIND,
        name=owner.metadata.name, uid=owner.metadata.uid,
        controller=True, block_owner_deletion=True))
