"""Real Kubernetes API client (stdlib HTTP, no client SDK).

The drop-in second implementation of the client interface the whole
control plane codes against (the first is core/client.py's
InMemoryClient, the fake-client test substrate). Mirrors what
controller-runtime gives the reference manager (cmd/manager/
main.go:145-368):

  * typed CRUD against kube-apiserver REST paths (core /api/v1,
    group /apis/{group}/{version}), status subresource updates,
    events POSTed as corev1 Events;
  * list+watch per kind with resourceVersion resume: each watch
    thread relists on 410 Gone and reconnects from the last seen
    resourceVersion otherwise (the informer contract reconcilers
    rely on);
  * optimistic-concurrency conflicts surface as the same
    ConflictError the in-memory client raises, so the Reconciler
    retry machinery is substrate-agnostic;
  * auth from a kubeconfig file (token / client cert) or the
    in-cluster service account (token + CA at
    /var/run/secrets/kubernetes.io/serviceaccount).

Kinds are resolved through a registry built from the repo's Resource
dataclasses — the serde layer produces/consumes exactly the JSON the
apiserver speaks.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import ssl
import tempfile
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Dict, Iterable, List, Optional, Type

from .client import Event
from .errors import AlreadyExistsError, ConflictError, NotFoundError
from .meta import Resource, now, plural_of

log = logging.getLogger("ome.kubeclient")


def kind_registry() -> Dict[str, Type[Resource]]:
    """kind name -> dataclass, over every Resource type in the repo."""
    from ..apis import v1 as _v1
    from . import k8s as _k8s
    reg: Dict[str, Type[Resource]] = {}
    for mod in (_k8s, _v1):
        for attr in vars(mod).values():
            if isinstance(attr, type) and issubclass(attr, Resource) \
                    and attr is not Resource and attr.KIND:
                reg[attr.KIND] = attr
    return reg


def rest_path(cls: Type[Resource], namespace: str = "",
              name: str = "") -> str:
    """REST collection/object path for a kind."""
    api_version = cls.API_VERSION
    if "/" in api_version:
        base = f"/apis/{api_version}"
    else:
        base = f"/api/{api_version}"
    plural = plural_of(cls)
    if cls.NAMESPACED and namespace:
        path = f"{base}/namespaces/{namespace}/{plural}"
    else:
        path = f"{base}/{plural}"
    if name:
        path += f"/{name}"
    return path


class KubeConfig:
    """Connection settings: server URL + TLS + auth header."""

    def __init__(self, server: str, token: Optional[str] = None,
                 ca_file: Optional[str] = None,
                 client_cert_file: Optional[str] = None,
                 client_key_file: Optional[str] = None,
                 insecure_skip_verify: bool = False):
        self.server = server.rstrip("/")
        self.token = token
        self.ca_file = ca_file
        self.client_cert_file = client_cert_file
        self.client_key_file = client_key_file
        self.insecure_skip_verify = insecure_skip_verify

    # -- loaders -------------------------------------------------------

    @classmethod
    def in_cluster(cls) -> "KubeConfig":
        sa = "/var/run/secrets/kubernetes.io/serviceaccount"
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(os.path.join(sa, "token")) as f:
            token = f.read().strip()
        return cls(server=f"https://{host}:{port}", token=token,
                   ca_file=os.path.join(sa, "ca.crt"))

    @classmethod
    def from_kubeconfig(cls, path: Optional[str] = None,
                        context: Optional[str] = None) -> "KubeConfig":
        import yaml
        path = path or os.environ.get(
            "KUBECONFIG", os.path.expanduser("~/.kube/config"))
        with open(path) as f:
            kc = yaml.safe_load(f)
        ctx_name = context or kc.get("current-context")
        ctx = next(c["context"] for c in kc.get("contexts", [])
                   if c["name"] == ctx_name)
        cluster = next(c["cluster"] for c in kc.get("clusters", [])
                       if c["name"] == ctx["cluster"])
        user = next(u["user"] for u in kc.get("users", [])
                    if u["name"] == ctx["user"])

        def inline(data_key: str, file_key: str) -> Optional[str]:
            src = cluster if data_key.startswith("certificate-authority") \
                else user
            if src.get(file_key):
                return src[file_key]
            if src.get(data_key):
                fd, p = tempfile.mkstemp(suffix=".pem")
                with os.fdopen(fd, "wb") as f:
                    f.write(base64.b64decode(src[data_key]))
                return p
            return None

        return cls(
            server=cluster["server"],
            token=user.get("token"),
            ca_file=inline("certificate-authority-data",
                           "certificate-authority"),
            client_cert_file=inline("client-certificate-data",
                                    "client-certificate"),
            client_key_file=inline("client-key-data", "client-key"),
            insecure_skip_verify=cluster.get(
                "insecure-skip-tls-verify", False))

    # -- transport -----------------------------------------------------

    def ssl_context(self) -> Optional[ssl.SSLContext]:
        if not self.server.startswith("https"):
            return None
        ctx = ssl.create_default_context(cafile=self.ca_file)
        if self.insecure_skip_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        if self.client_cert_file:
            ctx.load_cert_chain(self.client_cert_file, self.client_key_file)
        return ctx

    def headers(self) -> Dict[str, str]:
        h = {"Content-Type": "application/json",
             "Accept": "application/json"}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h


class KubeClient:
    """Typed client over kube-apiserver with the InMemoryClient API."""

    def __init__(self, config: KubeConfig,
                 watch_kinds: Iterable[Type[Resource]] = (),
                 field_manager: str = "ome-tpu-manager"):
        self.config = config
        self.field_manager = field_manager
        self._registry = kind_registry()
        self._watch_kinds: List[Type[Resource]] = list(watch_kinds)
        self._watchers: List[Callable[[Event], None]] = []
        self._watch_threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._ssl = config.ssl_context()

    # -- low-level HTTP ------------------------------------------------

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 query: Optional[Dict[str, str]] = None,
                 timeout: float = 30.0):
        url = self.config.server + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=self.config.headers())
        try:
            resp = urllib.request.urlopen(req, timeout=timeout,
                                          context=self._ssl)
        except urllib.error.HTTPError as e:
            detail = ""
            try:
                detail = e.read().decode("utf-8", "replace")[:500]
            except Exception:
                pass
            if e.code == 404:
                raise NotFoundError(f"{method} {path}: {detail}") from e
            if e.code == 409:
                # AlreadyExists on create, Conflict on update
                if method == "POST":
                    raise AlreadyExistsError(
                        f"{method} {path}: {detail}") from e
                raise ConflictError(f"{method} {path}: {detail}") from e
            if e.code == 410:
                raise StaleResourceVersion(detail) from e
            raise APIServerError(
                f"{method} {path}: HTTP {e.code}: {detail}") from e
        with resp:
            payload = resp.read()
        return json.loads(payload) if payload else None

    def _to_obj(self, data: dict) -> Resource:
        cls = self._registry[data["kind"]]
        return cls.from_dict(data)

    # -- CRUD ----------------------------------------------------------

    def create(self, obj: Resource) -> Resource:
        path = rest_path(type(obj), obj.metadata.namespace)
        out = self._request("POST", path, obj.to_dict(),
                            query={"fieldManager": self.field_manager})
        return type(obj).from_dict(out)

    def get(self, cls: Type[Resource], name: str,
            namespace: str = "") -> Resource:
        out = self._request("GET", rest_path(cls, namespace, name))
        return cls.from_dict(out)

    def try_get(self, cls: Type[Resource], name: str,
                namespace: str = "") -> Optional[Resource]:
        try:
            return self.get(cls, name, namespace)
        except NotFoundError:
            return None

    def list(self, cls: Type[Resource], namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None,
             ) -> List[Resource]:
        return self._list(cls, namespace, label_selector)[0]

    def _list(self, cls, namespace=None, label_selector=None):
        query: Dict[str, str] = {}
        if label_selector:
            query["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(label_selector.items()))
        path = rest_path(cls, namespace or "")
        out = self._request("GET", path, query=query or None)
        items = [cls.from_dict(item) for item in out.get("items", [])]
        items.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
        return items, out.get("metadata", {}).get("resourceVersion", "")

    def update(self, obj: Resource, bump_generation: bool = True,
               ) -> Resource:
        # bump_generation is accepted for InMemoryClient signature parity;
        # a real apiserver manages metadata.generation itself
        path = rest_path(type(obj), obj.metadata.namespace,
                         obj.metadata.name)
        out = self._request("PUT", path, obj.to_dict(),
                            query={"fieldManager": self.field_manager})
        return type(obj).from_dict(out)

    def update_status(self, obj: Resource) -> Resource:
        path = rest_path(type(obj), obj.metadata.namespace,
                         obj.metadata.name) + "/status"
        try:
            out = self._request("PUT", path, obj.to_dict(),
                                query={"fieldManager": self.field_manager})
        except NotFoundError:
            # kinds without a status subresource (plain ConfigMaps etc.)
            return self.update(obj)
        return type(obj).from_dict(out)

    def delete(self, obj_or_cls, name: Optional[str] = None,
               namespace: str = "") -> None:
        if isinstance(obj_or_cls, Resource):
            cls = type(obj_or_cls)
            name = obj_or_cls.metadata.name
            namespace = obj_or_cls.metadata.namespace
        else:
            cls = obj_or_cls
        self._request("DELETE", rest_path(cls, namespace, name))

    # -- events --------------------------------------------------------

    def record_event(self, obj: Resource, event_type: str, reason: str,
                     message: str):
        ns = obj.metadata.namespace or "default"
        body = {
            "apiVersion": "v1", "kind": "Event",
            "metadata": {"generateName": f"{obj.metadata.name}.",
                         "namespace": ns},
            "involvedObject": {
                "apiVersion": type(obj).API_VERSION,
                "kind": type(obj).KIND, "name": obj.metadata.name,
                "namespace": obj.metadata.namespace,
                "uid": obj.metadata.uid},
            "type": event_type, "reason": reason, "message": message,
            "firstTimestamp": now(), "lastTimestamp": now(), "count": 1,
            "source": {"component": self.field_manager},
        }
        try:
            self._request("POST", f"/api/v1/namespaces/{ns}/events", body)
        except Exception:  # events are best-effort
            log.debug("event POST failed", exc_info=True)

    # -- watch ---------------------------------------------------------

    def watch(self, handler: Callable[[Event], None],
              ) -> Callable[[], None]:
        """Start list+watch threads for every registered watch kind and
        fan events into `handler` (the Manager's router)."""
        self._watchers.append(handler)
        if not self._watch_threads:
            for cls in self._watch_kinds:
                t = threading.Thread(target=self._watch_loop, args=(cls,),
                                     name=f"watch-{cls.KIND}", daemon=True)
                t.start()
                self._watch_threads.append(t)

        def cancel():
            if handler in self._watchers:
                self._watchers.remove(handler)
            if not self._watchers:
                self._stop.set()
        return cancel

    def _dispatch(self, ev: Event):
        for h in list(self._watchers):
            try:
                h(ev)
            except Exception:
                log.exception("watch handler failed")

    def _watch_loop(self, cls: Type[Resource]):
        rv = ""
        while not self._stop.is_set():
            try:
                if not rv:
                    items, rv = self._list(cls)
                    for obj in items:
                        self._dispatch(Event("Added", obj))
                rv = self._watch_stream(cls, rv)
            except StaleResourceVersion:
                rv = ""  # relist from scratch
            except Exception:
                if self._stop.is_set():
                    return
                log.warning("watch %s failed; reconnecting", cls.KIND,
                            exc_info=True)
                time.sleep(1.0)

    def _watch_stream(self, cls: Type[Resource], rv: str) -> str:
        query = {"watch": "true", "allowWatchBookmarks": "true",
                 "resourceVersion": rv, "timeoutSeconds": "300"}
        url = (self.config.server + rest_path(cls, "")
               + "?" + urllib.parse.urlencode(query))
        req = urllib.request.Request(url, headers=self.config.headers())
        with urllib.request.urlopen(req, timeout=330,
                                    context=self._ssl) as resp:
            for raw in resp:
                if self._stop.is_set():
                    return rv
                line = raw.strip()
                if not line:
                    continue
                ev = json.loads(line)
                etype, data = ev["type"], ev["object"]
                if etype == "BOOKMARK":
                    rv = data["metadata"]["resourceVersion"]
                    continue
                if etype == "ERROR":
                    if data.get("code") == 410:
                        raise StaleResourceVersion(str(data))
                    raise APIServerError(str(data))
                obj = cls.from_dict(data)
                rv = obj.metadata.resource_version or rv
                self._dispatch(Event(
                    {"ADDED": "Added", "MODIFIED": "Modified",
                     "DELETED": "Deleted"}.get(etype, etype), obj))
        return rv


class APIServerError(Exception):
    pass


class StaleResourceVersion(Exception):
    """HTTP 410 Gone — the watch resourceVersion aged out; relist."""
