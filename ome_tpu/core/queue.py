"""Rate-limited work queue (controller-runtime workqueue equivalent).

Deduplicates keys while queued, supports delayed re-enqueue (RequeueAfter)
and per-item exponential backoff, like the client-go workqueue the
reference's controllers run on.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Dict, Hashable, List, Optional, Set, Tuple


class WorkQueue:
    def __init__(self, base_delay: float = 0.005, max_delay: float = 30.0):
        self._cond = threading.Condition()
        self._queue: List[Hashable] = []
        self._dirty: Set[Hashable] = set()
        self._processing: Set[Hashable] = set()
        self._delayed: List[Tuple[float, int, Hashable]] = []
        self._seq = 0
        self._failures: Dict[Hashable, int] = {}
        self._base_delay = base_delay
        self._max_delay = max_delay
        self._shutdown = False

    def add(self, item: Hashable):
        with self._cond:
            if self._shutdown or item in self._dirty:
                return
            self._dirty.add(item)
            if item not in self._processing:
                self._queue.append(item)
                self._cond.notify()

    def add_after(self, item: Hashable, delay: float):
        if delay <= 0:
            return self.add(item)
        with self._cond:
            if self._shutdown:
                return
            self._seq += 1
            heapq.heappush(self._delayed, (time.monotonic() + delay, self._seq, item))
            self._cond.notify()

    def add_rate_limited(self, item: Hashable):
        with self._cond:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        self.add_after(item, min(self._base_delay * (2 ** n), self._max_delay))

    def forget(self, item: Hashable):
        with self._cond:
            self._failures.pop(item, None)

    def _pump_delayed(self) -> Optional[float]:
        """Move due delayed items into the queue; return wait for next one."""
        nowt = time.monotonic()
        while self._delayed and self._delayed[0][0] <= nowt:
            _, _, item = heapq.heappop(self._delayed)
            if item not in self._dirty:
                self._dirty.add(item)
                if item not in self._processing:
                    self._queue.append(item)
        if self._delayed:
            return max(0.0, self._delayed[0][0] - nowt)
        return None

    def get(self, timeout: Optional[float] = None) -> Optional[Hashable]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                wait = self._pump_delayed()
                if self._queue:
                    item = self._queue.pop(0)
                    self._dirty.discard(item)
                    self._processing.add(item)
                    return item
                if self._shutdown:
                    return None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    wait = remaining if wait is None else min(wait, remaining)
                self._cond.wait(wait)

    def done(self, item: Hashable):
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    def shutdown(self):
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def __len__(self):
        with self._cond:
            return len(self._queue) + len(self._delayed)
