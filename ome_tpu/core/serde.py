"""Dataclass <-> plain-dict serialization with k8s-style camelCase keys.

The reference's API types are Go structs with JSON tags (e.g.
/root/reference/pkg/apis/ome/v1beta1/inference_service.go); here the same
role is played by Python dataclasses and this serde layer, which converts
snake_case field names to camelCase and back, drops None/empty values on
output (like `omitempty`), and recurses through nested dataclasses,
lists, dicts and enums.
"""

from __future__ import annotations

import dataclasses
import enum
import functools
import typing
from typing import Any, Optional, Type, TypeVar, Union, get_args, get_origin

T = TypeVar("T")


def camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p[:1].upper() + p[1:] for p in parts[1:])


def _json_name(f: dataclasses.Field) -> str:
    return f.metadata.get("json", camel(f.name))


@functools.lru_cache(maxsize=None)
def _ser_plan(tp: type):
    """(field_name, json_key) per serializable field, computed once
    per class — fields()/metadata lookups per instance add up on
    deepcopy-heavy paths (catalog selection)."""
    return tuple((f.name, _json_name(f))
                 for f in dataclasses.fields(tp)
                 if f.metadata.get("serialize", True))


@functools.lru_cache(maxsize=None)
def _deser_plan(tp: type):
    """(field_name, json_key, resolved_type) per field.
    typing.get_type_hints() re-evaluates every annotation string on
    EVERY call; caching the resolved hints per class is the whole
    win (~25x on deepcopy_resource)."""
    hints = typing.get_type_hints(tp)
    return tuple((f.name, _json_name(f), hints[f.name])
                 for f in dataclasses.fields(tp))


def to_dict(obj: Any, keep_empty: bool = False) -> Any:
    """Serialize a dataclass tree to plain dicts (camelCase keys, omitempty)."""
    if obj is None:
        return None
    if isinstance(obj, enum.Enum):
        return obj.value
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for name, key in _ser_plan(type(obj)):
            raw = getattr(obj, name)
            v = to_dict(raw, keep_empty)
            if v is None and not keep_empty:
                continue
            # Go omitempty semantics: a present-but-empty STRUCT is kept
            # (`engine: {}` is a meaningful component declaration on the
            # wire); empty lists/maps/strings are dropped
            if v in ({}, []) and not keep_empty \
                    and not dataclasses.is_dataclass(raw):
                continue
            out[key] = v
        return out
    if isinstance(obj, dict):
        return {k: to_dict(v, keep_empty) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v, keep_empty) for v in obj]
    return obj


def _strip_optional(tp: Any) -> Any:
    if get_origin(tp) is Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def from_dict(cls: Type[T], data: Any) -> T:
    """Deserialize plain dicts (camelCase keys) into dataclass `cls`."""
    return _from_value(cls, data)


def _from_value(tp: Any, data: Any) -> Any:
    if data is None:
        return None
    tp = _strip_optional(tp)
    if isinstance(tp, str):  # forward reference left unresolved
        raise TypeError(f"unresolved forward reference {tp!r}")
    origin = get_origin(tp)
    if origin in (list, tuple):
        (item_tp,) = get_args(tp) or (Any,)
        return [_from_value(item_tp, v) for v in data]
    if origin is dict:
        args = get_args(tp)
        val_tp = args[1] if len(args) == 2 else Any
        return {k: _from_value(val_tp, v) for k, v in data.items()}
    if isinstance(tp, type) and issubclass(tp, enum.Enum):
        return tp(data)
    if dataclasses.is_dataclass(tp):
        kwargs = {}
        for name, key, ftp in _deser_plan(tp):
            if key in data:
                kwargs[name] = _from_value(ftp, data[key])
        return tp(**kwargs)
    if tp in (Any, object) or origin is not None:
        return data
    return data


def deepcopy_resource(obj: T) -> T:
    """DeepCopy equivalent (zz_generated.deepcopy.go in the reference)."""
    if obj is None:
        return None
    return from_dict(type(obj), to_dict(obj, keep_empty=True))
