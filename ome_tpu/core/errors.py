"""API errors (k8s apierrors equivalents)."""


class APIError(Exception):
    pass


class NotFoundError(APIError):
    pass


class AlreadyExistsError(APIError):
    pass


class ConflictError(APIError):
    pass


class ValidationError(APIError):
    """Webhook admission denial."""
