"""Kubernetes built-in types (the subset the control plane stamps out).

The reference emits corev1/appsv1/batchv1/autoscaling/networking objects
plus LeaderWorkerSet and KEDA ScaledObject CRs (SURVEY.md §2.3 reconcilers
table). These dataclasses model the fields our reconcilers read or write;
loosely-structured corners (affinity, probe handlers) stay plain dicts.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, ClassVar, Dict, List, Optional

from .meta import Resource

# --------------------------------------------------------------------------
# core/v1 pod primitives


@dataclass
class EnvVar:
    name: str = ""
    value: Optional[str] = None
    value_from: Optional[dict] = None


@dataclass
class VolumeMount:
    name: str = ""
    mount_path: str = ""
    read_only: Optional[bool] = None
    sub_path: Optional[str] = None


@dataclass
class Volume:
    name: str = ""
    host_path: Optional[dict] = None
    empty_dir: Optional[dict] = None
    config_map: Optional[dict] = None
    secret: Optional[dict] = None
    persistent_volume_claim: Optional[dict] = None


@dataclass
class ContainerPort:
    name: Optional[str] = None
    container_port: int = 0
    protocol: Optional[str] = None


@dataclass
class ResourceRequirements:
    requests: Dict[str, str] = field(default_factory=dict)
    limits: Dict[str, str] = field(default_factory=dict)


@dataclass
class Probe:
    http_get: Optional[dict] = None
    tcp_socket: Optional[dict] = None
    exec: Optional[dict] = None
    initial_delay_seconds: Optional[int] = None
    period_seconds: Optional[int] = None
    timeout_seconds: Optional[int] = None
    failure_threshold: Optional[int] = None
    success_threshold: Optional[int] = None


@dataclass
class Container:
    name: str = ""
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    ports: List[ContainerPort] = field(default_factory=list)
    resources: Optional[ResourceRequirements] = None
    volume_mounts: List[VolumeMount] = field(default_factory=list)
    liveness_probe: Optional[Probe] = None
    readiness_probe: Optional[Probe] = None
    startup_probe: Optional[Probe] = None
    security_context: Optional[dict] = None
    working_dir: Optional[str] = None
    image_pull_policy: Optional[str] = None

    def env_dict(self) -> Dict[str, str]:
        return {e.name: (e.value or "") for e in self.env}

    def set_env(self, name: str, value: str):
        for e in self.env:
            if e.name == name:
                e.value = value
                return
        self.env.append(EnvVar(name=name, value=value))

    def get_env(self, name: str) -> Optional[str]:
        for e in self.env:
            if e.name == name:
                return e.value
        return None


@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    volumes: List[Volume] = field(default_factory=list)
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[dict] = None
    tolerations: List[dict] = field(default_factory=list)
    service_account_name: Optional[str] = None
    host_network: Optional[bool] = None
    host_ipc: Optional[bool] = None
    scheduler_name: Optional[str] = None
    termination_grace_period_seconds: Optional[int] = None
    image_pull_secrets: List[dict] = field(default_factory=list)
    subdomain: Optional[str] = None
    restart_policy: Optional[str] = None

    def container(self, name: str) -> Optional[Container]:
        for c in self.containers:
            if c.name == name:
                return c
        return None


@dataclass
class PodTemplateSpec:
    metadata: "ObjectMeta" = None
    spec: PodSpec = field(default_factory=PodSpec)

    def __post_init__(self):
        from .meta import ObjectMeta
        if self.metadata is None:
            self.metadata = ObjectMeta()


from .meta import ObjectMeta  # noqa: E402  (for PodTemplateSpec default)


@dataclass
class Pod(Resource):
    KIND: ClassVar[str] = "Pod"
    API_VERSION: ClassVar[str] = "v1"
    spec: PodSpec = field(default_factory=PodSpec)
    status: dict = field(default_factory=dict)


@dataclass
class NodeStatus:
    capacity: Dict[str, str] = field(default_factory=dict)
    allocatable: Dict[str, str] = field(default_factory=dict)
    conditions: List[dict] = field(default_factory=list)


@dataclass
class Node(Resource):
    KIND: ClassVar[str] = "Node"
    API_VERSION: ClassVar[str] = "v1"
    NAMESPACED: ClassVar[bool] = False
    spec: dict = field(default_factory=dict)
    status: NodeStatus = field(default_factory=NodeStatus)


@dataclass
class ConfigMap(Resource):
    KIND: ClassVar[str] = "ConfigMap"
    API_VERSION: ClassVar[str] = "v1"
    data: Dict[str, str] = field(default_factory=dict)


@dataclass
class Secret(Resource):
    KIND: ClassVar[str] = "Secret"
    API_VERSION: ClassVar[str] = "v1"
    data: Dict[str, str] = field(default_factory=dict)
    type: Optional[str] = None


@dataclass
class ServicePort:
    name: Optional[str] = None
    port: int = 0
    target_port: Any = None
    protocol: Optional[str] = None


@dataclass
class ServiceSpec:
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[ServicePort] = field(default_factory=list)
    cluster_ip: Optional[str] = None
    type: Optional[str] = None


@dataclass
class Service(Resource):
    KIND: ClassVar[str] = "Service"
    API_VERSION: ClassVar[str] = "v1"
    spec: ServiceSpec = field(default_factory=ServiceSpec)
    status: dict = field(default_factory=dict)


# --------------------------------------------------------------------------
# apps/v1, batch/v1


@dataclass
class DeploymentSpec:
    replicas: int = 1
    selector: Dict[str, Any] = field(default_factory=dict)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    strategy: Optional[dict] = None


@dataclass
class DeploymentStatus:
    replicas: int = 0
    ready_replicas: int = 0
    available_replicas: int = 0
    conditions: List[dict] = field(default_factory=list)


@dataclass
class Deployment(Resource):
    KIND: ClassVar[str] = "Deployment"
    API_VERSION: ClassVar[str] = "apps/v1"
    spec: DeploymentSpec = field(default_factory=DeploymentSpec)
    status: DeploymentStatus = field(default_factory=DeploymentStatus)


@dataclass
class JobSpec:
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    backoff_limit: Optional[int] = None
    ttl_seconds_after_finished: Optional[int] = None
    completions: Optional[int] = None
    parallelism: Optional[int] = None


@dataclass
class JobStatus:
    active: int = 0
    succeeded: int = 0
    failed: int = 0
    conditions: List[dict] = field(default_factory=list)


@dataclass
class Job(Resource):
    KIND: ClassVar[str] = "Job"
    API_VERSION: ClassVar[str] = "batch/v1"
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)


# --------------------------------------------------------------------------
# autoscaling, policy, networking


@dataclass
class HorizontalPodAutoscaler(Resource):
    KIND: ClassVar[str] = "HorizontalPodAutoscaler"
    API_VERSION: ClassVar[str] = "autoscaling/v2"
    spec: dict = field(default_factory=dict)
    status: dict = field(default_factory=dict)


@dataclass
class PodDisruptionBudget(Resource):
    KIND: ClassVar[str] = "PodDisruptionBudget"
    API_VERSION: ClassVar[str] = "policy/v1"
    spec: dict = field(default_factory=dict)
    status: dict = field(default_factory=dict)


@dataclass
class Ingress(Resource):
    KIND: ClassVar[str] = "Ingress"
    API_VERSION: ClassVar[str] = "networking.k8s.io/v1"
    spec: dict = field(default_factory=dict)
    status: dict = field(default_factory=dict)


@dataclass
class HTTPRoute(Resource):
    KIND: ClassVar[str] = "HTTPRoute"
    API_VERSION: ClassVar[str] = "gateway.networking.k8s.io/v1"
    spec: dict = field(default_factory=dict)
    status: dict = field(default_factory=dict)


@dataclass
class VirtualService(Resource):
    KIND: ClassVar[str] = "VirtualService"
    API_VERSION: ClassVar[str] = "networking.istio.io/v1beta1"
    spec: dict = field(default_factory=dict)
    status: dict = field(default_factory=dict)


# --------------------------------------------------------------------------
# LeaderWorkerSet (leaderworkerset.x-k8s.io) — multi-host slice groups


@dataclass
class LeaderWorkerTemplate:
    leader_template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    worker_template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    size: int = 1
    restart_policy: Optional[str] = None  # RecreateGroupOnPodRestart


@dataclass
class LeaderWorkerSetSpec:
    replicas: int = 1
    leader_worker_template: LeaderWorkerTemplate = field(default_factory=LeaderWorkerTemplate)
    rollout_strategy: Optional[dict] = None
    startup_policy: Optional[str] = None
    network_config: Optional[dict] = None


@dataclass
class LeaderWorkerSetStatus:
    replicas: int = 0
    ready_replicas: int = 0
    conditions: List[dict] = field(default_factory=list)


@dataclass
class LeaderWorkerSet(Resource):
    KIND: ClassVar[str] = "LeaderWorkerSet"
    API_VERSION: ClassVar[str] = "leaderworkerset.x-k8s.io/v1"
    spec: LeaderWorkerSetSpec = field(default_factory=LeaderWorkerSetSpec)
    status: LeaderWorkerSetStatus = field(default_factory=LeaderWorkerSetStatus)


# --------------------------------------------------------------------------
# KEDA ScaledObject, Knative Service (loose specs)


@dataclass
class ScaledObject(Resource):
    KIND: ClassVar[str] = "ScaledObject"
    API_VERSION: ClassVar[str] = "keda.sh/v1alpha1"
    spec: dict = field(default_factory=dict)
    status: dict = field(default_factory=dict)


@dataclass
class KnativeService(Resource):
    KIND: ClassVar[str] = "KnativeService"
    PLURAL: ClassVar[str] = "services.serving.knative.dev"
    API_VERSION: ClassVar[str] = "serving.knative.dev/v1"
    spec: dict = field(default_factory=dict)
    status: dict = field(default_factory=dict)


# --------------------------------------------------------------------------
# RBAC


@dataclass
class ServiceAccount(Resource):
    KIND: ClassVar[str] = "ServiceAccount"
    API_VERSION: ClassVar[str] = "v1"


@dataclass
class Role(Resource):
    KIND: ClassVar[str] = "Role"
    API_VERSION: ClassVar[str] = "rbac.authorization.k8s.io/v1"
    rules: list = field(default_factory=list)


@dataclass
class RoleBinding(Resource):
    KIND: ClassVar[str] = "RoleBinding"
    API_VERSION: ClassVar[str] = "rbac.authorization.k8s.io/v1"
    role_ref: dict = field(default_factory=dict)
    subjects: list = field(default_factory=list)


@dataclass
class ClusterRole(Resource):
    KIND: ClassVar[str] = "ClusterRole"
    API_VERSION: ClassVar[str] = "rbac.authorization.k8s.io/v1"
    NAMESPACED: ClassVar[bool] = False
    rules: list = field(default_factory=list)


@dataclass
class ClusterRoleBinding(Resource):
    KIND: ClassVar[str] = "ClusterRoleBinding"
    API_VERSION: ClassVar[str] = "rbac.authorization.k8s.io/v1"
    NAMESPACED: ClassVar[bool] = False
    role_ref: dict = field(default_factory=dict)
    subjects: list = field(default_factory=list)


# --------------------------------------------------------------------------
# cluster scaffolding + admission registration (chart-installed stack)


@dataclass
class Namespace(Resource):
    KIND: ClassVar[str] = "Namespace"
    API_VERSION: ClassVar[str] = "v1"
    NAMESPACED: ClassVar[bool] = False


@dataclass
class DaemonSet(Resource):
    KIND: ClassVar[str] = "DaemonSet"
    API_VERSION: ClassVar[str] = "apps/v1"
    spec: dict = field(default_factory=dict)
    status: dict = field(default_factory=dict)


@dataclass
class MutatingWebhookConfiguration(Resource):
    KIND: ClassVar[str] = "MutatingWebhookConfiguration"
    API_VERSION: ClassVar[str] = "admissionregistration.k8s.io/v1"
    NAMESPACED: ClassVar[bool] = False
    webhooks: list = field(default_factory=list)


@dataclass
class ValidatingWebhookConfiguration(Resource):
    KIND: ClassVar[str] = "ValidatingWebhookConfiguration"
    API_VERSION: ClassVar[str] = "admissionregistration.k8s.io/v1"
    NAMESPACED: ClassVar[bool] = False
    webhooks: list = field(default_factory=list)


@dataclass
class Certificate(Resource):
    """cert-manager.io Certificate (webhook serving cert)."""

    KIND: ClassVar[str] = "Certificate"
    API_VERSION: ClassVar[str] = "cert-manager.io/v1"
    spec: dict = field(default_factory=dict)
    status: dict = field(default_factory=dict)


@dataclass
class Issuer(Resource):
    KIND: ClassVar[str] = "Issuer"
    API_VERSION: ClassVar[str] = "cert-manager.io/v1"
    spec: dict = field(default_factory=dict)
    status: dict = field(default_factory=dict)


# --------------------------------------------------------------------------
# coordination.k8s.io (leader election)


@dataclass
class LeaseSpec:
    holder_identity: Optional[str] = None
    lease_duration_seconds: Optional[int] = None
    acquire_time: Optional[str] = None
    renew_time: Optional[str] = None
    lease_transitions: Optional[int] = None


@dataclass
class Lease(Resource):
    KIND: ClassVar[str] = "Lease"
    API_VERSION: ClassVar[str] = "coordination.k8s.io/v1"
    spec: LeaseSpec = field(default_factory=LeaseSpec)


# --------------------------------------------------------------------------
# Istio (service mesh)


@dataclass
class IstioSidecar(Resource):
    """networking.istio.io Sidecar — scopes the Envoy sidecar's config
    for multinode engine pods (reference: reconcilers/istiosidecar)."""

    KIND: ClassVar[str] = "Sidecar"
    API_VERSION: ClassVar[str] = "networking.istio.io/v1beta1"
    PLURAL: ClassVar[str] = "sidecars"
    spec: dict = field(default_factory=dict)
