"""Controller manager (controller-runtime manager + controller equivalents).

Mirrors the wiring in the reference's cmd/manager/main.go:145-368: each
controller declares the primary kind it reconciles plus watch mappings
from other kinds to reconcile keys; the manager fans API watch events
into per-controller rate-limited workqueues drained by worker threads.
"""

from __future__ import annotations

import logging
import threading
import traceback
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple, Type

from .client import Event, InMemoryClient
from .meta import Resource
from .queue import WorkQueue

log = logging.getLogger("ome.manager")

ReconcileKey = Tuple[str, str]  # (namespace, name)


@dataclass
class Result:
    requeue: bool = False
    requeue_after: float = 0.0


class Reconciler:
    """Subclasses implement reconcile(key) and declare watches()."""

    #: primary kind this controller reconciles
    FOR: Type[Resource] = None

    def __init__(self, client: InMemoryClient):
        self.client = client

    def reconcile(self, namespace: str, name: str) -> Result:
        raise NotImplementedError

    def watches(self) -> List[Tuple[Type[Resource], Callable[[Resource], List[ReconcileKey]]]]:
        """Extra (kind, mapper) pairs; mapper maps an event object to keys."""
        return []

    def owns(self) -> List[Type[Resource]]:
        """Kinds whose owner references should trigger the owning primary."""
        return []


class Manager:
    def __init__(self, client: InMemoryClient):
        self.client = client
        self._controllers: List[Tuple[Reconciler, WorkQueue]] = []
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._cancel_watch = None

    def register(self, reconciler: Reconciler):
        self._controllers.append((reconciler, WorkQueue()))

    def _route(self, ev: Event):
        obj = ev.obj
        kind = type(obj).KIND
        for rec, q in self._controllers:
            if rec.FOR is not None and kind == rec.FOR.KIND:
                q.add((obj.metadata.namespace, obj.metadata.name))
            for ref in obj.metadata.owner_references:
                if (rec.FOR and ref.controller and ref.kind == rec.FOR.KIND
                        and any(kind == k.KIND for k in rec.owns())):
                    q.add((obj.metadata.namespace, ref.name))
            for watched_cls, mapper in rec.watches():
                if kind == watched_cls.KIND:
                    for key in mapper(obj):
                        q.add(key)

    def start(self, workers_per_controller: int = 1):
        self._cancel_watch = self.client.watch(self._route)
        # seed initial reconciles for pre-existing objects
        for rec, q in self._controllers:
            if rec.FOR is not None:
                for obj in self.client.list(rec.FOR):
                    q.add((obj.metadata.namespace, obj.metadata.name))
        for rec, q in self._controllers:
            for i in range(workers_per_controller):
                t = threading.Thread(target=self._worker, args=(rec, q),
                                     name=f"{type(rec).__name__}-{i}", daemon=True)
                t.start()
                self._threads.append(t)

    def _worker(self, rec: Reconciler, q: WorkQueue):
        while not self._stop.is_set():
            item = q.get(timeout=0.2)
            if item is None:
                continue
            ns, name = item
            try:
                res = rec.reconcile(ns, name) or Result()
                q.forget(item)
                if res.requeue_after > 0:
                    q.add_after(item, res.requeue_after)
                elif res.requeue:
                    q.add_rate_limited(item)
            except Exception:
                log.error("reconcile %s %s/%s failed:\n%s",
                          type(rec).__name__, ns, name, traceback.format_exc())
                q.add_rate_limited(item)
            finally:
                q.done(item)

    def stop(self):
        self._stop.set()
        if self._cancel_watch:
            self._cancel_watch()
        for rec, q in self._controllers:
            q.shutdown()
        for t in self._threads:
            t.join(timeout=2)

    def reconcile_once(self, drain: bool = True, max_iters: int = 200):
        """Synchronously drain all queues — deterministic mode for tests
        (replaces the reference's ginkgo Eventually() polling)."""
        if self._cancel_watch is None:
            self._cancel_watch = self.client.watch(self._route)
            for rec, q in self._controllers:
                if rec.FOR is not None:
                    for obj in self.client.list(rec.FOR):
                        q.add((obj.metadata.namespace, obj.metadata.name))
        requeues: Dict[Tuple[str, ReconcileKey], int] = {}
        for _ in range(max_iters):
            progressed = False
            for rec, q in self._controllers:
                item = q.get(timeout=0)
                if item is None:
                    continue
                progressed = True
                ns, name = item
                try:
                    res = rec.reconcile(ns, name) or Result()
                    q.forget(item)
                    # test mode: requeues retry immediately (bounded per
                    # item so a periodic-resync reconciler that always
                    # returns requeue_after can't spin the drain loop)
                    if res.requeue or res.requeue_after > 0:
                        seen = requeues.get((type(rec).__name__, item), 0)
                        if seen < 5:
                            requeues[(type(rec).__name__, item)] = seen + 1
                            q.add(item)
                except Exception:
                    log.error("reconcile %s %s/%s failed:\n%s",
                              type(rec).__name__, ns, name, traceback.format_exc())
                finally:
                    q.done(item)
            if not progressed or not drain:
                return
