"""Runtime selection engine.

Re-designs the reference's pkg/runtimeselector (fetcher.go / matcher.go /
scorer.go / selector.go, SURVEY.md §2.4) for the TPU catalog: given a
BaseModel, fetch namespace + cluster ServingRuntimes, evaluate detailed
compatibility (format / framework / architecture / quantization / size
range / protocol / accelerator requirements), score the matches
(weight x priority with size-proximity and namespace tiebreaks) and pick
deterministically.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

from ..apis import v1
from ..core.client import InMemoryClient
from ..core.errors import APIError
from ..utils.modelver import compare_lenient

Runtime = Union[v1.ServingRuntime, v1.ClusterServingRuntime]

# scoring weights (reference scorer.go:30-100)
FORMAT_WEIGHT = 10
FRAMEWORK_WEIGHT = 5
ARCHITECTURE_WEIGHT = 8
QUANTIZATION_WEIGHT = 3


class SelectionError(APIError):
    pass


class NoRuntimeFoundError(SelectionError):
    def __init__(self, model: str, reports: List["CompatibilityReport"]):
        self.reports = reports
        detail = "; ".join(
            f"{r.runtime_name}: {r.first_failure()}" for r in reports[:5])
        super().__init__(
            f"no suitable runtime found for model {model!r}"
            + (f" (candidates: {detail})" if detail else ""))


class RuntimeNotFoundError(SelectionError):
    pass


class RuntimeIncompatibleError(SelectionError):
    def __init__(self, runtime: str, model: str, report: "CompatibilityReport"):
        self.report = report
        super().__init__(
            f"runtime {runtime!r} is incompatible with model {model!r}: "
            f"{report.first_failure()}")


class RuntimeDisabledError(SelectionError):
    pass


@dataclass
class CheckResult:
    name: str
    passed: bool
    reason: str = ""


@dataclass
class CompatibilityReport:
    """Per-runtime detailed evaluation (matcher.go GetCompatibilityDetails)."""

    runtime_name: str = ""
    cluster_scoped: bool = False
    checks: List[CheckResult] = field(default_factory=list)
    matched_format: Optional[v1.SupportedModelFormat] = None

    @property
    def compatible(self) -> bool:
        return all(c.passed for c in self.checks)

    def first_failure(self) -> str:
        for c in self.checks:
            if not c.passed:
                return f"{c.name}: {c.reason}"
        return ""


@dataclass
class RuntimeMatch:
    runtime: Runtime
    report: CompatibilityReport
    score: int = 0
    size_distance: float = float("inf")

    @property
    def name(self) -> str:
        return self.runtime.metadata.name


# -- fetcher (fetcher.go:29-97) --------------------------------------------


class Fetcher:
    def __init__(self, client: InMemoryClient):
        self.client = client

    def fetch(self, namespace: str) -> List[Runtime]:
        ns_runtimes: List[Runtime] = list(
            self.client.list(v1.ServingRuntime, namespace=namespace))
        cluster_runtimes: List[Runtime] = list(
            self.client.list(v1.ClusterServingRuntime))
        return sorted(ns_runtimes, key=lambda r: r.metadata.name) + \
            sorted(cluster_runtimes, key=lambda r: r.metadata.name)


# -- matcher (matcher.go:29-160) -------------------------------------------


def _name_version_match(want_name: Optional[str], want_version: Optional[str],
                        got: Optional[dict]) -> Tuple[bool, str]:
    if not want_name:
        return True, ""
    got = got or {}
    if got.get("name", "").lower() != want_name.lower():
        return False, f"want {want_name}, runtime supports {got.get('name') or 'any'}"
    if want_version and got.get("version"):
        if compare_lenient(want_version, got["version"]) != 0:
            return False, (f"version mismatch: model {want_version} "
                           f"vs runtime {got['version']}")
    return True, ""


class Matcher:
    def evaluate(self, runtime: Runtime, model: v1.BaseModelSpec,
                 accelerator: Optional[v1.AcceleratorClass] = None,
                 ) -> CompatibilityReport:
        spec = runtime.spec
        report = CompatibilityReport(
            runtime_name=runtime.metadata.name,
            cluster_scoped=isinstance(runtime, v1.ClusterServingRuntime))

        report.checks.append(CheckResult(
            "disabled", not spec.is_disabled(),
            "runtime is disabled" if spec.is_disabled() else ""))

        fmt_match, matched = self._match_formats(spec, model)
        report.matched_format = matched
        report.checks.append(CheckResult(
            "modelFormat", fmt_match,
            "" if fmt_match else
            f"no supported format entry matches format="
            f"{model.model_format.name!r} arch={model.model_architecture!r} "
            f"quant={model.quantization.value if model.quantization else None!r}"))

        size_ok, size_reason = self._check_size(spec, model)
        report.checks.append(CheckResult("modelSizeRange", size_ok, size_reason))

        acc_ok, acc_reason = self._check_accelerator(spec, accelerator)
        report.checks.append(CheckResult("acceleratorRequirements", acc_ok,
                                         acc_reason))
        return report

    def _match_formats(self, spec: v1.ServingRuntimeSpec,
                       model: v1.BaseModelSpec,
                       ) -> Tuple[bool, Optional[v1.SupportedModelFormat]]:
        """A model matches if any supported entry passes every sub-check
        the entry specifies (format, framework, architecture, quant)."""
        best: Optional[v1.SupportedModelFormat] = None
        for entry in spec.supported_model_formats:
            if entry.auto_select is False:
                continue
            fmt = entry.model_format or (
                {"name": entry.name, "version": entry.version}
                if entry.name else None)
            ok, _ = _name_version_match(
                model.model_format.name, model.model_format.version, fmt)
            if not ok:
                continue
            if entry.model_framework is not None:
                want = model.model_framework
                ok, _ = _name_version_match(
                    entry.model_framework.get("name"),
                    entry.model_framework.get("version"),
                    {"name": want.name if want else "",
                     "version": want.version if want else None})
                if not ok:
                    continue
            if entry.model_architecture:
                if (model.model_architecture or "").lower() != \
                        entry.model_architecture.lower():
                    continue
            # quantization matches STRICTLY both ways (matcher.go:
            # 204-212): a quantized model needs an entry declaring the
            # same quant, and a plain entry serves only unquantized
            # models — an fp8 checkpoint must never route to an engine
            # that can only load full-precision safetensors
            got = model.quantization.value if model.quantization else ""
            want = entry.quantization or ""
            if bool(got) != bool(want):
                continue
            if want and got.lower() != want.lower():
                continue
            if best is None or (entry.priority or 0) > (best.priority or 0):
                best = entry
        return best is not None, best

    def _check_size(self, spec: v1.ServingRuntimeSpec,
                    model: v1.BaseModelSpec) -> Tuple[bool, str]:
        rng = spec.model_size_range
        if rng is None:
            return True, ""
        size = v1.parse_parameter_size(model.model_parameter_size)
        if size is None:
            return True, ""  # unknown size: don't exclude
        lo = v1.parse_parameter_size(rng.min) or 0
        hi = v1.parse_parameter_size(rng.max) or float("inf")
        if lo <= size <= hi:
            return True, ""
        return False, (f"model size {model.model_parameter_size} outside "
                       f"runtime range [{rng.min}, {rng.max}]")

    def _check_accelerator(self, spec: v1.ServingRuntimeSpec,
                           accelerator: Optional[v1.AcceleratorClass],
                           ) -> Tuple[bool, str]:
        from .common import check_accelerator_requirements
        return check_accelerator_requirements(spec.accelerator_requirements,
                                              accelerator)


# -- scorer (scorer.go:30-164) ---------------------------------------------


class Scorer:
    def score(self, match: RuntimeMatch, model: v1.BaseModelSpec) -> None:
        entry = match.report.matched_format
        score = 0
        if entry is not None:
            prio = entry.priority or 1
            score += FORMAT_WEIGHT * prio * (model.model_format.weight or 1)
            if entry.model_framework is not None and model.model_framework:
                score += FRAMEWORK_WEIGHT * prio * \
                    (model.model_framework.weight or 1)
            if entry.model_architecture:
                score += ARCHITECTURE_WEIGHT * prio
            if entry.quantization:
                score += QUANTIZATION_WEIGHT * prio
        match.score = score
        match.size_distance = self._size_distance(match.runtime.spec, model)

    @staticmethod
    def _size_distance(spec: v1.ServingRuntimeSpec,
                       model: v1.BaseModelSpec) -> float:
        size = v1.parse_parameter_size(model.model_parameter_size)
        rng = spec.model_size_range
        if size is None or rng is None:
            return float("inf")
        lo = v1.parse_parameter_size(rng.min) or 0
        hi = v1.parse_parameter_size(rng.max) or size
        return abs((lo + hi) / 2 - size)

    @staticmethod
    def compare(a: RuntimeMatch, b: RuntimeMatch) -> int:
        """CompareRuntimes (scorer.go:67-100): score desc, size proximity
        asc, namespace-scoped first, then name for determinism."""
        if a.score != b.score:
            return -1 if a.score > b.score else 1
        if a.size_distance != b.size_distance:
            return -1 if a.size_distance < b.size_distance else 1
        if a.report.cluster_scoped != b.report.cluster_scoped:
            return -1 if not a.report.cluster_scoped else 1
        return -1 if a.name < b.name else (1 if a.name > b.name else 0)


# -- selector facade (selector.go:39-150) ----------------------------------


class RuntimeSelector:
    def __init__(self, client: InMemoryClient):
        self.client = client
        self.fetcher = Fetcher(client)
        self.matcher = Matcher()
        self.scorer = Scorer()

    def select(self, model: v1.BaseModelSpec, namespace: str,
               accelerator: Optional[v1.AcceleratorClass] = None,
               model_name: str = "") -> RuntimeMatch:
        """SelectRuntime: best compatible runtime or NoRuntimeFoundError."""
        import functools

        runtimes = self.fetcher.fetch(namespace)
        matches, failed = [], []
        for rt in runtimes:
            report = self.matcher.evaluate(rt, model, accelerator)
            if report.compatible:
                m = RuntimeMatch(runtime=rt, report=report)
                self.scorer.score(m, model)
                matches.append(m)
            else:
                failed.append(report)
        if not matches:
            raise NoRuntimeFoundError(model_name or model.model_format.name,
                                      failed)
        matches.sort(key=functools.cmp_to_key(self.scorer.compare))
        return matches[0]

    def get(self, name: str, namespace: str) -> Runtime:
        """GetRuntime: namespace-scoped first, then cluster-scoped."""
        rt = self.client.try_get(v1.ServingRuntime, name, namespace)
        if rt is None:
            rt = self.client.try_get(v1.ClusterServingRuntime, name)
        if rt is None:
            raise RuntimeNotFoundError(f"runtime {name!r} not found in "
                                       f"namespace {namespace!r} or cluster scope")
        return rt

    def validate(self, name: str, model: v1.BaseModelSpec, namespace: str,
                 accelerator: Optional[v1.AcceleratorClass] = None,
                 model_name: str = "") -> RuntimeMatch:
        """ValidateRuntime: explicit runtime must exist, be enabled and
        compatible."""
        rt = self.get(name, namespace)
        if rt.spec.is_disabled():
            raise RuntimeDisabledError(f"runtime {name!r} is disabled")
        report = self.matcher.evaluate(rt, model, accelerator)
        if not report.compatible:
            raise RuntimeIncompatibleError(name, model_name, report)
        m = RuntimeMatch(runtime=rt, report=report)
        self.scorer.score(m, model)
        return m
