"""AcceleratorClass selection engine.

Re-designs pkg/acceleratorclassselector (SURVEY.md §2.4) TPU-first:
resolution order is explicit name > component override > policy
(selector.go:46-105); candidates are filtered by runtime
AcceleratorRequirements and isvc constraints (policy_helpers.go:60-177);
policies:

  BestFit      — smallest slice whose aggregate HBM fits the model's
                 weights + KV-cache headroom (memory-fit scoring,
                 policy_helpers.go:178-319, re-based on chips x HBM/chip)
  Cheapest     — lowest $/chip-hour x chips needed (:320-364)
  MostCapable  — normalized TFLOPS/HBM/bandwidth score (:366-509)
  FirstAvailable — first candidate with matched ready nodes

Unlike the GPU reference (nvidia.com/gpu counting), sizing reasons in
chips / hosts / slice topologies, and returns the chosen TopologySpec so
downstream reconcilers can stamp slice-shaped LWS groups.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..apis import v1
from ..core.client import InMemoryClient
from ..core.errors import APIError

BYTES_PER_PARAM_BF16 = 2.0
KV_HEADROOM = 1.35  # weights + runtime KV/cache/activation headroom


class AcceleratorSelectionError(APIError):
    pass


@dataclass
class AcceleratorChoice:
    accelerator: v1.AcceleratorClass
    topology: Optional[v1.TopologySpec] = None
    chips: int = 0
    reason: str = ""

    @property
    def name(self) -> str:
        return self.accelerator.metadata.name


def required_hbm_gb(model: Optional[v1.BaseModelSpec]) -> Optional[float]:
    if model is None:
        return None
    size = v1.parse_parameter_size(model.model_parameter_size)
    if size is None:
        return None
    bytes_per_param = BYTES_PER_PARAM_BF16
    if model.quantization in (v1.ModelQuantization.FP8,
                              v1.ModelQuantization.FBGEMM_FP8,
                              v1.ModelQuantization.INT8):
        bytes_per_param = 1.0
    elif model.quantization == v1.ModelQuantization.INT4:
        bytes_per_param = 0.5
    return size * bytes_per_param * KV_HEADROOM / 1e9


def chips_needed(model: Optional[v1.BaseModelSpec],
                 ac: v1.AcceleratorClass) -> int:
    need = required_hbm_gb(model)
    per_chip = ac.spec.capabilities.memory_gb or 16.0
    if need is None:
        return 1
    import math
    return max(1, math.ceil(need / per_chip))


def smallest_fitting_topology(ac: v1.AcceleratorClass, chips: int,
                              ) -> Optional[v1.TopologySpec]:
    """Smallest declared slice with >= chips; None when nothing fits (or
    the class declares no topologies)."""
    topos = sorted(ac.spec.capabilities.topologies, key=lambda t: t.chips)
    for t in topos:
        if t.chips >= chips:
            return t
    return None


def _resolve_pinned_topology(ac: v1.AcceleratorClass, pin: str,
                             ) -> v1.TopologySpec:
    """A topology pinned by the isvc must be one the accelerator offers
    (or at least parse) — never fabricate an unsupported slice shape."""
    for t in ac.spec.capabilities.topologies:
        if t.name == pin:
            return t
    topo = v1.parse_topology(pin)
    if topo is None:
        raise AcceleratorSelectionError(
            f"requested topology {pin!r} is not parseable")
    if ac.spec.capabilities.topologies:
        raise AcceleratorSelectionError(
            f"AcceleratorClass {ac.metadata.name!r} does not offer "
            f"topology {pin!r} (offers "
            f"{[t.name for t in ac.spec.capabilities.topologies]})")
    return topo


class AcceleratorSelector:
    def __init__(self, client: InMemoryClient):
        self.client = client

    # -- resolution (selector.go:46-105) --------------------------------

    def resolve(self, isvc: v1.InferenceService,
                runtime_spec: Optional[v1.ServingRuntimeSpec] = None,
                model: Optional[v1.BaseModelSpec] = None,
                component_override: Optional[str] = None) -> AcceleratorChoice:
        sel = isvc.spec.accelerator_selector or v1.AcceleratorSelector()
        # 1. component-level override wins
        if component_override:
            return self._by_name(component_override, sel, model)
        # 2. explicit class on the isvc
        if sel.accelerator_class:
            return self._by_name(sel.accelerator_class, sel, model)
        # 3. policy over filtered candidates
        candidates = self._candidates(runtime_spec, model)
        if not candidates:
            raise AcceleratorSelectionError(
                "no AcceleratorClass candidates match the runtime "
                "requirements and model constraints")
        policy = sel.policy or v1.AcceleratorSelectorPolicy.BEST_FIT
        choice = self._apply_policy(policy, candidates, model)
        if sel.topology:
            choice.topology = _resolve_pinned_topology(
                choice.accelerator, sel.topology)
            choice.chips = choice.topology.chips
        return choice

    def _by_name(self, name: str, sel: v1.AcceleratorSelector,
                 model: Optional[v1.BaseModelSpec]) -> AcceleratorChoice:
        ac = self.client.try_get(v1.AcceleratorClass, name)
        if ac is None:
            raise AcceleratorSelectionError(
                f"AcceleratorClass {name!r} not found")
        chips = chips_needed(model, ac)
        if sel.topology:
            topo = _resolve_pinned_topology(ac, sel.topology)
        else:
            topo = smallest_fitting_topology(ac, chips)
            if topo is None and ac.spec.capabilities.topologies:
                raise AcceleratorSelectionError(
                    f"AcceleratorClass {name!r}: model needs {chips} chips "
                    f"but the largest offered topology is "
                    f"{max(t.chips for t in ac.spec.capabilities.topologies)}"
                    f" chips")
        return AcceleratorChoice(ac, topo, topo.chips if topo else chips,
                                 reason="explicit")

    # -- candidate filtering (policy_helpers.go:60-177) ------------------

    def _candidates(self, runtime_spec: Optional[v1.ServingRuntimeSpec],
                    model: Optional[v1.BaseModelSpec],
                    ) -> List[v1.AcceleratorClass]:
        from .common import check_accelerator_requirements
        out = []
        req = runtime_spec.accelerator_requirements if runtime_spec else None
        for ac in self.client.list(v1.AcceleratorClass):
            caps = ac.spec.capabilities
            ok, _ = check_accelerator_requirements(req, ac)
            if not ok:
                continue
            # model must fit on the largest available slice
            need = required_hbm_gb(model)
            if need is not None and caps.topologies:
                max_chips = max(t.chips for t in caps.topologies)
                if (caps.memory_gb or 0) * max_chips < need:
                    continue
            out.append(ac)
        return out

    # -- policies --------------------------------------------------------

    def _apply_policy(self, policy: v1.AcceleratorSelectorPolicy,
                      candidates: List[v1.AcceleratorClass],
                      model: Optional[v1.BaseModelSpec]) -> AcceleratorChoice:
        if policy == v1.AcceleratorSelectorPolicy.BEST_FIT:
            return self._best_fit(candidates, model)
        if policy == v1.AcceleratorSelectorPolicy.CHEAPEST:
            return self._cheapest(candidates, model)
        if policy == v1.AcceleratorSelectorPolicy.MOST_CAPABLE:
            return self._most_capable(candidates, model)
        if policy == v1.AcceleratorSelectorPolicy.FIRST_AVAILABLE:
            return self._first_available(candidates, model)
        raise AcceleratorSelectionError(f"unknown policy {policy}")

    def _best_fit(self, candidates, model) -> AcceleratorChoice:
        """Least wasted HBM across the smallest fitting slice; TFLOPS as
        tiebreak (policy_helpers.go:178-319 re-based on slices)."""
        best: Optional[Tuple[float, float, AcceleratorChoice]] = None
        need = required_hbm_gb(model)
        for ac in candidates:
            chips = chips_needed(model, ac)
            topo = smallest_fitting_topology(ac, chips)
            total_chips = topo.chips if topo else chips
            total_hbm = (ac.spec.capabilities.memory_gb or 0) * total_chips
            waste = total_hbm - (need or 0)
            tflops = (ac.spec.capabilities.bf16_tflops or 0) * total_chips
            choice = AcceleratorChoice(ac, topo, total_chips, reason="BestFit")
            key = (waste, -tflops)
            if best is None or key < best[:2] or \
                    (key == best[:2] and choice.name < best[2].name):
                best = (*key, choice)
        return best[2]

    def _cheapest(self, candidates, model) -> AcceleratorChoice:
        best = None
        for ac in candidates:
            chips = chips_needed(model, ac)
            topo = smallest_fitting_topology(ac, chips)
            total = topo.chips if topo else chips
            cost = (ac.spec.cost.per_chip_hour_usd
                    if ac.spec.cost and ac.spec.cost.per_chip_hour_usd
                    else float("inf")) * total
            choice = AcceleratorChoice(ac, topo, total, reason="Cheapest")
            if best is None or cost < best[0] or \
                    (cost == best[0] and choice.name < best[1].name):
                best = (cost, choice)
        return best[1]

    def _most_capable(self, candidates, model) -> AcceleratorChoice:
        """Normalized per-chip tflops + hbm + bandwidth (':366-509')."""
        max_tf = max((c.spec.capabilities.bf16_tflops or 1) for c in candidates)
        max_mem = max((c.spec.capabilities.memory_gb or 1) for c in candidates)
        max_bw = max((c.spec.capabilities.memory_bandwidth_gbps or 1)
                     for c in candidates)
        best = None
        for ac in candidates:
            caps = ac.spec.capabilities
            score = ((caps.bf16_tflops or 0) / max_tf
                     + (caps.memory_gb or 0) / max_mem
                     + (caps.memory_bandwidth_gbps or 0) / max_bw)
            chips = chips_needed(model, ac)
            topo = smallest_fitting_topology(ac, chips)
            choice = AcceleratorChoice(ac, topo, topo.chips if topo else chips,
                                       reason="MostCapable")
            if best is None or score > best[0] or \
                    (score == best[0] and choice.name < best[1].name):
                best = (score, choice)
        return best[1]

    def _first_available(self, candidates, model) -> AcceleratorChoice:
        for ac in sorted(candidates, key=lambda a: a.metadata.name):
            if ac.status.node_count > 0:
                chips = chips_needed(model, ac)
                topo = smallest_fitting_topology(ac, chips)
                return AcceleratorChoice(ac, topo,
                                         topo.chips if topo else chips,
                                         reason="FirstAvailable")
        raise AcceleratorSelectionError(
            "no AcceleratorClass has matched nodes (FirstAvailable)")
