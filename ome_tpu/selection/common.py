"""Checks shared by the runtime and accelerator selection engines."""

from __future__ import annotations

from typing import Optional, Tuple

from ..apis import v1


def check_accelerator_requirements(
        req: Optional[v1.AcceleratorRequirements],
        ac: Optional[v1.AcceleratorClass]) -> Tuple[bool, str]:
    """Does an AcceleratorClass satisfy a runtime's AcceleratorRequirements?

    Single source of truth for the four requirement checks
    (servingruntime_types.go:233-265) so the runtime matcher and the
    accelerator candidate filter cannot drift apart.
    """
    if req is None or ac is None:
        return True, ""
    if req.accelerator_classes and \
            ac.metadata.name not in req.accelerator_classes:
        return False, (f"accelerator {ac.metadata.name} not in "
                       f"{req.accelerator_classes}")
    caps = ac.spec.capabilities
    if req.min_memory_gb and (caps.memory_gb or 0) < req.min_memory_gb:
        return False, (f"accelerator HBM {caps.memory_gb}GB < required "
                       f"{req.min_memory_gb}GB")
    missing = [f for f in req.required_features if f not in caps.features]
    if missing:
        return False, f"accelerator missing features {missing}"
    if req.topologies:
        have = {t.name for t in caps.topologies}
        if not have.intersection(req.topologies):
            return False, (f"no supported topology among {req.topologies} "
                           f"(accelerator offers {sorted(have)})")
    return True, ""
