"""Priority classes for multi-tenant scheduling.

Every request carries one of three classes — ``interactive``,
``standard``, ``batch`` — set via the ``priority`` payload field or
the ``X-OME-Priority`` header (header wins; default ``standard``).
The class drives four decisions end to end:

* **Slot allocation**: the scheduler's weighted deficit round-robin
  picks the next admitted request by class weight (scheduler.py).
* **Admission shedding**: under saturation the per-class queue-wait
  cap sheds the lowest class first — a batch flood 429s batch traffic
  before it can touch interactive admission (scheduler.submit).
* **Preemption**: KV-pressure victim selection ranks slots by class,
  lowest first (core.py `_preempt_victim` via `set_preempt_rank`).
* **Observability**: per-class metrics, reqlog schema v3, journal
  admit records (kill-resume restores the class), router counters,
  and the autoscale pressure signal keyed to the highest class.

This module is dependency-free (no jax, no engine imports) so the
router, chaos harness, and autoscale controller can share the enum
without pulling in the serving stack.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional

# Highest-priority first. This tuple is the ONLY legal label set for
# per-class metrics (enforced by the metrics-label-cardinality lint).
PRIORITY_CLASSES = ("interactive", "standard", "batch")

DEFAULT_PRIORITY = "standard"

# WDRR weights: an interactive token-quantum is 8x a batch one. Each
# class still gets a non-zero weight — batch is deprioritized, never
# starved (invariant 5 in the chaos harness).
DEFAULT_CLASS_WEIGHTS: Dict[str, int] = {
    "interactive": 8,
    "standard": 4,
    "batch": 1,
}

# Shedding/preemption order: lower level = victimized/shed first.
CLASS_LEVEL: Dict[str, int] = {
    "batch": 0,
    "standard": 1,
    "interactive": 2,
}

# Per-class queue-wait caps as multipliers of the scheduler's global
# max_queue_wait. standard keeps exactly the historical cap so a
# single-class workload admits identically with priority scheduling
# on or off; interactive is tighter (shed early rather than serve
# late), batch is looser (a deep batch backlog is the point).
DEFAULT_WAIT_CAP_FACTORS: Dict[str, float] = {
    "interactive": 0.25,
    "standard": 1.0,
    "batch": 4.0,
}


def coerce_priority(value: Optional[str],
                    default: str = DEFAULT_PRIORITY) -> str:
    """Validate a user-supplied priority class. None/"" take the
    default; anything outside PRIORITY_CLASSES raises ValueError
    (the server maps that to a 400, never a silent downgrade)."""
    if value is None or value == "":
        return default
    v = str(value).strip().lower()
    if v not in PRIORITY_CLASSES:
        raise ValueError(
            f"unknown priority class {value!r} "
            f"(expected one of {', '.join(PRIORITY_CLASSES)})")
    return v


def class_weights(overrides: Optional[Mapping[str, int]] = None
                  ) -> Dict[str, int]:
    """Full weight table with user overrides folded in; every class
    keeps a weight >= 1 so no class can be configured to starve."""
    w = dict(DEFAULT_CLASS_WEIGHTS)
    for cls, weight in (overrides or {}).items():
        cls = coerce_priority(cls)
        w[cls] = max(1, int(weight))
    return w


def class_wait_caps(max_queue_wait: float,
                    overrides: Optional[Mapping[str, float]] = None
                    ) -> Dict[str, float]:
    """Per-class queue-wait caps in seconds, derived from the global
    cap unless explicitly overridden (seconds, not factors)."""
    caps = {cls: max_queue_wait * DEFAULT_WAIT_CAP_FACTORS[cls]
            for cls in PRIORITY_CLASSES}
    for cls, cap in (overrides or {}).items():
        cls = coerce_priority(cls)
        caps[cls] = float(cap)
    return caps


def highest_class() -> str:
    return PRIORITY_CLASSES[0]


def parse_weight_spec(spec: str) -> Dict[str, int]:
    """Parse a CLI weight spec like ``interactive=8,standard=4,batch=1``
    (partial specs fine — unnamed classes keep defaults)."""
    out: Dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad class-weight spec segment {part!r} "
                "(expected class=weight)")
        cls, _, weight = part.partition("=")
        out[coerce_priority(cls)] = int(weight)
    return out
