"""SimFleet: N simulated replicas behind the REAL router and the
REAL autoscale controller, on one virtual clock.

The router here is the production ``router.server.Router`` — its
rendezvous/round-robin selection, per-backend circuit breakers, and
health sweep run unmodified; only the probe goes through the
in-process transport and the clock is the virtual one. Likewise the
controller is the production ``ScaleController``: its scrape windows,
per-class SLO keying, pressure formula, and hysteresis policy all run
against simulated /metrics bodies, driven by event-loop ticks instead
of a thread.

The client side mirrors the router HTTP handler's forwarding
discipline in miniature: pick with prefix affinity, fail over on
transport errors while the retry budget allows, never retry once a
status arrived, count draining answers as deliberate (note_draining,
no breaker penalty).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..autoscale.controller import ScaleController, SLOConfig
from ..autoscale.policy import PolicyConfig, PoolPolicy
from ..autoscale.pool import DrainRecord
from ..autoscale.replay import ReplayResult
from ..autoscale.scrape import SharedScraper
from ..autoscale.trace import TraceRequest
from ..router.server import Backend, RetryBudget, Router
from ..slo import FleetRollup, SLOSpec, sim_spec
from ..telemetry import Registry
from .clock import EventLoop, VirtualClock
from .costmodel import CostModel
from .durability import JournalSet, SimJournal
from .engine import SimEngine, SimRequest
from .transport import SimTransport

# fault-event kinds the schedule runner applies (bounded metric label
# cardinality by construction — see _build_metrics)
FAULT_KINDS = ("kill", "restart", "slow", "stuck", "partition",
               "heal")

_MAX_ATTEMPTS = 3  # pick + up to two failovers, like replay's fronts


class SimRouter(Router):
    """The real Router with its probe routed through the transport
    and its clock virtual. Everything else — selection policies,
    breakers, retry budget, gauges — is inherited production code."""

    def __init__(self, transport, clock, **kw):
        super().__init__([], clock=clock, **kw)
        self._transport = transport

    def _probe_backend(self, b: Backend):
        return self._transport.probe(b.url)


@dataclass
class SimPoolMember:
    name: str
    url: str
    engine: SimEngine
    started_at: float
    ready: bool = False
    draining: bool = False


class SimPool:
    """EnginePool's controller-facing surface over simulated
    replicas: size()/member_urls()/draining_count()/spawn()/
    drain_one()/engine_seconds()/journals()/drains — the duck type
    ScaleController.tick drives. Spawn readiness and drains happen in
    virtual time; registration follows the real pool's discipline
    (never enters rotation before it can serve, DELETEd only after
    the drain completed)."""

    def __init__(self, name: str, fleet: "SimFleet",
                 spawn_delay: float = 2.0,
                 warmup_delay: float = 0.0):
        self.name = name
        self.fleet = fleet
        self.spawn_delay = float(spawn_delay)
        # compile/warmup time on top of process spawn (from the cost
        # table's warmup_ms) — a cold replica is NOT ready the moment
        # the process exists, and autoscale scenarios price that
        self.warmup_delay = float(warmup_delay)
        self.members: List[SimPoolMember] = []
        self.drains: List[DrainRecord] = []
        self._seq = 0
        self._engine_seconds = 0.0

    # -- observation ---------------------------------------------------

    def size(self) -> int:
        return sum(1 for m in self.members if not m.draining)

    def member_urls(self) -> List[str]:
        return [m.url for m in self.members
                if m.ready and not m.draining]

    def draining_count(self) -> int:
        return sum(1 for m in self.members if m.draining)

    def journals(self) -> List[SimJournal]:
        """The pool's virtual journals (durability model,
        docs/simulation.md) — SimJournal objects rather than the real
        pool's file paths; the sim-side invariant checks fold them
        with the same admit/prog/fin logic chaos runs on files."""
        return [j for _, j in self.fleet.sim_journals.items()]

    def member(self, name: str) -> Optional[SimPoolMember]:
        for m in self.members:
            if m.name == name:
                return m
        return None

    def engine_seconds(self) -> float:
        now = self.fleet.clock.now()
        live = sum(now - m.started_at for m in self.members)
        return self._engine_seconds + live

    # -- scale up -------------------------------------------------------

    def spawn(self, delay: Optional[float] = None) -> SimPoolMember:
        """Provision one replica. ``delay`` overrides the cold-start
        time (spawn + warmup) for THIS spawn only — the scoped form
        of the old mutate-and-restore of ``spawn_delay``, which an
        exception mid-block could leave permanently zeroed."""
        self._seq += 1
        name = f"{self.name}{self._seq}"
        url = f"sim://{name}"
        member = SimPoolMember(
            name=name, url=url,
            engine=self.fleet.new_engine(name, url),
            started_at=self.fleet.clock.now())
        self.members.append(member)
        if delay is None:
            delay = self.spawn_delay + self.warmup_delay
        if delay > 0:
            self.fleet.loop.call_later(
                delay, lambda: self._ready(member))
        else:
            self._ready(member)
        return member

    def _ready(self, member: SimPoolMember) -> None:
        if member.draining or member.ready:
            return
        member.ready = True
        self.fleet.transport.register(member.url, member.engine)
        self.fleet.router.add_backend(member.url, pool=self.name)

    # -- scale down -----------------------------------------------------

    def drain_one(self) -> Optional[str]:
        victim: Optional[SimPoolMember] = None
        for m in reversed(self.members):
            if not m.draining:
                victim = m
                break
        if victim is None:
            return None
        victim.draining = True
        victim.engine.drain(
            on_drained=lambda: self._finish_drain(victim))
        return victim.name

    def _finish_drain(self, member: SimPoolMember) -> None:
        if member.ready:
            self.fleet.router.remove_backend(member.url)
            self.fleet.transport.forget(member.url)
        now = self.fleet.clock.now()
        if member in self.members:
            self.members.remove(member)
            self._engine_seconds += now - member.started_at
        self.drains.append(DrainRecord(
            name=member.name, url=member.url, ok=True))

    def join_drains(self, timeout: float = 0.0) -> None:
        pass  # drains complete inside the event loop

    def stop_all(self) -> None:
        pass


class SimFleet:
    """The harness: clock + loop + transport + router + pool (+
    optionally the controller), plus the open-loop client that plays
    a trace through the router."""

    def __init__(self, cost: CostModel, *, seed: int = 0,
                 policy: str = "round_robin",
                 health_interval: float = 2.0,
                 spawn_delay: float = 2.0,
                 durability: bool = True,
                 engine_kw: Optional[dict] = None):
        self.cost = cost
        self.seed = seed
        self.clock = VirtualClock()
        self.loop = EventLoop(self.clock)
        self.transport = SimTransport()
        self.engine_kw = dict(engine_kw or {})
        self.router = SimRouter(self.transport, self.clock,
                                policy=policy,
                                health_interval=health_interval)
        self.pool = SimPool("engine", self, spawn_delay=spawn_delay,
                            warmup_delay=cost.warmup_ms / 1000.0)
        self.controller: Optional[ScaleController] = None
        self.slo_rollup: Optional[FleetRollup] = None
        # one scrape result per backend per virtual instant, shared
        # by the controller and the SLO rollup (max_age 0.0: both
        # tick at the same virtual time, so same-instant is enough)
        self.scraper = SharedScraper(
            fetch_fn=self.transport.fetch_metrics,
            clock=self.clock.now, max_age=0.0)
        self.retry_budget = RetryBudget()
        self.results: List[ReplayResult] = []
        self._inflight: Dict[int, tuple] = {}
        # durability model: one virtual journal per engine NAME,
        # surviving kill() so a restart incarnation resumes it
        self.durability = bool(durability)
        self.sim_journals = JournalSet()
        # applied fault events, in virtual-time order — part of the
        # chaos report, so the determinism smoke byte-compares the
        # fault path too
        self.fault_log: List[dict] = []
        self.registry = Registry()
        self._g_virtual = self.registry.gauge(
            "ome_sim_virtual_seconds",
            "Current virtual-clock reading of the simulation")
        self._c_events = self.registry.counter(
            "ome_sim_events_total",
            "Events executed by the simulation loop")
        fam = self.registry.counter(
            "ome_sim_fault_events_total",
            "Chaos fault events applied by the schedule runner, by "
            "kind", labelnames=("kind",))
        self._c_faults = {k: fam.labels(kind=k) for k in FAULT_KINDS}

    # -- topology -------------------------------------------------------

    def new_engine(self, name: str, url: str,
                   incarnation: int = 1) -> SimEngine:
        journal = self.sim_journals.get(name) if self.durability \
            else None
        return SimEngine(
            name, self.clock, self.loop, self.cost,
            journal=journal, incarnation=incarnation,
            on_finish=lambda r, u=url: self._request_done(u, r),
            **self.engine_kw)

    def add_engines(self, n: int) -> None:
        """Pre-provision n replicas, ready immediately (t=0 fleets
        skip the spawn and warmup delays — there is nothing to
        warm)."""
        for _ in range(n):
            self.pool.spawn(delay=0.0)

    def add_controller(self, policy_cfg: PolicyConfig,
                       slo: Optional[SLOConfig] = None,
                       interval: float = 1.0) -> ScaleController:
        self.controller = ScaleController(
            {self.pool.name: self.pool},
            {self.pool.name: PoolPolicy(policy_cfg)},
            slo or SLOConfig(),
            fetch_fn=self.scraper.fetch,
            burn_fn=(self.slo_rollup.max_burn
                     if self.slo_rollup is not None else None),
            interval=interval, clock=self.clock)

        def tick():
            self.controller.tick()
            self.loop.call_later(interval, tick)
        self.loop.call_later(interval, tick)
        return self.controller

    def add_slo(self, spec: Optional[SLOSpec] = None,
                interval: float = 1.0) -> FleetRollup:
        """Start the fleet SLO rollup on the virtual event loop —
        the same FleetRollup.tick the real router runs on a wall-
        clock thread (docs/slo.md parity contract). Call BEFORE
        add_controller if the controller should take burn rate as a
        pressure input."""
        self.slo_rollup = FleetRollup(
            spec or sim_spec(), clock=self.clock.now,
            fetch_fn=self.scraper.fetch,
            backends_fn=self.router.backend_snapshot,
            registry=self.registry,
            local_samples_fn=self.router.registry.snapshot)

        def tick():
            self.slo_rollup.tick()
            self.loop.call_later(interval, tick)
        self.loop.call_later(interval, tick)
        return self.slo_rollup

    def start_health_loop(self) -> None:
        def sweep():
            self.router.check_health_once()
            self.loop.call_later(self.router.health_interval, sweep)
        self.loop.call_later(self.router.health_interval, sweep)

    def kill_backend(self, url: str) -> None:
        eng = self.transport.engine(url)
        if eng is not None:
            eng.kill()

    # -- chaos fault events (sim/faultplan.py schedules) ----------------

    def restart_engine(self, name: str) -> bool:
        """Respawn a killed replica in place: same name, same URL,
        same router Backend (whose breaker/health state carries over
        — the real recovery shape), incarnation bumped, virtual
        journal resumed with progress folded."""
        member = self.pool.member(name)
        if member is None or not member.engine.killed:
            return False
        eng = self.new_engine(
            name, member.url,
            incarnation=member.engine.incarnation + 1)
        member.engine = eng
        self.transport.register(member.url, eng)
        eng.resume_from_journal()
        return True

    def apply_fault(self, action: str, target: str,
                    param: float = 0.0) -> bool:
        """Apply one fault event NOW (schedules call this from
        event-loop callbacks via ``at_fault``). Unknown targets and
        no-op transitions (restarting a live engine) return False
        without touching anything — a shrinker dropping one half of
        a kill/restart pair must degrade gracefully, not crash the
        run."""
        member = self.pool.member(target)
        if member is None:
            return False
        applied = False
        eng = member.engine
        if action == "kill":
            if not eng.killed:
                eng.kill()
                applied = True
        elif action == "restart":
            applied = self.restart_engine(target)
        elif action == "slow":
            if not eng.killed:
                eng.set_slow(param if param > 1.0 else 2.0)
                applied = True
        elif action == "stuck":
            if not eng.killed:
                eng.set_stuck(True)
                applied = True
        elif action == "partition":
            self.transport.partition(member.url)
            applied = True
        elif action == "heal":
            self.transport.heal(member.url)
            if not eng.killed:
                eng.set_slow(1.0)
                eng.set_stuck(False)
            applied = True
        if applied:
            c = self._c_faults.get(action)
            if c is not None:
                c.inc()
            self.fault_log.append(
                {"t": round(self.clock.now(), 6), "action": action,
                 "target": target, "param": param})
        return applied

    def at_fault(self, at: float, action: str, target: str,
                 param: float = 0.0) -> None:
        """Schedule one fault event on the sim loop."""
        self.loop.call_at(
            at, lambda: self.apply_fault(action, target, param))

    def recover_all(self) -> None:
        """End-of-schedule recovery, mirroring the subprocess
        harness: every killed engine respawns fault-free and resumes
        its journal, every partition heals, every slow/stuck replica
        clears — then the settle window lets invariants quiesce."""
        for m in list(self.pool.members):
            self.transport.heal(m.url)
            if m.engine.killed:
                self.apply_fault("restart", m.name)
            else:
                m.engine.set_slow(1.0)
                m.engine.set_stuck(False)

    # -- the open-loop client -------------------------------------------

    def submit_trace(self, trace: List[TraceRequest]) -> None:
        for t in trace:
            self.loop.call_at(
                t.arrival, lambda t=t: self._client_submit(t))

    def _client_submit(self, t: TraceRequest,
                       failovers: int = 0,
                       exclude: Optional[set] = None) -> None:
        now = self.clock.now()
        cls = t.priority or "standard"
        result = ReplayResult(
            trace_id=t.trace_id, arrival=t.arrival,
            prompt=t.prompt or "", max_tokens=t.max_tokens,
            temperature=t.temperature, priority=t.priority,
            failovers=failovers)
        affinity = (t.prompt or t.prompt_text(self.seed))[:256]
        backend = self.router.pick(self.pool.name,
                                   affinity_key=affinity,
                                   exclude=exclude)
        if backend is None:
            result.status = 503
            result.error = "no backend available"
            self.results.append(result)
            self.router.note_outcome(cls, ok=False)
            return
        req = SimRequest(
            prompt_tokens=t.prompt_tokens,
            max_new_tokens=t.max_tokens,
            priority=t.priority or "standard",
            temperature=t.temperature, trace_id=t.trace_id,
            arrival=t.arrival, prompt=affinity)
        try:
            status = self.transport.submit(backend.url, req)
        except OSError as e:
            self.router.note_result(backend, ok=False)
            if (failovers + 1 < _MAX_ATTEMPTS
                    and self.retry_budget.withdraw()):
                ex = set(exclude or ())
                ex.add(backend.url)
                self._client_submit(t, failovers + 1, ex)
            else:
                result.status = 502
                result.error = f"{type(e).__name__}: {e}"
                self.results.append(result)
                self.router.note_outcome(cls, ok=False)
            return
        self.retry_budget.deposit()
        if status == 503:
            # deliberate drain answer: out of rotation, no penalty
            self.router.note_draining(backend)
            if failovers + 1 < _MAX_ATTEMPTS:
                ex = set(exclude or ())
                ex.add(backend.url)
                self._client_submit(t, failovers + 1, ex)
            else:
                result.status = 503
                result.error = "backend draining"
                self.results.append(result)
                self.router.note_outcome(cls, ok=False)
            return
        if status != 200:
            result.status = status
            retry = self.transport.retry_after(backend.url)
            result.error = (f"admission answered {status}"
                            + (f" (retry after {retry}s)"
                               if retry is not None else ""))
            self.results.append(result)
            # an answered shed (429) is availability-good; only
            # server-side failures burn the budget (docs/slo.md)
            self.router.note_outcome(cls, ok=status < 500)
            return
        self.router.adjust_inflight(backend, 1)
        self._inflight[id(req)] = (backend, result, now)

    def _request_done(self, url: str, req: SimRequest) -> None:
        entry = self._inflight.pop(id(req), None)
        if entry is None:
            return
        backend, result, t0 = entry
        self.router.adjust_inflight(backend, -1)
        ok = req.finish_reason == "stop"
        self.router.note_result(backend, ok=ok)
        self.router.note_outcome(req.priority, ok=ok)
        result.status = req.status
        result.output_tokens = req.output_tokens
        result.finish_reason = req.finish_reason
        if not ok:
            result.error = "backend died mid-request"
        if req.first_token_at is not None:
            result.ttft_s = round(req.first_token_at - t0, 6)
        if req.finished_at is not None:
            result.e2e_s = round(req.finished_at - t0, 6)
            if req.first_token_at is not None \
                    and req.output_tokens > 1:
                result.tpot_s = round(
                    (req.finished_at - req.first_token_at)
                    / (req.output_tokens - 1), 6)
        self.results.append(result)

    # -- running --------------------------------------------------------

    def run_until(self, t_end: float) -> None:
        self.loop.run_until(t_end)
        self._g_virtual.set(self.clock.now())
        self._c_events.inc(self.loop.executed - self._c_events.value)

    def sim_stats(self) -> dict:
        stats = {"virtual_seconds": round(self.clock.now(), 6),
                 "events": self.loop.executed,
                 "engines_spawned": self.pool._seq,
                 "engine_seconds": round(
                     self.pool.engine_seconds(), 3)}
        if self.fault_log:
            stats["fault_events_applied"] = len(self.fault_log)
            stats["incarnations"] = sum(
                m.engine.incarnation for m in self.pool.members)
        return stats
