"""SimFleet: N simulated replicas behind the REAL router and the
REAL autoscale controller, on one virtual clock.

The router here is the production ``router.server.Router`` — its
rendezvous/round-robin selection, per-backend circuit breakers, and
health sweep run unmodified; only the probe goes through the
in-process transport and the clock is the virtual one. Likewise the
controller is the production ``ScaleController``: its scrape windows,
per-class SLO keying, pressure formula, and hysteresis policy all run
against simulated /metrics bodies, driven by event-loop ticks instead
of a thread.

The client side mirrors the router HTTP handler's forwarding
discipline in miniature: pick with prefix affinity, fail over on
transport errors while the retry budget allows, never retry once a
status arrived, count draining answers as deliberate (note_draining,
no breaker penalty).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..autoscale.controller import ScaleController, SLOConfig
from ..autoscale.policy import PolicyConfig, PoolPolicy
from ..autoscale.pool import DrainRecord
from ..autoscale.replay import ReplayResult
from ..autoscale.trace import TraceRequest
from ..router.server import Backend, RetryBudget, Router
from ..telemetry import Registry
from .clock import EventLoop, VirtualClock
from .costmodel import CostModel
from .engine import SimEngine, SimRequest
from .transport import SimTransport

_MAX_ATTEMPTS = 3  # pick + up to two failovers, like replay's fronts


class SimRouter(Router):
    """The real Router with its probe routed through the transport
    and its clock virtual. Everything else — selection policies,
    breakers, retry budget, gauges — is inherited production code."""

    def __init__(self, transport, clock, **kw):
        super().__init__([], clock=clock, **kw)
        self._transport = transport

    def _probe_backend(self, b: Backend):
        return self._transport.probe(b.url)


@dataclass
class SimPoolMember:
    name: str
    url: str
    engine: SimEngine
    started_at: float
    ready: bool = False
    draining: bool = False


class SimPool:
    """EnginePool's controller-facing surface over simulated
    replicas: size()/member_urls()/draining_count()/spawn()/
    drain_one()/engine_seconds()/journals()/drains — the duck type
    ScaleController.tick drives. Spawn readiness and drains happen in
    virtual time; registration follows the real pool's discipline
    (never enters rotation before it can serve, DELETEd only after
    the drain completed)."""

    def __init__(self, name: str, fleet: "SimFleet",
                 spawn_delay: float = 2.0):
        self.name = name
        self.fleet = fleet
        self.spawn_delay = float(spawn_delay)
        self.members: List[SimPoolMember] = []
        self.drains: List[DrainRecord] = []
        self._seq = 0
        self._engine_seconds = 0.0

    # -- observation ---------------------------------------------------

    def size(self) -> int:
        return sum(1 for m in self.members if not m.draining)

    def member_urls(self) -> List[str]:
        return [m.url for m in self.members
                if m.ready and not m.draining]

    def draining_count(self) -> int:
        return sum(1 for m in self.members if m.draining)

    def journals(self) -> List:
        return []  # durability is out of sim scope (docs/simulation.md)

    def engine_seconds(self) -> float:
        now = self.fleet.clock.now()
        live = sum(now - m.started_at for m in self.members)
        return self._engine_seconds + live

    # -- scale up -------------------------------------------------------

    def spawn(self) -> SimPoolMember:
        self._seq += 1
        name = f"{self.name}{self._seq}"
        url = f"sim://{name}"
        member = SimPoolMember(
            name=name, url=url,
            engine=self.fleet.new_engine(name, url),
            started_at=self.fleet.clock.now())
        self.members.append(member)
        if self.spawn_delay > 0:
            self.fleet.loop.call_later(
                self.spawn_delay, lambda: self._ready(member))
        else:
            self._ready(member)
        return member

    def _ready(self, member: SimPoolMember) -> None:
        if member.draining or member.ready:
            return
        member.ready = True
        self.fleet.transport.register(member.url, member.engine)
        self.fleet.router.add_backend(member.url, pool=self.name)

    # -- scale down -----------------------------------------------------

    def drain_one(self) -> Optional[str]:
        victim: Optional[SimPoolMember] = None
        for m in reversed(self.members):
            if not m.draining:
                victim = m
                break
        if victim is None:
            return None
        victim.draining = True
        victim.engine.drain(
            on_drained=lambda: self._finish_drain(victim))
        return victim.name

    def _finish_drain(self, member: SimPoolMember) -> None:
        if member.ready:
            self.fleet.router.remove_backend(member.url)
            self.fleet.transport.forget(member.url)
        now = self.fleet.clock.now()
        if member in self.members:
            self.members.remove(member)
            self._engine_seconds += now - member.started_at
        self.drains.append(DrainRecord(
            name=member.name, url=member.url, ok=True))

    def join_drains(self, timeout: float = 0.0) -> None:
        pass  # drains complete inside the event loop

    def stop_all(self) -> None:
        pass


class SimFleet:
    """The harness: clock + loop + transport + router + pool (+
    optionally the controller), plus the open-loop client that plays
    a trace through the router."""

    def __init__(self, cost: CostModel, *, seed: int = 0,
                 policy: str = "round_robin",
                 health_interval: float = 2.0,
                 spawn_delay: float = 2.0,
                 engine_kw: Optional[dict] = None):
        self.cost = cost
        self.seed = seed
        self.clock = VirtualClock()
        self.loop = EventLoop(self.clock)
        self.transport = SimTransport()
        self.engine_kw = dict(engine_kw or {})
        self.router = SimRouter(self.transport, self.clock,
                                policy=policy,
                                health_interval=health_interval)
        self.pool = SimPool("engine", self, spawn_delay=spawn_delay)
        self.controller: Optional[ScaleController] = None
        self.retry_budget = RetryBudget()
        self.results: List[ReplayResult] = []
        self._inflight: Dict[int, tuple] = {}
        self.registry = Registry()
        self._g_virtual = self.registry.gauge(
            "ome_sim_virtual_seconds",
            "Current virtual-clock reading of the simulation")
        self._c_events = self.registry.counter(
            "ome_sim_events_total",
            "Events executed by the simulation loop")

    # -- topology -------------------------------------------------------

    def new_engine(self, name: str, url: str) -> SimEngine:
        return SimEngine(
            name, self.clock, self.loop, self.cost,
            on_finish=lambda r, u=url: self._request_done(u, r),
            **self.engine_kw)

    def add_engines(self, n: int) -> None:
        """Pre-provision n replicas, ready immediately (t=0 fleets
        skip the spawn delay — there is nothing to warm)."""
        delay, self.pool.spawn_delay = self.pool.spawn_delay, 0.0
        try:
            for _ in range(n):
                self.pool.spawn()
        finally:
            self.pool.spawn_delay = delay

    def add_controller(self, policy_cfg: PolicyConfig,
                       slo: Optional[SLOConfig] = None,
                       interval: float = 1.0) -> ScaleController:
        self.controller = ScaleController(
            {self.pool.name: self.pool},
            {self.pool.name: PoolPolicy(policy_cfg)},
            slo or SLOConfig(),
            fetch_fn=self.transport.fetch_metrics,
            interval=interval, clock=self.clock)

        def tick():
            self.controller.tick()
            self.loop.call_later(interval, tick)
        self.loop.call_later(interval, tick)
        return self.controller

    def start_health_loop(self) -> None:
        def sweep():
            self.router.check_health_once()
            self.loop.call_later(self.router.health_interval, sweep)
        self.loop.call_later(self.router.health_interval, sweep)

    def kill_backend(self, url: str) -> None:
        eng = self.transport.engine(url)
        if eng is not None:
            eng.kill()

    # -- the open-loop client -------------------------------------------

    def submit_trace(self, trace: List[TraceRequest]) -> None:
        for t in trace:
            self.loop.call_at(
                t.arrival, lambda t=t: self._client_submit(t))

    def _client_submit(self, t: TraceRequest,
                       failovers: int = 0,
                       exclude: Optional[set] = None) -> None:
        now = self.clock.now()
        result = ReplayResult(
            trace_id=t.trace_id, arrival=t.arrival,
            prompt=t.prompt or "", max_tokens=t.max_tokens,
            temperature=t.temperature, priority=t.priority,
            failovers=failovers)
        affinity = (t.prompt or t.prompt_text(self.seed))[:256]
        backend = self.router.pick(self.pool.name,
                                   affinity_key=affinity,
                                   exclude=exclude)
        if backend is None:
            result.status = 503
            result.error = "no backend available"
            self.results.append(result)
            return
        req = SimRequest(
            prompt_tokens=t.prompt_tokens,
            max_new_tokens=t.max_tokens,
            priority=t.priority or "standard",
            temperature=t.temperature, trace_id=t.trace_id,
            arrival=t.arrival, prompt=affinity)
        try:
            status = self.transport.submit(backend.url, req)
        except OSError as e:
            self.router.note_result(backend, ok=False)
            if (failovers + 1 < _MAX_ATTEMPTS
                    and self.retry_budget.withdraw()):
                ex = set(exclude or ())
                ex.add(backend.url)
                self._client_submit(t, failovers + 1, ex)
            else:
                result.status = 502
                result.error = f"{type(e).__name__}: {e}"
                self.results.append(result)
            return
        self.retry_budget.deposit()
        if status == 503:
            # deliberate drain answer: out of rotation, no penalty
            self.router.note_draining(backend)
            if failovers + 1 < _MAX_ATTEMPTS:
                ex = set(exclude or ())
                ex.add(backend.url)
                self._client_submit(t, failovers + 1, ex)
            else:
                result.status = 503
                result.error = "backend draining"
                self.results.append(result)
            return
        if status != 200:
            result.status = status
            result.error = f"admission answered {status}"
            self.results.append(result)
            return
        self.router.adjust_inflight(backend, 1)
        self._inflight[id(req)] = (backend, result, now)

    def _request_done(self, url: str, req: SimRequest) -> None:
        entry = self._inflight.pop(id(req), None)
        if entry is None:
            return
        backend, result, t0 = entry
        self.router.adjust_inflight(backend, -1)
        ok = req.finish_reason == "stop"
        self.router.note_result(backend, ok=ok)
        result.status = req.status
        result.output_tokens = req.output_tokens
        result.finish_reason = req.finish_reason
        if not ok:
            result.error = "backend died mid-request"
        if req.first_token_at is not None:
            result.ttft_s = round(req.first_token_at - t0, 6)
        if req.finished_at is not None:
            result.e2e_s = round(req.finished_at - t0, 6)
            if req.first_token_at is not None \
                    and req.output_tokens > 1:
                result.tpot_s = round(
                    (req.finished_at - req.first_token_at)
                    / (req.output_tokens - 1), 6)
        self.results.append(result)

    # -- running --------------------------------------------------------

    def run_until(self, t_end: float) -> None:
        self.loop.run_until(t_end)
        self._g_virtual.set(self.clock.now())
        self._c_events.inc(self.loop.executed - self._c_events.value)

    def sim_stats(self) -> dict:
        return {"virtual_seconds": round(self.clock.now(), 6),
                "events": self.loop.executed,
                "engines_spawned": self.pool._seq,
                "engine_seconds": round(
                    self.pool.engine_seconds(), 3)}
