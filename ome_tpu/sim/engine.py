"""SimEngine: one simulated replica — real control plane, modeled device.

The admission surface, the WDRR pending queue (the REAL
engine/scheduler.ClassQueues, including its deficit rotation and
per-class bounds), KV-page accounting, drain semantics, and the
/metrics exposition are the production code paths or faithful
transcriptions of their formulas. What is replaced is exactly the
device: instead of dispatching a compiled decode program, a chunk
event advances every active slot by ``fused_k`` iterations after
``CostModel.step_ms`` virtual milliseconds.

Metric families reuse the REAL engine names and bucket layouts
(``ome_engine_ttft_seconds``, ``ome_engine_queue_wait_seconds``, the
per-class pair, the queue-depth and KV-utilization gauges), so the
autoscale controller's scrape loop — windows, per-class SLO keying,
pressure formula — runs UNMODIFIED against a simulated replica.

Everything here is event-driven on the injected virtual clock; no
code on this path may read wall time (the sim-wall-clock lint rule
enforces that transitively).
"""

from __future__ import annotations

import math
import queue
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..engine.scheduler import ClassQueues
from ..priority import DEFAULT_PRIORITY, PRIORITY_CLASSES
from ..telemetry import Registry
from .clock import EventLoop, VirtualClock
from .costmodel import CostModel

# same buckets as telemetry.registry DEFAULT_BUCKETS / the real
# engine's latency histograms — the controller's windowed-quantile
# estimator interpolates inside these exact bounds on both sides of
# the fidelity gate
_LATENCY_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0)


@dataclass
class SimRequest:
    """The simulator's request record. Carries the same lifecycle
    timestamps as engine/scheduler.Request (created -> scheduled ->
    first token -> finished) but in VIRTUAL seconds, set directly by
    events — never via Request.emit/finish, which read wall time."""

    prompt_tokens: int
    max_new_tokens: int
    priority: str = DEFAULT_PRIORITY
    temperature: float = 0.0
    trace_id: Optional[str] = None
    arrival: float = 0.0
    prompt: str = ""
    # lifecycle (virtual seconds)
    created: float = 0.0
    scheduled_at: Optional[float] = None
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    output_tokens: int = 0
    finish_reason: Optional[str] = None
    status: Optional[int] = None
    failovers: int = 0
    # device-side progress in fractional tokens (spec decode yields
    # >1 token per iteration in expectation)
    _progress: float = field(default=0.0, repr=False)
    _pages: int = field(default=0, repr=False)


class SimEngine:
    """One simulated serving replica on a shared clock + event loop.

    ``classes``/``class_weights`` pass straight through to the real
    ClassQueues — the WDRR fairness scenarios instantiate hundreds of
    tenant classes against the production pick loop.
    """

    def __init__(self, name: str, clock: VirtualClock, loop: EventLoop,
                 cost: CostModel, *,
                 max_slots: int = 8, kv_pages: int = 256,
                 kv_block: int = 16, max_pending: int = 512,
                 fused_k: int = 1, spec_accept: float = 0.0,
                 classes=None, class_weights=None,
                 on_finish: Optional[Callable[["SimRequest"], None]]
                 = None):
        self.name = name
        self.clock = clock
        self.loop = loop
        self.cost = cost
        self.max_slots = max(int(max_slots), 1)
        self.kv_pages = max(int(kv_pages), 1)
        self.kv_block = max(int(kv_block), 1)
        self.fused_k = max(int(fused_k), 1)
        self.spec_accept = float(spec_accept)
        self.on_finish = on_finish
        self.pending = ClassQueues(max_pending, weights=class_weights,
                                   classes=classes)
        self.active: List[SimRequest] = []
        self.pages_used = 0
        # one popped-but-unplaceable request parks here until pages
        # free up, preserving the WDRR pick the queue already made
        self._stalled: Optional[SimRequest] = None
        self.draining = False
        self.killed = False
        self._on_drained: Optional[Callable[[], None]] = None
        self._chunk_event = None
        self.stats: Dict[str, int] = {
            "requests_total": 0, "rejected_total": 0,
            "tokens_generated_total": 0, "chunks_total": 0}
        self._per_class_tokens: Dict[str, int] = {}
        self._build_metrics()

    # -- metrics (the controller's scrape surface) ---------------------

    def _build_metrics(self) -> None:
        R = self.registry = Registry()
        self._c_requests = R.counter(
            "ome_engine_requests_total",
            "Requests submitted to the scheduler")
        self._c_rejected = R.counter(
            "ome_engine_rejected_total",
            "Requests rejected at admission (429)")
        self._c_tokens = R.counter(
            "ome_engine_tokens_generated_total",
            "Decode tokens emitted across requests")
        self._h_ttft = R.histogram(
            "ome_engine_ttft_seconds",
            "Time to first token (admission to first emit)",
            buckets=_LATENCY_BUCKETS)
        self._h_queue_wait = R.histogram(
            "ome_engine_queue_wait_seconds",
            "Seconds between admission and first decode slot",
            buckets=_LATENCY_BUCKETS)
        self._h_e2e = R.histogram(
            "ome_engine_e2e_seconds",
            "End-to-end request seconds (admission to finish)",
            buckets=_LATENCY_BUCKETS)
        self._g_depth = R.gauge(
            "ome_engine_queue_depth", "Pending-queue depth")
        self._g_active = R.gauge(
            "ome_engine_active_slots", "Occupied decode slots")
        self._g_kv = R.gauge(
            "ome_engine_kv_block_utilization_ratio",
            "Fraction of the paged-KV block pool in use")
        # per-class children pre-created for the fixed priority enum
        # ONLY (bounded label cardinality by construction); tenant-
        # class scenarios beyond the enum get no per-class children
        def _by_class(fam):
            return {c: fam.labels(**{"class": c})
                    for c in PRIORITY_CLASSES}
        self._h_class_ttft = _by_class(R.histogram(
            "ome_engine_class_ttft_seconds",
            "Time to first token, by priority class",
            labelnames=("class",), buckets=_LATENCY_BUCKETS))
        self._h_class_queue_wait = _by_class(R.histogram(
            "ome_engine_class_queue_wait_seconds",
            "Admission-to-first-slot seconds, by priority class",
            labelnames=("class",), buckets=_LATENCY_BUCKETS))
        self._c_sim_chunks = R.counter(
            "ome_sim_chunks_total",
            "Fused decode chunks executed by the simulated device")

    def metrics_text(self) -> str:
        """The /metrics body a scrape would see, gauges refreshed at
        scrape time exactly like the real engine's update_gauges."""
        self._g_depth.set(self.pending.qsize()
                          + (1 if self._stalled is not None else 0))
        self._g_active.set(len(self.active))
        self._g_kv.set(self.pages_used / self.kv_pages)
        return self.registry.render()

    def ready_info(self) -> dict:
        return {"ready": not self.draining and not self.killed,
                "draining": self.draining}

    # -- admission (mirrors scheduler.submit's shed ladder) ------------

    def submit(self, req: SimRequest) -> int:
        """Admit a request; returns the HTTP-ish status the real
        serve layer would answer (200 admitted, 503 draining, 429
        overloaded)."""
        if self.killed:
            raise OSError(f"sim engine {self.name} is down")
        if self.draining:
            return 503
        req.created = self.clock.now()
        try:
            self.pending.put_nowait(req)
        except queue.Full:
            self.stats["rejected_total"] += 1
            self._c_rejected.inc()
            return 429
        self.stats["requests_total"] += 1
        self._c_requests.inc()
        self._admit()
        return 200

    def _request_pages(self, req: SimRequest) -> int:
        return max(1, math.ceil(
            (req.prompt_tokens + req.max_new_tokens) / self.kv_block))

    def _admit(self) -> None:
        """Fill free slots from the WDRR queue while KV pages last.
        Each admitted request schedules its own prefill-completion
        event; decode chunks pick the slot up afterwards."""
        if self.killed:
            return
        now = self.clock.now()
        while len(self.active) < self.max_slots:
            req = self._stalled
            self._stalled = None
            if req is None:
                try:
                    req = self.pending.get_nowait()
                except queue.Empty:
                    break
            pages = self._request_pages(req)
            if self.pages_used + pages > self.kv_pages:
                self._stalled = req  # wait for a slot to free pages
                break
            req._pages = pages
            self.pages_used += pages
            req.scheduled_at = now
            wait = now - req.created
            self._h_queue_wait.observe(wait)
            hq = self._h_class_queue_wait.get(req.priority)
            if hq is not None:
                hq.observe(wait)
            self.loop.call_later(
                self.cost.prefill_ms(req.prompt_tokens) / 1000.0,
                lambda r=req: self._activate(r))
            self.active.append(req)
        self._maybe_drained()

    def _activate(self, req: SimRequest) -> None:
        """Prefill finished: the first token emits, the slot joins
        the decode batch from the next chunk on."""
        if self.killed or req.finish_reason is not None:
            return
        now = self.clock.now()
        req.first_token_at = now
        req.output_tokens = 1
        req._progress = 1.0
        self.stats["tokens_generated_total"] += 1
        self._c_tokens.inc()
        ttft = now - req.created
        self._h_ttft.observe(ttft)
        ht = self._h_class_ttft.get(req.priority)
        if ht is not None:
            ht.observe(ttft)
        if req.max_new_tokens <= 1:
            self._finish(req, "stop")
        self._schedule_chunk()

    # -- the modeled device --------------------------------------------

    def _schedule_chunk(self) -> None:
        if self._chunk_event is not None or self.killed:
            return
        batch = [r for r in self.active if r.first_token_at is not None
                 and r.finish_reason is None]
        if not batch:
            return
        pages = float(sum(r._pages for r in batch))
        dt = self.cost.step_ms(len(batch), pages=pages,
                               fused_k=self.fused_k,
                               spec_accept=self.spec_accept) / 1000.0
        self._chunk_event = self.loop.call_later(dt, self._run_chunk)

    def _run_chunk(self) -> None:
        self._chunk_event = None
        if self.killed:
            return
        self.stats["chunks_total"] += 1
        self._c_sim_chunks.inc()
        gained = self.fused_k * self.cost.tokens_per_iteration(
            self.spec_accept)
        for req in list(self.active):
            if req.first_token_at is None \
                    or req.finish_reason is not None:
                continue
            before = req.output_tokens
            req._progress = min(req._progress + gained,
                                float(req.max_new_tokens))
            req.output_tokens = int(req._progress)
            emitted = req.output_tokens - before
            if emitted > 0:
                self.stats["tokens_generated_total"] += emitted
                self._c_tokens.inc(emitted)
                tc = self._per_class_tokens
                tc[req.priority] = tc.get(req.priority, 0) + emitted
            if req.output_tokens >= req.max_new_tokens:
                self._finish(req, "stop")
        self._admit()
        self._schedule_chunk()

    def _finish(self, req: SimRequest, reason: str) -> None:
        if req.finish_reason is not None:
            return
        req.finish_reason = reason
        req.status = 200 if reason == "stop" else 599
        req.finished_at = self.clock.now()
        self._h_e2e.observe(req.finished_at - req.created)
        if req in self.active:
            self.active.remove(req)
            self.pages_used -= req._pages
        if self.on_finish is not None:
            self.on_finish(req)
        self._maybe_drained()

    # -- lifecycle ------------------------------------------------------

    def drain(self, on_drained: Optional[Callable[[], None]]
              = None) -> None:
        """Graceful drain: stop admitting, finish in-flight + queued
        work, then fire ``on_drained`` (the SimPool's deregistration
        hook) — the same contract as the real SIGTERM drain."""
        self.draining = True
        self._on_drained = on_drained
        self._maybe_drained()

    def _maybe_drained(self) -> None:
        if (self.draining and not self.active
                and self.pending.empty() and self._stalled is None
                and self._on_drained is not None):
            cb, self._on_drained = self._on_drained, None
            cb()

    def kill(self) -> None:
        """Abrupt death (chaos): every in-flight and queued request
        fails; probes and scrapes start raising at the transport."""
        self.killed = True
        victims = list(self.active)
        if self._stalled is not None:
            victims.append(self._stalled)
            self._stalled = None
        while True:
            try:
                victims.append(self.pending.get_nowait())
            except queue.Empty:
                break
        self.active = []
        self.pages_used = 0
        for req in victims:
            req.finish_reason = "killed"
            req.status = 599
            req.finished_at = self.clock.now()
            if self.on_finish is not None:
                self.on_finish(req)

    def tokens_by_class(self) -> Dict[str, int]:
        """Decode tokens served per class (ALL classes, including
        tenant classes beyond the metric enum) — the WDRR fairness
        scenarios' measurement surface."""
        return dict(self._per_class_tokens)
