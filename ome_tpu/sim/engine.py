"""SimEngine: one simulated replica — real control plane, modeled device.

The admission surface, the WDRR pending queue (the REAL
engine/scheduler.ClassQueues, including its deficit rotation and
per-class bounds), KV-page accounting, drain semantics, and the
/metrics exposition are the production code paths or faithful
transcriptions of their formulas. What is replaced is exactly the
device: instead of dispatching a compiled decode program, a chunk
event advances every active slot by ``fused_k`` iterations after
``CostModel.step_ms`` virtual milliseconds.

Metric families reuse the REAL engine names and bucket layouts
(``ome_engine_ttft_seconds``, ``ome_engine_queue_wait_seconds``, the
per-class pair, the queue-depth and KV-utilization gauges), so the
autoscale controller's scrape loop — windows, per-class SLO keying,
pressure formula — runs UNMODIFIED against a simulated replica.

Everything here is event-driven on the injected virtual clock; no
code on this path may read wall time (the sim-wall-clock lint rule
enforces that transitively).
"""

from __future__ import annotations

import math
import queue
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..engine.scheduler import ClassQueues
from ..priority import (DEFAULT_PRIORITY, PRIORITY_CLASSES,
                        class_wait_caps)
from ..telemetry import Registry
from .clock import EventLoop, VirtualClock
from .costmodel import CostModel
from .durability import SimJournal

# same buckets as telemetry.registry DEFAULT_BUCKETS / the real
# engine's latency histograms — the controller's windowed-quantile
# estimator interpolates inside these exact bounds on both sides of
# the fidelity gate
_LATENCY_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0)


@dataclass
class SimRequest:
    """The simulator's request record. Carries the same lifecycle
    timestamps as engine/scheduler.Request (created -> scheduled ->
    first token -> finished) but in VIRTUAL seconds, set directly by
    events — never via Request.emit/finish, which read wall time."""

    prompt_tokens: int
    max_new_tokens: int
    priority: str = DEFAULT_PRIORITY
    temperature: float = 0.0
    trace_id: Optional[str] = None
    arrival: float = 0.0
    prompt: str = ""
    # lifecycle (virtual seconds)
    created: float = 0.0
    scheduled_at: Optional[float] = None
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    output_tokens: int = 0
    finish_reason: Optional[str] = None
    status: Optional[int] = None
    failovers: int = 0
    # device-side progress in fractional tokens (spec decode yields
    # >1 token per iteration in expectation)
    _progress: float = field(default=0.0, repr=False)
    _pages: int = field(default=0, repr=False)
    # journal id: assigned at admit, carried across restart-resume (a
    # resumed clone keeps the jid so fin tombstones the original
    # admit record, never a duplicate admit)
    _jid: Optional[int] = field(default=None, repr=False)


class SimEngine:
    """One simulated serving replica on a shared clock + event loop.

    ``classes``/``class_weights`` pass straight through to the real
    ClassQueues — the WDRR fairness scenarios instantiate hundreds of
    tenant classes against the production pick loop.
    """

    def __init__(self, name: str, clock: VirtualClock, loop: EventLoop,
                 cost: CostModel, *,
                 max_slots: int = 8, kv_pages: int = 256,
                 kv_block: int = 16, max_pending: int = 512,
                 fused_k: int = 1, spec_accept: float = 0.0,
                 classes=None, class_weights=None,
                 max_queue_wait: Optional[float] = 30.0,
                 journal: Optional[SimJournal] = None,
                 incarnation: int = 1,
                 on_finish: Optional[Callable[["SimRequest"], None]]
                 = None):
        self.name = name
        self.clock = clock
        self.loop = loop
        self.cost = cost
        self.max_slots = max(int(max_slots), 1)
        self.kv_pages = max(int(kv_pages), 1)
        self.kv_block = max(int(kv_block), 1)
        self.fused_k = max(int(fused_k), 1)
        self.spec_accept = float(spec_accept)
        self.on_finish = on_finish
        self.pending = ClassQueues(max_pending, weights=class_weights,
                                   classes=classes)
        self.active: List[SimRequest] = []
        self.pages_used = 0
        # one popped-but-unplaceable request parks here until pages
        # free up, preserving the WDRR pick the queue already made
        self._stalled: Optional[SimRequest] = None
        self.draining = False
        self.killed = False
        self._on_drained: Optional[Callable[[], None]] = None
        self._chunk_event = None
        self._chunk_dt = 0.0
        # durability (docs/simulation.md): the virtual WAL this
        # incarnation journals into; it outlives kill() so a restart
        # incarnation can resume_from_journal
        self.journal = journal
        self.incarnation = max(int(incarnation), 1)
        # chaos fault state: step-time inflation (slow replica) and a
        # full decode stall (stuck replica); both leave admission and
        # the metrics surface serving, exactly like a wedged device
        self.slow_factor = 1.0
        self.stuck = False
        # admission control (scheduler.submit's shed ladder): reject
        # 429 when the estimated queue wait exceeds the per-class
        # cap; None disables (saturation scenarios drive the queue as
        # the regime under test). EWMAs mirror the real scheduler's
        # alphas: 0.1 on step seconds, 0.2 on per-request steps.
        self.max_queue_wait = max_queue_wait
        self.class_wait_caps = (class_wait_caps(max_queue_wait)
                                if max_queue_wait is not None else {})
        self._ewma_step_s: Optional[float] = None
        self._ewma_req_steps: Optional[float] = None
        self.stats: Dict[str, int] = {
            "requests_total": 0, "rejected_total": 0,
            "tokens_generated_total": 0, "chunks_total": 0,
            "resumed_total": 0}
        self._per_class_tokens: Dict[str, int] = {}
        self._build_metrics()

    # -- metrics (the controller's scrape surface) ---------------------

    def _build_metrics(self) -> None:
        R = self.registry = Registry()
        self._c_requests = R.counter(
            "ome_engine_requests_total",
            "Requests submitted to the scheduler")
        self._c_rejected = R.counter(
            "ome_engine_rejected_total",
            "Requests rejected at admission (429)")
        self._c_tokens = R.counter(
            "ome_engine_tokens_generated_total",
            "Decode tokens emitted across requests")
        self._h_ttft = R.histogram(
            "ome_engine_ttft_seconds",
            "Time to first token (admission to first emit)",
            buckets=_LATENCY_BUCKETS)
        self._h_queue_wait = R.histogram(
            "ome_engine_queue_wait_seconds",
            "Seconds between admission and first decode slot",
            buckets=_LATENCY_BUCKETS)
        self._h_e2e = R.histogram(
            "ome_engine_e2e_seconds",
            "End-to-end request seconds (admission to finish)",
            buckets=_LATENCY_BUCKETS)
        self._g_depth = R.gauge(
            "ome_engine_queue_depth", "Pending-queue depth")
        self._g_active = R.gauge(
            "ome_engine_active_slots", "Occupied decode slots")
        self._g_kv = R.gauge(
            "ome_engine_kv_block_utilization_ratio",
            "Fraction of the paged-KV block pool in use")
        # per-class children pre-created for the fixed priority enum
        # ONLY (bounded label cardinality by construction); tenant-
        # class scenarios beyond the enum get no per-class children
        def _by_class(fam):
            return {c: fam.labels(**{"class": c})
                    for c in PRIORITY_CLASSES}
        self._h_class_ttft = _by_class(R.histogram(
            "ome_engine_class_ttft_seconds",
            "Time to first token, by priority class",
            labelnames=("class",), buckets=_LATENCY_BUCKETS))
        self._h_class_queue_wait = _by_class(R.histogram(
            "ome_engine_class_queue_wait_seconds",
            "Admission-to-first-slot seconds, by priority class",
            labelnames=("class",), buckets=_LATENCY_BUCKETS))
        self._h_class_e2e = _by_class(R.histogram(
            "ome_engine_class_e2e_seconds",
            "End-to-end request seconds, by priority class (the "
            "fleet SLO rollup's e2e objective source; docs/slo.md)",
            labelnames=("class",), buckets=_LATENCY_BUCKETS))
        self._c_sim_chunks = R.counter(
            "ome_sim_chunks_total",
            "Fused decode chunks executed by the simulated device")
        self._g_incarnation = R.gauge(
            "ome_sim_engine_incarnation",
            "Incarnation number of this simulated replica (bumps "
            "when a chaos restart resumes its virtual journal)")
        self._g_incarnation.set(self.incarnation)
        self._c_resumed = R.counter(
            "ome_sim_resumed_requests_total",
            "Requests re-admitted from the virtual journal after a "
            "simulated crash restart")

    def metrics_text(self) -> str:
        """The /metrics body a scrape would see, gauges refreshed at
        scrape time exactly like the real engine's update_gauges."""
        self._g_depth.set(self.pending.qsize()
                          + (1 if self._stalled is not None else 0))
        self._g_active.set(len(self.active))
        self._g_kv.set(self.pages_used / self.kv_pages)
        return self.registry.render()

    def ready_info(self) -> dict:
        return {"ready": not self.draining and not self.killed,
                "draining": self.draining}

    # -- admission (mirrors scheduler.submit's shed ladder) ------------

    def submit(self, req: SimRequest) -> int:
        """Admit a request; returns the HTTP-ish status the real
        serve layer would answer (200 admitted, 503 draining, 429
        overloaded — by queue bound or by the estimated-wait shed
        ladder, exactly scheduler.submit's admission control)."""
        if self.killed:
            raise OSError(f"sim engine {self.name} is down")
        if self.draining:
            return 503
        req.created = self.clock.now()
        if self._shed(req):
            self.stats["rejected_total"] += 1
            self._c_rejected.inc()
            return 429
        try:
            self.pending.put_nowait(req)
        except queue.Full:
            self.stats["rejected_total"] += 1
            self._c_rejected.inc()
            return 429
        self.stats["requests_total"] += 1
        self._c_requests.inc()
        if self.journal is not None and req._jid is None:
            req._jid = self.journal.admit(req, self.incarnation)
        self._admit()
        return 200

    # -- admission control (scheduler.submit's shed ladder) ------------

    def _queue_wait_estimate(self, depth: int) -> Optional[float]:
        """Rough seconds until a newly queued request would start
        decoding — the real scheduler's formula on sim-observed
        EWMAs: queue depth in batch waves x per-request decode steps
        x step seconds. None until both EWMAs have samples (cold
        start admits optimistically)."""
        if depth <= 0 or self._ewma_step_s is None \
                or self._ewma_req_steps is None:
            return None
        waves = math.ceil(depth / self.max_slots)
        return waves * self._ewma_req_steps * self._ewma_step_s

    def _class_wait_estimate(self, cls: str,
                             depth: int) -> Optional[float]:
        """Per-class estimate: the plain estimate scaled up by the
        inverse of the class's weight share over the active classes
        (the real _class_wait_estimate, generalized to whatever
        class set the queue was built with)."""
        base = self._queue_wait_estimate(depth)
        if base is None:
            return base
        w = self.pending.weights
        if cls not in w:
            return base
        active = {c for c in w if self.pending.qsize(c) > 0}
        active.add(cls)
        share = sum(w[c] for c in active)
        return base * (share / w[cls]) if share else base

    def _shed(self, req: SimRequest) -> bool:
        """True when the estimated queue wait for this request's
        class exceeds its cap (shed with 429 before the queue bound
        is even reached — the deep-saturation behavior the real
        serve layer shows)."""
        if self.max_queue_wait is None:
            return False
        cls = req.priority
        if cls in self.class_wait_caps:
            depth = self.pending.qsize(cls)
            cap = self.class_wait_caps[cls]
        else:
            depth = self.pending.qsize()
            cap = self.max_queue_wait
        est = self._class_wait_estimate(cls, depth + 1)
        return est is not None and est > cap

    def retry_after_hint(self, default: float = 1.0) -> int:
        """Seconds a rejected client should back off, from the live
        queue-wait estimate, clamped to [1, 30] — what the real
        server puts in Retry-After on its 429/503 answers."""
        est = self._queue_wait_estimate(self.pending.qsize() + 1)
        val = est if est is not None else default
        return int(min(max(math.ceil(val), 1), 30))

    def _request_pages(self, req: SimRequest) -> int:
        return max(1, math.ceil(
            (req.prompt_tokens + req.max_new_tokens) / self.kv_block))

    def _admit(self) -> None:
        """Fill free slots from the WDRR queue while KV pages last.
        Each admitted request schedules its own prefill-completion
        event; decode chunks pick the slot up afterwards."""
        if self.killed:
            return
        now = self.clock.now()
        while len(self.active) < self.max_slots:
            req = self._stalled
            self._stalled = None
            if req is None:
                try:
                    req = self.pending.get_nowait()
                except queue.Empty:
                    break
            pages = self._request_pages(req)
            if self.pages_used + pages > self.kv_pages:
                self._stalled = req  # wait for a slot to free pages
                break
            req._pages = pages
            self.pages_used += pages
            req.scheduled_at = now
            wait = now - req.created
            self._h_queue_wait.observe(wait)
            hq = self._h_class_queue_wait.get(req.priority)
            if hq is not None:
                hq.observe(wait)
            self.loop.call_later(
                self.cost.prefill_ms(req.prompt_tokens) / 1000.0
                * self.slow_factor,
                lambda r=req: self._activate(r))
            self.active.append(req)
        self._maybe_drained()

    def _activate(self, req: SimRequest) -> None:
        """Prefill finished: the first token emits, the slot joins
        the decode batch from the next chunk on."""
        if self.killed or req.finish_reason is not None:
            return
        now = self.clock.now()
        req.first_token_at = now
        # resumed requests carry their journaled progress; prefill
        # recomputed the folded prompt and this emit continues the
        # stream where the dead incarnation stopped
        req._progress += 1.0
        req.output_tokens = int(req._progress)
        self.stats["tokens_generated_total"] += 1
        self._c_tokens.inc()
        self._journal_prog(req, 1)
        ttft = now - req.created
        self._h_ttft.observe(ttft)
        ht = self._h_class_ttft.get(req.priority)
        if ht is not None:
            ht.observe(ttft)
        if req.output_tokens >= req.max_new_tokens:
            self._finish(req, "stop")
        self._schedule_chunk()

    # -- the modeled device --------------------------------------------

    def _schedule_chunk(self) -> None:
        if self._chunk_event is not None or self.killed or self.stuck:
            return
        batch = [r for r in self.active if r.first_token_at is not None
                 and r.finish_reason is None]
        if not batch:
            return
        pages = float(sum(r._pages for r in batch))
        dt = self.cost.step_ms(len(batch), pages=pages,
                               fused_k=self.fused_k,
                               spec_accept=self.spec_accept) / 1000.0 \
            * self.slow_factor
        self._chunk_dt = dt
        self._chunk_event = self.loop.call_later(dt, self._run_chunk)

    def _run_chunk(self) -> None:
        self._chunk_event = None
        if self.killed:
            return
        self.stats["chunks_total"] += 1
        self._c_sim_chunks.inc()
        # feed the admission ladder's step EWMA (alpha 0.1, like the
        # real decode loop's observation of its own step time)
        dt_step = self._chunk_dt / self.fused_k
        self._ewma_step_s = dt_step if self._ewma_step_s is None \
            else 0.9 * self._ewma_step_s + 0.1 * dt_step
        gained = self.fused_k * self.cost.tokens_per_iteration(
            self.spec_accept)
        for req in list(self.active):
            if req.first_token_at is None \
                    or req.finish_reason is not None:
                continue
            before = req.output_tokens
            req._progress = min(req._progress + gained,
                                float(req.max_new_tokens))
            req.output_tokens = int(req._progress)
            emitted = req.output_tokens - before
            if emitted > 0:
                self.stats["tokens_generated_total"] += emitted
                self._c_tokens.inc(emitted)
                self._journal_prog(req, emitted)
                tc = self._per_class_tokens
                tc[req.priority] = tc.get(req.priority, 0) + emitted
            if req.output_tokens >= req.max_new_tokens:
                self._finish(req, "stop")
        self._admit()
        self._schedule_chunk()

    def _finish(self, req: SimRequest, reason: str) -> None:
        if req.finish_reason is not None:
            return
        req.finish_reason = reason
        req.status = 200 if reason == "stop" else 599
        req.finished_at = self.clock.now()
        self._h_e2e.observe(req.finished_at - req.created)
        he = self._h_class_e2e.get(req.priority)
        if he is not None:
            he.observe(req.finished_at - req.created)
        if req in self.active:
            self.active.remove(req)
            self.pages_used -= req._pages
        if self.journal is not None and req._jid is not None:
            self.journal.finish(req._jid, self.incarnation, reason)
        # per-request steps EWMA (alpha 0.2) for the shed ladder
        steps = req.output_tokens / self.cost.tokens_per_iteration(
            self.spec_accept)
        self._ewma_req_steps = steps \
            if self._ewma_req_steps is None \
            else 0.8 * self._ewma_req_steps + 0.2 * steps
        if self.on_finish is not None:
            self.on_finish(req)
        self._maybe_drained()

    def _journal_prog(self, req: SimRequest, n: int) -> None:
        if self.journal is not None and req._jid is not None:
            self.journal.progress(req._jid, self.incarnation, n)

    # -- lifecycle ------------------------------------------------------

    def drain(self, on_drained: Optional[Callable[[], None]]
              = None) -> None:
        """Graceful drain: stop admitting, finish in-flight + queued
        work, then fire ``on_drained`` (the SimPool's deregistration
        hook) — the same contract as the real SIGTERM drain."""
        self.draining = True
        self._on_drained = on_drained
        self._maybe_drained()

    def _maybe_drained(self) -> None:
        if (self.draining and not self.active
                and self.pending.empty() and self._stalled is None
                and self._on_drained is not None):
            cb, self._on_drained = self._on_drained, None
            cb()

    def kill(self) -> None:
        """Abrupt death (SIGKILL analog): every in-flight and queued
        request fails client-side; probes and scrapes start raising
        at the transport. The virtual journal is NOT tombstoned —
        like the real WAL, the admits (and any progress records)
        survive the crash and a restart incarnation must resume
        them."""
        self.killed = True
        victims = list(self.active)
        if self._stalled is not None:
            victims.append(self._stalled)
            self._stalled = None
        while True:
            try:
                victims.append(self.pending.get_nowait())
            except queue.Empty:
                break
        self.active = []
        self.pages_used = 0
        for req in victims:
            req.finish_reason = "killed"
            req.status = 599
            req.finished_at = self.clock.now()
            if self.on_finish is not None:
                self.on_finish(req)

    # -- chaos fault surface (sim/faultplan.py events) -----------------

    def set_slow(self, factor: float) -> None:
        """Step-time inflation: decode chunks and prefills take
        ``factor`` x their modeled time until cleared (factor 1)."""
        self.slow_factor = max(float(factor), 1.0)

    def set_stuck(self, stuck: bool) -> None:
        """Full decode stall: no chunk completes while stuck (the
        wedged-device shape — admission and /metrics keep serving, so
        the controller and router see a live replica going dark on
        progress). Unsticking reschedules the chunk loop."""
        self.stuck = bool(stuck)
        if not stuck:
            self._schedule_chunk()

    def resume_from_journal(self) -> int:
        """Re-admit every live entry from the virtual journal — the
        Scheduler.resume_from_journal fold, virtualized: produced
        tokens join the prompt (recompute resume), the original
        budget stands, and an entry whose whole budget was produced
        finishes ``length`` (only its tombstone was lost). Entries
        the admission ladder bounces stay live for the next restart.
        Returns the number of requests re-admitted."""
        if self.journal is None:
            return 0
        n = 0
        for e in self.journal.resume_entries():
            produced = e.get("produced", 0)
            if produced >= e["max_new"]:
                self.journal.finish(e["jid"], self.incarnation,
                                    "length")
                continue
            req = SimRequest(
                prompt_tokens=e["prompt_tokens"] + produced,
                max_new_tokens=e["max_new"],
                priority=e.get("cls") or DEFAULT_PRIORITY,
                trace_id=e.get("trace_id"))
            req._jid = e["jid"]
            req._progress = float(produced)
            req.output_tokens = produced
            if self.submit(req) != 200:
                continue  # more journal than queue: stays live
            n += 1
        if n:
            self.stats["resumed_total"] += n
            self._c_resumed.inc(n)
        return n

    def tokens_by_class(self) -> Dict[str, int]:
        """Decode tokens served per class (ALL classes, including
        tenant classes beyond the metric enum) — the WDRR fairness
        scenarios' measurement surface."""
        return dict(self._per_class_tokens)
