"""Declarative fault schedules: one JSON document drives chaos in
BOTH harnesses.

A ``FaultSchedule`` is the portable description of a chaos run —
fleet size, workload length, a ``faults.py`` spec string for the
transport points, a list of timed events in the simulator's fault
vocabulary (kill / restart / slow / stuck / partition / heal), and
optionally a seeded durability bug for shrinker acceptance tests.
Everything is derived from ONE seed, so a schedule file and the two
numbers it carries replay exactly.

The same document serves two runners:

  * the simulator (``scenario.run_chaos``) applies the events on the
    virtual event loop across hundreds of SimEngines — minutes of
    fleet time per CPU-second, where schedules are explored;
  * the subprocess harness (``chaos_soak --schedule``) down-converts
    the kill events onto its real-process topology for a fidelity
    spot-check — the sim found it, the real stack confirms it.

Only process-death events survive down-conversion: slow / stuck /
partition / heal are simulator expressivity (the subprocess harness
expresses those through fault-point specs instead), while a ``kill``
maps onto a real SIGKILL and the harness's unconditional
respawn-and-resume covers the ``restart`` half.

``shrink`` is the counterexample minimizer: given a failing schedule
and a runner, ddmin over the event list, then halve the fleet, then
truncate the workload — keeping every step only if the run still
fails with the SAME violation kinds (prefix before the first ``:``),
so an unrelated failure mode cannot hijack the reduction. The result
plus ``write_bundle`` is the standard replay bundle: schedule.json,
violation.json, and a one-command repro.
"""

from __future__ import annotations

import json
import pathlib
import random
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from .fleet import FAULT_KINDS

SCHEMA_VERSION = 1

# fault-spec string armed for every sim chaos run unless the schedule
# overrides it: a handful of early submit failures, charging the
# client failover + retry-budget + breaker paths
DEFAULT_FAULT_SPEC = "sim_transport_submit.raise@2:3"


@dataclass
class FaultEvent:
    at: float          # virtual seconds from run start
    action: str        # one of fleet.FAULT_KINDS
    target: str        # engine member name, e.g. "engine3"
    param: float = 0.0  # slow factor for "slow"; unused otherwise

    def to_dict(self) -> dict:
        d = {"at": round(self.at, 3), "action": self.action,
             "target": self.target}
        if self.param:
            d["param"] = self.param
        return d

    @staticmethod
    def from_dict(d: dict) -> "FaultEvent":
        return FaultEvent(at=float(d["at"]), action=str(d["action"]),
                          target=str(d["target"]),
                          param=float(d.get("param", 0.0)))


@dataclass
class FaultSchedule:
    seed: int
    engines: int
    requests: int
    duration_s: float = 60.0
    events: List[FaultEvent] = field(default_factory=list)
    # faults.py grammar; installed process-wide for the run
    fault_spec: str = ""
    # seeded durability bug for shrinker acceptance:
    # {"kind": "drop_resume", "target": "engine1", "n": 1}
    inject_bug: Optional[dict] = None

    # -- serialization (the portable artifact) -------------------------

    def to_dict(self) -> dict:
        d = {"schema_version": SCHEMA_VERSION, "seed": self.seed,
             "engines": self.engines, "requests": self.requests,
             "duration_s": self.duration_s,
             "events": [e.to_dict() for e in self.events]}
        if self.fault_spec:
            d["fault_spec"] = self.fault_spec
        if self.inject_bug:
            d["inject_bug"] = self.inject_bug
        return d

    def dumps(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True,
                          indent=1) + "\n"

    @staticmethod
    def from_dict(doc: dict) -> "FaultSchedule":
        ver = doc.get("schema_version")
        if ver != SCHEMA_VERSION:
            raise ValueError(
                f"fault schedule: schema_version {ver!r} != "
                f"{SCHEMA_VERSION}")
        return FaultSchedule(
            seed=int(doc["seed"]), engines=int(doc["engines"]),
            requests=int(doc["requests"]),
            duration_s=float(doc.get("duration_s", 60.0)),
            events=[FaultEvent.from_dict(e)
                    for e in doc.get("events", [])],
            fault_spec=str(doc.get("fault_spec", "")),
            inject_bug=doc.get("inject_bug"))

    @staticmethod
    def load(path) -> "FaultSchedule":
        doc = json.loads(pathlib.Path(path).read_text(
            encoding="utf-8"))
        return FaultSchedule.from_dict(doc)

    def save(self, path) -> None:
        pathlib.Path(path).write_text(self.dumps(), encoding="utf-8")

    def replay_command(self,
                       schedule_path: str = "schedule.json") -> str:
        return ("python scripts/simulate.py --scenario chaos "
                f"--schedule {schedule_path}")


# -- validation --------------------------------------------------------


def preflight(schedule: FaultSchedule) -> None:
    """Refuse a schedule that uses an unknown event action or — via
    the SAME catalog check the subprocess harness runs — injects a
    fault point absent from docs/failure-semantics.md."""
    for e in schedule.events:
        if e.action not in FAULT_KINDS:
            raise ValueError(
                f"fault schedule: unknown event action {e.action!r} "
                f"(known: {', '.join(FAULT_KINDS)})")
    if schedule.fault_spec:
        from ..chaos import preflight_fault_points
        preflight_fault_points([schedule.fault_spec])


# -- seed-derived generation -------------------------------------------


def generate(seed: int, engines: int = 8, requests: int = 400,
             kills: int = 4, duration_s: float = 60.0,
             slow: int = 1, partitions: int = 1,
             fault_spec: str = DEFAULT_FAULT_SPEC,
             inject_bug: Optional[dict] = None) -> FaultSchedule:
    """Everything random comes from ONE generator seeded by
    ``f"{seed}:sim"`` (the sim-side analog of the subprocess
    harness's ``f"{seed}:{index}"`` discipline): kill/restart pairs,
    slow/heal pairs, partition/heal pairs, all landing inside the
    trace window so the invariants are exercised under load."""
    rng = random.Random(f"{seed}:sim")
    names = [f"engine{i + 1}" for i in range(engines)]
    events: List[FaultEvent] = []
    lo, hi = 0.1 * duration_s, 0.8 * duration_s
    # times rounded to the millisecond at GENERATION so the schedule
    # object and its JSON serialization are the same artifact (the
    # round trip is exact, not truncating)
    for _ in range(max(int(kills), 0)):
        t = round(rng.uniform(lo, hi), 3)
        victim = rng.choice(names)
        events.append(FaultEvent(t, "kill", victim))
        events.append(FaultEvent(round(t + rng.uniform(2.0, 8.0), 3),
                                 "restart", victim))
    for _ in range(max(int(slow), 0)):
        t = round(rng.uniform(lo, hi), 3)
        victim = rng.choice(names)
        kind = rng.choice(("slow", "stuck"))
        param = round(rng.uniform(2.0, 6.0), 2) \
            if kind == "slow" else 0.0
        events.append(FaultEvent(t, kind, victim, param))
        events.append(FaultEvent(round(t + rng.uniform(3.0, 10.0), 3),
                                 "heal", victim))
    for _ in range(max(int(partitions), 0)):
        t = round(rng.uniform(lo, hi), 3)
        victim = rng.choice(names)
        events.append(FaultEvent(t, "partition", victim))
        events.append(FaultEvent(round(t + rng.uniform(2.0, 6.0), 3),
                                 "heal", victim))
    events.sort(key=lambda e: (e.at, e.target, e.action))
    return FaultSchedule(seed=seed, engines=engines,
                         requests=requests, duration_s=duration_s,
                         events=events, fault_spec=fault_spec,
                         inject_bug=inject_bug)


# -- down-conversion (sim schedule -> subprocess episode) --------------


def to_chaos_events(schedule: FaultSchedule,
                    serving: Sequence[str],
                    spread: float) -> List[Tuple[float, str, str]]:
    """Map the schedule's kill events onto the subprocess topology's
    serving engines: round-robin over the real engine names, times
    rescaled into the episode's [0.2, 0.9] x spread window (the
    subprocess fleet is a few engines, not hundreds — what transfers
    is the kill COUNT and ordering, not the sim target names). The
    harness's unconditional respawn-and-resume stands in for the
    ``restart`` half of each pair; non-process events do not
    down-convert (see module docstring)."""
    kills = [e for e in schedule.events if e.action == "kill"]
    if not kills or not serving:
        return []
    t_hi = max(e.at for e in kills)
    t_lo = min(e.at for e in kills)
    span = (t_hi - t_lo) or 1.0
    out = []
    for i, e in enumerate(sorted(kills, key=lambda e: e.at)):
        frac = (e.at - t_lo) / span
        at = (0.2 + 0.7 * frac) * spread
        out.append((round(at, 3), "sigkill",
                    serving[i % len(serving)]))
    return out


# -- the shrinker ------------------------------------------------------


def violation_kinds(violations: Sequence[str]) -> Set[str]:
    """The stable prefix before the first ':' — the failure-mode
    identity the reduction must preserve."""
    return {v.split(":", 1)[0].strip() for v in violations}


def shrink(schedule: FaultSchedule,
           run_fn: Callable[[FaultSchedule], List[str]],
           violations: Optional[List[str]] = None,
           max_runs: int = 48,
           min_requests: int = 16) -> Tuple[FaultSchedule, dict]:
    """Minimize a failing schedule to a still-failing counterexample.

    ``run_fn(schedule) -> violations`` runs one candidate (a full sim
    chaos run). Reduction order: ddmin over the event list, halve the
    fleet, truncate the workload — each step kept only when the
    candidate still fails with an overlapping violation-kind set.
    Dropped event targets that no longer exist in a halved fleet are
    harmless: ``apply_fault`` no-ops on unknown members.

    Returns (minimal schedule, stats dict: runs used, sizes
    before/after)."""
    if violations is None:
        violations = run_fn(schedule)
    kinds = violation_kinds(violations)
    if not kinds:
        raise ValueError("shrink: schedule does not fail — nothing "
                         "to minimize")
    runs = {"n": 1}

    def failing(cand: FaultSchedule) -> bool:
        if runs["n"] >= max_runs:
            return False
        runs["n"] += 1
        return bool(violation_kinds(run_fn(cand)) & kinds)

    before = {"events": len(schedule.events),
              "engines": schedule.engines,
              "requests": schedule.requests}

    # 1. ddmin over events (Zeller's algorithm on complements)
    events = list(schedule.events)
    n = 2
    while len(events) >= 2:
        chunk = max(len(events) // n, 1)
        reduced = False
        for i in range(0, len(events), chunk):
            cand_events = events[:i] + events[i + chunk:]
            if failing(replace(schedule, events=cand_events)):
                events = cand_events
                n = max(n - 1, 2)
                reduced = True
                break
        if not reduced:
            if chunk <= 1:
                break
            n = min(len(events), n * 2)
    schedule = replace(schedule, events=events)
    # an empty event list can still fail (the bug may be in the
    # workload path); try dropping the last survivor too
    if len(events) == 1 and failing(replace(schedule, events=[])):
        schedule = replace(schedule, events=[])

    # 2. halve the fleet
    while schedule.engines > 1:
        cand = replace(schedule,
                       engines=max(schedule.engines // 2, 1))
        if not failing(cand):
            break
        schedule = cand

    # 3. truncate the workload
    while schedule.requests > min_requests:
        cand = replace(schedule,
                       requests=max(schedule.requests // 2,
                                    min_requests))
        if not failing(cand):
            break
        schedule = cand

    stats = {"runs": runs["n"], "before": before,
             "after": {"events": len(schedule.events),
                       "engines": schedule.engines,
                       "requests": schedule.requests}}
    return schedule, stats


# -- the replay bundle -------------------------------------------------


def write_bundle(bundle_dir, schedule: FaultSchedule,
                 violations: Sequence[str],
                 shrink_stats: Optional[dict] = None) -> str:
    """The standard chaos replay bundle: schedule.json (the minimal
    counterexample), violation.json (what failed + how to reproduce),
    and the returned one-command repro string."""
    d = pathlib.Path(bundle_dir)
    d.mkdir(parents=True, exist_ok=True)
    sched_path = d / "schedule.json"
    schedule.save(sched_path)
    cmd = schedule.replay_command(str(sched_path))
    doc: Dict[str, object] = {
        "violations": list(violations),
        "schedule": schedule.to_dict(),
        "replay": cmd}
    if shrink_stats:
        doc["shrink"] = shrink_stats
    (d / "violation.json").write_text(
        json.dumps(doc, sort_keys=True, indent=1) + "\n",
        encoding="utf-8")
    return cmd
