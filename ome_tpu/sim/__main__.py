"""``python -m ome_tpu.sim`` — the scenario runner, same CLI as
scripts/simulate.py."""

import os
import runpy
import sys

_here = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.argv[0] = "simulate"
runpy.run_path(os.path.join(_here, "scripts", "simulate.py"),
               run_name="__main__")
