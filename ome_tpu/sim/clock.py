"""Virtual time: the simulator's clock and seeded event loop.

`VirtualClock` is a monotonic counter that only moves when the event
loop executes an event — no wall-clock reads anywhere (the
`sim-wall-clock` omelint rule holds everything reachable from
`EventLoop.run` to that). It is callable, so it drops into every
`clock=` injection point the control plane grew for this PR
(Router, ScaleController, HistogramWindow, PoolPolicy, EnginePool).

`EventLoop` is a heap of ``(time, seq, callback)`` entries. ``seq``
is a monotonically increasing tie-breaker: two events scheduled for
the same instant fire in scheduling order, never in heap-internal
order — the property that makes a fixed seed reproduce byte-identical
decision logs run to run (the tier-1 determinism smoke asserts it).
"""

from __future__ import annotations

import heapq
import itertools
from typing import Callable, List, Optional, Tuple


class VirtualClock:
    """Monotonic simulated seconds. Callable (``clock()``) so it can
    stand in for ``time.monotonic`` at every injection point."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def __call__(self) -> float:
        return self._now

    def advance_to(self, t: float) -> None:
        if t < self._now:
            raise ValueError(
                f"virtual time cannot run backwards "
                f"({t} < {self._now})")
        self._now = t


class Event:
    """Handle returned by call_at/call_later; ``cancel()`` is O(1)
    (the entry stays heaped but is skipped when popped)."""

    __slots__ = ("time", "seq", "fn", "cancelled")

    def __init__(self, time: float, seq: int, fn: Callable[[], None]):
        self.time = time
        self.seq = seq
        self.fn = fn
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class EventLoop:
    """Deterministic discrete-event loop on a VirtualClock."""

    def __init__(self, clock: Optional[VirtualClock] = None):
        self.clock = clock if clock is not None else VirtualClock()
        self._heap: List[Tuple[float, int, Event]] = []
        self._seq = itertools.count()
        self.executed = 0

    def call_at(self, t: float, fn: Callable[[], None]) -> Event:
        if t < self.clock.now():
            t = self.clock.now()  # past-due events fire "now"
        ev = Event(t, next(self._seq), fn)
        heapq.heappush(self._heap, (ev.time, ev.seq, ev))
        return ev

    def call_later(self, delay: float,
                   fn: Callable[[], None]) -> Event:
        return self.call_at(self.clock.now() + max(delay, 0.0), fn)

    def pending(self) -> int:
        return sum(1 for _, _, ev in self._heap if not ev.cancelled)

    def _pop(self) -> Optional[Event]:
        while self._heap:
            _, _, ev = heapq.heappop(self._heap)
            if not ev.cancelled:
                return ev
        return None

    def run_until(self, t_end: float) -> int:
        """Execute events with time <= t_end in (time, seq) order;
        the clock lands exactly on t_end. Returns events executed."""
        n = 0
        while self._heap:
            t, _, ev = self._heap[0]
            if ev.cancelled:
                heapq.heappop(self._heap)
                continue
            if t > t_end:
                break
            heapq.heappop(self._heap)
            self.clock.advance_to(t)
            ev.fn()
            n += 1
        self.clock.advance_to(max(t_end, self.clock.now()))
        self.executed += n
        return n

    def run(self, max_events: int = 10_000_000) -> int:
        """Drain the heap completely (bounded by ``max_events`` as a
        runaway-feedback backstop). Returns events executed."""
        n = 0
        while n < max_events:
            ev = self._pop()
            if ev is None:
                break
            self.clock.advance_to(ev.time)
            ev.fn()
            n += 1
        self.executed += n
        return n
