"""Canned simulation scenarios + the report plumbing.

Each scenario builds a SimFleet, plays a seeded trace through it on
virtual time, and folds the results through the REAL
``autoscale.replay.report`` — so a simulated run and a live replay
emit the same per-class SLO report shape and are directly
comparable.

Reports are serialized through ``canonical_json`` (sorted keys, no
whitespace): the fixed-seed smoke test asserts two runs of the same
scenario are BYTE-identical, which is the determinism contract the
whole simulator is built around.

The two fleet-scale regressions the ISSUE pinned live here:

  * ``wdrr_fairness`` — hundreds of tenant classes through the real
    ClassQueues deficit rotation; served tokens must track the
    weight shares.
  * ``autoscale_stability`` — a diurnal baseline with a flash crowd
    on top; the controller must follow the load up and down WITHOUT
    flapping (no up/down pair within a stability window).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from ..autoscale import replay as replay_mod
from ..autoscale import trace as trace_mod
from ..autoscale.controller import SLOConfig
from ..autoscale.policy import PolicyConfig
from .clock import EventLoop, VirtualClock
from .costmodel import CostModel
from .engine import SimEngine, SimRequest
from .fleet import SimFleet


def canonical_json(doc: dict) -> str:
    """The byte-identity serialization the determinism smoke
    compares: sorted keys, minimal separators, newline-terminated."""
    return json.dumps(doc, sort_keys=True,
                      separators=(",", ":")) + "\n"


def default_cost_model(path: Optional[str] = None,
                       mode: Optional[str] = None) -> CostModel:
    if path:
        return CostModel.load(path, mode=mode)
    # synthetic fallback so scenarios run without a checked-in table
    return CostModel(weights_ms=4.3, attn_ms=1.3, dispatch_ms=2.8,
                     prefill_ms_per_token=0.031)


# -- steady-state replay ----------------------------------------------


def run_steady(seed: int = 0, engines: int = 2, requests: int = 200,
               cost: Optional[CostModel] = None,
               base_rate: float = 8.0,
               settle_s: float = 60.0, **engine_kw) -> dict:
    """Fixed-size fleet, bursty synthetic trace, no autoscaler — the
    baseline scenario (and the perf harness when scaled up)."""
    cost = cost or default_cost_model()
    fleet = SimFleet(cost, seed=seed,
                     engine_kw=dict({"max_slots": 4,
                                     "kv_pages": 512,
                                     "fused_k": 4}, **engine_kw))
    fleet.add_engines(engines)
    fleet.start_health_loop()
    fleet.add_slo()
    tr = trace_mod.synthetic_trace(seed, n=requests,
                                   base_rate=base_rate)
    fleet.submit_trace(tr)
    horizon = (max(r.arrival for r in tr) if tr else 0.0) + settle_s
    fleet.run_until(horizon)
    rep = replay_mod.report(fleet.results, slo_ttft_s=2.0)
    rep["scenario"] = "steady"
    rep["engines"] = engines
    rep["sim"] = fleet.sim_stats()
    rep["slo"] = fleet.slo_rollup.report()
    return rep


# -- autoscaler stability under diurnal + flash crowd -----------------


def oscillation_pairs(decisions: List[dict],
                      window_ticks: int = 5) -> int:
    """Count up/down action pairs landing within ``window_ticks`` of
    each other — the flap metric. A controller tracking a diurnal
    swing acts repeatedly, but opposite-direction actions in quick
    succession mean it is fighting its own last decision."""
    acts = [(d["tick"], 1 if d["target"] > d["size"] else -1)
            for d in decisions if d["target"] != d["size"]]
    flaps = 0
    for (t0, d0), (t1, d1) in zip(acts, acts[1:]):
        if d0 != d1 and (t1 - t0) <= window_ticks:
            flaps += 1
    return flaps


def run_autoscale(seed: int = 0, cost: Optional[CostModel] = None,
                  min_engines: int = 1, max_engines: int = 4,
                  interval: float = 1.0,
                  period_s: float = 60.0, cycles: float = 2.0,
                  crowd_at: float = 95.0,
                  crowd_factor: float = 8.0,
                  settle_s: float = 45.0) -> dict:
    """Diurnal baseline + flash crowd through the REAL controller:
    scrape -> windows -> pressure -> hysteresis policy -> spawn/drain,
    all on virtual time. The report carries the full decision log
    and the oscillation metric the stability regression asserts on."""
    cost = cost or default_cost_model()
    fleet = SimFleet(cost, seed=seed, health_interval=2.0,
                     spawn_delay=2.0,
                     engine_kw={"max_slots": 2, "kv_pages": 96,
                                "kv_block": 16, "fused_k": 1})
    fleet.add_engines(min_engines)
    fleet.start_health_loop()
    fleet.add_controller(
        PolicyConfig(min_size=min_engines, max_size=max_engines,
                     up_stable_ticks=2, down_stable_ticks=8,
                     cooldown_ticks=4, down_threshold=0.3),
        SLOConfig(ttft_p99_s=2.0, queue_wait_p99_s=1.0,
                  queue_depth_high=4.0),
        interval=interval)
    tr = trace_mod.merge_traces(
        trace_mod.diurnal_trace(seed, n=900, period_s=period_s,
                                cycles=cycles, base_rate=1.0,
                                peak_factor=10.0,
                                prompt_tokens=(16, 64),
                                max_tokens=(32, 64)),
        trace_mod.flash_crowd_trace(seed + 1, n=150,
                                    base_rate=0.5,
                                    crowd_at=crowd_at,
                                    crowd_duration=8.0,
                                    crowd_factor=crowd_factor,
                                    prompt_tokens=(16, 64),
                                    max_tokens=(24, 48)))
    fleet.submit_trace(tr)
    horizon = max(r.arrival for r in tr) + settle_s
    fleet.run_until(horizon)
    rep = replay_mod.report(fleet.results, slo_ttft_s=2.0)
    rep["scenario"] = "autoscale"
    decisions = [d.to_dict() for d in fleet.controller.decisions]
    rep["decisions"] = decisions
    actions = [d for d in decisions if d["target"] != d["size"]]
    rep["scale_ups"] = sum(1 for d in actions
                           if d["target"] > d["size"])
    rep["scale_downs"] = sum(1 for d in actions
                             if d["target"] < d["size"])
    rep["oscillation_pairs"] = oscillation_pairs(decisions)
    rep["final_size"] = fleet.pool.size()
    rep["sim"] = fleet.sim_stats()
    return rep


# -- WDRR fairness at fleet-tenant class counts -----------------------


def run_wdrr_fairness(seed: int = 0, n_classes: int = 120,
                      tokens_each: int = 16,
                      cost: Optional[CostModel] = None,
                      rotations: float = 10.0) -> dict:
    """Saturate ONE simulated engine with ``n_classes`` tenant
    classes (weights cycling 1/2/4/8) through the real ClassQueues
    WDRR rotation, closed-loop: every finished request immediately
    resubmits under the same class, so EVERY class stays backlogged
    — the regime Shreedhar & Varghese fairness applies to. After
    ``rotations`` full deficit rotations' worth of service, the
    served-token share per weight tier must match the weight share;
    the report carries the worst relative error."""
    cost = cost or default_cost_model()
    classes = [f"tenant-{i:03d}" for i in range(n_classes)]
    weights = {c: (1, 2, 4, 8)[i % 4]
               for i, c in enumerate(classes)}
    clock = VirtualClock()
    loop = EventLoop(clock)
    # per-class backlog must EXCEED the largest per-visit credit
    # (w_max x QUANTUM_TOKENS / cost requests), else a visit drains
    # the class to empty, it forfeits its deficit, and every class
    # degenerates to one-queue-flush-per-rotation (equal shares)
    from ..engine.scheduler import QUANTUM_TOKENS
    depth = (8 * QUANTUM_TOKENS) // tokens_each + 8
    eng = SimEngine("wdrr", clock, loop, cost,
                    max_slots=16, kv_pages=100000, kv_block=16,
                    max_pending=depth + 8,
                    fused_k=8, classes=classes,
                    class_weights=weights,
                    max_queue_wait=None)  # saturation IS the regime
    # under test here — the shed ladder must not thin the backlog

    def resubmit(req):
        # closed loop: the class replaces its served request, so the
        # backlog never drains and shares converge to the weights
        eng.submit(SimRequest(
            prompt_tokens=8, max_new_tokens=tokens_each,
            priority=req.priority))
    eng.on_finish = resubmit
    for c in classes:
        for j in range(depth):
            eng.submit(SimRequest(
                prompt_tokens=8, max_new_tokens=tokens_each,
                priority=c, trace_id=f"{c}-{j}"))
    # one full rotation serves sum(weight) x QUANTUM_TOKENS tokens
    target = rotations * sum(weights.values()) * QUANTUM_TOKENS
    t = 0.0
    while sum(eng.tokens_by_class().values()) < target \
            and loop.pending():
        t += 5.0
        loop.run_until(t)
    by_class = eng.tokens_by_class()
    tier_tokens: Dict[int, float] = {}
    tier_count: Dict[int, int] = {}
    for c in classes:
        w = weights[c]
        tier_tokens[w] = tier_tokens.get(w, 0.0) + by_class.get(c, 0)
        tier_count[w] = tier_count.get(w, 0) + 1
    total_served = sum(tier_tokens.values())
    total_weight = sum(weights.values())
    tiers = {}
    worst = 0.0
    for w in sorted(tier_tokens):
        # expected share of service for ONE class of weight w
        expected = w / total_weight
        got = (tier_tokens[w] / tier_count[w]) / total_served
        err = abs(got / expected - 1.0)
        worst = max(worst, err)
        tiers[str(w)] = {"classes": tier_count[w],
                         "tokens": round(tier_tokens[w], 1),
                         "share_per_class": round(got, 5),
                         "expected_share": round(expected, 5),
                         "rel_error": round(err, 4)}
    return {"scenario": "wdrr_fairness", "n_classes": n_classes,
            "served_tokens": round(total_served, 1),
            "tiers": tiers, "worst_rel_error": round(worst, 4),
            "virtual_seconds": round(clock.now(), 6),
            "events": loop.executed}


# -- fleet-scale throughput (the perf acceptance) ---------------------


def run_fleet_scale(seed: int = 0, engines: int = 1000,
                    requests: int = 50000, duration_s: float = 120.0,
                    cost: Optional[CostModel] = None) -> dict:
    """1,000 engines x 50k requests: the perf acceptance scenario.
    Round-robin router, health sweeps, no controller (scraping a
    thousand registries is a dashboard's job, not the replay's).
    Wall-clock budget is measured by the caller; this function is
    pure virtual time."""
    cost = cost or default_cost_model()
    fleet = SimFleet(cost, seed=seed, policy="round_robin",
                     health_interval=30.0,
                     engine_kw={"max_slots": 8, "kv_pages": 1024,
                                "fused_k": 4})
    fleet.add_engines(engines)
    fleet.start_health_loop()
    rate = requests / duration_s
    tr = trace_mod.synthetic_trace(seed, n=requests, base_rate=rate,
                                   burst_factor=2.0,
                                   prompt_tokens=(4, 16),
                                   max_tokens=(4, 12))
    fleet.submit_trace(tr)
    fleet.run_until(max(r.arrival for r in tr) + 60.0)
    rep = replay_mod.report(fleet.results, slo_ttft_s=2.0)
    rep["scenario"] = "fleet_scale"
    rep["engines"] = engines
    rep["sim"] = fleet.sim_stats()
    return rep


# -- fleet-scale chaos (fault schedules + invariants) -----------------


def chaos_invariants(fleet: SimFleet, tr) -> List[str]:
    """The fleet-wide invariants a chaos run must satisfy at
    quiescence — the sim-side mirror of the subprocess harness's
    checkers, over the SAME semantic contracts:

      * every driven request ends with exactly ONE client outcome
        (chaos invariant 7, fleet-wide no-loss / no-duplicate);
      * every journaled admit is tombstoned (chaos invariant 1:
        restart-resume finished — or answered — everything the dead
        incarnation had accepted);
      * KV pages return to zero on every live engine (invariant 3's
        conservation check, virtualized).

    Violation strings carry a stable ``kind:`` prefix — the shrinker
    keys its reduction predicate on it."""
    violations: List[str] = []
    counts: Dict[str, int] = {}
    for r in fleet.results:
        if r.trace_id is not None:
            counts[r.trace_id] = counts.get(r.trace_id, 0) + 1
    missing = [t.trace_id for t in tr
               if t.trace_id not in counts]
    dups = sorted(t for t, c in counts.items() if c > 1)
    if missing:
        violations.append(
            f"request-loss: {len(missing)} driven request(s) got no "
            f"outcome (first: {missing[:3]})")
    if dups:
        violations.append(
            f"fleet outcome: {len(dups)} request(s) got multiple "
            f"outcomes (first: {dups[:3]})")
    if fleet._inflight:
        violations.append(
            f"request-loss: {len(fleet._inflight)} request(s) still "
            "in flight at quiescence")
    live = fleet.sim_journals.live_by_engine()
    if live:
        total = sum(len(v) for v in live.values())
        violations.append(
            f"journal: {total} admitted request(s) never tombstoned "
            f"across {len(live)} journal(s) "
            f"({', '.join(sorted(live)[:3])})")
    for m in fleet.pool.members:
        eng = m.engine
        if not eng.killed and not eng.active and eng.pages_used:
            violations.append(
                f"kv: {m.name} holds {eng.pages_used} page(s) at "
                "quiescence")
    return violations


def slo_alerting_invariants(rollup) -> List[str]:
    """The alerting contract a chaos run must satisfy (docs/slo.md):
    any (class, objective) whose error budget ended the run
    exhausted must have raised a page-level burn alert at a moment
    when budget still remained — the SRE-workbook promise that a
    fast burn PAGES before the budget is gone, not after."""
    violations: List[str] = []
    rep = rollup.report()
    paged = {(e["class"], e["objective"]) for e in rep["alerts"]
             if e["severity"] == "page"
             and e["budget_consumed"] < 1.0}
    for cls in sorted(rep["classes"]):
        for name, obj in sorted(rep["classes"][cls].items()):
            if obj["budget_consumed"] >= 1.0 \
                    and (cls, name) not in paged:
                violations.append(
                    f"slo-alerting: {cls}/{name} exhausted its "
                    f"error budget (consumed "
                    f"{obj['budget_consumed']}) without a prior "
                    "page-level burn alert")
    return violations


def run_chaos(seed: int = 0, engines: int = 8, requests: int = 400,
              kills: int = 4, cost: Optional[CostModel] = None,
              schedule=None, settle_s: float = 60.0,
              inject_bug: Optional[dict] = None,
              **engine_kw) -> dict:
    """Fault-schedule chaos at simulator scale: a seed-derived (or
    supplied) FaultSchedule plays kill/restart/slow/stuck/partition
    events against the fleet while a synthetic trace drives it; the
    end-of-schedule recovery respawns and resumes everything (the
    subprocess harness's discipline), and the report carries the
    fleet-wide invariant verdict plus the schedule itself, so the
    determinism smoke byte-compares the whole chaos path."""
    from dataclasses import replace as _dc_replace

    from .. import faults
    from . import faultplan
    cost = cost or default_cost_model()
    if schedule is None:
        schedule = faultplan.generate(
            seed, engines=engines, requests=requests, kills=kills,
            inject_bug=inject_bug)
    elif inject_bug is not None and schedule.inject_bug is None:
        schedule = _dc_replace(schedule, inject_bug=inject_bug)
    faultplan.preflight(schedule)
    fleet = SimFleet(cost, seed=schedule.seed, policy="round_robin",
                     health_interval=2.0,
                     engine_kw=dict({"max_slots": 4, "kv_pages": 512,
                                     "fused_k": 4,
                                     "max_pending": 256},
                                    **engine_kw))
    fleet.add_engines(schedule.engines)
    fleet.start_health_loop()
    fleet.add_slo()
    bug = schedule.inject_bug or {}
    if bug.get("kind") == "drop_resume":
        # target "*" arms every journal: whichever kill first catches
        # in-flight work trips the bug (robust to scheduling drift)
        tgt = str(bug.get("target", "*"))
        names = ([m.name for m in fleet.pool.members]
                 if tgt == "*" else [tgt])
        for name in names:
            fleet.sim_journals.arm_drop_resume(
                name, int(bug.get("n", 1)))
    rate = schedule.requests / max(schedule.duration_s, 1.0)
    tr = trace_mod.synthetic_trace(schedule.seed,
                                   n=schedule.requests,
                                   base_rate=max(rate, 0.1),
                                   prompt_tokens=(8, 32),
                                   max_tokens=(8, 32))
    fleet.submit_trace(tr)
    for e in schedule.events:
        fleet.at_fault(e.at, e.action, e.target, e.param)
    t_trace = max(r.arrival for r in tr) if tr else 0.0
    t_events = max((e.at for e in schedule.events), default=0.0)
    t_recover = max(t_trace, t_events) + 5.0
    fleet.loop.call_at(t_recover, fleet.recover_all)
    faults.install(schedule.fault_spec or "")
    try:
        fleet.run_until(t_recover + settle_s)
    finally:
        faults.reset()
    rep = replay_mod.report(fleet.results, slo_ttft_s=2.0)
    rep["scenario"] = "chaos"
    rep["engines"] = schedule.engines
    rep["schedule"] = schedule.to_dict()
    rep["fault_log"] = fleet.fault_log
    rep["violations"] = (chaos_invariants(fleet, tr)
                         + slo_alerting_invariants(fleet.slo_rollup))
    rep["sim"] = fleet.sim_stats()
    rep["slo"] = fleet.slo_rollup.report()
    return rep


# -- total-outage kill storm (the alerting acceptance) ----------------


def run_kill_storm(seed: int = 0, engines: int = 4,
                   cost: Optional[CostModel] = None,
                   rate: float = 4.0, requests: int = 2800,
                   outage_tail_s: float = 70.0) -> dict:
    """Total outage against a well-populated compliance window — the
    non-vacuous exercise of the alerting contract. Hundreds of
    seconds of healthy traffic first fill the rolling window (a cold
    window exhausts its budget almost instantly, which no alert
    policy can beat), then EVERY replica is killed with no recovery
    while the client keeps arriving: availability hard-fails, the
    fast-burn page must fire while budget remains, and the budget
    must then exhaust (docs/slo.md). A run where nothing exhausts
    means the storm is miscalibrated — reported as a violation so
    the contract can never pass vacuously. The kill moment is
    derived from the trace itself (its end minus ``outage_tail_s``)
    so burst compression cannot land the storm after the traffic."""
    cost = cost or default_cost_model()
    fleet = SimFleet(cost, seed=seed, policy="round_robin",
                     health_interval=2.0,
                     engine_kw={"max_slots": 4, "kv_pages": 512,
                                "fused_k": 4})
    fleet.add_engines(engines)
    fleet.start_health_loop()
    fleet.add_slo()
    tr = trace_mod.synthetic_trace(seed, n=requests,
                                   base_rate=rate,
                                   prompt_tokens=(8, 32),
                                   max_tokens=(8, 32))
    span = max(r.arrival for r in tr)
    outage_at = round(max(span - outage_tail_s, 0.0), 6)
    fleet.submit_trace(tr)
    for m in fleet.pool.members:
        fleet.at_fault(outage_at, "kill", m.name)
    fleet.run_until(span + 5.0)
    rep = replay_mod.report(fleet.results, slo_ttft_s=2.0)
    rep["scenario"] = "killstorm"
    rep["engines"] = engines
    rep["outage_at"] = outage_at
    rep["fault_log"] = fleet.fault_log
    slo = fleet.slo_rollup.report()
    exhausted = sorted(
        f"{cls}/{name}"
        for cls, objs in slo["classes"].items()
        for name, o in objs.items() if o["budget_consumed"] >= 1.0)
    violations = slo_alerting_invariants(fleet.slo_rollup)
    if not exhausted:
        violations.append(
            "slo-alerting: kill storm exhausted no error budget — "
            "scenario miscalibrated, the page-before-exhaust "
            "contract was never exercised")
    rep["exhausted"] = exhausted
    rep["violations"] = violations
    rep["sim"] = fleet.sim_stats()
    rep["slo"] = slo
    return rep


SCENARIOS = {
    "steady": run_steady,
    "autoscale": run_autoscale,
    "wdrr": run_wdrr_fairness,
    "fleet": run_fleet_scale,
    "chaos": run_chaos,
    "killstorm": run_kill_storm,
}
