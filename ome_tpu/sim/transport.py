"""In-process transport shim: the simulator's network.

Maps fake backend URLs to SimEngines and presents the THREE surfaces
the real control plane consumes over HTTP, with identical signatures
and failure modes, so the router's health loop and the autoscale
controller's scrape loop run unmodified:

  * ``fetch_metrics(url)`` — the controller's ``fetch_fn``: renders
    the engine's registry to the Prometheus text exposition and
    parses it back through the REAL ``scrape.parse_exposition``, so
    the bytes crossing this boundary are exactly what a live scrape
    would carry. A dead engine raises OSError, the same exception
    family a refused connection produces.
  * ``probe(url)`` — the router's ``_probe_backend`` contract:
    ``(healthy, draining, info)`` from the engine's /ready view;
    ``(False, False, None)`` for dead or unknown backends.
  * ``submit(url, req)`` — the generate path: the engine's admission
    status (200/503/429), or OSError when the backend is gone.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..autoscale.scrape import parse_exposition
from .engine import SimEngine, SimRequest


class SimTransport:
    def __init__(self):
        self._engines: Dict[str, SimEngine] = {}

    # -- membership ----------------------------------------------------

    def register(self, url: str, engine: SimEngine) -> None:
        self._engines[url.rstrip("/")] = engine

    def forget(self, url: str) -> None:
        self._engines.pop(url.rstrip("/"), None)

    def engine(self, url: str) -> Optional[SimEngine]:
        return self._engines.get(url.rstrip("/"))

    # -- the three wire surfaces ---------------------------------------

    def fetch_metrics(self, url: str, timeout: float = 5.0):
        del timeout  # signature parity with scrape.fetch_metrics
        eng = self.engine(url)
        if eng is None or eng.killed:
            raise OSError(f"connection refused: {url}")
        return parse_exposition(eng.metrics_text())

    def probe(self, url: str):
        eng = self.engine(url)
        if eng is None or eng.killed:
            return (False, False, None)
        info = eng.ready_info()
        return (info["ready"], info["draining"], info)

    def submit(self, url: str, req: SimRequest) -> int:
        eng = self.engine(url)
        if eng is None or eng.killed:
            raise OSError(f"connection refused: {url}")
        return eng.submit(req)
