"""In-process transport shim: the simulator's network.

Maps fake backend URLs to SimEngines and presents the THREE surfaces
the real control plane consumes over HTTP, with identical signatures
and failure modes, so the router's health loop and the autoscale
controller's scrape loop run unmodified:

  * ``fetch_metrics(url)`` — the controller's ``fetch_fn``: renders
    the engine's registry to the Prometheus text exposition and
    parses it back through the REAL ``scrape.parse_exposition``, so
    the bytes crossing this boundary are exactly what a live scrape
    would carry. A dead engine raises OSError, the same exception
    family a refused connection produces.
  * ``probe(url)`` — the router's ``_probe_backend`` contract:
    ``(healthy, draining, info)`` from the engine's /ready view;
    ``(False, False, None)`` for dead or unknown backends.
  * ``submit(url, req)`` — the generate path: the engine's admission
    status (200/503/429), or OSError when the backend is gone.

Transport faults (docs/failure-semantics.md): each surface consults a
cataloged ``faults.py`` point — ``sim_transport_submit`` /
``sim_transport_probe`` / ``sim_transport_scrape``, key = backend URL
— through ``faults.check`` (never ``fire``: a wall-clock sleep on the
sim path breaks determinism, so an armed slow rule maps onto the
surface's own timeout semantics instead: a submit/scrape slowed past
``TIMEOUT_S`` surfaces as the same OSError a client timeout raises; a
slowed probe misses its deadline and reads down). ``partition(url)``
makes one backend unreachable on all three surfaces until
``heal(url)`` — the network-partition analog, charged against the
same breaker/health/scrape recovery paths.
"""

from __future__ import annotations

from typing import Dict, Optional

from .. import faults
from ..autoscale.scrape import parse_exposition
from .engine import SimEngine, SimRequest

# the virtual client/probe timeout budget an armed slow rule is
# measured against (the real stack's 5 s connect/read timeouts)
TIMEOUT_S = 5.0


class SimTransport:
    def __init__(self):
        self._engines: Dict[str, SimEngine] = {}
        self._partitioned: Dict[str, bool] = {}

    # -- membership ----------------------------------------------------

    def register(self, url: str, engine: SimEngine) -> None:
        self._engines[url.rstrip("/")] = engine

    def forget(self, url: str) -> None:
        self._engines.pop(url.rstrip("/"), None)

    def engine(self, url: str) -> Optional[SimEngine]:
        return self._engines.get(url.rstrip("/"))

    # -- faults --------------------------------------------------------

    def partition(self, url: str) -> None:
        """Network-partition one backend: every surface fails with
        OSError until heal()."""
        self._partitioned[url.rstrip("/")] = True

    def heal(self, url: str) -> None:
        self._partitioned.pop(url.rstrip("/"), None)

    def _severed(self, url: str) -> bool:
        return self._partitioned.get(url.rstrip("/"), False)

    # -- the three wire surfaces ---------------------------------------

    def fetch_metrics(self, url: str, timeout: float = 5.0):
        del timeout  # signature parity with scrape.fetch_metrics
        delay, boom = faults.check("sim_transport_scrape", key=url,
                                   exc=OSError)
        if boom is not None or delay >= TIMEOUT_S:
            raise OSError(f"scrape failed: {url}")
        eng = self.engine(url)
        if eng is None or eng.killed or self._severed(url):
            raise OSError(f"connection refused: {url}")
        return parse_exposition(eng.metrics_text())

    def probe(self, url: str):
        delay, boom = faults.check("sim_transport_probe", key=url,
                                   exc=OSError)
        if boom is not None or delay >= TIMEOUT_S:
            return (False, False, None)
        eng = self.engine(url)
        if eng is None or eng.killed or self._severed(url):
            return (False, False, None)
        info = eng.ready_info()
        return (info["ready"], info["draining"], info)

    def submit(self, url: str, req: SimRequest) -> int:
        delay, boom = faults.check("sim_transport_submit", key=url,
                                   exc=OSError)
        if boom is not None:
            raise OSError(f"connection refused: {url}")
        if delay >= TIMEOUT_S:
            raise OSError(f"client timeout after {TIMEOUT_S:g}s: "
                          f"{url}")
        eng = self.engine(url)
        if eng is None or eng.killed or self._severed(url):
            raise OSError(f"connection refused: {url}")
        return eng.submit(req)

    def retry_after(self, url: str) -> Optional[int]:
        """The Retry-After seconds a 429/503 answer from this
        backend would carry (the engine's live queue-wait hint)."""
        eng = self.engine(url)
        return None if eng is None else eng.retry_after_hint()
