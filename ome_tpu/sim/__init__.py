"""Fleet-in-a-process: a calibrated discrete-event simulator.

ROADMAP item 6 (docs/simulation.md): thousand-replica scenario
sweeps — WDRR fairness at hundreds of tenant classes, autoscaler
oscillation under diurnal + flash-crowd load, capacity planning
against a TTFT SLO — on one CPU, in seconds, deterministically.

The simulator reuses the REAL control-plane code paths:

  * `engine.scheduler.ClassQueues` — the weighted deficit round-robin
    pick order, byte-for-byte the production implementation;
  * `router.server.Router` — backend selection, circuit breakers,
    draining, rendezvous hashing, retry budget;
  * `autoscale.policy.PoolPolicy` / `autoscale.controller
    .ScaleController` / `autoscale.scrape.HistogramWindow` — the
    scrape -> pressure -> decide -> act loop, fed through the same
    Prometheus text exposition the real controller parses.

Only two things are replaced: the device step (a calibrated cost
model fitted from the perfgate cost table, `config/cost-table.json`)
and the wall clock (`sim.clock.VirtualClock` + a seeded event loop).
Everything downstream — queue-wait, TTFT, per-class SLO reports —
is derived the same way the real scheduler produces it.

Chaos at simulator scale (docs/simulation.md): `sim.durability`
gives every engine name a virtual request journal across
incarnations, `sim.faultplan` defines the declarative fault-schedule
format shared with the subprocess harness (plus the shrinker and the
replay bundle), and `scenario.run_chaos` plays a schedule against
the fleet and checks the fleet-wide durability invariants.
"""

from .clock import EventLoop, VirtualClock
from .costmodel import CostModel
from .durability import JournalSet, SimJournal
from .engine import SimEngine
from .faultplan import FaultEvent, FaultSchedule
from .fleet import SimFleet, SimPool
from .transport import SimTransport

__all__ = ["EventLoop", "VirtualClock", "CostModel", "SimEngine",
           "SimFleet", "SimPool", "SimTransport", "SimJournal",
           "JournalSet", "FaultEvent", "FaultSchedule"]
