"""Virtual request journal: the simulator's durability model.

One ``SimJournal`` per engine *name* plays the role of the on-disk
request journal (engine/journal.py): it outlives the SimEngine object
the same way the WAL outlives a SIGKILLed process, and it carries the
same record stream — ``admit`` / ``prog`` / ``fin`` — per engine
*incarnation*, so the chaos invariants the subprocess harness checks
on real journal files (no admitted request lost, every admit
eventually tombstoned) check fleet-wide at simulator scale.

Record shape (virtual analog of the JSONL WAL)::

    {"t": "admit", "jid": 7, "inc": 1, "prompt_tokens": 32,
     "max_new": 64, "cls": "standard", "trace_id": ...}
    {"t": "prog",  "jid": 7, "inc": 1, "n": 4}    # 4 more tokens
    {"t": "fin",   "jid": 7, "inc": 2, "reason": "stop"}

The sim has no token ids, so ``prog`` carries a count where the real
record carries the ids; the fold logic is otherwise
``chaos.journal_live_entries`` verbatim: admits minus fins, with
progress accumulated onto the live entry.

``resume_entries`` is the restart side: the live entries a new
incarnation must re-admit, folded exactly like
``Scheduler.resume_from_journal`` folds ``prompt_ids + output_ids``
(here: produced tokens join the prompt for recompute, the original
``max_new`` budget stands, and an entry whose budget was already
produced finishes ``length`` immediately — only its tombstone was
lost to the crash).

``drop_resume`` is the seeded-bug knob the shrinker acceptance test
uses: a journal constructed with ``drop_resume=N`` silently loses the
first N live entries on every resume — the exact class of durability
bug (resume skips an admit record) the fleet-wide invariants exist to
catch.
"""

from __future__ import annotations

from typing import Dict, List


class SimJournal:
    """Append-only virtual WAL for one engine name, across
    incarnations."""

    def __init__(self, name: str, drop_resume: int = 0):
        self.name = name
        self.records: List[dict] = []
        self.drop_resume = int(drop_resume)
        self._next_jid = 1

    # -- the WAL writes (SimEngine's journaling hooks) -----------------

    def admit(self, req, incarnation: int) -> int:
        jid = self._next_jid
        self._next_jid += 1
        self.records.append({
            "t": "admit", "jid": jid, "inc": incarnation,
            "prompt_tokens": req.prompt_tokens,
            "max_new": req.max_new_tokens,
            "cls": req.priority, "trace_id": req.trace_id})
        return jid

    def progress(self, jid: int, incarnation: int, n: int) -> None:
        if n > 0:
            self.records.append({"t": "prog", "jid": jid,
                                 "inc": incarnation, "n": int(n)})

    def finish(self, jid: int, incarnation: int, reason: str) -> None:
        self.records.append({"t": "fin", "jid": jid,
                             "inc": incarnation, "reason": reason})

    # -- reconciliation (chaos.journal_live_entries, virtualized) ------

    def live_entries(self) -> Dict[int, dict]:
        """Admitted-but-untombstoned requests: the fold the chaos
        harness runs over real journal files. Empty at quiescence is
        the journal-reconciliation invariant."""
        live: Dict[int, dict] = {}
        for rec in self.records:
            t, jid = rec.get("t"), rec.get("jid")
            if t == "admit":
                live[jid] = dict(rec, produced=0)
            elif t == "prog" and jid in live:
                live[jid]["produced"] += rec.get("n", 0)
            elif t == "fin":
                live.pop(jid, None)
        return live

    def resume_entries(self) -> List[dict]:
        """The restart-resume view, in admit order. Applies the
        seeded ``drop_resume`` bug when armed (once per journal, like
        a real one-off replay defect)."""
        entries = [live for _, live in sorted(self.live_entries()
                                              .items())]
        if self.drop_resume > 0 and entries:
            dropped = min(self.drop_resume, len(entries))
            entries = entries[dropped:]
            self.drop_resume = 0
        return entries


class JournalSet:
    """The fleet's journal directory: one SimJournal per engine name,
    created on first use and surviving engine kills — the analog of
    the per-engine journal dirs the subprocess harness keeps."""

    def __init__(self):
        self._journals: Dict[str, SimJournal] = {}

    def get(self, name: str) -> SimJournal:
        j = self._journals.get(name)
        if j is None:
            j = SimJournal(name)
            self._journals[name] = j
        return j

    def arm_drop_resume(self, name: str, n: int = 1) -> None:
        """Seed the drop-resume bug into one engine's journal."""
        self.get(name).drop_resume = max(int(n), 1)

    def items(self):
        return sorted(self._journals.items())

    def live_by_engine(self) -> Dict[str, Dict[int, dict]]:
        out: Dict[str, Dict[int, dict]] = {}
        for name, j in self.items():
            live = j.live_entries()
            if live:
                out[name] = live
        return out
