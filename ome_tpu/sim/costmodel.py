"""Calibrated device cost model: the simulator's replaced "device".

Loaded from the perfgate cost table (``config/cost-table.json``,
regenerate with ``python scripts/perfgate.py --cost-table``), which
carries the fitted per-program costs of one bench round: per-mode
decode step breakdowns (weights_sampling / attn_kv / dispatch),
prefill time for the 32x128 reference shape, the host dispatch floor,
and — on rounds that ran them — multistep and paged-sweep rows.

The analytic shape (documented with its caveats in
docs/simulation.md):

  chunk_ms(batch, k) = dispatch
                       + k * (weights + attn * batch/batch_ref
                                       * pages_scale)

``weights`` is the weight-streaming term — batch-invariant, the
dominant cost of memory-bound decode; ``attn`` scales with batch and
with resident KV pages; ``dispatch`` is paid once per fused chunk of
``k`` iterations (exactly the amortization multi-step decode buys on
real hardware). A speculative accept rate multiplies tokens per
iteration, not step time — accepted draft tokens are free tokens from
the same verify forward.

``from_measurements`` builds the same object from observed timings
(measured TPOT / prefill of a live engine) — how the sim-vs-real
fidelity gate calibrates against a CPU topology whose timings have
nothing to do with the TPU bench numbers.
"""

from __future__ import annotations

import json
import pathlib
from dataclasses import dataclass
from typing import Optional, Union

# bump when the emitter (scripts/perfgate.py cost_table) changes
# shape incompatibly; load() rejects tables from another major
SCHEMA_VERSION = 1

# the bench decode loop's batch (bench.py serving shape) — the batch
# the breakdown's attn_kv term was measured at
DEFAULT_BATCH_REF = 8
# KV pages per slot at the reference point; page counts scale the
# attention term relative to this
DEFAULT_PAGES_REF = 8.0


@dataclass
class CostModel:
    weights_ms: float          # batch-invariant per-iteration cost
    attn_ms: float             # per-iteration cost at batch_ref
    dispatch_ms: float         # per-chunk host dispatch floor
    prefill_ms_per_token: float
    batch_ref: int = DEFAULT_BATCH_REF
    pages_ref: float = DEFAULT_PAGES_REF
    # compile/warmup time of a cold replica (program compilation +
    # first-dispatch warmup) — optional in the table; 0 keeps the
    # historical constant-spawn-delay behavior
    warmup_ms: float = 0.0
    source: str = "synthetic"

    # -- construction --------------------------------------------------

    @staticmethod
    def load(path: Union[str, pathlib.Path],
             mode: Optional[str] = None) -> "CostModel":
        doc = json.loads(pathlib.Path(path).read_text(
            encoding="utf-8"))
        ver = doc.get("schema_version")
        if ver != SCHEMA_VERSION:
            raise ValueError(
                f"cost table {path}: schema_version {ver!r} != "
                f"{SCHEMA_VERSION} — regenerate with "
                "scripts/perfgate.py --cost-table")
        return CostModel.from_cost_table(doc, mode=mode)

    @staticmethod
    def from_cost_table(table: dict,
                        mode: Optional[str] = None) -> "CostModel":
        """Fit from a perfgate cost table dict. Every field is
        optional in the table (rounds grew the schema over time);
        missing pieces fall back to documented defaults so an older
        round still yields a usable — if coarser — model."""
        programs = table.get("programs") or {}
        decode = None
        if mode is not None:
            decode = programs.get(f"decode_{mode}")
        if decode is None:
            for m in ("int8", "int4", "bf16"):
                decode = programs.get(f"decode_{m}")
                if decode is not None:
                    break
        step_ms = float((decode or {}).get("step_ms") or 6.0)
        phases = (decode or {}).get("phases_ms") or {}
        weights = float(phases.get("weights_sampling") or 0.0)
        attn = float(phases.get("attn_kv") or 0.0)
        phase_dispatch = float(phases.get("dispatch") or 0.0)
        if weights <= 0.0:
            # no breakdown: treat the whole step as weight streaming
            weights = step_ms - phase_dispatch
            attn = 0.0
        dispatch = float(table.get("dispatch_ms")
                         or phase_dispatch or 0.5)
        prefill = programs.get("prefill_b32x128") or {}
        prefill_step = float(prefill.get("step_ms") or 0.0)
        if prefill_step > 0.0:
            per_token = prefill_step / (32.0 * 128.0)
        else:
            # fallback: prefill a token at roughly decode-step cost
            # amortized over the reference batch
            per_token = step_ms / (DEFAULT_BATCH_REF * 16.0)
        return CostModel(
            weights_ms=weights, attn_ms=attn, dispatch_ms=dispatch,
            prefill_ms_per_token=per_token,
            warmup_ms=float(table.get("warmup_ms") or 0.0),
            source=str(table.get("source") or "cost-table"))

    @staticmethod
    def from_measurements(tpot_ms: float, prefill_ms_per_token: float,
                          dispatch_ms: float = 0.0,
                          batch_ref: int = 1,
                          compute_bound: bool = False,
                          pages_per_slot: float = DEFAULT_PAGES_REF,
                          source: str = "measured") -> "CostModel":
        """Model from observed timings of a live engine.

        Memory-bound (default, the TPU shape): TPOT becomes the
        batch-invariant per-iteration cost — growing the batch is
        nearly free, as on hardware dominated by weight streaming.

        ``compute_bound=True`` (the CPU fidelity topology): step time
        scales LINEARLY with batch — ``tpot_ms`` is the single-stream
        per-token time, put entirely in the attention term at
        ``batch_ref=1``, so N concurrent slots each decode N x slower
        and total throughput stays ~1/tpot regardless of batch,
        which is how a compute-bound CPU engine actually behaves.
        ``pages_per_slot`` pins pages_ref to the workload's typical
        per-slot KV footprint so the page term is neutral at the
        measured operating point."""
        if compute_bound:
            return CostModel(
                weights_ms=0.0, attn_ms=max(tpot_ms, 0.01),
                dispatch_ms=max(dispatch_ms, 0.0),
                prefill_ms_per_token=max(prefill_ms_per_token, 0.0),
                batch_ref=1,
                pages_ref=max(pages_per_slot, 1.0), source=source)
        return CostModel(
            weights_ms=max(tpot_ms - dispatch_ms, 0.01),
            attn_ms=0.0, dispatch_ms=max(dispatch_ms, 0.0),
            prefill_ms_per_token=max(prefill_ms_per_token, 0.0),
            batch_ref=max(batch_ref, 1), source=source)

    # -- queries (all pure; determinism depends on it) -----------------

    def step_ms(self, batch: int, pages: float = 0.0,
                fused_k: int = 1, spec_accept: float = 0.0) -> float:
        """Latency of one fused chunk of ``fused_k`` decode
        iterations over ``batch`` active slots holding ``pages`` KV
        pages total. ``spec_accept`` does not change the step time
        (the verify forward costs one step) — it changes the tokens
        the chunk yields; see tokens_per_iteration."""
        del spec_accept  # tokens-side only; kept in the signature so
        # callers state the full operating point in one place
        batch = max(int(batch), 1)
        k = max(int(fused_k), 1)
        pages_scale = 1.0
        if pages > 0.0 and self.pages_ref > 0.0:
            per_slot = pages / batch
            pages_scale = max(per_slot / self.pages_ref, 0.25)
        attn = self.attn_ms * (batch / float(self.batch_ref)) \
            * pages_scale
        return self.dispatch_ms + k * (self.weights_ms + attn)

    def tokens_per_iteration(self, spec_accept: float = 0.0) -> float:
        """Expected tokens one decode iteration yields per slot: 1
        for plain decode, 1 + accepted drafts under speculation."""
        return 1.0 + max(min(spec_accept, 4.0), 0.0)

    def prefill_ms(self, prompt_tokens: int) -> float:
        return self.dispatch_ms + self.prefill_ms_per_token \
            * max(int(prompt_tokens), 1)

    def to_dict(self) -> dict:
        return {"weights_ms": self.weights_ms,
                "attn_ms": self.attn_ms,
                "dispatch_ms": self.dispatch_ms,
                "prefill_ms_per_token": self.prefill_ms_per_token,
                "batch_ref": self.batch_ref,
                "pages_ref": self.pages_ref,
                "warmup_ms": self.warmup_ms,
                "source": self.source}
