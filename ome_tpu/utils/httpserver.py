"""Shared background HTTP server scaffolding.

Every sidecar/binary exposes a small HTTP surface (health, metrics,
aggregation) — one helper owns the ThreadingHTTPServer + daemon-thread
start/stop/join pattern instead of each binary re-implementing it.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Type


class QuietHandler(BaseHTTPRequestHandler):
    """Base handler: silent access log + reply helpers."""

    def log_message(self, *a):  # noqa: D102 — quiet by design
        pass

    def reply(self, code: int, body: bytes,
              ctype: str = "application/json"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def reply_json(self, code: int, obj):
        self.reply(code, json.dumps(obj).encode())

    def reply_metrics(self, text: str):
        self.reply(200, text.encode(), "text/plain; version=0.0.4")


class BackgroundHTTPServer:
    """ThreadingHTTPServer on a daemon thread with clean shutdown."""

    def __init__(self, handler_cls: Type[BaseHTTPRequestHandler],
                 host: str = "127.0.0.1", port: int = 0):
        self.httpd = ThreadingHTTPServer((host, port), handler_cls)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
