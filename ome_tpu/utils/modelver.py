"""Lenient version comparison for model/framework versions.

Equivalent of the reference's pkg/modelver (modelver/util.go:45): accepts
loose version strings ("4.36", "v1.0.0", "2024.1-beta"), compares
numerically component-wise, falls back to string comparison for
non-numeric parts.
"""

from __future__ import annotations

import re
from typing import List, Tuple, Union

_PART = re.compile(r"(\d+|[a-zA-Z]+)")


def _tokens(v: str) -> List[Union[int, str]]:
    v = v.strip().lstrip("vV")
    out: List[Union[int, str]] = []
    for tok in _PART.findall(v):
        out.append(int(tok) if tok.isdigit() else tok.lower())
    return out


def compare_lenient(a: str, b: str) -> int:
    """-1 / 0 / 1; numeric-aware, tolerant of different lengths
    (trailing zeros are insignificant: 1.0 == 1.0.0)."""
    ta, tb = _tokens(a), _tokens(b)
    n = max(len(ta), len(tb))
    for i in range(n):
        x = ta[i] if i < len(ta) else 0
        y = tb[i] if i < len(tb) else 0
        if isinstance(x, int) and isinstance(y, int):
            if x != y:
                return -1 if x < y else 1
        else:
            xs, ys = str(x), str(y)
            if xs != ys:
                return -1 if xs < ys else 1
    return 0
