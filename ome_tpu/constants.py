"""Label / annotation / env contracts (pkg/constants/constants.go analog).

Every string two components agree on lives here, TPU-first: the
schedulable resource is google.com/tpu, rendezvous env is the GKE/libtpu
contract (TPU_WORKER_ID / TPU_WORKER_HOSTNAMES / MEGASCALE_*), and node
readiness labels mark staged models per node.
"""

GROUP = "ome.io"

# -- labels -----------------------------------------------------------------

ISVC_LABEL = f"serving.{GROUP}/inferenceservice"
COMPONENT_LABEL = f"component.{GROUP}/name"  # engine | decoder | router
RAW_DEPLOYMENT_LABEL = f"serving.{GROUP}/raw"
BENCHMARK_LABEL = f"benchmark.{GROUP}/name"

# model-agent writes these on nodes (constants.go:802-818 analog)
def model_ready_label(kind: str, name: str) -> str:
    """models.ome.io/clusterbasemodel.llama-3-8b = Ready|Updating|Failed."""
    return f"models.{GROUP}/{kind.lower()}.{name}"


MODEL_STATUS_READY = "Ready"
MODEL_STATUS_UPDATING = "Updating"
MODEL_STATUS_FAILED = "Failed"
MODEL_STATUS_DELETED = "Deleted"

# -- gang scheduling (cmd/manager/main.go:90,223-225 analog) ---------------
# Multi-host TPU slices are the canonical gang workload: all hosts of a
# group must schedule together or the ICI mesh never forms. Kueue keys
# are upstream's well-known labels; Volcano's are annotations.

KUEUE_QUEUE_LABEL = "kueue.x-k8s.io/queue-name"
KUEUE_PRIORITY_CLASS_LABEL = "kueue.x-k8s.io/priority-class"
VOLCANO_QUEUE_ANNOTATION = "scheduling.volcano.sh/queue-name"
VOLCANO_GROUP_ANNOTATION = "scheduling.volcano.sh/group-name"
VOLCANO_SCHEDULER_NAME = "volcano"
# isvc-level override: which gang scheduler stamps the group
# ("kueue" default when the AcceleratorClass carries a queue;
#  "volcano" switches to PodGroup annotations; "none" disables)
GANG_SCHEDULER_ANNOTATION = f"scheduling.{GROUP}/gang-scheduler"
GANG_QUEUE_ANNOTATION = f"scheduling.{GROUP}/queue-name"
GANG_PRIORITY_ANNOTATION = f"scheduling.{GROUP}/priority-class"

# -- annotations ------------------------------------------------------------

DEPLOYMENT_MODE_ANNOTATION = f"serving.{GROUP}/deployment-mode"
MODEL_INIT_ANNOTATION = f"{GROUP}/inject-model-init"
FINE_TUNED_ADAPTER_ANNOTATION = f"{GROUP}/inject-fine-tuned-adapter"
SERVING_SIDECAR_ANNOTATION = f"{GROUP}/inject-serving-sidecar"
TPU_INJECT_ANNOTATION = f"tpu.{GROUP}/auto-inject"       # rdma.ome.io analog
TPU_PROFILE_ANNOTATION = f"tpu.{GROUP}/profile"          # podslice | multislice
TPU_CONTAINER_ANNOTATION = f"tpu.{GROUP}/container-name"
METRICS_AGGREGATION_ANNOTATION = f"{GROUP}/enable-metric-aggregation"
PROMETHEUS_SCRAPE_ANNOTATION = "prometheus.io/scrape"
PROMETHEUS_PORT_ANNOTATION = "prometheus.io/port"

# -- finalizers -------------------------------------------------------------

ISVC_FINALIZER = f"inferenceservice.finalizers.{GROUP}"
BENCHMARK_FINALIZER = f"benchmarkjob.finalizers.{GROUP}"

# -- env contracts ----------------------------------------------------------

MODEL_PATH_ENV = "MODEL_PATH"
SERVED_MODEL_NAME_ENV = "SERVED_MODEL_NAME"
PARALLELISM_SIZE_ENV = "PARALLELISM_SIZE"  # constants.go:272 analog (chips)
PREFILL_SERVICE_URL_ENV = "PREFILL_SERVICE_URL"  # PD decode -> prefill pool
FINE_TUNED_WEIGHT_INFO_ENV = "FINE_TUNED_WEIGHT_INFO"

# libtpu / GKE podslice rendezvous contract (replaces NCCL_*/GLOO_* env)
TPU_WORKER_ID_ENV = "TPU_WORKER_ID"
TPU_WORKER_HOSTNAMES_ENV = "TPU_WORKER_HOSTNAMES"
TPU_TOPOLOGY_ENV = "TPU_TOPOLOGY"
TPU_CHIPS_PER_HOST_ENV = "TPU_CHIPS_PER_HOST_BOUNDS"
TPU_ACCELERATOR_ENV = "TPU_ACCELERATOR_TYPE"
# multislice (DCN) contract
MEGASCALE_COORDINATOR_ENV = "MEGASCALE_COORDINATOR_ADDRESS"
MEGASCALE_NUM_SLICES_ENV = "MEGASCALE_NUM_SLICES"
MEGASCALE_SLICE_ID_ENV = "MEGASCALE_SLICE_ID"
# JAX-level rendezvous for engines that use jax.distributed directly
JAX_COORDINATOR_ENV = "JAX_COORDINATOR_ADDRESS"
JAX_NUM_PROCESSES_ENV = "JAX_NUM_PROCESSES"
JAX_PROCESS_ID_ENV = "JAX_PROCESS_ID"

# LWS-injected env consumed by the leader/worker templates
LWS_LEADER_ADDRESS_ENV = "LWS_LEADER_ADDRESS"
LWS_GROUP_SIZE_ENV = "LWS_GROUP_SIZE"
LWS_WORKER_INDEX_ENV = "LWS_WORKER_INDEX"

# -- resources --------------------------------------------------------------

TPU_RESOURCE = "google.com/tpu"

# -- ports / names ----------------------------------------------------------

ENGINE_PORT = 8080
ROUTER_PORT = 8000
METRICS_PORT = 9090
MAIN_CONTAINER = "ome-container"  # the engine runner container name

OPERATOR_NAMESPACE = "ome"
ISVC_CONFIG_NAME = "inferenceservice-config"

# container name for the model download init container
MODEL_INIT_CONTAINER = "model-init"
SERVING_SIDECAR_CONTAINER = "serving-sidecar"


def engine_name(isvc_name: str) -> str:
    return f"{isvc_name}-engine"


def decoder_name(isvc_name: str) -> str:
    return f"{isvc_name}-decoder"


def router_name(isvc_name: str) -> str:
    return f"{isvc_name}-router"


def predictor_service_name(isvc_name: str) -> str:
    return isvc_name
