"""TPU-native serving engine (JetStream-style continuous batching).

What the reference treats as external L0 engines (SGLang/vLLM inside
runtime containers) is in-repo here: compiled prefill/insert/decode over
the JAX data plane, a continuous-batching scheduler, and an
OpenAI-compatible HTTP front-end.
"""

from .core import DecodeState, InferenceEngine
from .journal import RequestJournal
from .sampling import sample
from .scheduler import (Request, Scheduler, SchedulerDraining,
                        SchedulerOverloaded)
from .server import EngineServer
from .tokenizer import ByteTokenizer, load_tokenizer

__all__ = ["DecodeState", "InferenceEngine", "Request",
           "RequestJournal", "Scheduler", "SchedulerDraining",
           "SchedulerOverloaded", "EngineServer", "ByteTokenizer",
           "load_tokenizer", "sample"]
