"""Inference engine core: slot-based continuous batching primitives.

TPU-first re-design of what the reference delegates to SGLang/vLLM
(SURVEY.md L0 — external engines, out of its repo): here the engine is
in-repo and JAX-native, structured like JetStream for XLA's compilation
model:

  * fixed decode batch of `max_slots` slots, one sequence each — every
    decode step is ONE compiled program with static shapes, whatever
    mix of requests is in flight;
  * prefill runs per-request at bucketed lengths (few compilations),
    producing a KV prefix that is *inserted* into a slot;
  * per-slot cache write positions (KVCache.index as a [B] vector) let
    every slot sit at a different sequence length;
  * sampling params are [B] vectors so one program serves all requests.

The three jitted programs (prefill / insert / decode) donate their
state buffers, so cache updates are in-place in HBM.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models import llama
from ..models.config import ModelConfig
from .sampling import sample

Params = llama.Params


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DecodeState:
    """Device-resident state of the decode batch."""

    k: jax.Array        # [L, B, Smax, K, Dh]
    v: jax.Array        # [L, B, Smax, K, Dh]
    lengths: jax.Array  # [B] int32 — valid kv rows / next write index
    tokens: jax.Array   # [B] int32 — last sampled token per slot


def _bucketize(n: int, buckets: List[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class PrefixCache:
    """LRU of prompt-prefix KV (device arrays).

    Coarse-grained prefix caching: after a prefill, the full prompt's
    KV stays cached; a later prompt sharing that prefix (same system
    prompt, a continuing conversation) prefills only its suffix.
    Entries hold [L, 1, bucket, K, Dh] device buffers — size the
    capacity to HBM headroom (bytes/entry ≈ 2 * L*bucket*K*Dh * 2).
    """

    def __init__(self, capacity: int = 8, min_prefix: int = 16):
        from collections import OrderedDict
        self.capacity = capacity
        self.min_prefix = min_prefix
        self._entries: "OrderedDict[tuple, tuple]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def put(self, ids, k, v, true_len: int, bucket: int):
        if self.capacity <= 0 or true_len < self.min_prefix:
            return
        key = tuple(ids)
        self._entries.pop(key, None)
        self._entries[key] = (k, v, true_len, bucket)
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)

    def match(self, ids) -> Optional[tuple]:
        """Longest cached STRICT prefix of `ids` (the last prompt token
        must re-run so its logits exist for sampling)."""
        if self.capacity <= 0:
            return None
        ids_t = tuple(ids)
        best_key, best_eff = None, 0
        for key, entry in self._entries.items():
            # an exact repeat reuses all but the last token (its logits
            # must be recomputed for sampling)
            eff = min(entry[2], len(ids_t) - 1)
            if eff < self.min_prefix:
                continue
            if ids_t[:eff] == key[:eff] and eff > best_eff:
                best_key, best_eff = key, eff
        if best_key is None:
            self.misses += 1
            return None
        self.hits += 1
        self._entries.move_to_end(best_key)
        k, v, _, bucket = self._entries[best_key]
        return (k, v, best_eff, bucket)


class InferenceEngine:
    """Compiled prefill/insert/decode over one model + one mesh."""

    def __init__(self, params: Params, cfg: ModelConfig,
                 max_slots: int = 8, max_seq: Optional[int] = None,
                 prefill_buckets: Optional[List[int]] = None,
                 prefix_cache_size: int = 0):
        self.params = params
        self.cfg = cfg
        self.max_slots = max_slots
        self.max_seq = max_seq or cfg.max_seq_len
        if prefill_buckets is None:
            prefill_buckets, b = [], 64
            while b < self.max_seq:
                prefill_buckets.append(b)
                b *= 2
            prefill_buckets.append(self.max_seq)
        self.prefill_buckets = prefill_buckets
        self.prefix_cache = PrefixCache(prefix_cache_size)

        cfg_ = cfg

        @functools.partial(jax.jit, static_argnames=("bucket",))
        def _prefill(params, padded: jax.Array, true_len: jax.Array,
                     temperature, top_k, top_p, key, bucket: int):
            cache = llama.KVCache(
                k=jnp.zeros((cfg_.num_layers, 1, bucket, cfg_.num_kv_heads,
                             cfg_.head_dim), cfg_.dtype),
                v=jnp.zeros((cfg_.num_layers, 1, bucket, cfg_.num_kv_heads,
                             cfg_.head_dim), cfg_.dtype),
                index=jnp.zeros((), jnp.int32))
            logits, new_cache = llama.forward(params, cfg_, padded,
                                              cache=cache)
            # last REAL token's logits (right padding occupies the tail)
            last = jnp.take_along_axis(
                logits, (true_len - 1)[:, None, None], axis=1)[:, 0]
            tok = sample(last, key, temperature, top_k, top_p)
            return tok[0], new_cache.k, new_cache.v

        @functools.partial(jax.jit,
                           static_argnames=("total_bucket", "keep"))
        def _prefill_suffix(params, prefix_k, prefix_v,
                            prefix_len: jax.Array, padded: jax.Array,
                            suffix_len: jax.Array, temperature, top_k,
                            top_p, key, total_bucket: int, keep: int):
            """Chunked prefill atop a cached prefix: seed a
            total_bucket cache with the prefix KV, run only the suffix
            (positions continue at prefix_len). Rows past the valid
            lengths hold stale data — kv_len masking makes them
            unreachable."""
            shape = (cfg_.num_layers, 1, total_bucket,
                     cfg_.num_kv_heads, cfg_.head_dim)
            k0 = lax.dynamic_update_slice(
                jnp.zeros(shape, cfg_.dtype),
                prefix_k[:, :, :keep], (0, 0, 0, 0, 0))
            v0 = lax.dynamic_update_slice(
                jnp.zeros(shape, cfg_.dtype),
                prefix_v[:, :, :keep], (0, 0, 0, 0, 0))
            cache = llama.KVCache(k=k0, v=v0, index=prefix_len)
            logits, new_cache = llama.forward(params, cfg_, padded,
                                              cache=cache)
            last = jnp.take_along_axis(
                logits, (suffix_len - 1)[:, None, None], axis=1)[:, 0]
            tok = sample(last, key, temperature, top_k, top_p)
            return tok[0], new_cache.k, new_cache.v

        @functools.partial(jax.jit, donate_argnums=(0,),
                           static_argnames=("bucket",))
        def _insert(state: DecodeState, kv_k, kv_v, slot: jax.Array,
                    true_len: jax.Array, token: jax.Array, bucket: int):
            keep = min(bucket, self.max_seq)
            k = lax.dynamic_update_slice(
                state.k, kv_k[:, :, :keep], (0, slot, 0, 0, 0))
            v = lax.dynamic_update_slice(
                state.v, kv_v[:, :, :keep], (0, slot, 0, 0, 0))
            return DecodeState(
                k=k, v=v,
                lengths=state.lengths.at[slot].set(true_len),
                tokens=state.tokens.at[slot].set(token))

        @functools.partial(jax.jit, donate_argnums=(1,))
        def _decode(params, state: DecodeState, temperature, top_k, top_p,
                    key) -> Tuple[DecodeState, jax.Array]:
            cache = llama.KVCache(k=state.k, v=state.v, index=state.lengths)
            logits, new_cache = llama.forward(
                params, cfg_, state.tokens[:, None], cache=cache)
            toks = sample(logits[:, -1], key, temperature, top_k, top_p)
            return DecodeState(k=new_cache.k, v=new_cache.v,
                               lengths=new_cache.index,
                               tokens=toks), toks

        self._prefill_fn = _prefill
        self._prefill_suffix_fn = _prefill_suffix
        self._insert_fn = _insert
        self._decode_fn = _decode
        self._step = 0
        self._root_key = jax.random.PRNGKey(0)

    # -- state ---------------------------------------------------------

    def new_state(self) -> DecodeState:
        L, B, S = self.cfg.num_layers, self.max_slots, self.max_seq
        shape = (L, B, S, self.cfg.num_kv_heads, self.cfg.head_dim)
        return DecodeState(
            k=jnp.zeros(shape, self.cfg.dtype),
            v=jnp.zeros(shape, self.cfg.dtype),
            lengths=jnp.zeros((B,), jnp.int32),
            tokens=jnp.zeros((B,), jnp.int32))

    # -- ops -----------------------------------------------------------

    def prefill(self, prompt_ids: List[int], temperature: float = 0.0,
                top_k: int = 0, top_p: float = 1.0):
        """Returns (first_token:int, kv pair, true_len, bucket).

        With a prefix cache enabled, a prompt whose leading tokens were
        prefetched by an earlier request runs only its suffix through
        the model (chunked prefill atop the cached KV)."""
        # leave room for one generated token; cap at the largest bucket
        max_prompt = min(self.max_seq - 1, self.prefill_buckets[-1])
        ids = prompt_ids[-max_prompt:]
        self._step += 1
        key = jax.random.fold_in(self._root_key, self._step)
        sampling = (np.asarray([temperature], np.float32),
                    np.asarray([top_k], np.int32),
                    np.asarray([top_p], np.float32))

        hit = self.prefix_cache.match(ids)
        if hit is not None:
            pk, pv, plen, pbucket = hit
            suffix = ids[plen:]
            sbucket = _bucketize(len(suffix), self.prefill_buckets)
            if plen + sbucket > self.prefill_buckets[-1]:
                hit = None  # prefix + suffix overflows: full prefill
        if hit is not None:
            bucket = _bucketize(plen + sbucket, self.prefill_buckets)
            padded = np.asarray(
                [suffix + [0] * (sbucket - len(suffix))], np.int32)
            tok, k, v = self._prefill_suffix_fn(
                self.params, pk, pv, np.asarray(plen, np.int32),
                padded, np.asarray([len(suffix)], np.int32),
                *sampling, key, total_bucket=bucket,
                keep=min(pbucket, bucket))
        else:
            bucket = _bucketize(len(ids), self.prefill_buckets)
            padded = np.asarray(
                [ids + [0] * (bucket - len(ids))], np.int32)
            tok, k, v = self._prefill_fn(
                self.params, padded, np.asarray([len(ids)], np.int32),
                *sampling, key, bucket=bucket)
        self.prefix_cache.put(ids, k, v, len(ids), bucket)
        # multi-host: int() on an array spanning non-addressable
        # devices raises; fetch the local replica instead
        from .multihost import host_value
        return int(host_value(tok)), (k, v), len(ids), bucket

    def insert(self, state: DecodeState, kv, slot: int, true_len: int,
               token: int, bucket: int) -> DecodeState:
        return self._insert_fn(
            state, kv[0], kv[1], np.asarray(slot, np.int32),
            np.asarray(true_len, np.int32),
            np.asarray(token, np.int32), bucket=bucket)

    def decode(self, state: DecodeState, temperature, top_k, top_p,
               ) -> Tuple[DecodeState, jax.Array]:
        """One decode step for ALL slots. Sampling params: [B] arrays."""
        self._step += 1
        key = jax.random.fold_in(self._root_key, self._step)
        return self._decode_fn(self.params, state,
                               np.asarray(temperature, np.float32),
                               np.asarray(top_k, np.int32),
                               np.asarray(top_p, np.float32), key)
