"""Inference engine core: slot-based continuous batching primitives.

TPU-first re-design of what the reference delegates to SGLang/vLLM
(SURVEY.md L0 — external engines, out of its repo): here the engine is
in-repo and JAX-native, structured like JetStream for XLA's compilation
model:

  * fixed decode batch of `max_slots` slots, one sequence each — every
    decode step is ONE compiled program with static shapes, whatever
    mix of requests is in flight;
  * prefill runs per-request at bucketed lengths (few compilations),
    producing a KV prefix that is *inserted* into a slot;
  * per-slot cache write positions (KVCache.index as a [B] vector) let
    every slot sit at a different sequence length;
  * sampling params are [B] vectors so one program serves all requests.

The three jitted programs (prefill / insert / decode) donate their
state buffers, so cache updates are in-place in HBM.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models import llama
from ..models.config import ModelConfig
from .sampling import sample, spec_verify

Params = llama.Params

log = logging.getLogger("ome.engine.core")


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DecodeState:
    """Device-resident state of the decode batch."""

    k: jax.Array        # [L, B, Smax, K, Dh]
    v: jax.Array        # [L, B, Smax, K, Dh]
    lengths: jax.Array  # [B] int32 — valid kv rows / next write index
    tokens: jax.Array   # [B] int32 — last sampled token per slot
    # [B] int32 — LoRA adapter slot per sequence (0 = base model);
    # selects the per-slot low-rank delta inside the decode matmuls
    adapters: jax.Array = None
    # int8 paged pools only ([L, N, K, block] f32): per-(row, head)
    # dequant scales riding next to the quantized pools; None for
    # bf16 pools and the dense cache
    k_scale: jax.Array = None
    v_scale: jax.Array = None


class UnknownAdapterError(ValueError):
    """Request names a LoRA adapter the engine doesn't have loaded —
    a PER-REQUEST error (e.g. racing a hot unload), never a scheduler
    fault."""


class KVPoolExhausted(RuntimeError):
    """Paged-KV insert could not allocate blocks for a new sequence —
    BACKPRESSURE, not a fault: the scheduler requeues the request
    until streams finish and free blocks (decode-time growth instead
    preempts a victim sequence, which re-enters the queue)."""


def _bucketize(n: int, buckets: List[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def _sampling_array(x, dtype) -> np.ndarray:
    """Per-step sampling params: convert host inputs, but pass
    device-resident jax.Arrays through untouched — converting those
    back with np.asarray would force a device->host sync in the middle
    of the decode loop (exactly the bubble the pipelined scheduler
    removes by caching them on device)."""
    if isinstance(x, jax.Array):
        return x
    return np.asarray(x, dtype)


class PrefixCache:
    """Radix (token-block trie) cache of prompt-prefix KV with an HBM
    byte budget.

    Prompts are split into fixed token BLOCKS; each trie node owns one
    block's KV slice ([L, 1, block, K, Dh] device buffers). Sibling
    prompts therefore share every common leading block — a prompt that
    diverges halfway through a cached entry still reuses the shared
    half (the sharing the sglang-router's cache-aware steering relies
    on, round-2 review weak #5). Eviction is byte-accounted LRU over
    leaf nodes: total device bytes never exceed `capacity_bytes`
    regardless of entry count or sequence lengths.

    A hit returns the concatenated leading blocks, so suffix-prefill
    `keep` lengths are block multiples (bounded recompilation:
    max_seq/block variants).
    """

    def __init__(self, capacity_bytes: int = 0, block: int = 32,
                 min_prefix: int = 16, host_capacity_bytes: int = 0):
        self.capacity_bytes = capacity_bytes
        self.block = block
        self.min_prefix = min_prefix
        self._root: Dict[tuple, dict] = {}
        self._tick = 0
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # host-DRAM tier (--prefix-cache-host-mb): LRU of evicted
        # block KV as host numpy copies, keyed by the block's full
        # token path. A device hit that continues into host-resident
        # blocks only ENQUEUES an async swap-in — the admitting
        # request recomputes the remainder locally (suffix prefill is
        # the correctness fallback), the NEXT same-prefix request
        # hits the swapped-in device blocks. 0 disables the tier.
        self.host_capacity_bytes = host_capacity_bytes
        self.host_bytes = 0
        self.host_hits = 0
        self.host_swapins = 0
        self.host_recomputes = 0
        # path -> (np_k, np_v, nbytes); insertion order = LRU order
        import collections
        self._host: "collections.OrderedDict" = \
            collections.OrderedDict()
        import queue
        import threading
        self._tier_lock = threading.Lock()
        self._swap_q = queue.Queue()
        self._swap_thread: Optional[object] = None

    def _leaf_bytes(self, k, v) -> int:
        return k.nbytes + v.nbytes

    def put(self, ids, k, v, true_len: int, bucket: int):
        """Store the KV of `ids[:true_len]` block by block. k/v:
        [L, 1, S>=true_len, K, D*] device arrays (rows past true_len
        are padding and never stored)."""
        if self.capacity_bytes <= 0 or true_len < self.min_prefix:
            return
        with self._tier_lock:
            node_map = self._root
            self._tick += 1
            for off in range(0, (true_len // self.block) * self.block,
                             self.block):
                key = tuple(ids[off:off + self.block])
                node = node_map.get(key)
                if node is None:
                    ks = k[:, :, off:off + self.block]
                    vs = v[:, :, off:off + self.block]
                    node = {"kv": (ks, vs), "children": {},
                            "last": self._tick}
                    node_map[key] = node
                    self.bytes += self._leaf_bytes(ks, vs)
                    # device copy is authoritative again: a stale
                    # host-tier copy of the same path just wastes
                    # host budget
                    ent = self._host.pop(tuple(ids[:off + self.block]),
                                         None)
                    if ent is not None:
                        self.host_bytes -= ent[2]
                node["last"] = self._tick
                node_map = node["children"]
            spills = self._evict_locked()
        self._spill(spills)

    def _evict_locked(self):
        """Drop least-recently-used LEAF nodes until within budget
        (parents stay useful for the prompts that still share them).
        One DFS collects every current leaf; evicting a leaf can
        expose its parent as a new leaf, so loop (bounded by trie
        depth) only if a whole pass wasn't enough. With the host tier
        enabled, an evicted leaf's KV is returned as [(path, kv)] for
        the caller to spill to host DRAM AFTER releasing _tier_lock —
        the device->host copy blocks, and a lock region must never
        reach a blocking fetch."""
        spills = []
        while self.bytes > self.capacity_bytes:
            leaves = []
            stack = [(self._root, ())]
            while stack:
                node_map, path = stack.pop()
                for key, node in node_map.items():
                    if node["children"]:
                        stack.append((node["children"], path + key))
                    else:
                        leaves.append((node["last"], node_map, key,
                                       node, path + key))
            if not leaves:
                return spills
            leaves.sort(key=lambda t: t[0])
            for _, parent_map, key, node, path in leaves:
                if self.bytes <= self.capacity_bytes:
                    return spills
                self.bytes -= self._leaf_bytes(*node["kv"])
                self.evictions += 1
                if self.host_capacity_bytes > 0:
                    spills.append((path, node["kv"]))
                del parent_map[key]
        return spills

    def _device_resident_locked(self, path: tuple) -> bool:
        node_map = self._root
        for off in range(0, len(path), self.block):
            node = node_map.get(path[off:off + self.block])
            if node is None:
                return False
            node_map = node["children"]
        return True

    def _spill(self, spills) -> None:
        """Copy evicted blocks' KV to the host tier. Runs OUTSIDE
        _tier_lock (the jax arrays are immutable, so the fetch needs
        no guard; admission path, never the step path), re-acquiring
        only for the dict edits. A put() that re-created the same
        path while the copy ran wins — its device copy is
        authoritative, so the stale spill is dropped."""
        for path, kv in spills:
            ks = np.asarray(kv[0])
            vs = np.asarray(kv[1])
            nbytes = ks.nbytes + vs.nbytes
            with self._tier_lock:
                if self._device_resident_locked(path):
                    continue
                old = self._host.pop(path, None)
                if old is not None:
                    self.host_bytes -= old[2]
                self._host[path] = (ks, vs, nbytes)
                self.host_bytes += nbytes
                while self.host_bytes > self.host_capacity_bytes \
                        and self._host:
                    _, (_, _, nb) = self._host.popitem(last=False)
                    self.host_bytes -= nb

    def _request_swapin(self, ids, eff: int) -> bool:
        """Queue every consecutive host-resident continuation block
        past the device hit for async swap-in. Called under
        _tier_lock; the actual device upload happens on the swap
        thread so admission never waits on it. Returns whether
        anything was queued — the caller starts the swap thread
        AFTER releasing the lock."""
        paths = []
        while eff + self.block <= len(ids) - 1:
            path = tuple(ids[:eff + self.block])
            if path not in self._host:
                break
            self._host.move_to_end(path)  # refresh host LRU
            paths.append(path)
            eff += self.block
        if not paths:
            return False
        self.host_hits += len(paths)
        # this request cannot use host blocks (the swap must never
        # gate admission): it recomputes the remainder locally
        self.host_recomputes += 1
        for path in paths:
            self._swap_q.put(path)
        return True

    def _ensure_swap_thread(self) -> None:
        import threading
        if self._swap_thread is not None and \
                self._swap_thread.is_alive():
            return
        self._swap_thread = threading.Thread(
            target=self._swap_loop, name="prefix-swap", daemon=True)
        self._swap_thread.start()

    def _swap_loop(self) -> None:
        """Swap-in worker: re-attach host-tier blocks to the device
        trie. Each upload is an async host->device transfer; trie
        surgery holds _tier_lock only for the dict edits. A block
        whose parent chain was evicted in the meantime stays in the
        host tier (a later deeper hit re-queues it)."""
        while True:
            path = self._swap_q.get()
            try:
                if path is None:  # shutdown sentinel (tests)
                    return
                self._swapin_one(path)
            except Exception:  # pragma: no cover — a failed swap
                pass           # only costs a future recompute
            finally:
                self._swap_q.task_done()

    def _swapin_one(self, path: tuple) -> None:
        with self._tier_lock:
            ent = self._host.get(path)
            if ent is None:
                return
            # the parent chain must be device-resident for the block
            # to be reachable by match(); otherwise leave it hosted
            node_map = self._root
            ok = True
            for off in range(0, len(path) - self.block, self.block):
                node = node_map.get(path[off:off + self.block])
                if node is None:
                    ok = False
                    break
                node_map = node["children"]
            key = path[-self.block:]
            if not ok or key in node_map:
                return
            ks, vs, nbytes = self._host.pop(path)
            self.host_bytes -= nbytes
            kd, vd = jnp.asarray(ks), jnp.asarray(vs)
            self._tick += 1
            node_map[key] = {"kv": (kd, vd), "children": {},
                             "last": self._tick}
            self.bytes += self._leaf_bytes(kd, vd)
            self.host_swapins += 1
            spills = self._evict_locked()
        self._spill(spills)

    def drain_swapins(self, timeout: float = 5.0) -> None:
        """Block until every queued swap-in has been applied — test
        and chaos-harness hook, never called from the serving path."""
        import time as _time
        q = self._swap_q
        deadline = _time.monotonic() + timeout
        # unfinished_tasks (not empty()): a popped path still being
        # applied must count — queue-empty races the apply
        while q.unfinished_tasks:
            if _time.monotonic() >= deadline:
                return
            _time.sleep(0.005)

    def tier_conservation(self) -> Tuple[bool, int, int]:
        """Two-tier accounting check: recounted device-trie bytes and
        host-tier bytes must equal the running counters, no block may
        be resident in both tiers, and the host tier must respect its
        budget. Returns (ok, device_blocks, host_blocks) — chaos
        asserts this alongside the pool's kv_conservation."""
        with self._tier_lock:
            dev_bytes = 0
            dev_blocks = 0
            overlap = False
            stack = [(self._root, ())]
            while stack:
                node_map, path = stack.pop()
                for key, node in node_map.items():
                    dev_blocks += 1
                    dev_bytes += self._leaf_bytes(*node["kv"])
                    if path + key in self._host:
                        overlap = True
                    stack.append((node["children"], path + key))
            host_bytes = sum(e[2] for e in self._host.values())
            ok = (dev_bytes == self.bytes
                  and host_bytes == self.host_bytes
                  and not overlap
                  and host_bytes <= max(self.host_capacity_bytes, 0))
            return ok, dev_blocks, len(self._host)

    def match(self, ids, usable=None) -> Optional[tuple]:
        """Longest cached STRICT prefix of `ids` in whole blocks (the
        last prompt token must re-run so its logits exist for
        sampling). Returns (k, v, eff, eff) with k/v concatenated over
        the matched blocks.

        `usable(eff) -> bool` lets the caller veto prefix lengths its
        downstream budget cannot use (e.g. prefix + suffix bucket
        overflowing the largest prefill bucket) BEFORE the hit is
        counted and recency refreshed — shorter candidates are tried
        block by block.

        Host-tier blocks NEVER serve the current request: a match
        that continues into the host tier queues an async swap-in and
        returns only the device-resident prefix (possibly None) — the
        caller recomputes the rest, the next same-prefix request hits
        on device."""
        if self.capacity_bytes <= 0:
            return None
        queued = False
        try:
            with self._tier_lock:
                limit = len(ids) - 1
                node_map = self._root
                slices = []
                eff = 0
                self._tick += 1
                while eff + self.block <= limit:
                    key = tuple(ids[eff:eff + self.block])
                    node = node_map.get(key)
                    if node is None:
                        break
                    node["last"] = self._tick
                    slices.append(node["kv"])
                    eff += self.block
                    node_map = node["children"]
                if self.host_capacity_bytes > 0:
                    queued = self._request_swapin(ids, eff)
                while slices and usable is not None \
                        and not usable(eff):
                    slices.pop()
                    eff -= self.block
                if eff < self.min_prefix:
                    self.misses += 1
                    return None
                self.hits += 1
                if len(slices) == 1:
                    k, v = slices[0]
                else:
                    k = jnp.concatenate([s[0] for s in slices],
                                        axis=2)
                    v = jnp.concatenate([s[1] for s in slices],
                                        axis=2)
                return (k, v, eff, eff)
        finally:
            # thread start stays OUTSIDE the lock region (it is the
            # edge to the swap loop, whose uploads block)
            if queued:
                self._ensure_swap_thread()


class InferenceEngine:
    """Compiled prefill/insert/decode over one model + one mesh."""

    # multi-token device decode (decode_multi) is available: wrappers
    # that delegate per-attribute (ReplicatedEngine) override this to
    # False so the scheduler degrades to K=1 instead of dispatching a
    # program their op stream cannot replicate
    supports_multi_step = True

    def __init__(self, params: Params, cfg: ModelConfig,
                 max_slots: int = 8, max_seq: Optional[int] = None,
                 prefill_buckets: Optional[List[int]] = None,
                 prefix_cache_bytes: int = 0,
                 prefix_host_bytes: int = 0,
                 lora_slots: int = 0, lora_rank: int = 16,
                 kv_block: int = 0, kv_blocks: Optional[int] = None,
                 kv_dtype: Optional[str] = None,
                 mask_table_rows: int = 64,
                 ledger=None):
        self.params = params
        self.cfg = cfg
        self.max_slots = max_slots
        self.max_seq = max_seq or cfg.max_seq_len
        # paged KV (kv_block > 0): the decode cache is a POOL of
        # `kv_blocks` fixed-size blocks + a per-slot block table
        # instead of the dense [L, B, Smax, ...] worst-case slab —
        # HBM sized by tokens in flight, so the same budget serves
        # more slots with mixed-length sequences (vLLM/SGLang
        # PagedAttention, TPU-static: ops/paged.py; r4 verdict #2)
        self.kv_block = int(kv_block)
        # int8-quantized paged pools (--kv-dtype int8): KV rows are
        # stored as int8 + a per-(row, head) f32 scale plane, halving
        # block-pool HBM per cached token — the same budget holds ~2x
        # the sequences (docs/kv-hierarchy.md). Quantization happens
        # on append inside the compiled decode/insert programs;
        # dequantization inside the paged attention kernel.
        kv_dtype = (kv_dtype or "").replace("bfloat16", "bf16")
        if kv_dtype not in ("", "bf16", "int8"):
            raise ValueError(
                f"kv_dtype must be bf16 or int8, got {kv_dtype!r}")
        self.kv_quantized = kv_dtype == "int8"
        if self.kv_quantized and not self.kv_block:
            raise ValueError(
                "--kv-dtype int8 quantizes the paged block pool; "
                "enable paged KV (--kv-block) to use it")
        if self.kv_block:
            if (cfg.mla or cfg.is_moe or cfg.first_k_dense
                    or cfg.sliding_window or cfg.alt_sliding_window
                    or cfg.norm_type != "rmsnorm" or cfg.parallel_block
                    or cfg.attn_sinks):
                raise ValueError(
                    "paged KV supports standard rmsnorm GQA models; "
                    "MLA/MoE/sliding-window/parallel-block/layernorm/"
                    "sink models use the dense cache")
            if jax.devices()[0].platform == "tpu" and (
                    self.kv_block % 128 or cfg.head_dim % 128
                    or cfg.num_heads < 8):
                # outside the Pallas kernel's coverage every layer
                # would silently fall back to the XLA gather, which
                # materializes the dense-equivalent KV per step —
                # defeating the feature; refuse loudly instead
                raise ValueError(
                    f"paged KV on TPU needs --kv-block % 128 == 0, "
                    f"head_dim % 128 == 0 and >= 8 heads for the "
                    f"Pallas kernel (got kv_block={self.kv_block}, "
                    f"head_dim={cfg.head_dim}, heads={cfg.num_heads})")
            self.max_blocks = -(-self.max_seq // self.kv_block)
            # default pool = dense-equivalent capacity (+1: block 0 is
            # the reserved trash block, never allocated, never read)
            self.kv_blocks = kv_blocks or (
                max_slots * self.max_blocks + 1)
            self._table = np.zeros((max_slots, self.max_blocks),
                                   np.int32)
            self._owned: List[List[int]] = [[] for _ in
                                            range(max_slots)]
            self._free_blocks = list(range(self.kv_blocks - 1, 0, -1))
            self._host_len = np.zeros(max_slots, np.int64)
            self._preempted: List[int] = []
            # device-resident copy of the block table, re-uploaded
            # only when the host table actually changed (insert /
            # free_slot / a _grow_blocks block append) — most decode
            # steps append no block, so they reuse the previous upload
            self._table_dirty = True
            self._table_dev: Optional[jax.Array] = None
        if prefill_buckets is None:
            prefill_buckets, b = [], 64
            while b < self.max_seq:
                prefill_buckets.append(b)
                b *= 2
            prefill_buckets.append(self.max_seq)
        self.prefill_buckets = prefill_buckets
        self.prefix_cache = PrefixCache(
            prefix_cache_bytes,
            host_capacity_bytes=prefix_host_bytes)

        # multi-LoRA serving: preallocate `lora_slots` zeroed factor
        # stacks as extra scanned layer leaves ([L, slots+1, r, K]).
        # Slot 0 is the all-zero base; register_adapter hot-writes a
        # slot IN PLACE of the zeros — shapes never change, so no
        # recompilation on adapter load (the punica idea, TPU-shaped).
        self.lora_slots = lora_slots
        self.lora_rank = lora_rank
        self._lora_names: Dict[str, int] = {}
        # which adapter id each DECODE slot currently decodes with —
        # unregister_adapter refuses while any slot references it
        # (r4 advisor: a freed slot id reused mid-stream silently
        # flips in-flight sequences to another adapter)
        self._slot_adapters = np.zeros(max_slots, np.int32)
        import threading as _threading
        self._lora_lock = _threading.Lock()
        if lora_slots > 0:
            if cfg.is_moe and cfg.first_k_dense:
                raise ValueError("multi-LoRA does not support "
                                 "first_k_dense models yet")
            from ..models.lora import _target_dims
            layers = dict(params["layers"])
            n, r, L = lora_slots + 1, lora_rank, cfg.num_layers
            for leaf, (K, N) in _target_dims(cfg).items():
                if leaf not in layers:
                    continue  # MoE models: attention targets only
                layers[leaf + "_lora_a"] = jnp.zeros((L, n, r, K),
                                                     cfg.dtype)
                layers[leaf + "_lora_b"] = jnp.zeros((L, n, r, N),
                                                     cfg.dtype)
            self.params = dict(params, layers=layers)

        cfg_ = cfg

        @functools.partial(jax.jit, static_argnames=("bucket",))
        def _prefill(params, padded: jax.Array, true_len: jax.Array,
                     temperature, top_k, top_p, key, adapter,
                     bucket: int):
            cache = llama.KVCache.create(cfg_, 1, bucket)
            logits, new_cache = llama.forward(params, cfg_, padded,
                                              cache=cache,
                                              adapter_ids=adapter)
            # last REAL token's logits (right padding occupies the tail)
            last = jnp.take_along_axis(
                logits, (true_len - 1)[:, None, None], axis=1)[:, 0]
            tok = sample(last, key, temperature, top_k, top_p)
            return tok[0], new_cache.k, new_cache.v

        @functools.partial(jax.jit,
                           static_argnames=("total_bucket", "keep"))
        def _prefill_suffix(params, prefix_k, prefix_v,
                            prefix_len: jax.Array, padded: jax.Array,
                            suffix_len: jax.Array, temperature, top_k,
                            top_p, key, total_bucket: int, keep: int):
            """Chunked prefill atop a cached prefix: seed a
            total_bucket cache with the prefix KV, run only the suffix
            (positions continue at prefix_len). Rows past the valid
            lengths hold stale data — kv_len masking makes them
            unreachable."""
            base = (cfg_.num_layers, 1, total_bucket,
                    cfg_.kv_cache_heads)
            k0 = lax.dynamic_update_slice(
                jnp.zeros(base + (cfg_.kv_cache_k_dim,), cfg_.dtype),
                prefix_k[:, :, :keep], (0, 0, 0, 0, 0))
            v0 = lax.dynamic_update_slice(
                jnp.zeros(base + (cfg_.kv_cache_v_dim,), cfg_.dtype),
                prefix_v[:, :, :keep], (0, 0, 0, 0, 0))
            cache = llama.KVCache(k=k0, v=v0, index=prefix_len)
            logits, new_cache = llama.forward(params, cfg_, padded,
                                              cache=cache)
            last = jnp.take_along_axis(
                logits, (suffix_len - 1)[:, None, None], axis=1)[:, 0]
            tok = sample(last, key, temperature, top_k, top_p)
            # (suffix prefill stays base-model-only: adapter requests
            # bypass the prefix cache — their KV depends on the
            # adapter, so shared-prefix reuse would be wrong)
            return tok[0], new_cache.k, new_cache.v

        @functools.partial(jax.jit, donate_argnums=(0,),
                           static_argnames=("bucket",))
        def _insert(state: DecodeState, kv_k, kv_v, slot: jax.Array,
                    true_len: jax.Array, token: jax.Array,
                    adapter: jax.Array, bucket: int):
            keep = min(bucket, self.max_seq)
            k = lax.dynamic_update_slice(
                state.k, kv_k[:, :, :keep], (0, slot, 0, 0, 0))
            v = lax.dynamic_update_slice(
                state.v, kv_v[:, :, :keep], (0, slot, 0, 0, 0))
            return DecodeState(
                k=k, v=v,
                lengths=state.lengths.at[slot].set(true_len),
                tokens=state.tokens.at[slot].set(token),
                adapters=state.adapters.at[slot].set(adapter))

        @functools.partial(jax.jit, donate_argnums=(1,))
        def _decode(params, state: DecodeState, temperature, top_k, top_p,
                    key) -> Tuple[DecodeState, jax.Array]:
            cache = llama.KVCache(k=state.k, v=state.v, index=state.lengths)
            logits, new_cache = llama.forward(
                params, cfg_, state.tokens[:, None], cache=cache,
                adapter_ids=state.adapters)
            toks = sample(logits[:, -1], key, temperature, top_k, top_p)
            return DecodeState(k=new_cache.k, v=new_cache.v,
                               lengths=new_cache.index,
                               tokens=toks,
                               adapters=state.adapters), toks

        @functools.partial(jax.jit, donate_argnums=(1,))
        def _decode_masked(params, state: DecodeState, temperature,
                           top_k, top_p, key, mask,
                           ) -> Tuple[DecodeState, jax.Array]:
            """Decode with a [B, V] allowed-token mask (structured
            outputs / JSON mode — engine/structured.py). Separate
            program so unconstrained batches never pay the mask
            transfer."""
            cache = llama.KVCache(k=state.k, v=state.v, index=state.lengths)
            logits, new_cache = llama.forward(
                params, cfg_, state.tokens[:, None], cache=cache,
                adapter_ids=state.adapters)
            masked = jnp.where(mask, logits[:, -1], -jnp.inf)
            toks = sample(masked, key, temperature, top_k, top_p)
            return DecodeState(k=new_cache.k, v=new_cache.v,
                               lengths=new_cache.index,
                               tokens=toks,
                               adapters=state.adapters), toks

        @functools.partial(jax.jit, static_argnames=("bucket",))
        def _prefill_masked(params, padded, true_len, temperature,
                            top_k, top_p, key, mask, adapter,
                            bucket: int):
            """Bucketed prefill whose FIRST sampled token honors the
            structured-output mask."""
            cache = llama.KVCache.create(cfg_, 1, bucket)
            logits, new_cache = llama.forward(params, cfg_, padded,
                                              cache=cache,
                                              adapter_ids=adapter)
            last = jnp.take_along_axis(
                logits, (true_len - 1)[:, None, None], axis=1)[:, 0]
            last = jnp.where(mask, last, -jnp.inf)
            tok = sample(last, key, temperature, top_k, top_p)
            return tok[0], new_cache.k, new_cache.v

        kvb = self.kv_block
        kvq = self.kv_quantized

        @functools.partial(jax.jit, donate_argnums=(0,),
                           static_argnames=("bucket",))
        def _insert_paged(state: DecodeState, kv_k, kv_v,
                          block_ids: jax.Array, slot: jax.Array,
                          true_len: jax.Array, token: jax.Array,
                          adapter: jax.Array, bucket: int):
            """Scatter a prefilled [L, 1, bucket, K, D] KV slab into
            the pool blocks listed in `block_ids` (host-allocated;
            entries past the valid length point at the trash block).
            int8 pools quantize the slab per (layer, row, head) on the
            way in — prefill always computes at the model dtype, so
            the quantization cost rides the (rare) insert, never the
            decode loop."""
            k, v = state.k, state.v
            ksc, vsc = state.k_scale, state.v_scale
            if kvq:
                def quant(x):
                    amax = jnp.max(jnp.abs(x.astype(jnp.float32)),
                                   axis=-1)      # [L, 1, bucket, K]
                    s = jnp.maximum(amax, 1e-8) / 127.0
                    q = jnp.clip(
                        jnp.round(x.astype(jnp.float32)
                                  / s[..., None]),
                        -127, 127).astype(jnp.int8)
                    # scale slab S-minor: [L, 1, K, bucket]
                    return q, jnp.swapaxes(s, -1, -2)
                kv_k, ks = quant(kv_k)
                kv_v, vs = quant(kv_v)
            for i in range(-(-bucket // kvb)):
                ck = kv_k[:, 0, i * kvb:(i + 1) * kvb]
                cv = kv_v[:, 0, i * kvb:(i + 1) * kvb]
                k = lax.dynamic_update_slice(
                    k, ck[:, None], (0, block_ids[i], 0, 0, 0))
                v = lax.dynamic_update_slice(
                    v, cv[:, None], (0, block_ids[i], 0, 0, 0))
                if kvq:
                    csk = ks[:, :, :, i * kvb:(i + 1) * kvb]
                    csv = vs[:, :, :, i * kvb:(i + 1) * kvb]
                    ksc = lax.dynamic_update_slice(
                        ksc, csk, (0, block_ids[i], 0, 0))
                    vsc = lax.dynamic_update_slice(
                        vsc, csv, (0, block_ids[i], 0, 0))
            return DecodeState(
                k=k, v=v,
                lengths=state.lengths.at[slot].set(true_len),
                tokens=state.tokens.at[slot].set(token),
                adapters=state.adapters.at[slot].set(adapter),
                k_scale=ksc, v_scale=vsc)

        @functools.partial(jax.jit, donate_argnums=(1,))
        def _decode_paged(params, state: DecodeState, table,
                          temperature, top_k, top_p, key):
            cache = llama.PagedKVCache(k=state.k, v=state.v,
                                       index=state.lengths, table=table,
                                       k_scale=state.k_scale,
                                       v_scale=state.v_scale)
            logits, nc = llama.forward_paged(
                params, cfg_, state.tokens[:, None], cache,
                adapter_ids=state.adapters)
            toks = sample(logits[:, -1], key, temperature, top_k, top_p)
            return DecodeState(k=nc.k, v=nc.v, lengths=nc.index,
                               tokens=toks,
                               adapters=state.adapters,
                               k_scale=nc.k_scale,
                               v_scale=nc.v_scale), toks

        @functools.partial(jax.jit, donate_argnums=(1,))
        def _decode_masked_paged(params, state: DecodeState, table,
                                 temperature, top_k, top_p, key, mask):
            cache = llama.PagedKVCache(k=state.k, v=state.v,
                                       index=state.lengths, table=table,
                                       k_scale=state.k_scale,
                                       v_scale=state.v_scale)
            logits, nc = llama.forward_paged(
                params, cfg_, state.tokens[:, None], cache,
                adapter_ids=state.adapters)
            masked = jnp.where(mask, logits[:, -1], -jnp.inf)
            toks = sample(masked, key, temperature, top_k, top_p)
            return DecodeState(k=nc.k, v=nc.v, lengths=nc.index,
                               tokens=toks,
                               adapters=state.adapters,
                               k_scale=nc.k_scale,
                               v_scale=nc.v_scale), toks

        smax = self.max_seq

        def _multi_body(i, carry, key, temperature, top_k, top_p,
                        budget, stop_ids, forward_one, mask=None):
            """One fori_loop iteration of the multi-token decode
            program: forward the batch one position, sample on device,
            append KV, and feed the sampled token back as the next
            iteration's input. Per-slot freeze: a slot that sampled a
            stop-table token, spent its token budget, or reached cache
            capacity goes inactive — its token and length are held
            frozen (the re-written row sits past its committed length,
            so it is never readable), keeping every shape static.
            The freeze conditions are a conservative SUBSET of the
            host's finish rules: the device may run long (the host
            discards overshoot at the drain) but never stops a slot
            the host would have continued.

            `mask` ([B, n, V] bool, optional) constrains iteration i's
            sampling to mask[:, i] — the structured-output mask STACK a
            plan precomputed by walking each slot's grammar automaton
            through its forced token run (docs/step-plan.md). All-True
            rows leave a slot unconstrained."""
            st, done, acc, adv = carry
            active = (~done) & (i < budget) & (st.lengths < smax)
            logits, nc = forward_one(st)
            last = logits[:, -1]
            if mask is not None:
                last = jnp.where(mask[:, i], last, -jnp.inf)
            toks = sample(last, jax.random.fold_in(key, i),
                          temperature, top_k, top_p)
            toks = jnp.where(active, toks, st.tokens)
            done = done | jnp.any(toks[:, None] == stop_ids, axis=1)
            acc = acc.at[:, i].set(toks)
            adv = adv + active.astype(jnp.int32)
            st = DecodeState(
                k=nc.k, v=nc.v,
                lengths=jnp.where(active, nc.index, st.lengths),
                tokens=toks, adapters=st.adapters,
                k_scale=getattr(nc, "k_scale", None),
                v_scale=getattr(nc, "v_scale", None))
            return st, done, acc, adv

        def _multi_loop(state, key, temperature, top_k, top_p, budget,
                        stop_ids, forward_one, n: int, mask=None):
            B = state.tokens.shape[0]
            # a slot whose INPUT token is already a stop (the previous
            # chunk sampled it; the host finishes on every stop token)
            # freezes for the whole chunk instead of appending the
            # stop's KV and decoding past it
            done0 = (budget <= 0) | jnp.any(
                state.tokens[:, None] == stop_ids, axis=1)
            carry = (state, done0, jnp.zeros((B, n), jnp.int32),
                     jnp.zeros((B,), jnp.int32))
            state, _, acc, adv = lax.fori_loop(
                0, n, functools.partial(
                    _multi_body, key=key, temperature=temperature,
                    top_k=top_k, top_p=top_p, budget=budget,
                    stop_ids=stop_ids, forward_one=forward_one,
                    mask=mask),
                carry)
            return state, acc, adv

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=("n",))
        def _decode_multi(params, state: DecodeState, temperature,
                          top_k, top_p, key, budget, stop_ids,
                          n: int):
            """n decode iterations inside ONE device program (ROADMAP
            item 2): a fori_loop over {forward → sample → KV append →
            next-token embed} with sampling fused as the loop epilogue
            (per-iteration keys folded from the chunk key), so the
            host syncs once per n tokens instead of once per token.
            budget: [B] int32 remaining-token cap per slot; stop_ids:
            [B, NS] int32 stop table (-1 padding). Returns (state,
            tokens [B, n], advanced [B]) — slot b's real output is
            tokens[b, :advanced[b]], the rest is frozen filler the
            host discards."""

            def forward_one(st):
                cache = llama.KVCache(k=st.k, v=st.v,
                                      index=st.lengths)
                return llama.forward(params, cfg_, st.tokens[:, None],
                                     cache=cache,
                                     adapter_ids=st.adapters)

            return _multi_loop(state, key, temperature, top_k, top_p,
                               budget, stop_ids, forward_one, n)

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=("n",))
        def _decode_multi_paged(params, state: DecodeState, table,
                                temperature, top_k, top_p, key,
                                budget, stop_ids, n: int):
            """Paged-pool multi-token decode. The block table is
            STATIC for the whole chunk: the host pre-allocates blocks
            covering every row the n iterations can write
            (_grow_blocks_spec, the spec-decode discipline) and
            commit_spec() reconciles lengths + returns the surplus
            once `advanced` is drained."""

            def forward_one(st):
                cache = llama.PagedKVCache(k=st.k, v=st.v,
                                           index=st.lengths,
                                           table=table,
                                           k_scale=st.k_scale,
                                           v_scale=st.v_scale)
                return llama.forward_paged(params, cfg_,
                                           st.tokens[:, None], cache,
                                           adapter_ids=st.adapters)

            return _multi_loop(state, key, temperature, top_k, top_p,
                               budget, stop_ids, forward_one, n)

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=("n",))
        def _decode_multi_masked(params, state: DecodeState,
                                 temperature, top_k, top_p, key,
                                 budget, stop_ids, mask, n: int):
            """Multi-token decode with a [B, n, V] per-iteration mask
            stack (structured outputs inside a fused chunk). Separate
            program so unmasked chunks never pay the mask transfer."""

            def forward_one(st):
                cache = llama.KVCache(k=st.k, v=st.v,
                                      index=st.lengths)
                return llama.forward(params, cfg_, st.tokens[:, None],
                                     cache=cache,
                                     adapter_ids=st.adapters)

            return _multi_loop(state, key, temperature, top_k, top_p,
                               budget, stop_ids, forward_one, n,
                               mask=mask)

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=("n",))
        def _decode_multi_masked_paged(params, state: DecodeState,
                                       table, temperature, top_k,
                                       top_p, key, budget, stop_ids,
                                       mask, n: int):

            def forward_one(st):
                cache = llama.PagedKVCache(k=st.k, v=st.v,
                                           index=st.lengths,
                                           table=table,
                                           k_scale=st.k_scale,
                                           v_scale=st.v_scale)
                return llama.forward_paged(params, cfg_,
                                           st.tokens[:, None], cache,
                                           adapter_ids=st.adapters)

            return _multi_loop(state, key, temperature, top_k, top_p,
                               budget, stop_ids, forward_one, n,
                               mask=mask)

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=("k",))
        def _verify(params, state: DecodeState, drafts, draft_len,
                    temperature, top_k, top_p, key, k: int):
            """Speculative verify: one forward over [last_token,
            draft_0..draft_{k-1}] per slot scores all k+1 positions in
            a single weight pass. Draft K/V is written at the slot's
            cache index like any decode write; the ROLLBACK of
            rejected rows is just the per-slot index update below —
            rows past `lengths + accepted + 1` are unreachable
            (kv_len masking) and the next step overwrites them."""
            toks = jnp.concatenate([state.tokens[:, None], drafts],
                                   axis=1)  # [B, k+1]
            cache = llama.KVCache(k=state.k, v=state.v,
                                  index=state.lengths)
            logits, nc = llama.forward(params, cfg_, toks, cache=cache,
                                       adapter_ids=state.adapters)
            out, accepted = spec_verify(logits, drafts, draft_len, key,
                                        temperature, top_k, top_p)
            new_tok = jnp.take_along_axis(out, accepted[:, None],
                                          axis=1)[:, 0]
            return DecodeState(k=nc.k, v=nc.v,
                               lengths=state.lengths + accepted + 1,
                               tokens=new_tok,
                               adapters=state.adapters), out, accepted

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=("k",))
        def _verify_paged(params, state: DecodeState, table, drafts,
                          draft_len, temperature, top_k, top_p, key,
                          k: int):
            """Paged-pool verify: the engine pre-allocates blocks
            covering all k+1 speculative rows before dispatch
            (_grow_blocks_spec); commit_spec() returns the surplus to
            the pool after the accepted count is known."""
            toks = jnp.concatenate([state.tokens[:, None], drafts],
                                   axis=1)
            cache = llama.PagedKVCache(k=state.k, v=state.v,
                                       index=state.lengths, table=table,
                                       k_scale=state.k_scale,
                                       v_scale=state.v_scale)
            logits, nc = llama.forward_paged(
                params, cfg_, toks, cache, adapter_ids=state.adapters)
            out, accepted = spec_verify(logits, drafts, draft_len, key,
                                        temperature, top_k, top_p)
            new_tok = jnp.take_along_axis(out, accepted[:, None],
                                          axis=1)[:, 0]
            return DecodeState(k=nc.k, v=nc.v,
                               lengths=state.lengths + accepted + 1,
                               tokens=new_tok,
                               adapters=state.adapters,
                               k_scale=nc.k_scale,
                               v_scale=nc.v_scale), out, accepted

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=("k",))
        def _verify_masked(params, state: DecodeState, drafts,
                           draft_len, temperature, top_k, top_p, key,
                           mask, k: int):
            """Verify with a [B, V] position-0 mask: masked
            (structured-output) slots ride a verify plan at
            draft_len 0 — their single sampled token honors the
            grammar mask while drafting slots verify normally
            (masked rows never draft, so positions past 0 are only
            reached by unmasked slots). All-True rows are a no-op."""
            toks = jnp.concatenate([state.tokens[:, None], drafts],
                                   axis=1)
            cache = llama.KVCache(k=state.k, v=state.v,
                                  index=state.lengths)
            logits, nc = llama.forward(params, cfg_, toks, cache=cache,
                                       adapter_ids=state.adapters)
            logits = logits.at[:, 0].set(
                jnp.where(mask, logits[:, 0], -jnp.inf))
            out, accepted = spec_verify(logits, drafts, draft_len, key,
                                        temperature, top_k, top_p)
            new_tok = jnp.take_along_axis(out, accepted[:, None],
                                          axis=1)[:, 0]
            return DecodeState(k=nc.k, v=nc.v,
                               lengths=state.lengths + accepted + 1,
                               tokens=new_tok,
                               adapters=state.adapters), out, accepted

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=("k",))
        def _verify_masked_paged(params, state: DecodeState, table,
                                 drafts, draft_len, temperature,
                                 top_k, top_p, key, mask, k: int):
            toks = jnp.concatenate([state.tokens[:, None], drafts],
                                   axis=1)
            cache = llama.PagedKVCache(k=state.k, v=state.v,
                                       index=state.lengths, table=table,
                                       k_scale=state.k_scale,
                                       v_scale=state.v_scale)
            logits, nc = llama.forward_paged(
                params, cfg_, toks, cache, adapter_ids=state.adapters)
            logits = logits.at[:, 0].set(
                jnp.where(mask, logits[:, 0], -jnp.inf))
            out, accepted = spec_verify(logits, drafts, draft_len, key,
                                        temperature, top_k, top_p)
            new_tok = jnp.take_along_axis(out, accepted[:, None],
                                          axis=1)[:, 0]
            return DecodeState(k=nc.k, v=nc.v,
                               lengths=state.lengths + accepted + 1,
                               tokens=new_tok,
                               adapters=state.adapters,
                               k_scale=nc.k_scale,
                               v_scale=nc.v_scale), out, accepted

        # -- device-resident grammar mask table (docs/structured-
        # outputs.md): cached automaton-state masks live as rows of a
        # [S, V] device buffer; the *_idx program variants gather each
        # slot's row in-program from int32 state indices, so a masked
        # step ships K ints per slot instead of K*V mask bools. Row 0
        # is reserved all-True (the unmasked sentinel every idx array
        # defaults to); set_mask_row() refuses to write it.

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _mask_row_set(tab, row, bits):
            return tab.at[row].set(bits)

        @functools.partial(jax.jit, donate_argnums=(1,))
        def _decode_masked_idx(params, state: DecodeState, temperature,
                               top_k, top_p, key, mtab, midx,
                               ) -> Tuple[DecodeState, jax.Array]:
            """Decode gathering each slot's allowed-token row from the
            device mask table by state index ([B] int32)."""
            cache = llama.KVCache(k=state.k, v=state.v,
                                  index=state.lengths)
            logits, new_cache = llama.forward(
                params, cfg_, state.tokens[:, None], cache=cache,
                adapter_ids=state.adapters)
            masked = jnp.where(mtab[midx], logits[:, -1], -jnp.inf)
            toks = sample(masked, key, temperature, top_k, top_p)
            return DecodeState(k=new_cache.k, v=new_cache.v,
                               lengths=new_cache.index,
                               tokens=toks,
                               adapters=state.adapters), toks

        @functools.partial(jax.jit, donate_argnums=(1,))
        def _decode_masked_idx_paged(params, state: DecodeState, table,
                                     temperature, top_k, top_p, key,
                                     mtab, midx):
            cache = llama.PagedKVCache(k=state.k, v=state.v,
                                       index=state.lengths, table=table,
                                       k_scale=state.k_scale,
                                       v_scale=state.v_scale)
            logits, nc = llama.forward_paged(
                params, cfg_, state.tokens[:, None], cache,
                adapter_ids=state.adapters)
            masked = jnp.where(mtab[midx], logits[:, -1], -jnp.inf)
            toks = sample(masked, key, temperature, top_k, top_p)
            return DecodeState(k=nc.k, v=nc.v, lengths=nc.index,
                               tokens=toks,
                               adapters=state.adapters,
                               k_scale=nc.k_scale,
                               v_scale=nc.v_scale), toks

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=("n",))
        def _decode_multi_masked_idx(params, state: DecodeState,
                                     temperature, top_k, top_p, key,
                                     budget, stop_ids, mtab, midx,
                                     n: int):
            """Multi-token decode whose per-iteration [B, n, V] mask
            stack is gathered from the mask table ([B, n] int32)."""

            def forward_one(st):
                cache = llama.KVCache(k=st.k, v=st.v,
                                      index=st.lengths)
                return llama.forward(params, cfg_, st.tokens[:, None],
                                     cache=cache,
                                     adapter_ids=st.adapters)

            return _multi_loop(state, key, temperature, top_k, top_p,
                               budget, stop_ids, forward_one, n,
                               mask=mtab[midx])

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=("n",))
        def _decode_multi_masked_idx_paged(params, state: DecodeState,
                                           table, temperature, top_k,
                                           top_p, key, budget,
                                           stop_ids, mtab, midx,
                                           n: int):

            def forward_one(st):
                cache = llama.PagedKVCache(k=st.k, v=st.v,
                                           index=st.lengths,
                                           table=table,
                                           k_scale=st.k_scale,
                                           v_scale=st.v_scale)
                return llama.forward_paged(params, cfg_,
                                           st.tokens[:, None], cache,
                                           adapter_ids=st.adapters)

            return _multi_loop(state, key, temperature, top_k, top_p,
                               budget, stop_ids, forward_one, n,
                               mask=mtab[midx])

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=("k",))
        def _verify_masked_idx(params, state: DecodeState, drafts,
                               draft_len, temperature, top_k, top_p,
                               key, mtab, midx, k: int):
            """Verify masking ALL k+1 positions from gathered table
            rows ([B, k+1] int32) — unlike the dense variant's
            position-0 mask, because grammar-constrained slots now
            DRAFT (spec-through-grammar): the token emitted at a
            rejection position comes from that position's target
            logits, which must honor that position's mask. Unmasked
            slots point every position at reserved row 0 (all-True)."""
            toks = jnp.concatenate([state.tokens[:, None], drafts],
                                   axis=1)
            cache = llama.KVCache(k=state.k, v=state.v,
                                  index=state.lengths)
            logits, nc = llama.forward(params, cfg_, toks, cache=cache,
                                       adapter_ids=state.adapters)
            logits = jnp.where(mtab[midx], logits, -jnp.inf)
            out, accepted = spec_verify(logits, drafts, draft_len, key,
                                        temperature, top_k, top_p)
            new_tok = jnp.take_along_axis(out, accepted[:, None],
                                          axis=1)[:, 0]
            return DecodeState(k=nc.k, v=nc.v,
                               lengths=state.lengths + accepted + 1,
                               tokens=new_tok,
                               adapters=state.adapters), out, accepted

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=("k",))
        def _verify_masked_idx_paged(params, state: DecodeState, table,
                                     drafts, draft_len, temperature,
                                     top_k, top_p, key, mtab, midx,
                                     k: int):
            toks = jnp.concatenate([state.tokens[:, None], drafts],
                                   axis=1)
            cache = llama.PagedKVCache(k=state.k, v=state.v,
                                       index=state.lengths, table=table,
                                       k_scale=state.k_scale,
                                       v_scale=state.v_scale)
            logits, nc = llama.forward_paged(
                params, cfg_, toks, cache, adapter_ids=state.adapters)
            logits = jnp.where(mtab[midx], logits, -jnp.inf)
            out, accepted = spec_verify(logits, drafts, draft_len, key,
                                        temperature, top_k, top_p)
            new_tok = jnp.take_along_axis(out, accepted[:, None],
                                          axis=1)[:, 0]
            return DecodeState(k=nc.k, v=nc.v,
                               lengths=state.lengths + accepted + 1,
                               tokens=new_tok,
                               adapters=state.adapters,
                               k_scale=nc.k_scale,
                               v_scale=nc.v_scale), out, accepted

        self._prefill_fn = _prefill
        self._prefill_masked_fn = _prefill_masked
        self._prefill_suffix_fn = _prefill_suffix
        self._insert_fn = _insert
        self._decode_fn = _decode
        self._decode_masked_fn = _decode_masked
        self._insert_paged_fn = _insert_paged
        self._decode_paged_fn = _decode_paged
        self._decode_masked_paged_fn = _decode_masked_paged
        self._decode_multi_fn = _decode_multi
        self._decode_multi_paged_fn = _decode_multi_paged
        self._decode_multi_masked_fn = _decode_multi_masked
        self._decode_multi_masked_paged_fn = _decode_multi_masked_paged
        self._verify_fn = _verify
        self._verify_paged_fn = _verify_paged
        self._verify_masked_fn = _verify_masked
        self._verify_masked_paged_fn = _verify_masked_paged
        self._mask_row_fn = _mask_row_set
        self._decode_masked_idx_fn = _decode_masked_idx
        self._decode_masked_idx_paged_fn = _decode_masked_idx_paged
        self._decode_multi_masked_idx_fn = _decode_multi_masked_idx
        self._decode_multi_masked_idx_paged_fn = \
            _decode_multi_masked_idx_paged
        self._verify_masked_idx_fn = _verify_masked_idx
        self._verify_masked_idx_paged_fn = _verify_masked_idx_paged
        self.mask_table_rows = int(mask_table_rows)
        self._mask_table_dev = None  # lazy: [rows, V] bool, row 0 True
        self._step = 0
        self._root_key = jax.random.PRNGKey(0)
        # prefill (admission thread) and decode (scheduler thread) both
        # draw keys; the counter bump must be atomic for distinct keys
        import threading
        self._rng_lock = threading.Lock()
        # optional policy hook: the scheduler ranks preemption victims
        # (priority class, quota overage); None = least progress only
        self._preempt_rank_fn = None
        # the slot whose block growth triggered the current preemption
        # scan; excluded from victim candidates while alternatives
        # exist (a near-pool-size batch request must not livelock as
        # its own repeated victim)
        self._growing_slot: Optional[int] = None
        # program cost ledger (perf/ledger.py): every dispatch below
        # routes through _ledger_capture so each compiled program gets
        # one cost entry; the default is mode "auto" (introspect on
        # TPU, analytic model elsewhere)
        if ledger is None:
            from ..perf.ledger import ProgramLedger
            ledger = ProgramLedger()
        self.ledger = ledger
        self._weight_bytes: Optional[int] = None
        self._param_count: Optional[int] = None

    # -- cost model (perf ledger fallback) -----------------------------

    def kv_row_bytes(self) -> int:
        """HBM bytes one cached KV row (all layers, all heads) costs —
        the single per-token byte model shared by the cost ledger and
        the HbmAccountant kv_cache tenant (perf/hbm.py) so they can't
        drift. int8 pools store 1 byte/element plus two f32 scales per
        (layer, head) row."""
        cfg = self.cfg
        if getattr(self, "kv_quantized", False):
            return cfg.num_layers * cfg.kv_cache_heads * (
                cfg.kv_cache_k_dim + cfg.kv_cache_v_dim + 2 * 4)
        return (cfg.num_layers * cfg.kv_cache_heads
                * (cfg.kv_cache_k_dim + cfg.kv_cache_v_dim)
                * jnp.dtype(cfg.dtype).itemsize)

    def _cost_model(self, tokens: int, kv_rows: int,
                    weight_passes: int = 1) -> Dict[str, float]:
        """Analytic {flops, bytes} for a program moving the whole
        weight set `weight_passes` times while processing `tokens`
        positions against `kv_rows` cached KV rows — the ledger's
        estimate when compiler introspection is unavailable. Shares
        the quantizer's byte model so ledger and checkpoint-size
        accounting can't drift."""
        if self._weight_bytes is None:
            from ..models.quant import quantized_bytes
            self._weight_bytes = quantized_bytes(self.params)
            self._param_count = sum(
                int(leaf.size) for leaf in jax.tree_util.tree_leaves(
                    self.params))
        row = self.kv_row_bytes()
        return {
            "bytes": float(weight_passes * self._weight_bytes
                           + kv_rows * row),
            "flops": 2.0 * self._param_count * max(tokens, 1),
        }

    def _kv_capacity_rows(self) -> int:
        """KV rows the decode cache can address — the bytes a decode
        step's attention streams in the worst case."""
        if self.kv_block:
            return self.kv_blocks * self.kv_block
        return self.max_slots * self.max_seq

    def _ledger_capture(self, name: str, static_desc: str, fn, args,
                        static_kwargs, *, tokens: int, kv_rows: int,
                        weight_passes: int = 1) -> None:
        """Record the program about to be dispatched. Never raises:
        observability must not take down a decode step."""
        led = self.ledger
        if led is None or led.mode == "off":
            return
        try:
            led.capture(name, static_desc, fn, args, static_kwargs,
                        self._cost_model(tokens, kv_rows,
                                         weight_passes))
        except Exception:  # pragma: no cover - defensive
            log.debug("ledger capture failed for %s", name,
                      exc_info=True)

    def _next_key(self):
        with self._rng_lock:
            self._step += 1
            return jax.random.fold_in(self._root_key, self._step)

    # -- state ---------------------------------------------------------

    def new_state(self) -> DecodeState:
        cfg = self.cfg
        L, B, S = cfg.num_layers, self.max_slots, self.max_seq
        if self.kv_block:
            # pool-shaped k/v; the block table stays host-side and is
            # passed to the decode program each step (tiny int32)
            self._table[:] = 0
            self._owned = [[] for _ in range(B)]
            self._free_blocks = list(range(self.kv_blocks - 1, 0, -1))
            self._host_len[:] = 0
            self._preempted = []
            self._table_dirty = True
            self._table_dev = None
            pool = (L, self.kv_blocks, self.kv_block,
                    cfg.kv_cache_heads)
            pool_dtype = jnp.int8 if self.kv_quantized else cfg.dtype
            # distinct scale buffers: the jitted programs donate the
            # whole state, and XLA refuses aliased donated arguments
            scale_shape = (L, self.kv_blocks, cfg.kv_cache_heads,
                           self.kv_block)
            return DecodeState(
                k=jnp.zeros(pool + (cfg.kv_cache_k_dim,), pool_dtype),
                v=jnp.zeros(pool + (cfg.kv_cache_v_dim,), pool_dtype),
                lengths=jnp.zeros((B,), jnp.int32),
                tokens=jnp.zeros((B,), jnp.int32),
                adapters=jnp.zeros((B,), jnp.int32),
                k_scale=(jnp.zeros(scale_shape, jnp.float32)
                         if self.kv_quantized else None),
                v_scale=(jnp.zeros(scale_shape, jnp.float32)
                         if self.kv_quantized else None))
        base = (L, B, S, cfg.kv_cache_heads)
        return DecodeState(
            k=jnp.zeros(base + (cfg.kv_cache_k_dim,), cfg.dtype),
            v=jnp.zeros(base + (cfg.kv_cache_v_dim,), cfg.dtype),
            lengths=jnp.zeros((B,), jnp.int32),
            tokens=jnp.zeros((B,), jnp.int32),
            adapters=jnp.zeros((B,), jnp.int32))

    # -- paged-pool block allocator ------------------------------------

    def free_slot(self, slot: int) -> None:
        """Release a finished slot: its adapter reference always, its
        KV blocks in paged mode (the scheduler calls this; insert()
        also frees implicitly on slot reuse)."""
        self._slot_adapters[slot] = 0
        if not self.kv_block:
            return
        self._free_blocks.extend(reversed(self._owned[slot]))
        self._owned[slot] = []
        self._table[slot] = 0
        self._table_dirty = True
        self._host_len[slot] = 0

    def take_preempted(self) -> List[int]:
        """Slots whose sequences were evicted by pool pressure since
        the last call; the scheduler requeues their requests (their
        generated-so-far tokens become part of the re-prefill
        prompt)."""
        if not self.kv_block:
            return []
        out, self._preempted = list(self._preempted), []
        return out

    def set_preempt_rank(self, fn) -> None:
        """Install a victim-ranking hook: fn(slot) -> sortable key,
        lower = preempt first. The scheduler uses it to rank by
        (quota overage, priority class); ties and the no-hook case
        fall back to least progress (cheapest to re-prefill)."""
        self._preempt_rank_fn = fn

    def _preempt_victim(self) -> bool:
        """Free the blocks of one active sequence to relieve pool
        pressure; False when none remain. Victim order: the installed
        rank hook first (class-aware), then least progress. The slot
        whose growth started the scan (`_growing_slot`) is only
        eligible when it is the sole candidate — otherwise a request
        near pool size could repeatedly evict itself (livelock)."""
        cands = [b for b in range(self.max_slots)
                 if self._owned[b] and b not in self._preempted]
        if not cands:
            return False
        if (self._growing_slot in cands and len(cands) > 1):
            cands = [b for b in cands if b != self._growing_slot]
        rank = self._preempt_rank_fn
        if rank is not None:
            victim = min(cands, key=lambda b: (rank(b),
                                               int(self._host_len[b])))
        else:
            victim = min(cands, key=lambda b: int(self._host_len[b]))
        self._preempted.append(victim)
        self.free_slot(victim)
        return True

    def _grow_blocks(self) -> None:
        """Pre-allocate the block each active slot's NEXT write needs
        (called before every paged decode step, which writes at
        index = length). Pool pressure preempts victims instead of
        failing the node (vLLM-style recompute preemption)."""
        for b in range(self.max_slots):
            if not self._owned[b]:
                continue
            w = int(self._host_len[b])
            if w >= self.max_seq:
                continue
            j = w // self.kv_block
            if j >= len(self._owned[b]) and j < self.max_blocks:
                self._growing_slot = b
                while not self._free_blocks:
                    if not self._preempt_victim():
                        break
                self._growing_slot = None
                if not self._owned[b]:
                    continue  # b itself was the victim
                if not self._free_blocks:
                    # nothing evictable and no block for b's next
                    # write: preempt b EXPLICITLY rather than letting
                    # its writes land in the trash block (a host/
                    # device length desync a future allocator change
                    # could silently re-enable). Defensively
                    # unreachable today — any _preempt_victim success
                    # above frees blocks — but cheap to keep honest.
                    self._preempted.append(b)
                    self.free_slot(b)
                    continue
                nid = self._free_blocks.pop()
                self._owned[b].append(nid)
                self._table[b, j] = nid
                self._table_dirty = True
            self._host_len[b] = w + 1  # mirror of the device +1

    def _grow_blocks_spec(self, rows: int) -> None:
        """Pre-allocate blocks covering each active slot's next `rows`
        writes (a verify step writes k+1 speculative rows at once) —
        WITHOUT advancing the host length mirror: how far the device
        actually advanced is only known after the accepted counts are
        drained, when commit_spec() reconciles and returns the
        surplus. Pool pressure preempts victims exactly like
        _grow_blocks."""
        for b in range(self.max_slots):
            if not self._owned[b]:
                continue
            w = int(self._host_len[b])
            top = min(w + rows, self.max_seq)  # write rows [w, top)
            need = min(-(-top // self.kv_block), self.max_blocks)
            while len(self._owned[b]) < need:
                j = len(self._owned[b])
                self._growing_slot = b
                while not self._free_blocks:
                    if not self._preempt_victim():
                        break
                self._growing_slot = None
                if not self._owned[b]:
                    break  # b itself was the victim
                if not self._free_blocks:
                    # same honesty guard as _grow_blocks: never let a
                    # live slot write into the trash block
                    self._preempted.append(b)
                    self.free_slot(b)
                    break
                nid = self._free_blocks.pop()
                self._owned[b].append(nid)
                self._table[b, j] = nid
                self._table_dirty = True

    def commit_spec(self, slot: int, advance: int,
                    reserve: int = 0) -> None:
        """Reconcile a slot's host length mirror after a drained
        verify (or multi-token decode) step advanced its device
        length by `advance`, and return speculatively-allocated
        blocks past the new length to the pool — the paged-KV
        rollback of rejected draft rows. `reserve` keeps blocks
        covering that many rows PAST the new length allocated:
        under chunk pipelining, later chunks already dispatched will
        write rows [len, len+reserve) — trimming those blocks here
        would let an insert re-allocate them before the in-flight
        writes execute."""
        if not self.kv_block or not self._owned[slot]:
            return
        self._host_len[slot] = min(
            int(self._host_len[slot]) + advance, self.max_seq)
        need = self.blocks_needed(min(
            int(self._host_len[slot]) + max(int(reserve), 0),
            self.max_seq))
        while len(self._owned[slot]) > need:
            nid = self._owned[slot].pop()
            self._table[slot, len(self._owned[slot])] = 0
            self._free_blocks.append(nid)
            self._table_dirty = True

    @property
    def kv_pool_stats(self) -> Dict[str, int]:
        return {"kv_blocks": getattr(self, "kv_blocks", 0),
                "kv_blocks_free": len(getattr(self, "_free_blocks",
                                              ())),
                "kv_block_tokens": self.kv_block}

    def kv_conservation(self) -> Tuple[bool, int]:
        """Block-pool conservation check (the PagedAttention
        discipline): free + owned must account for every allocatable
        block (kv_blocks − 1; block 0 is the reserved trash block), no
        block may appear twice, block 0 may never be owned, and the
        device block table must mirror the host owned lists. Returns
        (ok, owned_count). Authoritative at quiescence — the chaos
        harness asserts it between episodes; a concurrent insert can
        make a mid-step scrape read False transiently."""
        if not self.kv_block:
            return True, 0
        free = list(self._free_blocks)
        owned_all: List[int] = []
        for slot in range(self.max_slots):
            owned = [int(b) for b in self._owned[slot]]
            owned_all.extend(owned)
            row = [int(x) for x in
                   np.asarray(self._table[slot, :len(owned)])]
            if row != owned:
                return False, len(owned_all)
        blocks = [int(b) for b in free] + owned_all
        ok = (len(blocks) == self.kv_blocks - 1
              and len(set(blocks)) == len(blocks)
              and 0 not in blocks)
        # hierarchical-KV extension: the prefix cache's two tiers
        # must also account exactly (device trie + host LRU sum, no
        # double residency) — one gauge covers the whole KV hierarchy
        tc = getattr(self.prefix_cache, "tier_conservation", None)
        if callable(tc):
            ok = ok and tc()[0]
        return ok, len(owned_all)

    # -- multi-LoRA registry -------------------------------------------

    @property
    def adapter_names(self) -> List[str]:
        return sorted(self._lora_names)

    def adapter_id(self, name: Optional[str]) -> int:
        """Resolve an adapter name to its slot id (0/None = base)."""
        if not name:
            return 0
        try:
            return self._lora_names[name]
        except KeyError:
            raise UnknownAdapterError(
                f"unknown adapter {name!r} (loaded: "
                f"{self.adapter_names or 'none'})")

    def register_adapter(self, name: str, adapter_dir: str) -> int:
        """Load a PEFT adapter dir into a free LoRA slot (hot, no
        recompilation: writes into the preallocated factor stacks).
        Re-registering a name overwrites its slot (adapter update)."""
        if self.lora_slots <= 0:
            raise ValueError("engine started without LoRA slots "
                             "(--lora-slots)")
        from ..models.lora import load_adapter_matrices
        mats = load_adapter_matrices(adapter_dir, self.cfg,
                                     rank_pad=self.lora_rank)
        with self._lora_lock:
            idx = self._lora_names.get(name)
            if idx is None:
                used = set(self._lora_names.values())
                free = [i for i in range(1, self.lora_slots + 1)
                        if i not in used]
                if not free:
                    raise ValueError(
                        f"all {self.lora_slots} LoRA slots in use")
                idx = free[0]
            layers = dict(self.params["layers"])
            for leaf, (A, B) in mats.items():
                ka, kb = leaf + "_lora_a", leaf + "_lora_b"
                if ka not in layers:
                    raise ValueError(f"model has no target {leaf}")
                layers[ka] = layers[ka].at[:, idx].set(
                    A.astype(self.cfg.dtype))
                layers[kb] = layers[kb].at[:, idx].set(
                    B.astype(self.cfg.dtype))
            # atomic reference swap: in-flight steps keep the old tree
            self.params = dict(self.params, layers=layers)
            self._lora_names[name] = idx
        return idx

    def unregister_adapter(self, name: str) -> None:
        with self._lora_lock:
            idx = self._lora_names.get(name)
            if idx is None:
                return
            if (self._slot_adapters == idx).any():
                raise ValueError(
                    f"adapter {name!r} is decoding in-flight "
                    f"sequences; retry after they finish")
            self._lora_names.pop(name)
            layers = dict(self.params["layers"])
            for key in list(layers):
                if key.endswith("_lora_a") or key.endswith("_lora_b"):
                    layers[key] = layers[key].at[:, idx].set(0.0)
            self.params = dict(self.params, layers=layers)

    # -- ops -----------------------------------------------------------

    def prefill(self, prompt_ids: List[int], temperature: float = 0.0,
                top_k: int = 0, top_p: float = 1.0,
                first_mask: Optional[np.ndarray] = None,
                adapter: Optional[str] = None):
        """Returns (first_token:int, kv pair, true_len, bucket).

        With a prefix cache enabled, a prompt whose leading tokens were
        prefetched by an earlier request runs only its suffix through
        the model (chunked prefill atop the cached KV). `first_mask`
        ([V] bool) constrains the first sampled token (structured
        outputs) and bypasses the prefix-cache suffix path (one shape
        fewer to compile; constrained prompts still seed the cache)."""
        # leave room for one generated token; cap at the largest bucket
        max_prompt = min(self.max_seq - 1, self.prefill_buckets[-1])
        ids = prompt_ids[-max_prompt:]
        key = self._next_key()
        sampling = (np.asarray([temperature], np.float32),
                    np.asarray([top_k], np.int32),
                    np.asarray([top_p], np.float32))

        def _pow2_keep(plen: int) -> int:
            # quantize the reused prefix length to a power of two:
            # `keep` is a STATIC jit arg, so arbitrary block multiples
            # would compile a fresh _prefill_suffix program per length
            # (seconds each on TPU); powers of two bound the compile
            # space to ~log2(max_seq) x len(buckets) variants
            return 1 << (max(plen, 1).bit_length() - 1)

        def _usable(plen: int) -> bool:
            k = _pow2_keep(plen)
            # quantized prefix + bucketized suffix must fit the
            # largest bucket
            return (k >= self.prefix_cache.min_prefix
                    and k + _bucketize(len(ids) - k,
                                       self.prefill_buckets)
                    <= self.prefill_buckets[-1])

        aid = self.adapter_id(adapter)
        # adapter prefills bypass the prefix cache entirely: cached KV
        # was computed with (some) adapter's projections, so sharing
        # across adapters — or with the base — would be silently wrong
        hit = None if (first_mask is not None or aid != 0) \
            else self.prefix_cache.match(ids, usable=_usable)
        if hit is not None:
            pk, pv, plen, _pbucket = hit
            plen = _pow2_keep(plen)  # discard the ragged tail blocks
            # slice to the quantized length HOST-side: the arrays'
            # shapes are part of the jit compile key too
            pk, pv = pk[:, :, :plen], pv[:, :, :plen]
            suffix = ids[plen:]
            sbucket = _bucketize(len(suffix), self.prefill_buckets)
            bucket = _bucketize(plen + sbucket, self.prefill_buckets)
            padded = np.asarray(
                [suffix + [0] * (sbucket - len(suffix))], np.int32)
            args = (self.params, pk, pv, np.asarray(plen, np.int32),
                    padded, np.asarray([len(suffix)], np.int32),
                    *sampling, key)
            kw = dict(total_bucket=bucket, keep=min(plen, bucket))
            self._ledger_capture(
                "prefill_suffix", f"total={bucket},keep={kw['keep']}",
                self._prefill_suffix_fn, args, kw,
                tokens=sbucket, kv_rows=bucket)
            tok, k, v = self._prefill_suffix_fn(*args, **kw)
        else:
            bucket = _bucketize(len(ids), self.prefill_buckets)
            padded = np.asarray(
                [ids + [0] * (bucket - len(ids))], np.int32)
            aid_arr = np.asarray([aid], np.int32)
            if first_mask is not None:
                args = (self.params, padded,
                        np.asarray([len(ids)], np.int32), *sampling,
                        key, np.asarray(first_mask, bool)[None, :],
                        aid_arr)
                self._ledger_capture(
                    "prefill_masked", f"bucket={bucket}",
                    self._prefill_masked_fn, args,
                    dict(bucket=bucket), tokens=bucket,
                    kv_rows=bucket)
                tok, k, v = self._prefill_masked_fn(*args,
                                                    bucket=bucket)
            else:
                args = (self.params, padded,
                        np.asarray([len(ids)], np.int32), *sampling,
                        key, aid_arr)
                self._ledger_capture(
                    "prefill", f"bucket={bucket}", self._prefill_fn,
                    args, dict(bucket=bucket), tokens=bucket,
                    kv_rows=bucket)
                tok, k, v = self._prefill_fn(*args, bucket=bucket)
        if aid == 0:
            self.prefix_cache.put(ids, k, v, len(ids), bucket)
        # multi-host: int() on an array spanning non-addressable
        # devices raises; fetch the local replica instead
        from .multihost import host_value
        return int(host_value(tok)), (k, v), len(ids), bucket

    def blocks_needed(self, n_tokens: int) -> int:
        """Pool blocks covering `n_tokens` KV rows + the next write —
        the single accounting used by insert() AND the scheduler's
        pre-prefill pool check (they must not drift)."""
        return min(-(-(n_tokens + 1) // self.kv_block),
                   self.max_blocks)

    def insert(self, state: DecodeState, kv, slot: int, true_len: int,
               token: int, bucket: int,
               adapter: Optional[str] = None) -> DecodeState:
        if self.kv_block:
            with self._lora_lock:
                # fail fast BEFORE the allocator touches any blocks;
                # the dense path's only resolve is the locked one below
                self.adapter_id(adapter)
            bs = self.kv_block
            self.free_slot(slot)  # BEFORE recording the adapter ref
            need = self.blocks_needed(true_len)
            if len(self._free_blocks) < need:
                # backpressure, not a fault: the scheduler requeues
                # this request until running streams free blocks
                raise KVPoolExhausted(
                    f"need {need} KV blocks, {len(self._free_blocks)} "
                    f"free (pool {self.kv_blocks} x {bs} tokens)")
            ids = [self._free_blocks.pop() for _ in range(need)]
            self._owned[slot] = ids
            self._table[slot, :need] = ids
            self._table_dirty = True
            self._host_len[slot] = true_len
        # re-resolve + record under the adapter lock: an unregister
        # between resolution and recording would zero the stacks this
        # sequence is about to decode with (review TOCTOU); if it
        # slipped into the window above, return the freshly allocated
        # blocks instead of orphaning them on a live slot
        try:
            with self._lora_lock:
                aid_i = self.adapter_id(adapter)
                self._slot_adapters[slot] = aid_i
        except UnknownAdapterError:
            if self.kv_block:
                self.free_slot(slot)
            raise
        aid = np.asarray(aid_i, np.int32)
        if self.kv_block:
            nb_write = -(-bucket // bs)
            # blocks past the valid length land in the trash block (0)
            block_ids = np.zeros(nb_write, np.int32)
            nw = min(need, nb_write)
            block_ids[:nw] = ids[:nw]
            return self._insert_paged_fn(
                state, kv[0], kv[1], block_ids,
                np.asarray(slot, np.int32),
                np.asarray(true_len, np.int32),
                np.asarray(token, np.int32), aid, bucket=bucket)
        return self._insert_fn(
            state, kv[0], kv[1], np.asarray(slot, np.int32),
            np.asarray(true_len, np.int32),
            np.asarray(token, np.int32), aid,
            bucket=bucket)

    def _mask_table(self) -> jax.Array:
        """The device-resident [mask_table_rows, V] grammar mask
        table, created all-True on first touch (all-True rows are
        safe: they mask nothing). Row 0 stays all-True forever — the
        sentinel unmasked slots index."""
        if self._mask_table_dev is None:
            self._mask_table_dev = jnp.ones(
                (self.mask_table_rows, self.cfg.vocab_size), bool)
        return self._mask_table_dev

    def set_mask_row(self, row: int, bits: np.ndarray) -> None:
        """Upload one grammar-state mask as row `row` (>= 1; row 0 is
        the reserved all-True sentinel) of the device mask table.
        Called by the scheduler's GrammarMaskCache on cache miss;
        eviction is just the next upload overwriting the row. The
        update is an ordinary device computation, so it serializes
        with in-flight decode dispatches — a row can be rewritten
        while the plan that referenced it is still executing only
        after that plan's gather has been issued."""
        row = int(row)
        if not 1 <= row < self.mask_table_rows:
            raise ValueError(f"mask row {row} out of range "
                             f"[1, {self.mask_table_rows})")
        tab = self._mask_table()
        self._mask_table_dev = self._mask_row_fn(
            tab, np.asarray(row, np.int32), np.asarray(bits, bool))

    def decode(self, state: DecodeState, temperature, top_k, top_p,
               mask: Optional[np.ndarray] = None,
               mask_idx: Optional[np.ndarray] = None,
               ) -> Tuple[DecodeState, jax.Array]:
        """One decode step for ALL slots. Sampling params: [B] arrays
        — host arrays are converted; already-device-resident
        jax.Arrays (the scheduler's sampling cache) pass straight
        through. `mask` ([B, V] bool) routes through the masked
        program (structured outputs); None keeps the maskless one.
        `mask_idx` ([B] int32, wins over `mask`) instead gathers each
        slot's mask row from the device-resident mask table — B ints
        of transfer instead of B*V bools; unmasked slots pass 0 (the
        reserved all-True row).

        The returned tokens stay device-resident with a host copy
        already in flight (`copy_to_host_async`), so a pipelined
        caller can dispatch the next step before reading them; the
        eventual `np.asarray(toks)` then completes an overlapped copy
        instead of starting a blocking one."""
        key = self._next_key()
        sampling = (_sampling_array(temperature, np.float32),
                    _sampling_array(top_k, np.int32),
                    _sampling_array(top_p, np.float32))
        if self.kv_block:
            self._grow_blocks()
            if self._table_dirty or self._table_dev is None:
                # upload once per table CHANGE, not once per step; the
                # copy keeps the device table stable while steps run
                self._table_dev = jnp.asarray(self._table.copy())
                self._table_dirty = False
            table = self._table_dev
            cap = self._kv_capacity_rows()
            if mask_idx is not None:
                args = (self.params, state, table, *sampling, key,
                        self._mask_table(),
                        np.asarray(mask_idx, np.int32))
                self._ledger_capture(
                    "decode_masked_idx_paged", "",
                    self._decode_masked_idx_paged_fn, args, {},
                    tokens=self.max_slots, kv_rows=cap)
                state, toks = self._decode_masked_idx_paged_fn(*args)
            elif mask is not None:
                args = (self.params, state, table, *sampling, key,
                        np.asarray(mask, bool))
                self._ledger_capture(
                    "decode_masked_paged", "",
                    self._decode_masked_paged_fn, args, {},
                    tokens=self.max_slots, kv_rows=cap)
                state, toks = self._decode_masked_paged_fn(*args)
            else:
                args = (self.params, state, table, *sampling, key)
                self._ledger_capture(
                    "decode_paged", "", self._decode_paged_fn, args,
                    {}, tokens=self.max_slots, kv_rows=cap)
                state, toks = self._decode_paged_fn(*args)
        elif mask_idx is not None:
            args = (self.params, state, *sampling, key,
                    self._mask_table(), np.asarray(mask_idx, np.int32))
            self._ledger_capture(
                "decode_masked_idx", "", self._decode_masked_idx_fn,
                args, {}, tokens=self.max_slots,
                kv_rows=self._kv_capacity_rows())
            state, toks = self._decode_masked_idx_fn(*args)
        elif mask is not None:
            args = (self.params, state, *sampling, key,
                    np.asarray(mask, bool))
            self._ledger_capture(
                "decode_masked", "", self._decode_masked_fn, args, {},
                tokens=self.max_slots, kv_rows=self._kv_capacity_rows())
            state, toks = self._decode_masked_fn(*args)
        else:
            args = (self.params, state, *sampling, key)
            self._ledger_capture(
                "decode", "", self._decode_fn, args, {},
                tokens=self.max_slots, kv_rows=self._kv_capacity_rows())
            state, toks = self._decode_fn(*args)
        copy = getattr(toks, "copy_to_host_async", None)
        if copy is not None:  # sharded/global arrays may not have it
            copy()
        return state, toks

    def decode_multi(self, state: DecodeState, temperature, top_k,
                     top_p, steps: int, budget, stop_ids,
                     lookahead_rows: Optional[int] = None,
                     mask: Optional[np.ndarray] = None,
                     mask_idx: Optional[np.ndarray] = None,
                     ) -> Tuple[DecodeState, jax.Array, jax.Array]:
        """`steps` decode iterations for ALL slots in ONE device
        program — the host pays one dispatch and one sync per chunk
        instead of per token (docs/multi-step-decode.md).

        budget: [B] int32 per-slot remaining-token cap (0 freezes the
        slot for the chunk); stop_ids: [B, NS] int32 per-slot stop
        table, -1 padding (sampled tokens are non-negative, so -1
        never matches). Both may be host numpy or device-cached
        jax.Arrays, like the sampling params. lookahead_rows (paged
        only): KV rows to pre-allocate per slot before dispatch —
        pipelined callers pass the summed rows of every plan in
        flight plus this one so each dispatch's writes land in owned
        blocks; defaults to `steps`. mask ([B, steps, V] bool,
        optional) applies a per-iteration structured-output mask
        stack (docs/step-plan.md) through the masked program
        variants; mask_idx ([B, steps] int32, wins over mask) gathers
        the stack from the device-resident mask table instead —
        steps ints per slot on the wire, 0 = the all-True row.

        Returns (state, tokens [B, steps], advanced [B]) with host
        copies of the outputs already in flight (mirroring decode()):
        slot b really produced tokens[b, :advanced[b]] — columns past
        that are frozen filler the caller must discard. Paged callers
        reconcile each drained chunk with commit_spec(slot, advanced,
        reserve=...)."""
        key = self._next_key()
        sampling = (_sampling_array(temperature, np.float32),
                    _sampling_array(top_k, np.int32),
                    _sampling_array(top_p, np.float32))
        budget = _sampling_array(budget, np.int32)
        stop_ids = _sampling_array(stop_ids, np.int32)
        n = int(steps)
        if self.kv_block:
            rows = n if lookahead_rows is None else int(lookahead_rows)
            self._grow_blocks_spec(rows)
            if self._table_dirty or self._table_dev is None:
                self._table_dev = jnp.asarray(self._table.copy())
                self._table_dirty = False
            if mask_idx is not None:
                args = (self.params, state, self._table_dev, *sampling,
                        key, budget, stop_ids, self._mask_table(),
                        np.asarray(mask_idx, np.int32))
                self._ledger_capture(
                    "decode_multi_masked_idx_paged", f"n={n}",
                    self._decode_multi_masked_idx_paged_fn, args,
                    dict(n=n), tokens=self.max_slots * n,
                    kv_rows=n * self._kv_capacity_rows(),
                    weight_passes=n)
                state, toks, adv = \
                    self._decode_multi_masked_idx_paged_fn(*args, n=n)
            elif mask is not None:
                args = (self.params, state, self._table_dev, *sampling,
                        key, budget, stop_ids, np.asarray(mask, bool))
                self._ledger_capture(
                    "decode_multi_masked_paged", f"n={n}",
                    self._decode_multi_masked_paged_fn, args,
                    dict(n=n), tokens=self.max_slots * n,
                    kv_rows=n * self._kv_capacity_rows(),
                    weight_passes=n)
                state, toks, adv = \
                    self._decode_multi_masked_paged_fn(*args, n=n)
            else:
                args = (self.params, state, self._table_dev, *sampling,
                        key, budget, stop_ids)
                self._ledger_capture(
                    "decode_multi_paged", f"n={n}",
                    self._decode_multi_paged_fn, args, dict(n=n),
                    tokens=self.max_slots * n,
                    kv_rows=n * self._kv_capacity_rows(),
                    weight_passes=n)
                state, toks, adv = \
                    self._decode_multi_paged_fn(*args, n=n)
        elif mask_idx is not None:
            args = (self.params, state, *sampling, key, budget,
                    stop_ids, self._mask_table(),
                    np.asarray(mask_idx, np.int32))
            self._ledger_capture(
                "decode_multi_masked_idx", f"n={n}",
                self._decode_multi_masked_idx_fn, args, dict(n=n),
                tokens=self.max_slots * n,
                kv_rows=n * self._kv_capacity_rows(), weight_passes=n)
            state, toks, adv = \
                self._decode_multi_masked_idx_fn(*args, n=n)
        elif mask is not None:
            args = (self.params, state, *sampling, key, budget,
                    stop_ids, np.asarray(mask, bool))
            self._ledger_capture(
                "decode_multi_masked", f"n={n}",
                self._decode_multi_masked_fn, args, dict(n=n),
                tokens=self.max_slots * n,
                kv_rows=n * self._kv_capacity_rows(), weight_passes=n)
            state, toks, adv = self._decode_multi_masked_fn(*args, n=n)
        else:
            args = (self.params, state, *sampling, key, budget,
                    stop_ids)
            self._ledger_capture(
                "decode_multi", f"n={n}", self._decode_multi_fn, args,
                dict(n=n), tokens=self.max_slots * n,
                kv_rows=n * self._kv_capacity_rows(), weight_passes=n)
            state, toks, adv = self._decode_multi_fn(*args, n=n)
        for arr in (toks, adv):
            copy = getattr(arr, "copy_to_host_async", None)
            if copy is not None:
                copy()
        return state, toks, adv

    def verify(self, state: DecodeState, drafts: np.ndarray,
               draft_len: np.ndarray, temperature, top_k, top_p,
               lookahead_rows: Optional[int] = None,
               mask: Optional[np.ndarray] = None,
               mask_idx: Optional[np.ndarray] = None,
               ) -> Tuple[DecodeState, jax.Array, jax.Array]:
        """One speculative verify step for ALL slots: score the k
        drafted tokens plus one bonus position in a single weight
        pass and accept per slot the longest valid prefix
        (sampling.spec_verify). A slot with draft_len 0 degenerates
        to a plain decode step — same logits, same sampling rule.

        drafts: [B, k] int32 host array (garbage past draft_len);
        draft_len: [B] int32 in [0, k]. Sampling params as decode().
        mask ([B, V] bool, optional) constrains position-0 sampling —
        how masked (structured-output) slots ride a verify plan at
        draft_len 0. mask_idx ([B, k+1] int32, wins over mask)
        gathers a full per-position mask from the device mask table —
        the spec-through-grammar path, where masked slots DRAFT and
        every scored position honors its own grammar mask (0 = the
        all-True row). Returns (state, out_tokens [B, k+1], accepted
        [B]) with host copies of the outputs already in flight,
        mirroring decode(): slot b emits out_tokens[b, :accepted[b]+1].

        Verify steps pipeline like decode steps; paged callers pass
        lookahead_rows (summed rows of every plan in flight plus this
        one's k+1, defaulting to k+1) so the block pre-allocation
        covers in-flight plans, and reconcile each drained step with
        commit_spec(slot, accepted+1, reserve=...) — the same surplus
        discipline as decode_multi."""
        key = self._next_key()
        sampling = (_sampling_array(temperature, np.float32),
                    _sampling_array(top_k, np.int32),
                    _sampling_array(top_p, np.float32))
        drafts = np.asarray(drafts, np.int32)
        draft_len = np.asarray(draft_len, np.int32)
        k = int(drafts.shape[1])
        if self.kv_block:
            rows = (k + 1 if lookahead_rows is None
                    else int(lookahead_rows))
            self._grow_blocks_spec(rows)
            if self._table_dirty or self._table_dev is None:
                self._table_dev = jnp.asarray(self._table.copy())
                self._table_dirty = False
            if mask_idx is not None:
                args = (self.params, state, self._table_dev, drafts,
                        draft_len, *sampling, key, self._mask_table(),
                        np.asarray(mask_idx, np.int32))
                self._ledger_capture(
                    "verify_masked_idx_paged", f"k={k}",
                    self._verify_masked_idx_paged_fn, args, dict(k=k),
                    tokens=self.max_slots * (k + 1),
                    kv_rows=self._kv_capacity_rows()
                    + self.max_slots * (k + 1))
                state, out, accepted = \
                    self._verify_masked_idx_paged_fn(*args, k=k)
            elif mask is not None:
                args = (self.params, state, self._table_dev, drafts,
                        draft_len, *sampling, key,
                        np.asarray(mask, bool))
                self._ledger_capture(
                    "verify_masked_paged", f"k={k}",
                    self._verify_masked_paged_fn, args, dict(k=k),
                    tokens=self.max_slots * (k + 1),
                    kv_rows=self._kv_capacity_rows()
                    + self.max_slots * (k + 1))
                state, out, accepted = \
                    self._verify_masked_paged_fn(*args, k=k)
            else:
                args = (self.params, state, self._table_dev, drafts,
                        draft_len, *sampling, key)
                self._ledger_capture(
                    "verify_paged", f"k={k}", self._verify_paged_fn,
                    args, dict(k=k),
                    tokens=self.max_slots * (k + 1),
                    kv_rows=self._kv_capacity_rows()
                    + self.max_slots * (k + 1))
                state, out, accepted = self._verify_paged_fn(*args,
                                                             k=k)
        elif mask_idx is not None:
            args = (self.params, state, drafts, draft_len, *sampling,
                    key, self._mask_table(),
                    np.asarray(mask_idx, np.int32))
            self._ledger_capture(
                "verify_masked_idx", f"k={k}",
                self._verify_masked_idx_fn, args, dict(k=k),
                tokens=self.max_slots * (k + 1),
                kv_rows=self._kv_capacity_rows()
                + self.max_slots * (k + 1))
            state, out, accepted = \
                self._verify_masked_idx_fn(*args, k=k)
        elif mask is not None:
            args = (self.params, state, drafts, draft_len, *sampling,
                    key, np.asarray(mask, bool))
            self._ledger_capture(
                "verify_masked", f"k={k}", self._verify_masked_fn,
                args, dict(k=k), tokens=self.max_slots * (k + 1),
                kv_rows=self._kv_capacity_rows()
                + self.max_slots * (k + 1))
            state, out, accepted = self._verify_masked_fn(*args, k=k)
        else:
            args = (self.params, state, drafts, draft_len, *sampling,
                    key)
            self._ledger_capture(
                "verify", f"k={k}", self._verify_fn, args, dict(k=k),
                tokens=self.max_slots * (k + 1),
                kv_rows=self._kv_capacity_rows()
                + self.max_slots * (k + 1))
            state, out, accepted = self._verify_fn(*args, k=k)
        for arr in (out, accepted):
            copy = getattr(arr, "copy_to_host_async", None)
            if copy is not None:
                copy()
        return state, out, accepted
