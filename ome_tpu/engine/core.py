"""Inference engine core: slot-based continuous batching primitives.

TPU-first re-design of what the reference delegates to SGLang/vLLM
(SURVEY.md L0 — external engines, out of its repo): here the engine is
in-repo and JAX-native, structured like JetStream for XLA's compilation
model:

  * fixed decode batch of `max_slots` slots, one sequence each — every
    decode step is ONE compiled program with static shapes, whatever
    mix of requests is in flight;
  * prefill runs per-request at bucketed lengths (few compilations),
    producing a KV prefix that is *inserted* into a slot;
  * per-slot cache write positions (KVCache.index as a [B] vector) let
    every slot sit at a different sequence length;
  * sampling params are [B] vectors so one program serves all requests.

The three jitted programs (prefill / insert / decode) donate their
state buffers, so cache updates are in-place in HBM.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..models import llama
from ..models.config import ModelConfig
from .sampling import sample

Params = llama.Params


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DecodeState:
    """Device-resident state of the decode batch."""

    k: jax.Array        # [L, B, Smax, K, Dh]
    v: jax.Array        # [L, B, Smax, K, Dh]
    lengths: jax.Array  # [B] int32 — valid kv rows / next write index
    tokens: jax.Array   # [B] int32 — last sampled token per slot


def _bucketize(n: int, buckets: List[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class InferenceEngine:
    """Compiled prefill/insert/decode over one model + one mesh."""

    def __init__(self, params: Params, cfg: ModelConfig,
                 max_slots: int = 8, max_seq: Optional[int] = None,
                 prefill_buckets: Optional[List[int]] = None):
        self.params = params
        self.cfg = cfg
        self.max_slots = max_slots
        self.max_seq = max_seq or cfg.max_seq_len
        if prefill_buckets is None:
            prefill_buckets, b = [], 64
            while b < self.max_seq:
                prefill_buckets.append(b)
                b *= 2
            prefill_buckets.append(self.max_seq)
        self.prefill_buckets = prefill_buckets

        cfg_ = cfg

        @functools.partial(jax.jit, static_argnames=("bucket",))
        def _prefill(params, padded: jax.Array, true_len: jax.Array,
                     temperature, top_k, top_p, key, bucket: int):
            cache = llama.KVCache(
                k=jnp.zeros((cfg_.num_layers, 1, bucket, cfg_.num_kv_heads,
                             cfg_.head_dim), cfg_.dtype),
                v=jnp.zeros((cfg_.num_layers, 1, bucket, cfg_.num_kv_heads,
                             cfg_.head_dim), cfg_.dtype),
                index=jnp.zeros((), jnp.int32))
            logits, new_cache = llama.forward(params, cfg_, padded,
                                              cache=cache)
            # last REAL token's logits (right padding occupies the tail)
            last = jnp.take_along_axis(
                logits, (true_len - 1)[:, None, None], axis=1)[:, 0]
            tok = sample(last, key, temperature, top_k, top_p)
            return tok[0], new_cache.k, new_cache.v

        @functools.partial(jax.jit, donate_argnums=(0,),
                           static_argnames=("bucket",))
        def _insert(state: DecodeState, kv_k, kv_v, slot: jax.Array,
                    true_len: jax.Array, token: jax.Array, bucket: int):
            keep = min(bucket, self.max_seq)
            k = lax.dynamic_update_slice(
                state.k, kv_k[:, :, :keep], (0, slot, 0, 0, 0))
            v = lax.dynamic_update_slice(
                state.v, kv_v[:, :, :keep], (0, slot, 0, 0, 0))
            return DecodeState(
                k=k, v=v,
                lengths=state.lengths.at[slot].set(true_len),
                tokens=state.tokens.at[slot].set(token))

        @functools.partial(jax.jit, donate_argnums=(1,))
        def _decode(params, state: DecodeState, temperature, top_k, top_p,
                    key) -> Tuple[DecodeState, jax.Array]:
            cache = llama.KVCache(k=state.k, v=state.v, index=state.lengths)
            logits, new_cache = llama.forward(
                params, cfg_, state.tokens[:, None], cache=cache)
            toks = sample(logits[:, -1], key, temperature, top_k, top_p)
            return DecodeState(k=new_cache.k, v=new_cache.v,
                               lengths=new_cache.index,
                               tokens=toks), toks

        self._prefill_fn = _prefill
        self._insert_fn = _insert
        self._decode_fn = _decode
        self._step = 0
        self._root_key = jax.random.PRNGKey(0)

    # -- state ---------------------------------------------------------

    def new_state(self) -> DecodeState:
        L, B, S = self.cfg.num_layers, self.max_slots, self.max_seq
        shape = (L, B, S, self.cfg.num_kv_heads, self.cfg.head_dim)
        return DecodeState(
            k=jnp.zeros(shape, self.cfg.dtype),
            v=jnp.zeros(shape, self.cfg.dtype),
            lengths=jnp.zeros((B,), jnp.int32),
            tokens=jnp.zeros((B,), jnp.int32))

    # -- ops -----------------------------------------------------------

    def prefill(self, prompt_ids: List[int], temperature: float = 0.0,
                top_k: int = 0, top_p: float = 1.0):
        """Returns (first_token:int, kv pair, true_len, bucket)."""
        # leave room for one generated token; cap at the largest bucket
        max_prompt = min(self.max_seq - 1, self.prefill_buckets[-1])
        ids = prompt_ids[-max_prompt:]
        bucket = _bucketize(len(ids), self.prefill_buckets)
        padded = jnp.asarray(
            [ids + [0] * (bucket - len(ids))], jnp.int32)
        self._step += 1
        key = jax.random.fold_in(self._root_key, self._step)
        tok, k, v = self._prefill_fn(
            self.params, padded, jnp.asarray([len(ids)], jnp.int32),
            jnp.asarray([temperature], jnp.float32),
            jnp.asarray([top_k], jnp.int32),
            jnp.asarray([top_p], jnp.float32), key, bucket=bucket)
        return int(tok), (k, v), len(ids), bucket

    def insert(self, state: DecodeState, kv, slot: int, true_len: int,
               token: int, bucket: int) -> DecodeState:
        return self._insert_fn(
            state, kv[0], kv[1], jnp.asarray(slot, jnp.int32),
            jnp.asarray(true_len, jnp.int32),
            jnp.asarray(token, jnp.int32), bucket=bucket)

    def decode(self, state: DecodeState, temperature, top_k, top_p,
               ) -> Tuple[DecodeState, jax.Array]:
        """One decode step for ALL slots. Sampling params: [B] arrays."""
        self._step += 1
        key = jax.random.fold_in(self._root_key, self._step)
        return self._decode_fn(self.params, state,
                               jnp.asarray(temperature, jnp.float32),
                               jnp.asarray(top_k, jnp.int32),
                               jnp.asarray(top_p, jnp.float32), key)
