"""Multi-host serving: jax.distributed rendezvous + op replication.

Honors the LWS contract the operator stamps out
(controllers/reconcilers/multinode.py:53-58): every pod in the group
gets JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID, the
engine joins the cross-host rendezvous at startup, and the compiled
prefill/insert/decode programs run SPMD over a mesh spanning every
host's chips. This is the role the reference's runtimes fill with
`--dist-init-addr $(LWS_LEADER_ADDRESS):5757 --nnodes ... --node-rank`
(config/runtimes/srt/deepseek-rdma-pd-rt.yaml:108-115 in
/root/reference) — redesigned for XLA's execution model:

  * SPMD means every process must enqueue the SAME compiled programs
    in the SAME order (collectives rendezvous across hosts). Only the
    leader (process 0) sees HTTP traffic, so the leader REPLICATES its
    op stream (prefill/insert/decode + host args) to followers over a
    TCP control channel, and followers replay it. Device results never
    cross the channel — each process computes identical values from
    identical programs (sampling keys derive from a shared fold_in
    counter), so the only bytes on the wire are op headers and token
    ids. This is JetStream/Pathways-style leader-driven serving.
  * Worker loss fails FAST: a dropped control socket kills the whole
    group (followers exit nonzero, the leader marks itself unhealthy),
    and the LeaderWorkerSet recreates the group — the same crash-and-
    recreate discipline the reference's multinode runtimes rely on.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import socket
import struct
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from .. import constants

log = logging.getLogger("ome.engine.multihost")

# leader's op-replication channel; distinct from the jax.distributed
# coordinator port (JAX_COORDINATOR_PORT in controllers/reconcilers)
CONTROL_PORT = 5858


@dataclasses.dataclass(frozen=True)
class DistContext:
    coordinator: str          # host:port of the jax.distributed service
    num_processes: int
    process_id: int

    @property
    def is_leader(self) -> bool:
        return self.process_id == 0

    @property
    def coordinator_host(self) -> str:
        return self.coordinator.rsplit(":", 1)[0]


def init_from_env(env=None) -> Optional[DistContext]:
    """Join the cross-host rendezvous if the operator injected one.

    Reads the env contract from controllers/reconcilers/multinode.py;
    returns None (single-host mode) when JAX_COORDINATOR_ADDRESS is
    absent. MUST run before any other JAX call — jax.distributed can
    only initialize ahead of backend creation.
    """
    env = env if env is not None else os.environ
    coord = env.get(constants.JAX_COORDINATOR_ENV)
    if not coord:
        return None
    num = int(env.get(constants.JAX_NUM_PROCESSES_ENV, "1"))
    pid = int(env.get(constants.JAX_PROCESS_ID_ENV, "0"))
    if num <= 1:
        return None
    import jax
    if env.get("JAX_PLATFORMS", "").strip() == "cpu":
        # a multi-process CPU group (dev/CI topologies) needs an
        # explicit collectives implementation; the default "none"
        # rejects every cross-process computation
        try:
            jax.config.update(
                "jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):
            pass
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=num, process_id=pid)
    log.info("joined jax.distributed rendezvous %s as process %d/%d "
             "(%d global devices)", coord, pid, num, jax.device_count())
    return DistContext(coordinator=coord, num_processes=num,
                       process_id=pid)


def host_value(x) -> np.ndarray:
    """Fetch a (replicated) device value to host, multi-host safe.

    np.asarray on an array spanning non-addressable devices raises;
    the local shard of a replicated value is the whole value.
    """
    if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
        return np.asarray(x.addressable_shards[0].data)
    return np.asarray(x)


# -- control channel -------------------------------------------------------


def _send_msg(sock: socket.socket, msg: dict) -> None:
    data = json.dumps(msg).encode()
    sock.sendall(struct.pack("<I", len(data)) + data)


def _recv_msg(sock: socket.socket) -> Optional[dict]:
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = struct.unpack("<I", hdr)
    body = _recv_exact(sock, n)
    if body is None:
        return None
    return json.loads(body)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class OpPublisher:
    """Leader side: accepts every follower, then fans ops out in order.

    TCP per-connection ordering + one sender thread per send() caller
    (the scheduler thread) gives all followers the identical op
    sequence. A send failure means a follower died — the caller (the
    scheduler step) propagates, flipping the leader unhealthy so the
    LWS group restarts together.
    """

    def __init__(self, n_followers: int, port: int = CONTROL_PORT,
                 host: str = "0.0.0.0", accept_timeout: float = 600.0):
        self._server = socket.create_server((host, port))
        self._server.settimeout(accept_timeout)
        self._socks: List[socket.socket] = []
        for _ in range(n_followers):
            conn, addr = self._server.accept()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks.append(conn)
            log.info("follower joined from %s (%d/%d)", addr,
                     len(self._socks), n_followers)

    def send(self, msg: dict) -> None:
        for sock in self._socks:
            _send_msg(sock, msg)

    def close(self) -> None:
        try:
            self.send({"op": "stop"})
        except OSError:
            pass
        for s in self._socks:
            s.close()
        self._server.close()


class OpSubscriber:
    """Follower side: connect (with retry — the leader pod may still be
    loading weights) and stream ops."""

    def __init__(self, host: str, port: int = CONTROL_PORT,
                 connect_timeout: float = 600.0):
        deadline = time.monotonic() + connect_timeout
        while True:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=10)
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(1.0)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)

    def recv(self) -> Optional[dict]:
        return _recv_msg(self._sock)

    def close(self) -> None:
        self._sock.close()


# -- leader / follower engine drivers --------------------------------------


class ReplicatedEngine:
    """Wraps an InferenceEngine so every device-touching op is
    published to the followers before the leader runs it. Drop-in for
    the Scheduler: same prefill/insert/decode surface.

    All ops publish AND execute under one lock: the scheduler thread
    drives prefill/insert/decode, but adapter registration arrives on
    an HTTP handler thread — without the lock, two sendall()s could
    interleave framed bytes, and the leader could apply a param swap
    at a different op-stream position than its followers (divergent
    SPMD state)."""

    # multi-token decode IS in the replicated op vocabulary:
    # decode_multi / verify / commit_spec below publish before
    # executing, so every plan kind the scheduler can build (chunk,
    # spec-verify, masked, pipelined) replays identically on the
    # followers. Without these explicit methods __getattr__ would leak
    # the wrapped engine's programs through unpublished (divergent
    # SPMD state) — which is why the attr used to be False.
    supports_multi_step = True

    def __init__(self, engine, publisher: OpPublisher):
        self._engine = engine
        self._pub = publisher
        self._oplock = threading.Lock()
        # honest per-instance capability: replication only helps if
        # the wrapped engine actually has the multi-step program
        self.supports_multi_step = bool(
            callable(getattr(engine, "decode_multi", None))
            and getattr(engine, "supports_multi_step", False))

    def __getattr__(self, name):
        return getattr(self._engine, name)

    def new_state(self):
        return self._engine.new_state()

    def prefill(self, prompt_ids, temperature: float = 0.0,
                top_k: int = 0, top_p: float = 1.0, first_mask=None,
                adapter=None, deadline=None, trace=None):
        from .structured import pack_mask
        kw = {}
        if first_mask is not None:
            kw["first_mask"] = first_mask
        if adapter is not None:
            kw["adapter"] = adapter
        with self._oplock:
            blob_fn = getattr(self._engine, "prefill_blob", None)
            if blob_fn is not None:
                # PD decode group: the leader fetches the KV wire blob
                # ONCE and ships the bytes to followers — a follower
                # re-fetching could draw a different sampled token on
                # the prefill node (its RNG advances per request).
                # deadline/trace stay leader-side: followers only see
                # the replicated bytes, never the network. Forwarded
                # only when set, so blob providers predating the pool
                # (no deadline/trace kwargs) keep working.
                import base64
                if deadline is not None:
                    kw["deadline"] = deadline
                if trace is not None:
                    kw["trace"] = trace
                blob = blob_fn(prompt_ids, temperature, top_k, top_p,
                               **kw)
                self._pub.send({"op": "prefill_blob",
                                "blob": base64.b64encode(blob).decode()})
                from .pd import deserialize_kv
                token, k, v, true_len, bucket = deserialize_kv(blob)
                return token, (k, v), true_len, bucket
            self._pub.send({"op": "prefill",
                            "ids": list(map(int, prompt_ids)),
                            "temperature": float(temperature),
                            "top_k": int(top_k), "top_p": float(top_p),
                            # omelint: disable=lock-discipline -- the host-built mask IS the op payload; _oplock serializes whole ops by design
                            "first_mask": pack_mask(first_mask),
                            "adapter": adapter})
            return self._engine.prefill(prompt_ids, temperature, top_k,
                                        top_p, **kw)

    def insert(self, state, kv, slot: int, true_len: int, token: int,
               bucket: int, adapter=None):
        with self._oplock:
            self._pub.send({"op": "insert", "slot": int(slot),
                            "true_len": int(true_len),
                            "token": int(token),
                            "bucket": int(bucket), "adapter": adapter})
            kw = {} if adapter is None else {"adapter": adapter}
            return self._engine.insert(state, kv, slot, true_len,
                                       token, bucket, **kw)

    def register_adapter(self, name: str, adapter_dir: str) -> int:
        """Replicated hot adapter load: the staged dir must exist on
        every host (shared PVC / serving-agent staging on each).
        Local call FIRST: if it raises (bad dir, no free slot), no op
        is published and followers stay consistent."""
        with self._oplock:
            idx = self._engine.register_adapter(name, adapter_dir)
            self._pub.send({"op": "register_adapter", "name": name,
                            "path": adapter_dir})
            return idx

    def unregister_adapter(self, name: str) -> None:
        with self._oplock:
            # local first: an in-flight-adapter refusal
            # (core.unregister_adapter ValueError) must not reach
            # followers — their slot refs clear via the free_slot op,
            # so a leader success replays cleanly
            self._engine.unregister_adapter(name)
            self._pub.send({"op": "unregister_adapter", "name": name})

    def free_slot(self, slot: int) -> None:
        """Replicated slot release (adapter refs + paged KV blocks) —
        keeps follower allocators and the unregister guard in
        lockstep with the leader's scheduler."""
        with self._oplock:
            self._pub.send({"op": "free_slot", "slot": int(slot)})
            self._engine.free_slot(slot)

    def set_mask_row(self, row: int, bits) -> None:
        """Replicated grammar mask-table upload: the leader's
        scheduler installs a compiled automaton-state mask; followers
        must install the IDENTICAL row before any plan references its
        index, which op-stream ordering guarantees (uploads publish
        before the decode/verify ops that gather them)."""
        from .structured import pack_mask
        with self._oplock:
            self._pub.send({"op": "set_mask_row", "row": int(row),
                            # omelint: disable=lock-discipline -- the host-built mask row IS the op payload; _oplock serializes whole ops by design
                            "bits": pack_mask(np.asarray(bits, bool))})
            self._engine.set_mask_row(row, bits)

    def decode(self, state, temperature, top_k, top_p, mask=None,
               mask_idx=None):
        from .structured import pack_mask
        # grammar mask-table row indices (ints on the wire, vs ~V/8
        # bytes per packed row) — converted before taking the op lock
        midx = None if mask_idx is None \
            else np.asarray(mask_idx, np.int32).tolist()
        with self._oplock:
            self._pub.send({"op": "decode",
                            "mask_idx": midx,
                            # omelint: disable=lock-discipline -- sampling params ship host-side in the op; _oplock serializes whole ops by design
                            "temperature": np.asarray(
                                temperature, np.float32).tolist(),
                            # omelint: disable=lock-discipline -- sampling params ship host-side in the op; _oplock serializes whole ops by design
                            "top_k": np.asarray(top_k,
                                                np.int32).tolist(),
                            # omelint: disable=lock-discipline -- sampling params ship host-side in the op; _oplock serializes whole ops by design
                            "top_p": np.asarray(top_p,
                                                np.float32).tolist(),
                            # structured outputs: the leader's host-
                            # built mask ships in the op (packbits
                            # ~V/8 bytes per constrained slot) so
                            # followers run the IDENTICAL masked
                            # program — no recompute drift
                            # omelint: disable=lock-discipline -- the host-built mask IS the op payload; _oplock serializes whole ops by design
                            "mask": pack_mask(mask)})
            if mask_idx is not None:
                state, toks = self._engine.decode(
                    state, temperature, top_k, top_p,
                    mask_idx=mask_idx)
            elif mask is not None:
                state, toks = self._engine.decode(
                    state, temperature, top_k, top_p, mask=mask)
            else:
                state, toks = self._engine.decode(state, temperature,
                                                  top_k, top_p)
            # omelint: disable=lock-discipline -- the local-replica fetch completes the op; _oplock serializes whole ops by design
            return state, host_value(toks)

    def decode_multi(self, state, temperature, top_k, top_p,
                     steps: int, budget, stop_ids,
                     lookahead_rows=None, mask=None, mask_idx=None):
        """Replicated multi-token chunk: the whole StepPlan payload
        (sampling, per-slot budget, stop table, paged lookahead, the
        [B, steps, V] mask stack OR its [B, steps] mask-table row
        indices) ships in the op, so followers run the IDENTICAL
        K-step device loop."""
        from .structured import pack_mask
        # mask-table row indices converted before taking the op lock
        midx = None if mask_idx is None \
            else np.asarray(mask_idx, np.int32).tolist()
        with self._oplock:
            self._pub.send({"op": "decode_multi",
                            "steps": int(steps),
                            "mask_idx": midx,
                            # omelint: disable=lock-discipline -- sampling params ship host-side in the op; _oplock serializes whole ops by design
                            "temperature": np.asarray(
                                temperature, np.float32).tolist(),
                            # omelint: disable=lock-discipline -- sampling params ship host-side in the op; _oplock serializes whole ops by design
                            "top_k": np.asarray(top_k,
                                                np.int32).tolist(),
                            # omelint: disable=lock-discipline -- sampling params ship host-side in the op; _oplock serializes whole ops by design
                            "top_p": np.asarray(top_p,
                                                np.float32).tolist(),
                            # omelint: disable=lock-discipline -- plan payloads ship host-side in the op; _oplock serializes whole ops by design
                            "budget": np.asarray(
                                budget, np.int32).tolist(),
                            # omelint: disable=lock-discipline -- plan payloads ship host-side in the op; _oplock serializes whole ops by design
                            "stop_ids": np.asarray(
                                stop_ids, np.int32).tolist(),
                            "lookahead_rows": None
                            if lookahead_rows is None
                            else int(lookahead_rows),
                            # omelint: disable=lock-discipline -- the host-built mask stack IS the op payload; _oplock serializes whole ops by design
                            "mask": pack_mask(mask)})
            kw = {}
            if lookahead_rows is not None:
                kw["lookahead_rows"] = lookahead_rows
            if mask_idx is not None:
                kw["mask_idx"] = mask_idx
            elif mask is not None:
                kw["mask"] = mask
            state, out, adv = self._engine.decode_multi(
                state, temperature, top_k, top_p, steps=steps,
                budget=budget, stop_ids=stop_ids, **kw)
            # omelint: disable=lock-discipline -- the local-replica fetch completes the op; _oplock serializes whole ops by design
            return state, host_value(out), host_value(adv)

    def verify(self, state, drafts, draft_len, temperature, top_k,
               top_p, lookahead_rows=None, mask=None, mask_idx=None):
        """Replicated spec-verify: the leader's host-built drafts (and
        the position-0 mask, or per-position mask-table row indices,
        for masked slots) ship in the op — followers never run the
        drafter, they replay its output."""
        from .structured import pack_mask
        # mask-table row indices converted before taking the op lock
        midx = None if mask_idx is None \
            else np.asarray(mask_idx, np.int32).tolist()
        with self._oplock:
            self._pub.send({"op": "verify",
                            "mask_idx": midx,
                            # omelint: disable=lock-discipline -- plan payloads ship host-side in the op; _oplock serializes whole ops by design
                            "drafts": np.asarray(
                                drafts, np.int32).tolist(),
                            # omelint: disable=lock-discipline -- plan payloads ship host-side in the op; _oplock serializes whole ops by design
                            "draft_len": np.asarray(
                                draft_len, np.int32).tolist(),
                            # omelint: disable=lock-discipline -- sampling params ship host-side in the op; _oplock serializes whole ops by design
                            "temperature": np.asarray(
                                temperature, np.float32).tolist(),
                            # omelint: disable=lock-discipline -- sampling params ship host-side in the op; _oplock serializes whole ops by design
                            "top_k": np.asarray(top_k,
                                                np.int32).tolist(),
                            # omelint: disable=lock-discipline -- sampling params ship host-side in the op; _oplock serializes whole ops by design
                            "top_p": np.asarray(top_p,
                                                np.float32).tolist(),
                            "lookahead_rows": None
                            if lookahead_rows is None
                            else int(lookahead_rows),
                            # omelint: disable=lock-discipline -- the host-built mask IS the op payload; _oplock serializes whole ops by design
                            "mask": pack_mask(mask)})
            kw = {}
            if lookahead_rows is not None:
                kw["lookahead_rows"] = lookahead_rows
            if mask_idx is not None:
                kw["mask_idx"] = mask_idx
            elif mask is not None:
                kw["mask"] = mask
            state, out, acc = self._engine.verify(
                state, drafts, draft_len, temperature, top_k, top_p,
                **kw)
            # omelint: disable=lock-discipline -- the local-replica fetch completes the op; _oplock serializes whole ops by design
            return state, host_value(out), host_value(acc)

    def commit_spec(self, slot: int, advance: int,
                    reserve: int = 0) -> None:
        """Replicated spec/chunk commit: pure host bookkeeping, but it
        trims speculative paged-KV blocks — followers must replay it
        or their block tables drift from the leader's and the next
        compiled program sees different allocations."""
        with self._oplock:
            self._pub.send({"op": "commit_spec", "slot": int(slot),
                            "advance": int(advance),
                            "reserve": int(reserve)})
            self._engine.commit_spec(slot, advance, reserve=reserve)


def _unknown_adapter(e: Exception) -> bool:
    try:
        from .core import UnknownAdapterError
    except Exception:  # pragma: no cover
        return False
    return isinstance(e, UnknownAdapterError)


def follower_loop(engine, sub: OpSubscriber,
                  pd_export: bool = False) -> int:
    """Replay the leader's op stream against the local engine.

    Every value the replay needs beyond the op headers (prefill KV,
    sampled tokens) is recomputed locally — identical programs +
    identical inputs + shared RNG counters give identical results, so
    insert() can consume the follower's OWN last prefill output.
    Structured-output masks arrive IN the ops (leader-built, packed) so
    masked sampling is bit-identical across the group.
    `pd_export`: this is a PD prefill-pool follower — after each
    prefill replay, join the leader's process_allgather collective
    (pd.gather_kv) that exports the KV to the wire.
    Returns an exit code: 0 on orderly stop, 1 on a dropped leader.
    """
    from .structured import unpack_mask
    state = engine.new_state()
    last_prefill: Optional[Tuple] = None
    while True:
        msg = sub.recv()
        if msg is None:
            log.error("control channel dropped; exiting for group "
                      "restart")
            return 1
        op = msg["op"]
        if op == "stop":
            return 0
        if op == "prefill":
            fm = unpack_mask(msg.get("first_mask"))
            kwargs = {} if fm is None else {"first_mask": fm}
            if msg.get("adapter") is not None:
                kwargs["adapter"] = msg["adapter"]
            try:
                last_prefill = engine.prefill(
                    msg["ids"], msg["temperature"], msg["top_k"],
                    msg["top_p"], **kwargs)
            except Exception as e:
                if not _unknown_adapter(e):
                    raise
                # the leader hit the IDENTICAL per-request error before
                # any device op ran on either side (it publishes, then
                # executes) — skip in lockstep instead of dying
                last_prefill = None
                continue
            if pd_export:
                from .pd import gather_kv
                _, (k, v), _, _ = last_prefill
                gather_kv(k)
                gather_kv(v)
        elif op == "prefill_blob":
            # PD decode group: the leader shipped the prefill pool's
            # KV bytes; deserialize locally — no fetch, no compute
            import base64
            from .pd import deserialize_kv
            token, k, v, true_len, bucket = deserialize_kv(
                base64.b64decode(msg["blob"]))
            last_prefill = (token, (k, v), true_len, bucket)
        elif op == "insert":
            if last_prefill is None:
                continue  # its prefill failed in lockstep (adapter)
            tok, kv, _true_len, _bucket = last_prefill
            ikw = {} if msg.get("adapter") is None \
                else {"adapter": msg["adapter"]}
            try:
                state = engine.insert(state, kv, msg["slot"],
                                      msg["true_len"], tok,
                                      msg["bucket"], **ikw)
            except Exception as e:
                if not _unknown_adapter(e):
                    raise
        elif op == "register_adapter":
            engine.register_adapter(msg["name"], msg["path"])
        elif op == "unregister_adapter":
            try:
                engine.unregister_adapter(msg["name"])
            except ValueError:
                # the leader only publishes after ITS unload succeeded;
                # a local refusal means this follower's adapter refs
                # drifted (e.g. a missed free_slot) — clear ONLY the
                # refused adapter's slot refs (other adapters' in-
                # flight sequences are not drifted, and zeroing them
                # would let a racing unregister of a busy adapter slip
                # through), NOT the KV blocks: active sequences still
                # own those. Then follow the leader rather than
                # killing the group.
                log.warning("unregister %r refused locally; clearing "
                            "its stale adapter refs to follow the "
                            "leader", msg["name"])
                idx = engine.adapter_id(msg["name"])
                refs = engine._slot_adapters
                refs[refs == idx] = 0
                engine.unregister_adapter(msg["name"])
        elif op == "free_slot":
            engine.free_slot(msg["slot"])
        elif op == "set_mask_row":
            # grammar mask-table upload: install the leader's row
            # before any subsequent op gathers its index (op-stream
            # order guarantees the happens-before)
            engine.set_mask_row(msg["row"],
                                unpack_mask(msg["bits"]))
        elif op == "decode":
            mask = unpack_mask(msg.get("mask"))
            kwargs = {} if mask is None else {"mask": mask}
            if msg.get("mask_idx") is not None:
                kwargs = {"mask_idx": np.asarray(msg["mask_idx"],
                                                 np.int32)}
            state, _ = engine.decode(
                state,
                np.asarray(msg["temperature"], np.float32),
                np.asarray(msg["top_k"], np.int32),
                np.asarray(msg["top_p"], np.float32), **kwargs)
        elif op == "decode_multi":
            kwargs = {}
            if msg.get("lookahead_rows") is not None:
                kwargs["lookahead_rows"] = msg["lookahead_rows"]
            mask = unpack_mask(msg.get("mask"))
            if msg.get("mask_idx") is not None:
                kwargs["mask_idx"] = np.asarray(msg["mask_idx"],
                                                np.int32)
            elif mask is not None:
                kwargs["mask"] = mask
            state, _, _ = engine.decode_multi(
                state,
                np.asarray(msg["temperature"], np.float32),
                np.asarray(msg["top_k"], np.int32),
                np.asarray(msg["top_p"], np.float32),
                steps=msg["steps"],
                budget=np.asarray(msg["budget"], np.int32),
                stop_ids=np.asarray(msg["stop_ids"], np.int32),
                **kwargs)
        elif op == "verify":
            kwargs = {}
            if msg.get("lookahead_rows") is not None:
                kwargs["lookahead_rows"] = msg["lookahead_rows"]
            mask = unpack_mask(msg.get("mask"))
            if msg.get("mask_idx") is not None:
                kwargs["mask_idx"] = np.asarray(msg["mask_idx"],
                                                np.int32)
            elif mask is not None:
                kwargs["mask"] = mask
            state, _, _ = engine.verify(
                state,
                np.asarray(msg["drafts"], np.int32),
                np.asarray(msg["draft_len"], np.int32),
                np.asarray(msg["temperature"], np.float32),
                np.asarray(msg["top_k"], np.int32),
                np.asarray(msg["top_p"], np.float32), **kwargs)
        elif op == "commit_spec":
            engine.commit_spec(msg["slot"], msg["advance"],
                               reserve=msg["reserve"])
        else:
            log.error("unknown op %r from leader", op)
            return 1
