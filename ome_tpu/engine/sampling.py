"""Token sampling — per-slot parameters, fully vectorized.

Each decode step samples one token per batch slot. Because slots in the
continuous-batching engine belong to different requests, temperature /
top-k / top-p are [B] vectors rather than scalars, and everything is
computed with static shapes (sort + mask, no data-dependent gathers) so
the whole step stays inside one compiled XLA program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1.0e30


def sample(logits: jax.Array, key: jax.Array, temperature: jax.Array,
           top_k: jax.Array, top_p: jax.Array) -> jax.Array:
    """Sample next tokens.

    logits: [B, V] float; temperature/top_k/top_p: [B]
    (temperature<=0 means greedy; top_k<=0 disables top-k;
    top_p>=1 disables nucleus filtering).
    Returns [B] int32.
    """
    logits = logits.astype(jnp.float32)
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # scale by temperature (guard the greedy rows against div-by-zero)
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_t

    # one descending ordering; both filters are rank-based prefix masks
    # scattered back by rank — never probability-threshold comparisons,
    # which are brittle to softmax rounding across recomputations
    order = jnp.argsort(scaled, axis=-1)[:, ::-1]  # [B, V] desc indices
    sorted_logits = jnp.take_along_axis(scaled, order, axis=-1)

    ranks = jnp.arange(V)[None, :]
    # top-k: keep the first k ranks (top_k<=0 disables)
    keep_k = jnp.where(top_k[:, None] > 0, ranks < top_k[:, None], True)

    # top-p (nucleus): smallest prefix of the sorted distribution whose
    # mass reaches top_p — a rank is kept if the mass before it is < top_p
    probs_sorted = jax.nn.softmax(sorted_logits, axis=-1)
    cumulative = jnp.cumsum(probs_sorted, axis=-1)
    keep_p = (cumulative - probs_sorted) < top_p[:, None]

    keep_sorted = keep_k & keep_p  # rank 0 always survives both
    keep = jax.vmap(
        lambda o, m: jnp.zeros((V,), bool).at[o].set(m))(order, keep_sorted)
    scaled = jnp.where(keep, scaled, NEG_INF)

    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)
