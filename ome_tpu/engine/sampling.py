"""Token sampling — per-slot parameters, fully vectorized.

Each decode step samples one token per batch slot. Because slots in the
continuous-batching engine belong to different requests, temperature /
top-k / top-p are [B] vectors rather than scalars, and everything is
computed with static shapes (sort + mask, no data-dependent gathers) so
the whole step stays inside one compiled XLA program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1.0e30


def filtered_logits(logits: jax.Array, temperature: jax.Array,
                    top_k: jax.Array, top_p: jax.Array) -> jax.Array:
    """Temperature-scaled, top-k/top-p-masked logits.

    The distribution `sample` (and the speculative verify acceptance
    rule) actually draws from: logits [B, V] float, params [B].
    Filtered-out entries are NEG_INF; greedy rows (temperature<=0)
    pass through with temperature 1 — callers pick argmax for those.
    Returns [B, V] float32.
    """
    logits = logits.astype(jnp.float32)
    B, V = logits.shape

    # scale by temperature (guard the greedy rows against div-by-zero)
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_t

    # one descending ordering; both filters are rank-based prefix masks
    # scattered back by rank — never probability-threshold comparisons,
    # which are brittle to softmax rounding across recomputations
    order = jnp.argsort(scaled, axis=-1)[:, ::-1]  # [B, V] desc indices
    sorted_logits = jnp.take_along_axis(scaled, order, axis=-1)

    ranks = jnp.arange(V)[None, :]
    # top-k: keep the first k ranks (top_k<=0 disables)
    keep_k = jnp.where(top_k[:, None] > 0, ranks < top_k[:, None], True)

    # top-p (nucleus): smallest prefix of the sorted distribution whose
    # mass reaches top_p — a rank is kept if the mass before it is < top_p
    probs_sorted = jax.nn.softmax(sorted_logits, axis=-1)
    cumulative = jnp.cumsum(probs_sorted, axis=-1)
    keep_p = (cumulative - probs_sorted) < top_p[:, None]

    keep_sorted = keep_k & keep_p  # rank 0 always survives both
    keep = jax.vmap(
        lambda o, m: jnp.zeros((V,), bool).at[o].set(m))(order, keep_sorted)
    return jnp.where(keep, scaled, NEG_INF)


def sample(logits: jax.Array, key: jax.Array, temperature: jax.Array,
           top_k: jax.Array, top_p: jax.Array) -> jax.Array:
    """Sample next tokens.

    logits: [B, V] float; temperature/top_k/top_p: [B]
    (temperature<=0 means greedy; top_k<=0 disables top-k;
    top_p>=1 disables nucleus filtering).
    Returns [B] int32.
    """
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = filtered_logits(logits, temperature, top_k, top_p)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


def spec_verify(logits: jax.Array, drafts: jax.Array,
                draft_len: jax.Array, key: jax.Array,
                temperature: jax.Array, top_k: jax.Array,
                top_p: jax.Array) -> tuple:
    """Batched draft verification (Leviathan et al. 2023).

    One verify forward scored `S = k+1` positions per slot: position 0
    follows the committed last token, position i (1<=i<=k) follows
    draft token i-1. This decides, per slot, the longest accepted
    draft prefix and the one extra token the step emits beyond it.

    logits: [B, S, V] — verify-forward logits; drafts: [B, k] int32;
    draft_len: [B] int32 in [0, k] (0 = slot did not draft: the step
    degenerates to a plain decode for that slot); key: PRNG key;
    temperature/top_k/top_p: [B].

    Acceptance: greedy slots accept draft d_i iff it equals the argmax
    at position i; temperature>0 slots accept d_i with probability
    p_i(d_i) under the *filtered* target distribution (the same one
    `sample` draws from — a point-mass n-gram draft makes the
    Leviathan rule reduce to this), and on rejection resample from
    p_i with d_i zeroed and renormalized, which preserves the target
    distribution exactly.

    Returns (out_tokens [B, S] int32, accepted [B] int32): slot b
    emits out_tokens[b, :accepted[b]+1]; out_tokens[b, accepted[b]]
    is the slot's new "last sampled token" (the next step's input).
    """
    logits = logits.astype(jnp.float32)
    B, S, V = logits.shape
    k = S - 1
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, S]
    filt = filtered_logits(
        logits.reshape(B * S, V),
        jnp.repeat(temperature, S), jnp.repeat(top_k, S),
        jnp.repeat(top_p, S)).reshape(B, S, V)
    probs = jax.nn.softmax(filt, axis=-1)

    pos = jnp.arange(k)[None, :]
    in_draft = pos < draft_len[:, None]
    kacc, kres, kbon = jax.random.split(key, 3)

    # per-position accept decisions, then the longest accepted prefix
    draft_p = jnp.take_along_axis(
        probs[:, :k], drafts[..., None], axis=-1)[..., 0]  # [B, k]
    u = jax.random.uniform(kacc, (B, k))
    accept = jnp.where(temperature[:, None] > 0,
                       u < draft_p, drafts == greedy[:, :k])
    run = jnp.cumprod((accept & in_draft).astype(jnp.int32), axis=1)
    accepted = jnp.sum(run, axis=1).astype(jnp.int32)  # [B] in [0, k]

    # the token emitted at the stop position: on rejection at i, the
    # residual sample (p_i with d_i removed, renormalized); on full
    # acceptance (stop == draft_len), a plain sample from p_stop
    is_draft_tok = jnp.arange(V)[None, None, :] == drafts[..., None]
    resid_tok = jax.random.categorical(
        kres, jnp.where(is_draft_tok, NEG_INF, filt[:, :k]),
        axis=-1).astype(jnp.int32)  # [B, k]
    bonus_tok = jax.random.categorical(
        kbon, filt, axis=-1).astype(jnp.int32)  # [B, S]
    stop_tok = jnp.concatenate([
        jnp.where(pos == draft_len[:, None],
                  bonus_tok[:, :k], resid_tok),
        bonus_tok[:, k:]], axis=1)  # [B, S]
    # greedy slots emit argmax(raw logits) at the stop position either
    # way: on rejection the masked argmax equals the unmasked one
    # (the rejected draft wasn't the argmax), matching `sample`
    stop_tok = jnp.where(temperature[:, None] > 0, stop_tok, greedy)

    next_tok = jnp.take_along_axis(stop_tok, accepted[:, None], axis=1)
    drafts_pad = jnp.concatenate(
        [drafts, jnp.zeros((B, 1), jnp.int32)], axis=1)  # [B, S]
    j = jnp.arange(S)[None, :]
    out = jnp.where(j < accepted[:, None], drafts_pad,
                    jnp.where(j == accepted[:, None], next_tok, 0))
    return out.astype(jnp.int32), accepted
