"""Regex -> byte NFA for JSON-Schema `pattern` constrained decoding.

The reference gets `pattern` support from xgrammar's regex->grammar
compiler inside its SGLang runtime images (SURVEY.md L0, e.g.
/root/reference/config/runtimes/srt/ --grammar-backend); here a small
Thompson-construction NFA walks byte sets so the schema automaton
(engine/schema.py) can mask tokens byte-by-byte AND steer a minimal
close-out path (shortest distance-to-accept is precomputed per state,
so `closing_bytes` always has a byte that strictly decreases it).

Scope (SchemaError beyond it, so the API 400s instead of silently
under-constraining): literals, '.', character classes incl. ranges and
negation, \\d \\w \\s (+ complements), escapes, grouping, alternation,
'*' '+' '?' '{m}' '{m,}' '{m,n}', anchors '^'/'$' at the ends.
Per JSON-Schema semantics an unanchored pattern is a substring match:
missing '^'/'$' get an implicit '.*' on that side.

The byte universe is printable ASCII minus '"' and '\\' (bytes that
would need JSON escaping inside a string literal) — the automaton
never emits escapes inside pattern-constrained strings, which narrows
the emittable language but never widens it.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Optional, Tuple


class PatternError(ValueError):
    """Pattern uses syntax this compiler does not support."""


# emittable bytes inside a JSON string without escaping
_UNIVERSE = frozenset(range(0x20, 0x7F)) - frozenset((0x22, 0x5C))
_DIGITS = frozenset(b"0123456789")
_WORD = frozenset(b"abcdefghijklmnopqrstuvwxyz"
                  b"ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_")
_SPACE = frozenset(b" \t\n\r\f\v") & _UNIVERSE  # -> {space}

_MAX_REPEAT = 64
_MAX_STATES = 4096

# AST: ("cls", frozenset) | ("seq", [ast]) | ("alt", [ast])
#    | ("rep", ast, min, max|None)


def _class_escape(c: str) -> Optional[FrozenSet[int]]:
    return {"d": _DIGITS, "D": _UNIVERSE - _DIGITS, "w": _WORD,
            "W": _UNIVERSE - _WORD, "s": _SPACE,
            "S": _UNIVERSE - _SPACE}.get(c)


class _Parser:
    def __init__(self, pat: str):
        self.pat = pat
        self.i = 0

    def peek(self) -> Optional[str]:
        return self.pat[self.i] if self.i < len(self.pat) else None

    def take(self) -> str:
        c = self.pat[self.i]
        self.i += 1
        return c

    def parse(self):
        ast = self.alt()
        if self.i != len(self.pat):
            raise PatternError(f"unexpected {self.pat[self.i]!r} at "
                               f"{self.i}")
        return ast

    def alt(self):
        branches = [self.seq()]
        while self.peek() == "|":
            self.take()
            branches.append(self.seq())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def seq(self):
        items = []
        while self.peek() not in (None, "|", ")"):
            items.append(self.rep())
        return ("seq", items)

    def rep(self):
        a = self.atom()
        c = self.peek()
        if c == "*":
            self.take()
            return ("rep", a, 0, None)
        if c == "+":
            self.take()
            return ("rep", a, 1, None)
        if c == "?":
            self.take()
            return ("rep", a, 0, 1)
        if c == "{":
            return self.counted(a)
        return a

    def counted(self, a):
        self.take()  # {
        lo = self.int_until(",}")
        if self.peek() is None:
            raise PatternError("unterminated {m,n} quantifier")
        c = self.take()
        if c == "}":
            hi: Optional[int] = lo
        else:
            if self.peek() == "}":
                self.take()
                hi = None
            else:
                hi = self.int_until("}")
                if self.peek() is None:
                    raise PatternError("unterminated {m,n} quantifier")
                self.take()
        if lo > _MAX_REPEAT or (hi or 0) > _MAX_REPEAT:
            raise PatternError(f"repeat bound > {_MAX_REPEAT}")
        if hi is not None and hi < lo:
            raise PatternError("bad repeat {m,n} with n < m")
        return ("rep", a, lo, hi)

    def int_until(self, stops: str) -> int:
        s = ""
        while self.peek() is not None and self.peek() not in stops:
            s += self.take()
        if not s.isdigit():
            raise PatternError("bad {m,n} bound")
        return int(s)

    def atom(self):
        c = self.take()
        if c == "(":
            if self.peek() == "?":
                self.take()
                if self.peek() != ":":
                    raise PatternError("only (?:...) groups supported")
                self.take()
            inner = self.alt()
            if self.peek() != ")":
                raise PatternError("unbalanced group")
            self.take()
            return inner
        if c == ".":
            return ("cls", _UNIVERSE)
        if c == "[":
            return self.char_class()
        if c == "\\":
            return ("cls", self.escape())
        if c in "^$":
            raise PatternError("anchors only at the pattern ends")
        if c in "*+?{":
            raise PatternError(f"dangling quantifier {c!r}")
        return ("cls", self._lit(c))

    @staticmethod
    def _lit(c: str) -> FrozenSet[int]:
        b = ord(c)
        if b not in _UNIVERSE:
            raise PatternError(
                f"pattern character {c!r} cannot appear unescaped in a "
                f"JSON string")
        return frozenset((b,))

    def escape(self) -> FrozenSet[int]:
        if self.peek() is None:
            raise PatternError("trailing backslash")
        c = self.take()
        cls = _class_escape(c)
        if cls is not None:
            return cls
        mapped = {"n": "\n", "t": "\t", "r": "\r"}.get(c, c)
        b = ord(mapped)
        if b not in _UNIVERSE:
            raise PatternError(
                f"escape \\{c} maps outside the emittable JSON-string "
                f"byte range")
        return frozenset((b,))

    def char_class(self):
        neg = False
        if self.peek() == "^":
            self.take()
            neg = True
        out: set = set()
        first = True
        while True:
            c = self.peek()
            if c is None:
                raise PatternError("unterminated character class")
            if c == "]" and not first:
                self.take()
                break
            first = False
            c = self.take()
            if c == "\\":
                cls = self.escape()
                out |= cls
                continue
            lo = ord(c)
            if self.peek() == "-" and self.pat[self.i + 1: self.i + 2] \
                    not in ("]", ""):
                self.take()
                hi_c = self.take()
                if hi_c == "\\":
                    raise PatternError("escape as range endpoint")
                hi = ord(hi_c)
                if hi < lo:
                    raise PatternError("reversed class range")
                out |= set(range(lo, hi + 1))
            else:
                out.add(lo)
        cls = frozenset(out) & _UNIVERSE if not neg \
            else _UNIVERSE - frozenset(out)
        if not cls:
            raise PatternError("character class matches no emittable "
                               "byte")
        return ("cls", cls)


def _toplevel_alternation(pat: str) -> bool:
    """True when an unescaped '|' sits at group-depth 0 outside a
    character class."""
    depth = 0
    in_class = False
    i = 0
    while i < len(pat):
        c = pat[i]
        if c == "\\":
            i += 2
            continue
        if in_class:
            if c == "]":
                in_class = False
        elif c == "[":
            in_class = True
        elif c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        elif c == "|" and depth == 0:
            return True
        i += 1
    return False


class Regex:
    """Compiled byte NFA with per-state shortest-distance-to-accept.

    States are ints; `advance` works on frozensets of states (the
    standard subset walk). min_dist/closing_byte drive the schema
    automaton's greedy close-out.
    """

    def __init__(self, pattern: str):
        self.pattern = pattern
        pat = pattern
        anchored_l = pat.startswith("^")
        anchored_r = pat.endswith("$") and not pat.endswith("\\$")
        if (anchored_l or anchored_r) and _toplevel_alternation(pat):
            # '^a|b$' means (^a)|(b$) under regex precedence; stripping
            # the anchors here would silently compile '^(a|b)$' — a
            # narrower language. Refuse instead of under-serving.
            raise PatternError(
                "anchors with a top-level alternation are ambiguous; "
                "group the alternation: ^(?:a|b)$")
        if anchored_l:
            pat = pat[1:]
        if anchored_r:
            pat = pat[:-1]
        ast = _Parser(pat).parse()  # parser rejects interior anchors
        if not anchored_l:
            ast = ("seq", [("rep", ("cls", _UNIVERSE), 0, None), ast])
        if not anchored_r:
            ast = ("seq", [ast, ("rep", ("cls", _UNIVERSE), 0, None)])

        # Thompson construction
        self.eps: List[List[int]] = []
        self.trans: List[List[Tuple[FrozenSet[int], int]]] = []
        start = self._state()
        accept = self._build(ast, start)
        self.accept = accept
        self._closure_memo: Dict[FrozenSet[int], FrozenSet[int]] = {}
        self.dist = self._distances()
        if self.dist[start] >= _MAX_STATES * 2:
            raise PatternError("pattern matches no string")
        self.start_set = self._closure(frozenset((start,)))

    def _state(self) -> int:
        if len(self.eps) >= _MAX_STATES:
            raise PatternError("pattern too large")
        self.eps.append([])
        self.trans.append([])
        return len(self.eps) - 1

    def _build(self, ast, entry: int) -> int:
        """Wire ast from `entry`, return its exit state."""
        kind = ast[0]
        if kind == "cls":
            out = self._state()
            self.trans[entry].append((ast[1], out))
            return out
        if kind == "seq":
            cur = entry
            for item in ast[1]:
                cur = self._build(item, cur)
            return cur
        if kind == "alt":
            out = self._state()
            for br in ast[1]:
                b_in = self._state()
                self.eps[entry].append(b_in)
                self.eps[self._build(br, b_in)].append(out)
            return out
        if kind == "rep":
            _, sub, lo, hi = ast
            cur = entry
            for _ in range(lo):
                cur = self._build(sub, cur)
            if hi is None:  # star tail: loop on a fresh state
                loop = self._state()
                self.eps[cur].append(loop)
                self.eps[self._build(sub, loop)].append(loop)
                return loop
            for _ in range(hi - lo):
                nxt = self._build(sub, cur)
                self.eps[cur].append(nxt)  # skip edge
                cur = nxt
            return cur
        raise AssertionError(kind)

    def _closure(self, states: FrozenSet[int]) -> FrozenSet[int]:
        memo = self._closure_memo.get(states)
        if memo is not None:
            return memo
        seen = set(states)
        todo = list(states)
        while todo:
            s = todo.pop()
            for t in self.eps[s]:
                if t not in seen:
                    seen.add(t)
                    todo.append(t)
        out = frozenset(seen)
        self._closure_memo[states] = out
        return out

    def _distances(self) -> List[int]:
        """Shortest #bytes from each state to accept (eps edges free):
        0-1 BFS on the reversed graph."""
        import collections
        INF = _MAX_STATES * 4
        n = len(self.eps)
        radj_e: List[List[int]] = [[] for _ in range(n)]
        radj_b: List[List[int]] = [[] for _ in range(n)]
        for s in range(n):
            for t in self.eps[s]:
                radj_e[t].append(s)
            for _, t in self.trans[s]:
                radj_b[t].append(s)
        dist = [INF] * n
        dist[self.accept] = 0
        dq = collections.deque([self.accept])
        while dq:
            s = dq.popleft()
            for p in radj_e[s]:
                if dist[s] < dist[p]:
                    dist[p] = dist[s]
                    dq.appendleft(p)
            for p in radj_b[s]:
                if dist[s] + 1 < dist[p]:
                    dist[p] = dist[s] + 1
                    dq.append(p)
        return dist

    # -- the walk interface used by schema.SchemaAutomaton -------------

    def advance(self, states: FrozenSet[int],
                b: int) -> FrozenSet[int]:
        nxt = set()
        for s in states:
            for cls, t in self.trans[s]:
                if b in cls:
                    nxt.add(t)
        return self._closure(frozenset(nxt)) if nxt else frozenset()

    def accepting(self, states: FrozenSet[int]) -> bool:
        return self.accept in states

    def min_dist(self, states: FrozenSet[int]) -> int:
        return min((self.dist[s] for s in states),
                   default=_MAX_STATES * 4)

    def closing_byte(self, states: FrozenSet[int]) -> int:
        """A byte that strictly decreases min_dist (exists whenever
        min_dist > 0 and finite)."""
        target = self.min_dist(states) - 1
        best = None
        for s in states:
            for cls, t in self.trans[s]:
                if self.dist[t] <= target:
                    cand = min(cls)
                    if best is None or cand < best:
                        best = cand
        if best is None:
            raise AssertionError("no closing byte (pattern dead end)")
        return best
