"""AOT grammar-mask compiler and the adaptive state-mask cache.

XGrammar (Dong et al., arXiv:2411.15100 — PAPERS.md) splits grammar
masking into an ahead-of-time part (classify the vocabulary once per
grammar x tokenizer pair) and a tiny per-step residual. This module is
that split for the in-repo byte automata:

  * `CompiledTokenTable` — one compilation per tokenizer: raw token
    bytes plus numpy-indexable first-byte / length / plain-string
    columns. `mask_bits()` computes an allowed-token mask with the
    first-byte prefilter (256 trial `advance()` calls decide most of
    the vocabulary), a plain-string-interior fast path (inside an
    unconstrained JSON string, every printable token whose bytes avoid
    `"` and `\\` is legal — no walk at all), and a per-first-byte
    advanced-automaton reuse so a miss costs O(surviving tokens), not
    O(V) full byte-walks.
  * `compiled_table()` — the process-wide table cache. Keyed by
    tokenizer identity with `weakref.finalize` eviction so a GC'd
    tokenizer's reused `id()` can never alias a stale table (the old
    `TokenMasker._tables` bug).
  * `GrammarMaskCache` — bounded LRU from automaton-state signature
    (structured.TokenMasker.cache_key) to a row of the engine's
    device-resident `[S, V]` mask table. Steady-state decode plans
    reference cached states by row index (K ints per slot on the
    wire instead of K*V mask bools); rows referenced by the plan
    being built are pinned so eviction can't pull a row out from
    under an in-flight gather.

Host-side numpy only — nothing here touches the device; uploads go
through the engine callback handed to `GrammarMaskCache`.
"""

from __future__ import annotations

import weakref
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np


class CompiledTokenTable:
    """Per-tokenizer AOT artifact: token byte strings plus the numpy
    columns the first-byte prefilter indexes by token id."""

    def __init__(self, table: List[bytes]):
        self.raw = table
        n = len(table)
        self.lengths = np.fromiter((len(t) for t in table),
                                   dtype=np.int32, count=n)
        self.first_byte = np.fromiter((t[0] if t else 0 for t in table),
                                      dtype=np.int32, count=n)
        self.nonempty = self.lengths > 0
        self.max_len = int(self.lengths.max()) if n else 0
        self.present_first = sorted(
            {t[0] for t in table if t})
        self.single_first = sorted(
            {t[0] for t in table if len(t) == 1})
        # tokens made only of plain string-interior bytes: printable,
        # no quote, no backslash — legal anywhere inside an
        # unconstrained JSON string, and they leave the automaton
        # state unchanged
        self.str_plain = np.fromiter(
            (bool(t) and all(0x20 <= b and b != 0x22 and b != 0x5C
                             for b in t) for t in table),
            dtype=bool, count=n)

    def mask_bits(self, automaton, eos_id: Optional[int],
                  vocab_size: int, closing: bool = False,
                  budget: Optional[int] = None,
                  with_slack: bool = False):
        """Allowed-token mask for one automaton state.

        Semantics match the original TokenMasker.mask() byte-walk:
        `closing` restricts to the minimal completion path, `budget`
        (bytes) bans tokens after which the minimal completion no
        longer fits. The prefilter only changes the cost model.

        `with_slack` (budget-free, non-closing only) returns
        `(mask, slack)` where slack is the worst growth of
        `closing_distance()` over any single accepted token. A cache
        holding this mask may serve a budget-limited request exactly
        when `remaining - 1 >= closing_distance() + slack`: past that
        horizon no accepted token can push the minimal completion out
        of budget, so the budgeted mask equals this one."""
        if with_slack and (closing or budget is not None):
            raise ValueError("with_slack requires the budget-free, "
                             "non-closing mask")
        n = len(self.raw)
        m = np.zeros(vocab_size, dtype=bool)
        slack = 0
        cd_now = automaton.closing_distance() if with_slack else 0
        if closing:
            cb = automaton.closing_bytes()
            surv = self.nonempty.copy()
            if cb:
                allowed = np.zeros(256, dtype=bool)
                allowed[list(cb)] = True
                surv &= allowed[self.first_byte]
            else:
                surv[:] = False
            for i in np.flatnonzero(surv):
                if automaton.accepts_closing(self.raw[i]):
                    m[i] = True
        else:
            # first-byte prefilter: one trial advance per byte value
            # present in the vocab; keep the advanced copies so
            # surviving tokens skip their first byte
            allowed = np.zeros(256, dtype=bool)
            advanced: Dict[int, object] = {}
            for b in self.present_first:
                w = automaton.copy()
                if w.advance(b):
                    allowed[b] = True
                    advanced[b] = w
            surv = self.nonempty & allowed[self.first_byte]
            if budget is None:
                plain = getattr(automaton, "plain_str_interior", None)
                if plain is not None and plain():
                    # inside a plain string every surviving
                    # plain-bytes token is legal as-is
                    sp = surv & self.str_plain
                    m[:n] |= sp
                    surv &= ~sp
                # single-byte tokens are fully decided by the prefilter
                one = surv & (self.lengths == 1)
                m[:n] |= one
                surv &= ~one
                if with_slack:
                    # plain-interior tokens leave the state (and its
                    # closing distance) unchanged; single-byte tokens
                    # end in the already-advanced prefilter state
                    for b in self.single_first:
                        if allowed[b]:
                            slack = max(slack, advanced[b]
                                        .closing_distance() - cd_now)
            for i in np.flatnonzero(surv):
                w = advanced[self.raw[i][0]].copy()
                ok = True
                for b in self.raw[i][1:]:
                    if not w.advance(b):
                        ok = False
                        break
                if ok and (budget is None
                           or w.closing_distance() <= budget):
                    m[i] = True
                    if with_slack:
                        slack = max(slack,
                                    w.closing_distance() - cd_now)
        if eos_id is not None and automaton.is_complete():
            m[eos_id] = True
        if not m.any() and eos_id is not None:
            m[eos_id] = True  # dead end: finish rather than hang
        if with_slack:
            return m, slack
        return m


# tokenizer identity -> (table, pin). `pin` keeps a strong reference
# only when the tokenizer is not weakref-able (then its id can never
# be reused while the entry lives); otherwise weakref.finalize evicts
# the entry the moment the tokenizer is collected.
_COMPILED: Dict[int, Tuple[CompiledTokenTable, object]] = {}


def _evict_compiled(key: int) -> None:
    _COMPILED.pop(key, None)


def compiled_table(tok) -> CompiledTokenTable:
    """The process-wide CompiledTokenTable for `tok` (built once)."""
    key = id(tok)
    ent = _COMPILED.get(key)
    if ent is not None:
        return ent[0]
    from .structured import _build_token_table
    ctab = CompiledTokenTable(_build_token_table(tok))
    try:
        weakref.finalize(tok, _evict_compiled, key)
        pin = None
    except TypeError:
        pin = tok
    _COMPILED[key] = (ctab, pin)
    return ctab


class GrammarMaskCache:
    """Bounded LRU of automaton-state masks resident on the device.

    Owns rows 1..rows-1 of the engine's `[rows, V]` mask table — row 0
    is the engine's reserved all-True row that unmasked slots index.
    Each entry carries the state's budget-free mask bits, its device
    row, and its budget *slack*: the worst growth of the automaton's
    closing distance over any single accepted token, measured when the
    mask was compiled. A cached row substitutes for a budget-limited
    dense mask exactly when `remaining - 1 >= closing_distance +
    slack` — past that horizon the byte budget provably bans nothing
    the grammar allows, so the masks are identical.

    `get()` hits touch the LRU and pin the row; `insert()` installs a
    freshly compiled mask, uploading its row (row None when every row
    is pinned by the plan being built — the caller then keeps that
    position dense). Eviction simply reuses the LRU unpinned row: the
    next upload overwrites it, which is the invalidation; pinning
    keeps eviction from pulling a row out from under the plan that
    referenced it."""

    def __init__(self, rows: int,
                 upload: Callable[[int, np.ndarray], None],
                 on_hit: Optional[Callable[[], None]] = None,
                 on_miss: Optional[Callable[[], None]] = None,
                 on_evict: Optional[Callable[[], None]] = None):
        self.rows = int(rows)
        self._upload = upload
        self._on_hit = on_hit or (lambda: None)
        self._on_miss = on_miss or (lambda: None)
        self._on_evict = on_evict or (lambda: None)
        # key -> (row, host bits, slack), in LRU order (oldest first)
        self._lru: "OrderedDict[object, Tuple[int, np.ndarray, int]]" \
            = OrderedDict()
        self._free = list(range(self.rows - 1, 0, -1))
        self._pinned: set = set()

    def __len__(self) -> int:
        return len(self._lru)

    def begin_plan(self) -> None:
        """Start a new step plan: rows looked up from here on are
        pinned (ineligible for eviction) until the next begin_plan."""
        self._pinned.clear()

    def get(self, key):
        """(bits, row, slack) on a hit — touching LRU order and
        pinning the row — or None on a miss."""
        ent = self._lru.get(key)
        if ent is None:
            return None
        self._lru.move_to_end(key)
        self._pinned.add(ent[0])
        self._on_hit()
        return ent[1], ent[0], ent[2]

    def insert(self, key, bits: np.ndarray, slack: int):
        """Install a freshly compiled state mask and upload its row.
        Returns (bits, row, slack); row is None — and nothing is
        installed — when the table is exhausted by pinned rows."""
        self._on_miss()
        row = self._alloc()
        if row is None:
            return bits, None, slack
        self._lru[key] = (row, bits, slack)
        self._pinned.add(row)
        self._upload(row, bits)
        return bits, row, slack

    def _alloc(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        for key, (row, _, _) in self._lru.items():
            if row not in self._pinned:
                del self._lru[key]
                self._on_evict()
                return row
        return None
