"""Crash-safe request journal (write-ahead log) for restart resume.

Durability discipline (docs/durability.md): every admitted request
gets an ``admit`` record (prompt ids, sampling params, absolute
deadline, trace id) before it can occupy a decode slot; as the
scheduler emits tokens, ``prog`` records append the generated-so-far
ids; a normal finish writes a ``fin`` tombstone. On restart, replay
returns every admitted-but-untombstoned request with its accumulated
output, and the scheduler re-admits it with the prompt folded with
those tokens — the same recompute-resume fold paged-KV preemption
uses — so a greedy stream picks up byte-identical to an uninterrupted
run.

Format: one JSON object per line (JSONL), append-only:

    {"t": "admit", "jid": 7, "prompt": [...], "max_new": 64, ...}
    {"t": "prog",  "jid": 7, "toks": [513, 9, ...]}
    {"t": "fin",   "jid": 7, "reason": "stop"}

A crash mid-append leaves a torn tail line; replay drops it (and
repairs the file) rather than refusing to start. Size-triggered
compaction rewrites the file atomically (tmp + fsync + os.replace)
with one admit + one consolidated prog per live request.

Fsync policy (``--journal-fsync``): ``always`` fsyncs after every
append batch (strongest, slowest), ``batch`` (default) fsyncs at most
every ``fsync_interval`` seconds from the scheduler's poll, ``off``
leaves flushing to the OS. Journal I/O failures DEGRADE the journal
(counted, logged once) instead of failing requests: availability wins
over durability for a serving replica.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .. import faults

log = logging.getLogger("ome.engine.journal")

FILENAME = "requests.jsonl"
FSYNC_POLICIES = ("always", "batch", "off")


@dataclass
class JournalEntry:
    """One unfinished request as reconstructed by replay."""

    jid: int
    prompt_ids: List[int]
    max_new_tokens: int = 64
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    stop_ids: List[int] = field(default_factory=list)
    adapter: Optional[str] = None
    # priority class (docs/multi-tenancy.md): restored on resume so a
    # kill -9 cannot launder a batch request into a higher class
    cls: str = "standard"
    # absolute EPOCH seconds (time.time clock): monotonic deadlines do
    # not survive a process restart, so the journal stores wall-clock
    # and the resume path converts back
    deadline_epoch: Optional[float] = None
    trace_id: Optional[str] = None
    output_ids: List[int] = field(default_factory=list)
    # PD provenance stamped by the serving node (e.g. {"mode":
    # "pd-decode", "peers": [...]}): records that this request's
    # prefill came over the PD handoff, so a resumed process knows the
    # replay must re-prefill through its prefill pool (or local
    # fallback) rather than assume local compute produced the KV
    pd: Optional[dict] = None


class _Live:
    """Tracking state for a journaled request still in this process:
    how many of req.output_ids have been written already."""

    __slots__ = ("req", "upto")

    def __init__(self, req):
        self.req = req
        self.upto = len(req.output_ids)


class RequestJournal:
    """Append-only JSONL WAL; thread-safe (scheduler thread appends
    progress, HTTP handler threads append admits and tombstones)."""

    def __init__(self, directory: str, fsync: str = "batch",
                 fsync_interval: float = 0.1,
                 compact_bytes: int = 4 << 20,
                 provenance: Optional[dict] = None):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"journal fsync policy {fsync!r} not in "
                f"{FSYNC_POLICIES}")
        # stamped into every admit record (see JournalEntry.pd); the
        # PD decode role passes its pool topology here
        self.provenance = provenance
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, FILENAME)
        self.fsync = fsync
        self.fsync_interval = fsync_interval
        self.compact_bytes = compact_bytes
        self._lock = threading.RLock()
        # full journal state: jid -> record dict (admit fields +
        # "toks"); finished requests are deleted, so this is exactly
        # what replay returns and what compaction rewrites
        self._state: Dict[int, dict] = {}
        self._live: Dict[int, _Live] = {}
        self._dirty = False          # bytes appended since last fsync
        self._last_fsync = time.monotonic()
        self.degraded = False
        # metrics are optional (bind() wires them); plain ints mirror
        # them so tests can assert without a registry
        self.appends = 0
        self.errors = 0
        self.compactions = 0
        self.replayed = 0
        self._c_appends = self._c_errors = None
        self._c_compactions = self._c_replayed = None
        self._g_bytes = None
        next_jid = self._load()
        self._seq = next_jid
        self._fh = open(self.path, "a", encoding="utf-8")
        self._bytes = os.path.getsize(self.path)

    # -- metrics -------------------------------------------------------

    def bind(self, registry) -> None:
        """Attach journal metrics to the process's shared registry."""
        if registry is None:
            return
        self._c_appends = registry.counter(
            "ome_engine_journal_appends_total",
            "Journal records appended (admit + progress + tombstone)")
        self._c_errors = registry.counter(
            "ome_engine_journal_errors_total",
            "Journal I/O failures (append/fsync/replay); the journal "
            "degrades, serving continues")
        self._c_compactions = registry.counter(
            "ome_engine_journal_compactions_total",
            "Size-triggered journal compactions")
        self._c_replayed = registry.counter(
            "ome_engine_journal_replayed_requests_total",
            "Unfinished requests re-admitted from journal replay")
        self._g_bytes = registry.gauge(
            "ome_engine_journal_bytes",
            "Current journal file size in bytes")
        self._g_bytes.set(self._bytes)

    def _count(self, counter, attr: str, by: int = 1):
        setattr(self, attr, getattr(self, attr) + by)
        if counter is not None:
            counter.inc(by)

    # -- load / replay -------------------------------------------------

    def _load(self) -> int:
        """Scan an existing journal into _state; repair a torn tail
        line (crash mid-append) by truncating it. Returns the next
        free jid."""
        if not os.path.exists(self.path):
            return 0
        max_jid = -1
        good_end = 0
        with open(self.path, "rb") as fh:
            data = fh.read()
        pos = 0
        while pos < len(data):
            nl = data.find(b"\n", pos)
            if nl < 0:
                # no terminating newline: a torn tail from a crash
                # mid-append — drop it
                log.warning("journal: dropping torn tail line "
                            "(%d bytes)", len(data) - pos)
                break
            line = data[pos:nl]
            pos = nl + 1
            if not line.strip():
                good_end = pos
                continue
            try:
                rec = json.loads(line)
                kind = rec["t"]
                jid = int(rec["jid"])
            except (ValueError, KeyError, TypeError):
                if pos >= len(data):
                    # torn-but-newline-terminated tail (crash between
                    # the partial write and the newline of the NEXT
                    # record is impossible, but a truncated filesystem
                    # can produce it): drop, don't keep good_end
                    log.warning("journal: dropping corrupt tail line")
                    break
                # mid-file garbage: skip the record, keep the rest
                log.warning("journal: skipping corrupt mid-file line")
                good_end = pos
                continue
            if kind == "admit":
                rec.setdefault("toks", [])
                self._state[jid] = rec
            elif kind == "prog":
                entry = self._state.get(jid)
                if entry is not None:
                    entry["toks"] = list(entry.get("toks", [])) + [
                        int(t) for t in rec.get("toks", [])]
            elif kind == "fin":
                self._state.pop(jid, None)
            max_jid = max(max_jid, jid)
            good_end = pos
        if good_end < len(data):
            # repair in place so future appends start on a clean line
            with open(self.path, "r+b") as fh:
                fh.truncate(good_end)
        return max_jid + 1

    def replay(self) -> List[JournalEntry]:
        """Unfinished requests from the journal this process opened,
        oldest admission first. The caller (Scheduler.resume_from_
        journal) re-submits them with prompt+output folded."""
        faults.fire("journal_replay")
        out = []
        with self._lock:
            for jid in sorted(self._state):
                rec = self._state[jid]
                out.append(JournalEntry(
                    jid=jid,
                    prompt_ids=[int(t) for t in rec.get("prompt", [])],
                    max_new_tokens=int(rec.get("max_new", 64)),
                    temperature=float(rec.get("temp", 0.0)),
                    top_k=int(rec.get("top_k", 0)),
                    top_p=float(rec.get("top_p", 1.0)),
                    stop_ids=[int(t) for t in rec.get("stop", [])],
                    adapter=rec.get("adapter"),
                    cls=rec.get("cls", "standard"),
                    deadline_epoch=rec.get("deadline"),
                    trace_id=rec.get("trace"),
                    output_ids=[int(t) for t in rec.get("toks", [])],
                    pd=rec.get("pd")))
        return out

    def note_replayed(self, n: int):
        self._count(self._c_replayed, "replayed", n)

    # -- append paths --------------------------------------------------

    def _append(self, rec: dict):
        """Append one record; caller holds self._lock. Failures
        degrade the journal instead of propagating into the serving
        path."""
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        try:
            faults.fire("journal_append")
            self._fh.write(line)
            self._fh.flush()
        except Exception as e:  # noqa: BLE001 — durability must not
            # take down availability
            self._degrade("append", e)
            return
        self._bytes += len(line)
        self._dirty = True
        self._count(self._c_appends, "appends")
        if self._g_bytes is not None:
            self._g_bytes.set(self._bytes)
        if self.fsync == "always":
            self._fsync()

    def _fsync(self):
        if not self._dirty:
            return
        try:
            faults.fire("journal_fsync")
            os.fsync(self._fh.fileno())
        except Exception as e:  # noqa: BLE001
            self._degrade("fsync", e)
            return
        self._dirty = False
        self._last_fsync = time.monotonic()

    def _degrade(self, op: str, err: Exception):
        self._count(self._c_errors, "errors")
        if not self.degraded:
            self.degraded = True
            log.error("journal %s failed (%s); journal DEGRADED — "
                      "serving continues without durability", op, err)

    # -- request lifecycle ---------------------------------------------

    def admit(self, req) -> None:
        """Durably record an admitted request. A request replayed from
        this journal already carries its jid — it is re-registered for
        progress tracking without a duplicate admit record."""
        with self._lock:
            jid = getattr(req, "journal_id", None)
            if jid is not None and jid in self._state:
                self._live[jid] = _Live(req)
                return
            if jid is None:
                jid = self._seq
                self._seq += 1
                req.journal_id = jid
            deadline_epoch = None
            if req.deadline is not None:
                # convert the scheduler's monotonic deadline to epoch
                # so it survives the restart
                deadline_epoch = time.time() + (
                    req.deadline - time.monotonic())
            rec = {"t": "admit", "jid": jid,
                   "prompt": [int(t) for t in req.prompt_ids],
                   "max_new": int(req.max_new_tokens),
                   "temp": float(req.temperature),
                   "top_k": int(req.top_k),
                   "top_p": float(req.top_p),
                   "stop": [int(t) for t in req.stop_ids],
                   "adapter": req.adapter,
                   "cls": getattr(req, "priority", "standard"),
                   "deadline": deadline_epoch,
                   "trace": getattr(req.trace, "trace_id", None)}
            if self.provenance is not None:
                rec["pd"] = self.provenance
            self._append(rec)
            rec = dict(rec)
            rec["toks"] = []
            self._state[jid] = rec
            self._live[jid] = _Live(req)

    def _flush_one(self, jid: int, live: _Live):
        """Append a prog record for tokens emitted since the last
        flush; caller holds self._lock."""
        toks = live.req.output_ids
        n = len(toks)
        if n <= live.upto:
            return
        fresh = [int(t) for t in toks[live.upto:n]]
        live.upto = n
        self._append({"t": "prog", "jid": jid, "toks": fresh})
        entry = self._state.get(jid)
        if entry is not None:
            entry["toks"] = list(entry.get("toks", [])) + fresh

    def poll(self) -> None:
        """Scheduler-cadence maintenance: flush per-request progress,
        apply the batch fsync policy, compact when oversized. Called
        from the scheduler thread at each step boundary — every token
        a client has seen is journaled by the time the step returns."""
        with self._lock:
            for jid, live in list(self._live.items()):
                self._flush_one(jid, live)
            if self.fsync == "batch" and self._dirty and (
                    time.monotonic() - self._last_fsync
                    >= self.fsync_interval):
                self._fsync()
            if self._bytes > self.compact_bytes:
                self._compact()

    def finish(self, req, resumable: bool = False) -> None:
        """Request reached a terminal state in THIS process.

        ``resumable=False`` (the work is done: stop/length/timeout/
        per-request error) writes a tombstone. ``resumable=True``
        (the PROCESS is going away with the work unfinished — a
        drain-timeout ``shutdown`` eviction, or an ``engine_fault``
        from a dead scheduler about to be replaced) instead flushes
        the final progress and leaves the entry live, so the next
        process replays and resumes it. The scheduler decides which —
        it knows whether the finish was a crash or a completion."""
        jid = getattr(req, "journal_id", None)
        if jid is None:
            return
        with self._lock:
            live = self._live.pop(jid, None)
            if live is not None:
                self._flush_one(jid, live)
            if resumable:
                self._fsync()
                return
            self._append({"t": "fin", "jid": jid,
                          "reason": req.finish_reason})
            self._state.pop(jid, None)

    # -- compaction ----------------------------------------------------

    def _compact(self):
        """Atomically rewrite the journal with one admit + one
        consolidated prog per live entry; caller holds self._lock."""
        tmp = self.path + ".tmp"
        try:
            faults.fire("journal_append")  # compaction is an append path
            with open(tmp, "w", encoding="utf-8") as fh:
                for jid in sorted(self._state):
                    rec = dict(self._state[jid])
                    toks = rec.pop("toks", [])
                    fh.write(json.dumps(rec, separators=(",", ":"))
                             + "\n")
                    if toks:
                        fh.write(json.dumps(
                            {"t": "prog", "jid": jid, "toks": toks},
                            separators=(",", ":")) + "\n")
                fh.flush()
                os.fsync(fh.fileno())
            self._fh.close()
            os.replace(tmp, self.path)
            self._fh = open(self.path, "a", encoding="utf-8")
            self._bytes = os.path.getsize(self.path)
            self._dirty = False
            self._last_fsync = time.monotonic()
            self._count(self._c_compactions, "compactions")
            if self._g_bytes is not None:
                self._g_bytes.set(self._bytes)
        except Exception as e:  # noqa: BLE001
            self._degrade("compact", e)
            try:
                os.remove(tmp)
            except OSError:
                pass

    # -- teardown ------------------------------------------------------

    def flush(self) -> None:
        """Flush all pending progress and fsync regardless of policy
        (drain/shutdown path)."""
        with self._lock:
            for jid, live in list(self._live.items()):
                self._flush_one(jid, live)
            self._fsync()

    def close(self) -> None:
        with self._lock:
            try:
                self.flush()
                self._fh.close()
            except Exception:  # noqa: BLE001 — already shutting down
                pass
