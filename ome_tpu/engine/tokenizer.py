"""Tokenizers for the serving engine.

A dependency-free byte-level tokenizer is the default (works with any
vocab >= 259 and makes CI/zero-egress tests hermetic); when a model dir
carries a real HF tokenizer, `load_tokenizer` upgrades to it via
`transformers` (baked into the image).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

PAD_ID, BOS_ID, EOS_ID = 0, 1, 2
_BYTE_OFFSET = 3


class ByteTokenizer:
    """UTF-8 bytes + {pad, bos, eos}. Reversible for any text."""

    vocab_size = 256 + _BYTE_OFFSET
    pad_id, bos_id, eos_id = PAD_ID, BOS_ID, EOS_ID

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = [b + _BYTE_OFFSET for b in text.encode("utf-8")]
        return ([BOS_ID] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        # ids past the byte range (models with larger vocabs) are skipped
        data = bytes(i - _BYTE_OFFSET for i in ids
                     if _BYTE_OFFSET <= i < _BYTE_OFFSET + 256)
        return data.decode("utf-8", errors="replace")

    def decode_bytes(self, ids: Sequence[int]) -> bytes:
        """Raw byte view (no str decode): the streaming path feeds
        these through an incremental UTF-8 decoder so a chunk ending
        mid-codepoint holds its tail bytes instead of flushing
        U+FFFD (server._stream)."""
        return bytes(i - _BYTE_OFFSET for i in ids
                     if _BYTE_OFFSET <= i < _BYTE_OFFSET + 256)

    def apply_chat_template(self, messages: List[dict]) -> str:
        parts = [f"{m.get('role', 'user')}: {m.get('content', '')}"
                 for m in messages]
        return "\n".join(parts) + "\nassistant:"


class HFTokenizer:
    """Thin adapter over transformers' PreTrainedTokenizer."""

    def __init__(self, tok):
        self._tok = tok
        self.vocab_size = len(tok)
        self.bos_id = tok.bos_token_id
        self.eos_id = tok.eos_token_id
        self.pad_id = tok.pad_token_id or 0

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        if add_bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def apply_chat_template(self, messages: List[dict]) -> str:
        try:
            return self._tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True)
        except Exception:
            return ByteTokenizer.apply_chat_template(self, messages)


def load_tokenizer(model_dir: Optional[str] = None):
    """HF tokenizer if the model dir ships one, else byte-level."""
    if model_dir and os.path.exists(
            os.path.join(model_dir, "tokenizer.json")):
        try:
            from transformers import AutoTokenizer
            return HFTokenizer(AutoTokenizer.from_pretrained(model_dir))
        except Exception:
            pass
    return ByteTokenizer()
